# kubedtn-tpu top-level targets (build/test/bench parity with the
# reference's Makefile + .mk/ tree, minus the Go/buf/kustomize toolchain
# the TPU architecture doesn't need).

PY ?= python

.PHONY: all test test-fast bench native crd daemon scenario-% docker clean \
	lint typecheck verify verify-fast

all: native test

lint:                      ## dtnlint contract suite (+ ruff when installed)
	$(PY) -m kubedtn_tpu.analysis --json ANALYSIS.json
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check kubedtn_tpu tests bench.py; \
	else \
		echo "ruff not installed; dtnlint's hygiene pass covered the floor"; \
	fi

typecheck:                 ## strict types over the contract core (when installed)
	@if command -v pyright >/dev/null 2>&1; then \
		pyright; \
	elif $(PY) -m mypy --version >/dev/null 2>&1; then \
		$(PY) -m mypy; \
	else \
		echo "pyright/mypy not installed; configs live in pyproject.toml"; \
	fi

verify: typecheck native   ## all three analysis layers + types, then tier-1
	$(PY) -m kubedtn_tpu.analysis --verify --scale --json ANALYSIS.json
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check kubedtn_tpu tests bench.py; \
	else \
		echo "ruff not installed; dtnlint's hygiene pass covered the floor"; \
	fi
	$(PY) -m pytest tests/ -q -m "not slow"

verify-fast:               ## pre-commit gate: dtnlint + dtnverify + dtnscale (cached), no pytest
	$(PY) -m kubedtn_tpu.analysis --verify --scale --cached -q --json ANALYSIS.json

test: native               ## full suite (CPU, virtual 8-device mesh)
	$(PY) -m pytest tests/ -q

test-fast:                 ## skip the slow sharded/e2e tests
	$(PY) -m pytest tests/ -q -m "not slow" 2>/dev/null || \
	$(PY) -m pytest tests/ -q -x

bench:                     ## headline metric (one JSON line)
	$(PY) bench.py

native:                    ## C++ runtime library
	$(MAKE) -C native

crd:                       ## regenerate the checked-in CRD manifest
	$(PY) -m kubedtn_tpu.cli crd > config/crd/.topologies.yaml.tmp
	mv config/crd/.topologies.yaml.tmp config/crd/topologies.yaml

daemon:                    ## run the gRPC control plane + metrics
	$(PY) -m kubedtn_tpu.cli daemon

scenario-%:                ## run a BASELINE ladder rung, e.g. make scenario-clos_100k
	$(PY) -m kubedtn_tpu.cli scenario $*

docker:                    ## container image for the daemon DaemonSet
	docker build -t kubedtn-tpu:latest .

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -prune -exec rm -rf {} +

manager:                   ## run the controller manager (probes + leader election)
	$(PY) -m kubedtn_tpu.cli manager

loc:                       ## reproducible LoC diagnostic (exact commands recorded here)
	@echo "repo (non-test Python + C++):"
	@find kubedtn_tpu native \( -name '*.py' -o -name '*.cc' -o -name '*.h' \) \
		-print0 | xargs -0 cat | wc -l
	@echo "tests:"
	@find tests -name '*.py' -print0 | xargs -0 cat | wc -l
	@echo "reference core (hand-written Go + eBPF C, excluding generated+tests):"
	@find /root/reference \
		\( \( -name '*.go' ! -name '*.pb.go' ! -name 'zz_generated*' \
		      ! -name '*_bpfe[lb].go' ! -name '*_test.go' \) \
		   -o -name '*.c' -o -name '*.h' \) \
		! -path '*/test/*' -print0 2>/dev/null | xargs -0 cat | wc -l
