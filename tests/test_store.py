"""Tests for the in-process Topology store's K8s API semantics."""

import pytest

from kubedtn_tpu.api.types import Link, Topology, TopologySpec
from kubedtn_tpu.topology.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    TopologyStore,
    retry_on_conflict,
)


def mk(name, uids=(1,)):
    return Topology(name=name, spec=TopologySpec(links=[
        Link(local_intf=f"eth{u}", peer_intf=f"eth{u}", peer_pod="p",
             uid=u) for u in uids
    ]))


def test_create_get_list_delete():
    s = TopologyStore()
    s.create(mk("a"))
    s.create(mk("b"))
    assert s.get("default", "a").name == "a"
    assert [t.name for t in s.list()] == ["a", "b"]
    with pytest.raises(AlreadyExistsError):
        s.create(mk("a"))
    s.delete("default", "a")
    with pytest.raises(NotFoundError):
        s.get("default", "a")


def test_conflict_on_stale_write():
    s = TopologyStore()
    s.create(mk("a"))
    t1 = s.get("default", "a")
    t2 = s.get("default", "a")
    t1.status.src_ip = "10.0.0.1"
    s.update_status(t1)
    t2.status.src_ip = "10.0.0.2"
    with pytest.raises(ConflictError):
        s.update_status(t2)  # stale resourceVersion


def test_retry_on_conflict_rereads():
    s = TopologyStore()
    s.create(mk("a"))
    stale = s.get("default", "a")
    other = s.get("default", "a")
    other.status.net_ns = "/run/netns/x"
    s.update_status(other)

    calls = []

    def txn():
        calls.append(1)
        t = s.get("default", "a")
        if len(calls) == 1:
            # simulate losing a race after the read
            racer = s.get("default", "a")
            racer.status.src_ip = "10.9.9.9"
            s.update_status(racer)
            t.status.src_ip = "10.0.0.1"
            s.update_status(t)  # conflicts
        else:
            t.status.src_ip = "10.0.0.1"
            s.update_status(t)

    retry_on_conflict(txn)
    assert len(calls) == 2
    assert s.get("default", "a").status.src_ip == "10.0.0.1"
    assert stale.resource_version < s.get("default", "a").resource_version


def test_status_update_does_not_touch_spec():
    s = TopologyStore()
    s.create(mk("a", uids=(1, 2)))
    t = s.get("default", "a")
    t.spec.links = []  # try to sneak a spec change through update_status
    t.status.src_ip = "1.2.3.4"
    s.update_status(t)
    got = s.get("default", "a")
    assert len(got.spec.links) == 2
    assert got.status.src_ip == "1.2.3.4"


def test_finalizer_gates_deletion():
    s = TopologyStore()
    s.create(mk("a"))
    t = s.get("default", "a")
    t.finalizers = ["y-young.github.io/v1"]
    s.update(t)
    s.delete("default", "a")
    # still present: finalizer holds it
    held = s.get("default", "a")
    assert held.deletion_requested
    held.finalizers = []
    s.update(held)
    with pytest.raises(NotFoundError):
        s.get("default", "a")


def test_watch_stream():
    s = TopologyStore()
    w = s.watch()
    s.create(mk("a"))
    t = s.get("default", "a")
    t.status.src_ip = "9.9.9.9"
    s.update_status(t)
    s.delete("default", "a")
    events = [e.type for e in w.poll()]
    assert events == ["ADDED", "MODIFIED", "DELETED"]
    w.close()
