"""Federated planes — zero-loss live tenant migration (ISSUE 11).

The headline pins:

- A tenant migrated src→dst mid-run delivers a payload stream
  BYTE-IDENTICAL to the same tenant never migrated (solo-plane
  reference), at pipeline depths 1 and 2, with byte-exact accounting
  (fed == accounted_src + accounted_dst, mismatch gauge 0). The
  alignment contract: federation planes share a PRNG seed and tick in
  lockstep (the same dispatch-schedule alignment the cohabited ≡ solo
  tenancy contract already requires), and the migration lands inside a
  feed gap so no frame's shaping tick moves.
- Crash-at-every-step: an injected failure at each of the six steps
  (side effects applied, journal commit NOT written — the worst
  instant) leads to either idempotent resume or byte-exact rollback;
  in all cases frames_lost == 0 and the stream stays byte-identical.
- The journal's double-crash discipline: a torn manifest resolves to
  the `.prev` generation; checksum damage raises typed errors.
- Satellites: tenant registry checkpoint persistence, tenant delete,
  migration RPCs, kubedtn_migration_* metrics.
"""

import os
import tempfile

import numpy as np
import pytest

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.chaos import ChaosError, ChaosInjector
from kubedtn_tpu.federation import (STEPS, FederationController,
                                    MigrationCoordinator,
                                    MigrationStats, PlaneHandle)
from kubedtn_tpu.federation import journal as fjournal
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.tenancy import TenantRegistry
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.server import Daemon

pytestmark = pytest.mark.federation

PAIRS = 2
# mig exercises the correlated/jitter/loss path; the bg tenants keep
# BOTH planes dispatching every tick, which is what keeps the per-tick
# key chains aligned across the reference, src and dst planes
PROPS = {
    "mig": LinkProperties(latency="2ms", jitter="1ms", loss="10"),
    "bg": LinkProperties(latency="1ms"),
    "bg2": LinkProperties(latency="1ms"),
}
ALL = sorted(PROPS)
T_TOTAL, GAP_START, GAP_END = 60, 20, 35
TAIL = 60
DT = 0.002
FPT = 3


def _build_plane(tenants, depth=1, seed=0, addr="10.0.0.1"):
    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * PAIRS * len(PROPS) + 8,
                       node_ip=addr)
    registry = TenantRegistry(engine)
    for ns in tenants:
        registry.create(ns)
        props = PROPS[ns]
        base_uid = ALL.index(ns) * PAIRS
        for i in range(PAIRS):
            uid = base_uid + i + 1
            a, b = f"{ns}-a{i}", f"{ns}-b{i}"
            store.create(Topology(name=a, namespace=ns,
                                  spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                     uid=uid, properties=props)])))
            store.create(Topology(name=b, namespace=ns,
                                  spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                     uid=uid, properties=props)])))
            engine.setup_pod(a, ns)
            engine.setup_pod(b, ns)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=depth,
                          seed=seed)
    plane.pipeline_explicit_clock = True
    plane.attach_tenancy(registry)
    for ns in tenants:
        base_uid = ALL.index(ns) * PAIRS
        for i in range(PAIRS):
            uid = base_uid + i + 1
            daemon._add_wire(pb.WireDef(
                local_pod_name=f"{ns}-a{i}", kube_ns=ns, link_uid=uid,
                intf_name_in_pod="eth1"))
            daemon._add_wire(pb.WireDef(
                local_pod_name=f"{ns}-b{i}", kube_ns=ns, link_uid=uid,
                intf_name_in_pod="eth1"))
    return daemon, plane, registry


def _tagged(ns, wire_i, j, size=64):
    tag = f"{ns}/{wire_i}".encode()
    return tag + j.to_bytes(4, "big") + b"\x00" * (size - len(tag) - 4)


def _wire(daemon, ns, side, i):
    base_uid = ALL.index(ns) * PAIRS
    return daemon.wires.get_by_key(f"{ns}/{ns}-{side}{i}",
                                   base_uid + i + 1)


class _Harness:
    """Lockstep tick driver over 1 or 2 planes; the tick index drives
    clocks AND frame tags, so any two harnesses with the same feed
    schedule produce comparable streams — including one that ran a
    migration in the middle."""

    def __init__(self, planes, bg_map):
        self.planes = planes          # [(daemon, plane)]
        self.bg_map = bg_map          # id(daemon) -> bg tenant ns
        self.k = 0
        self.deliv = [[] for _ in range(PAIRS)]
        self.fed = 0

    @property
    def t(self):
        return 100.0 + self.k * DT

    def feed_mig(self, daemon):
        for i in range(PAIRS):
            w = _wire(daemon, "mig", "a", i)
            for n in range(FPT):
                w.ingress.append(_tagged("mig", i, self.k * FPT + n))
        self.fed += FPT * PAIRS

    def drain(self):
        for d, _p in self.planes:
            for i in range(PAIRS):
                w = _wire(d, "mig", "b", i)
                if w is None:
                    continue
                while True:
                    try:
                        self.deliv[i].append(w.egress.popleft())
                    except IndexError:
                        break

    def tick(self):
        self.k += 1
        t = self.t
        for d, p in self.planes:
            bg = self.bg_map[id(d)]
            base_uid = ALL.index(bg) * PAIRS
            for i in range(PAIRS):
                w = d.wires.get_by_key(f"{bg}/{bg}-a{i}",
                                       base_uid + i + 1)
                w.ingress.extend(_tagged(bg, i, self.k * 2 + n)
                                 for n in range(2))
            p.tick(now_s=t)
        self.drain()

    def finish(self):
        for _ in range(TAIL):
            self.tick()
        for _d, p in self.planes:
            p.flush()
        self.k += 5000
        for _d, p in self.planes:
            p.tick(now_s=self.t)
        self.drain()
        for _d, p in self.planes:
            assert p.tick_errors == 0


_REF_CACHE = {}


def _reference(depth=1):
    """The never-migrated stream: one plane hosting mig + bg, same
    schedule, no migration. Cached per depth — every comparison is
    against the same bits."""
    if depth not in _REF_CACHE:
        d, p, r = _build_plane(["bg", "mig"], depth=depth)
        h = _Harness([(d, p)], {id(d): "bg"})
        while h.k < T_TOTAL:
            if h.k < GAP_START or h.k >= GAP_END:
                h.feed_mig(d)
            h.tick()
        h.finish()
        _REF_CACHE[depth] = (h.deliv, h.fed,
                             r.tenant_counters(p, "mig"))
    return _REF_CACHE[depth]


def _run_migrated(depth=1, fail_step=None, do="resume",
                  restart_controller=False, neighbor_wire=False):
    """Two federated planes, same seed, lockstep ticks; the migration
    runs inside the feed gap (settle = harness ticks). Returns
    (record, harness, accounting, stats, controller)."""
    d_s, p_s, r_s = _build_plane(["bg", "mig"], depth=depth,
                                 addr="10.0.0.1")
    d_d, p_d, r_d = _build_plane(["bg2"], depth=depth,
                                 addr="10.0.0.2")
    root = tempfile.mkdtemp(prefix="kdt-fed-test-")
    stats = MigrationStats()
    chaos = ChaosInjector(seed=1)
    if fail_step:
        chaos.fail_migration_step(fail_step)
    fed = FederationController(root, stats=stats, chaos=chaos)
    fed.register(PlaneHandle("A", d_s, p_s, r_s))
    fed.register(PlaneHandle("B", d_d, p_d, r_d))
    if neighbor_wire:
        # a pre-existing dst wire in the tenant's namespace that the
        # migration did NOT create — undo must leave it alone
        d_d._add_wire(pb.WireDef(local_pod_name="neighbor",
                                 kube_ns="mig", link_uid=9999,
                                 intf_name_in_pod="eth9"))
    h = _Harness([(d_s, p_s), (d_d, p_d)],
                 {id(d_s): "bg", id(d_d): "bg2"})
    while h.k < GAP_START:
        h.feed_mig(d_s)
        h.tick()
    rolled = False
    try:
        rec = fed.migrate("mig", "A", "B", settle=h.tick,
                          reconcile_timeout_s=10.0)
        mid = rec["migration_id"]
    except ChaosError:
        mid = fed.status(tenant="mig")[-1]["migration_id"]
        if restart_controller:
            # a daemon restart: a FRESH controller over the same
            # journal root must rebuild the coordinator from disk
            fed = FederationController(root, stats=stats)
            fed.register(PlaneHandle("A", d_s, p_s, r_s))
            fed.register(PlaneHandle("B", d_d, p_d, r_d))
        co = fed.coordinator(mid)
        co.settle = h.tick
        if do == "resume":
            rec = co.resume()
        else:
            rec = co.rollback()
            rolled = True
    assert h.k < GAP_END, f"migration overran the feed gap: k={h.k}"
    while h.k < GAP_END:
        h.tick()
    target = d_s if rolled else d_d
    while h.k < T_TOTAL:
        h.feed_mig(target)
        h.tick()
    h.finish()
    acct = None
    if not rolled:
        acct = fed.coordinator(mid).check_accounting(h.fed)
    return rec, h, acct, stats, fed


# -- headline: byte identity + accounting ------------------------------

@pytest.mark.parametrize("depth", [1, 2], ids=["d1", "d2"])
def test_migration_byte_identical(depth):
    """Clean migration: the migrated tenant's delivered stream (src
    deliveries + dst deliveries, in order) equals the never-migrated
    reference bit for bit; accounting reconciles exactly."""
    ref_deliv, ref_fed, ref_cnt = _reference(depth)
    rec, h, acct, stats, _fed = _run_migrated(depth=depth)
    assert rec["state"] == "done"
    assert rec["steps_done"] == list(STEPS)
    assert h.fed == ref_fed
    for i in range(PAIRS):
        assert h.deliv[i] == ref_deliv[i], f"wire {i} stream"
    assert acct["mismatch"] == 0.0
    # split accounting matches the solo plane's single-plane totals
    assert (acct["accounted_src"] + acct["accounted_dst"]
            == pytest.approx(ref_cnt["delivered_packets"]
                             + ref_cnt["dropped_loss"]
                             + ref_cnt["dropped_queue"]
                             + ref_cnt["dropped_ring"]))
    assert stats.snapshot()["accounting_mismatch"] == 0.0


@pytest.mark.parametrize("depth", [1, 2], ids=["d1", "d2"])
def test_crash_at_every_step_resumes_byte_identical(depth):
    """The <30s crash smoke: an injected failure at EACH of the six
    steps (side effects done, commit not written), then resume — the
    stream stays byte-identical to the never-migrated reference and
    accounting reconciles to 0 mismatch at both pipeline depths."""
    ref_deliv, _ref_fed, _ = _reference(depth)
    for step in STEPS:
        rec, h, acct, stats, _fed = _run_migrated(depth=depth,
                                                  fail_step=step)
        assert rec["state"] == "done", step
        assert rec["resumed"] >= 1, step
        for i in range(PAIRS):
            assert h.deliv[i] == ref_deliv[i], f"{step} wire {i}"
        assert acct["mismatch"] == 0.0, (step, acct)
        snap = stats.snapshot()
        assert snap["resumed"] >= 1 and snap["completed"] == 1
        assert snap["accounting_mismatch"] == 0.0


def test_crash_rollback_byte_identical():
    """Failures before cutover commits may also ROLL BACK: the tenant
    stays on src and its stream equals a plane that never attempted
    the migration. A rolled-back migration refuses resume(), and the
    undo touches only the wires the restore created — never a
    neighbor wire sharing the tenant's namespace on dst."""
    from kubedtn_tpu.federation import MigrationError

    ref_deliv, _ref_fed, _ = _reference(1)
    for step in ("throttle", "fork", "restore", "cutover"):
        rec, h, _acct, stats, fed = _run_migrated(depth=1,
                                                  fail_step=step,
                                                  do="rollback",
                                                  neighbor_wire=True)
        assert rec["state"] == "rolled_back", step
        for i in range(PAIRS):
            assert h.deliv[i] == ref_deliv[i], f"{step} wire {i}"
        assert stats.snapshot()["rolled_back"] == 1
        # src keeps the tenant, dst has no trace of it
        assert fed.handle("A").registry.get("mig") is not None
        assert fed.handle("B").registry.get("mig") is None
        assert fed.handle("B").registry.rows_of("mig").size == 0
        # the dst neighbor wire in the tenant's namespace survived
        dst_d = fed.handle("B").daemon
        assert dst_d.wires.get_by_key("mig/neighbor", 9999) is not None
        # an explicit abort is final: resume refuses
        with pytest.raises(MigrationError):
            fed.resume(rec["migration_id"])


def test_migration_ids_never_reuse_journaled_records():
    """A restarted controller (fresh in-memory sequence) over the same
    journal root must not clobber committed records, and a requested
    id that already has a record is refused."""
    from kubedtn_tpu.federation import MigrationError

    root = tempfile.mkdtemp(prefix="kdt-fed-test-")
    fjournal.save_record(root, "t-0001", {"migration_id": "t-0001",
                                          "state": "done"})
    fed = FederationController(root)
    assert fed._new_migration_id("t", None) == "t-0002"
    with pytest.raises(MigrationError):
        fed._new_migration_id("t", "t-0001")
    rec = fjournal.load_record_meta(root, "t-0001")
    assert rec["state"] == "done"  # untouched


def test_concurrent_migration_of_same_tenant_refused():
    from kubedtn_tpu.federation import MigrationError

    fed = FederationController(tempfile.mkdtemp(prefix="kdt-fed-"))
    fed._begin("t")
    with pytest.raises(MigrationError):
        fed._begin("t")
    fed._begin("other")  # a different tenant is fine
    fed._end("t")
    fed._begin("t")  # released: reacquirable


def test_resume_after_controller_restart():
    """A crash mid-migration followed by a DAEMON restart: a fresh
    controller rebuilds the coordinator from the journal alone and
    resumes to a byte-identical stream."""
    ref_deliv, _ref_fed, _ = _reference(1)
    rec, h, acct, _stats, _fed = _run_migrated(
        depth=1, fail_step="restore", restart_controller=True)
    assert rec["state"] == "done"
    for i in range(PAIRS):
        assert h.deliv[i] == ref_deliv[i]
    assert acct["mismatch"] == 0.0


def test_rollback_after_cutover_refused():
    """Once CUTOVER commits, rollback is refused — the migration
    rolls forward (make-before-break: dst is authoritative)."""
    from kubedtn_tpu.federation import MigrationError

    d_s, p_s, r_s = _build_plane(["bg", "mig"], addr="10.0.0.1")
    d_d, p_d, r_d = _build_plane(["bg2"], addr="10.0.0.2")
    root = tempfile.mkdtemp(prefix="kdt-fed-test-")
    chaos = ChaosInjector(seed=1)
    chaos.fail_migration_step("reconcile")
    fed = FederationController(root, chaos=chaos)
    fed.register(PlaneHandle("A", d_s, p_s, r_s))
    fed.register(PlaneHandle("B", d_d, p_d, r_d))
    h = _Harness([(d_s, p_s), (d_d, p_d)],
                 {id(d_s): "bg", id(d_d): "bg2"})
    for _ in range(3):
        h.feed_mig(d_s)
        h.tick()
    with pytest.raises(ChaosError):
        fed.migrate("mig", "A", "B", settle=h.tick,
                    reconcile_timeout_s=5.0)
    mid = fed.status(tenant="mig")[-1]["migration_id"]
    with pytest.raises(MigrationError):
        fed.coordinator(mid).rollback()


# -- migration hold (the THROTTLE clamp) -------------------------------

def test_hold_queues_frames_with_typed_verdict():
    d, p, r = _build_plane(["bg", "mig"])
    h = _Harness([(d, p)], {id(d): "bg"})
    r.hold("mig")
    for _ in range(3):
        h.feed_mig(d)
        h.tick()
    # nothing delivered, nothing dropped — frames queued on ingress
    assert sum(len(x) for x in h.deliv) == 0
    assert sum(len(_wire(d, "mig", "a", i).ingress)
               for i in range(PAIRS)) == h.fed
    verdicts = r.admission.recent()
    assert verdicts and all(v.reason == "migration-hold"
                            for v in verdicts)
    r.release_hold("mig")
    for _ in range(10):
        h.tick()
    h.finish()
    assert sum(len(x) for x in h.deliv) > 0


# -- journal crash discipline ------------------------------------------

def test_journal_prev_generation_survives_torn_write(tmp_path):
    root = str(tmp_path)
    fjournal.save_record(root, "m-1", {"step": 1},
                         arrays={"x": np.arange(4)})
    fjournal.save_record(root, "m-1", {"step": 2})
    rec, arrays = fjournal.load_record(root, "m-1")
    assert rec["step"] == 2
    # fork.npz carried forward across an arrays-less commit
    np.testing.assert_array_equal(arrays["x"], np.arange(4))
    # tear the CURRENT generation's manifest: load resolves .prev —
    # wait, save prunes .prev after landing; tear the manifest and
    # verify the typed error instead, then a re-save recovers
    mpath = os.path.join(fjournal.record_dir(root, "m-1"),
                         "manifest.json")
    with open(mpath, "w") as f:
        f.write("{ torn")
    with pytest.raises(fjournal.JournalCorruptError):
        fjournal.load_record(root, "m-1")
    fjournal.save_record(root, "m-1", {"step": 3},
                         arrays={"x": np.arange(4)})
    rec, _ = fjournal.load_record(root, "m-1")
    assert rec["step"] == 3


def test_journal_mid_swap_crash_resolves_prev(tmp_path):
    """Simulate a crash between save's two renames: path absent,
    `.prev` holding the last complete generation — load resolves it."""
    root = str(tmp_path)
    fjournal.save_record(root, "m-2", {"step": 1},
                         arrays={"x": np.arange(3)})
    d = fjournal.record_dir(root, "m-2")
    os.rename(d, d + ".prev")
    rec, arrays = fjournal.load_record(root, "m-2")
    assert rec["step"] == 1
    np.testing.assert_array_equal(arrays["x"], np.arange(3))
    assert "m-2" in fjournal.list_records(root)


def test_journal_checksum_damage_is_typed(tmp_path):
    root = str(tmp_path)
    fjournal.save_record(root, "m-3", {"step": 1},
                         arrays={"x": np.arange(64)})
    fpath = os.path.join(fjournal.record_dir(root, "m-3"), "fork.npz")
    with open(fpath, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(10)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(fjournal.JournalCorruptError):
        fjournal.load_record(root, "m-3")


def test_journal_missing_is_typed(tmp_path):
    with pytest.raises(fjournal.JournalMissingError):
        fjournal.load_record(str(tmp_path), "nope")


# -- satellite: tenant registry checkpoint persistence ------------------

def test_tenancy_survives_checkpoint_roundtrip(tmp_path):
    from kubedtn_tpu import checkpoint

    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    reg = TenantRegistry(engine)
    reg.create("gold-t", qos="gold", frame_budget_per_s=1000.0,
               byte_budget_per_s=5e6, block_edges=8,
               namespaces=["ns-a", "ns-b"])
    reg.create("bronze-t", qos="bronze")
    reg.get("gold-t").admitted_frames = 42
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    store2, engine2 = checkpoint.load(path)
    reg2 = checkpoint.load_tenancy(path, engine2)
    assert reg2 is not None
    g = reg2.get("gold-t")
    assert g.qos == "gold"
    assert g.frame_budget_per_s == 1000.0
    assert g.byte_budget_per_s == 5e6
    assert g.namespaces == {"ns-a", "ns-b"}
    assert g.block_rows == 8 and g.block is not None
    assert g.block[1] - g.block[0] == 8
    assert g.admitted_frames == 42
    b = reg2.get("bronze-t")
    assert b.qos == "bronze" and b.frame_budget_per_s == 0.0
    assert reg2.tenant_of_pod_key("ns-a/p0") is g
    # row conservation through the round trip: global free + reserved
    # free + active rows == capacity (the reserved block must come OUT
    # of the persisted free list at re-carve, never leak from both
    # pools — the repeated-restart leak the drive caught)
    assert (len(engine2._free) + reg2.reserved_free()
            + len(engine2._rows) == engine2._state.capacity)
    # a second round trip neither leaks nor drifts
    path2 = str(tmp_path / "ckpt2")
    checkpoint.save(path2, store2, engine2)
    _s3, engine3 = checkpoint.load(path2)
    reg3 = checkpoint.load_tenancy(path2, engine3)
    assert reg3.get("gold-t").block_rows == 8
    assert (len(engine3._free) + reg3.reserved_free()
            + len(engine3._rows) == engine3._state.capacity)


def test_tenancy_section_absent_returns_none(tmp_path):
    from kubedtn_tpu import checkpoint

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)  # engine.tenancy is None
    _s, engine2 = checkpoint.load(path)
    assert checkpoint.load_tenancy(path, engine2) is None
    assert checkpoint.load_tenancy(str(tmp_path / "missing"),
                                   engine2) is None


# -- satellite: tenant delete ------------------------------------------

def test_tenant_delete_frees_block_and_namespaces():
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    reg = TenantRegistry(engine)
    reg.create("t", block_edges=8, namespaces=["nsx"])
    t = reg.get("t")
    blk = t.block
    free_before = len(engine._free)
    assert reg.delete("t") is True
    assert reg.get("t") is None
    assert reg.tenant_of_pod_key("nsx/p") is None
    # the unused reserve returned to the global pool
    assert len(engine._free) == free_before + (blk[1] - blk[0])
    assert reg.delete("t") is False  # idempotent


def test_tenant_delete_rpc():
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    daemon = Daemon(engine)
    reg = TenantRegistry(engine)
    daemon.tenancy = reg
    reg.create("t")
    resp = daemon.TenantDelete(pb.TenantQuery(name="t"), None)
    assert resp.ok and resp.tenant.name == "t"
    resp = daemon.TenantDelete(pb.TenantQuery(name="t"), None)
    assert not resp.ok and "unknown tenant" in resp.error
    daemon.tenancy = None
    resp = daemon.TenantDelete(pb.TenantQuery(name="t"), None)
    assert not resp.ok and "not enabled" in resp.error


# -- RPC surface --------------------------------------------------------

def test_migrate_rpcs_in_process():
    d_s, p_s, r_s = _build_plane(["bg", "mig"], addr="10.0.0.1")
    d_d, p_d, r_d = _build_plane(["bg2"], addr="10.0.0.2")
    root = tempfile.mkdtemp(prefix="kdt-fed-test-")
    fed = FederationController(root)
    fed.register(PlaneHandle("A", d_s, p_s, r_s))
    fed.register(PlaneHandle("B", d_d, p_d, r_d))
    h = _Harness([(d_s, p_s), (d_d, p_d)],
                 {id(d_s): "bg", id(d_d): "bg2"})
    for _ in range(3):
        h.feed_mig(d_s)
        h.tick()
    # drain the tenant's in-flight before the RPC: the RPC path has no
    # settle hook, so reconcile must find zero residue immediately
    for _ in range(20):
        h.tick()
    resp = d_s.MigrateTenant(pb.MigrateRequest(
        tenant="mig", dst="B", reconcile_timeout_s=5.0), None)
    assert resp.ok, resp.error
    m = resp.migration
    assert m.state == "done"
    assert list(m.steps_done) == list(STEPS)
    assert m.src == "A" and m.dst == "B"  # src defaulted to serving
    st = d_s.MigrationStatus(pb.MigrationStatusRequest(), None)
    assert st.ok and len(st.migrations) == 1
    st = d_s.MigrationStatus(pb.MigrationStatusRequest(
        tenant="other"), None)
    assert st.ok and len(st.migrations) == 0
    # unknown dst is an error, not an exception
    resp = d_s.MigrateTenant(pb.MigrateRequest(
        tenant="bg", dst="nope"), None)
    assert not resp.ok and "unknown federation plane" in resp.error
    # federation not enabled
    d_bare = Daemon(SimEngine(TopologyStore(), capacity=8))
    resp = d_bare.MigrateTenant(pb.MigrateRequest(
        tenant="x", dst="B"), None)
    assert not resp.ok and "not enabled" in resp.error


# -- metrics ------------------------------------------------------------

def test_migration_metrics_collector():
    from kubedtn_tpu.metrics.metrics import (MigrationStatsCollector,
                                             make_registry)
    from prometheus_client import generate_latest

    stats = MigrationStats()
    stats.add(attempts=2, completed=1, rolled_back=1,
              bytes_reconciled=1234.0)
    stats.add_step_seconds("fork", 0.5)
    stats.set_mismatch(0.0)
    fams = {f.name: f for f in MigrationStatsCollector(stats).collect()}
    assert fams["kubedtn_migration_attempts"].samples[0].value == 2.0
    assert fams["kubedtn_migration_completed"].samples[0].value == 1.0
    assert fams["kubedtn_migration_bytes_reconciled"].samples[0] \
        .value == 1234.0
    step = {s.labels["step"]: s.value
            for s in fams["kubedtn_migration_step_seconds"].samples}
    assert step["fork"] == 0.5 and step["release"] == 0.0
    assert fams["kubedtn_migration_accounting_mismatch"].samples[0] \
        .value == 0.0
    registry, _hist = make_registry(migration_stats=stats)
    body = generate_latest(registry).decode()
    assert "kubedtn_migration_accounting_mismatch 0.0" in body


# -- live scenario smoke (two real gRPC daemons, flapping breaker) ------

@pytest.mark.chaos
def test_migration_under_flap_smoke():
    """Fast tier-1 cut of the live scenario: a migration lands while
    the src→dst breaker cycles; clean verdict required — zero loss,
    accounting mismatch 0, window rings agreeing with counters."""
    from kubedtn_tpu.scenarios import migration_under_flap

    r = migration_under_flap(pairs=2, seconds=3.0,
                             migrate_after_s=0.8,
                             offered_frames_per_s=2_000)
    assert r["frames_lost"] == 0
    assert r["tick_errors"] == 0
    assert r["outcome"] in ("completed", "rolled_back")
    assert r["accounting_mismatch_gauge"] == 0.0
    if r["outcome"] == "completed":
        assert r["accounting"]["mismatch"] == 0.0
        assert r["ring_totals_agree"]
        assert r["steps_done"] == list(STEPS)
    assert r["in_guardrails"], r


def test_coordinator_from_journal_unknown_plane():
    root = tempfile.mkdtemp(prefix="kdt-fed-test-")
    fjournal.save_record(root, "m-x", {
        "migration_id": "m-x", "tenant": "t", "src": "A", "dst": "B",
        "state": "running", "steps_done": [], "resumed": 0,
        "rollbacks": 0, "step_seconds": {}})
    with pytest.raises(KeyError):
        MigrationCoordinator.from_journal(root, "m-x", {})
