"""Two live daemons: the reference's cross-node path, end to end.

Reference branch D (daemon/kubedtn/handler.go:419-453): a link whose peer
pod lives on another node is realized locally toward the peer node's VTEP,
then completed on the far side via a `Remote.Update` RPC to the peer
daemon — with the link lock released before dialing (the documented
deadlock avoidance, handler.go:442-446). The steady-state data path is the
grpc-wire tunnel (grpcwire.go:386-462): frames shaped on the local egress
row, then one unary `SendToOnce` per frame into the peer daemon, which
writes them pod-side.

Here BOTH daemons are real gRPC servers in this process on localhost
ports, each with its own store/engine/data plane — nothing is faked below
the RPC boundary.
"""

import time

import numpy as np
import pytest

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, TopologySpec
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.topology import SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.client import DaemonClient
from kubedtn_tpu.wire.server import Daemon, make_server


def make_node():
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0, host="127.0.0.1")
    server.start()
    addr = f"127.0.0.1:{port}"
    engine.node_ip = addr  # HOST_IP equivalent; ports differ in-process
    return store, engine, daemon, server, addr


def seed(store, addr_a, addr_b, latency="10ms"):
    """Both daemons see the full cluster (the reference daemons watch all
    topologies): r1 on node A, r2 on node B, one link uid 7."""
    props = LinkProperties(latency=latency)
    l1 = Link(local_intf="eth1", peer_intf="eth1", peer_pod="r2", uid=7,
              properties=props)
    l2 = Link(local_intf="eth1", peer_intf="eth1", peer_pod="r1", uid=7,
              properties=props)
    t1 = Topology(name="r1", spec=TopologySpec(links=[l1]))
    t2 = Topology(name="r2", spec=TopologySpec(links=[l2]))
    t1.status.src_ip, t1.status.net_ns = addr_a, "/proc/1/ns/net"
    t2.status.src_ip, t2.status.net_ns = addr_b, "/proc/2/ns/net"
    for t in (t1, t2):
        store.create(t)
    return t1, t2


@pytest.fixture
def two_nodes():
    a = make_node()
    b = make_node()
    yield a, b
    a[3].stop(0)
    b[3].stop(0)


def test_cross_node_link_completed_via_remote_update(two_nodes):
    (store_a, engine_a, _, _, addr_a), (store_b, engine_b, _, _, addr_b) = \
        two_nodes
    t1, _ = seed(store_a, addr_a, addr_b)
    seed(store_b, addr_a, addr_b)

    assert engine_a.add_links(t1, t1.spec.links)
    # local end realized at A toward B's VTEP
    assert ("default/r1", 7) in engine_a._rows
    row_a = engine_a.link_row("default/r1", 7)
    assert row_a["latency_us"] == 10_000
    # REMOTE end realized at B — via a real gRPC Remote.Update
    assert ("default/r2", 7) in engine_b._rows
    row_b = engine_b.link_row("default/r2", 7)
    assert row_b["latency_us"] == 10_000
    assert engine_a.stats.remote_errors == 0


def test_cross_node_remote_error_counted(two_nodes):
    (store_a, engine_a, _, _, addr_a), _ = two_nodes
    # peer daemon address that nobody listens on
    dead = "127.0.0.1:1"
    t1, _ = seed(store_a, addr_a, dead)
    # fail fast instead of gRPC's default connect backoff
    engine_a._dialer = lambda ip: (_ for _ in ()).throw(
        ConnectionError(ip))
    assert engine_a.add_links(t1, t1.spec.links) is False
    assert engine_a.stats.remote_errors == 1
    # the local end is still realized (the reference leaves its half up;
    # the peer plumbs on arrival/reconcile)
    assert ("default/r1", 7) in engine_a._rows


def test_cross_node_frames_shaped_then_tunneled(two_nodes):
    """Pod frame at A -> shaped on A's egress row (10ms) -> unary
    SendToOnce to daemon B -> pod-side egress at B."""
    (store_a, engine_a, daemon_a, _, addr_a), \
        (store_b, engine_b, daemon_b, _, addr_b) = two_nodes
    t1, _ = seed(store_a, addr_a, addr_b)
    seed(store_b, addr_a, addr_b)
    assert engine_a.add_links(t1, t1.spec.links)

    # wires: B's end first (reference CreateGRPCWireRemoteTriggered — A
    # asks B over gRPC and learns B's wire id), then A's end pointing at it
    client_b = DaemonClient(addr_b)
    resp = client_b.AddGRPCWireRemote(pb.WireDef(
        local_pod_name="r2", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_a))
    assert resp.response
    wire_a = daemon_a._add_wire(pb.WireDef(
        local_pod_name="r1", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_b,
        peer_intf_id=resp.peer_intf_id))

    dp_a = WireDataPlane(daemon_a)
    client_a = DaemonClient(addr_a)
    frame = b"\x02" * 12 + b"\x08\x06" + b"\x00" * 40
    # pod-origin injection on a cross-daemon wire uses InjectFrame
    assert client_a.InjectFrame(pb.Packet(remot_intf_id=wire_a.wire_id,
                                          frame=frame)).response
    dp_a.tick(now_s=50.0)
    wire_b = daemon_b.wires.get_by_key("default/r2", 7)
    assert len(wire_b.egress) == 0      # 10ms not yet elapsed
    dp_a.tick(now_s=50.011)             # past the netem delay: crosses now
    assert dp_a.flush_peers()           # egress is async per-peer now
    assert list(wire_b.egress) == [frame]
    assert daemon_a.forward_errors == 0
    client_a.close()
    client_b.close()


def test_sendtoonce_on_cross_wire_is_pod_bound(two_nodes):
    """Frames arriving over SendToOnce for a peer_ip wire go straight to
    the pod side (already shaped by the sender), never back into shaping —
    no ping-pong between daemons."""
    _, (store_b, engine_b, daemon_b, _, addr_b) = two_nodes
    wire = daemon_b._add_wire(pb.WireDef(
        local_pod_name="r2", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip="127.0.0.1:9", peer_intf_id=1))
    client = DaemonClient(addr_b)
    client.SendToOnce(pb.Packet(remot_intf_id=wire.wire_id, frame=b"x" * 60))
    assert list(wire.egress) == [b"x" * 60]
    assert not wire.ingress
    client.close()


def test_health_service(two_nodes):
    import grpc

    (_, _, _, _, addr_a), _ = two_nodes
    channel = grpc.insecure_channel(addr_a)
    check = channel.unary_unary(
        "/grpc.health.v1.Health/Check",
        request_serializer=lambda m: m,
        response_deserializer=lambda b: b)
    raw = check(b"")  # empty HealthCheckRequest
    # HealthCheckResponse{status=SERVING}: field 1 varint 1 -> 0x08 0x01
    assert raw == b"\x08\x01"
    channel.close()


def test_daemon_address_forms():
    from kubedtn_tpu.wire.client import daemon_address

    assert daemon_address("10.0.0.5") == "10.0.0.5:51111"
    assert daemon_address("10.0.0.5:6000") == "10.0.0.5:6000"
    assert daemon_address("fd00::1") == "[fd00::1]:51111"
    assert daemon_address("[fd00::1]") == "[fd00::1]:51111"
    assert daemon_address("[fd00::1]:6000") == "[fd00::1]:6000"


def test_retry_heals_half_realized_cross_node_link(two_nodes):
    """A failed completion RPC leaves the link half-realized; the caller's
    retry must re-send Remote.Update, not silently report success."""
    (store_a, engine_a, _, _, addr_a), (_, engine_b, _, _, addr_b) = \
        two_nodes
    t1, _ = seed(store_a, addr_a, addr_b)

    calls = {"n": 0}
    real_client = DaemonClient(addr_b)

    class FlakyOnce:
        def Update(self, rp):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ConnectionError("transient")
            return real_client.Update(rp)

    engine_a._dialer = lambda ip: FlakyOnce()
    assert engine_a.add_links(t1, t1.spec.links) is False
    assert ("default/r2", 7) not in engine_b._rows
    # retry (the reconciler/CNI would): second RPC goes out and succeeds
    assert engine_a.add_links(t1, t1.spec.links) is True
    assert calls["n"] == 2
    assert ("default/r2", 7) in engine_b._rows


def test_concurrent_setup_pods_no_distributed_deadlock(two_nodes):
    """Node A sets up r1 while node B sets up r2, each dialing the other's
    Remote.Update — must complete (no lock held across the RPC)."""
    import threading

    (store_a, engine_a, _, _, addr_a), (store_b, engine_b, _, _, addr_b) = \
        two_nodes
    seed(store_a, addr_a, addr_b)
    seed(store_b, addr_a, addr_b)

    results = {}

    def setup(engine, pod):
        results[pod] = engine.setup_pod(pod)

    ta = threading.Thread(target=setup, args=(engine_a, "r1"))
    tb = threading.Thread(target=setup, args=(engine_b, "r2"))
    ta.start(); tb.start()
    ta.join(timeout=30); tb.join(timeout=30)
    assert not ta.is_alive() and not tb.is_alive(), "distributed deadlock"
    assert results == {"r1": True, "r2": True}
    assert ("default/r1", 7) in engine_a._rows
    assert ("default/r2", 7) in engine_b._rows


def test_health_watch_stream_stays_open(two_nodes):
    import queue
    import grpc

    (_, _, _, _, addr_a), _ = two_nodes
    channel = grpc.insecure_channel(addr_a)
    watch = channel.unary_stream(
        "/grpc.health.v1.Health/Watch",
        request_serializer=lambda m: m,
        response_deserializer=lambda b: b)
    call = watch(b"")
    q = queue.Queue()
    import threading

    def consume():
        try:
            for msg in call:
                q.put(("msg", msg))
            q.put(("closed", None))
        except grpc.RpcError as e:
            q.put(("err", e.code()))

    threading.Thread(target=consume, daemon=True).start()
    kind, first = q.get(timeout=10)
    assert kind == "msg" and first == b"\x08\x01"  # SERVING
    # stream must NOT complete on its own
    import time as _t
    _t.sleep(0.5)
    assert q.empty(), "Watch stream closed prematurely"
    call.cancel()
    channel.close()


def test_deliver_egress_deadline_on_blackholed_peer():
    """Regression: a peer daemon that accepts the connection but never
    answers must cost at most forward_timeout_s per frame, not stall the
    tick thread forever."""

    class BlackholeDaemon(Daemon):
        def SendToOnce(self, request, context):
            time.sleep(5)
            return pb.BoolResponse(response=True)

    store_b = TopologyStore()
    engine_b = SimEngine(store_b, capacity=16)
    daemon_b = BlackholeDaemon(engine_b)
    server_b, port_b = make_server(daemon_b, port=0, host="127.0.0.1")
    server_b.start()
    addr_b = f"127.0.0.1:{port_b}"

    store_a = TopologyStore()
    engine_a = SimEngine(store_a, capacity=16)
    daemon_a = Daemon(engine_a, forward_timeout_s=0.2)
    daemon_a._add_wire(pb.WireDef(
        local_pod_name="r1", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_b, peer_intf_id=1))

    t0 = time.perf_counter()
    ok = daemon_a.deliver_egress("default/r1", 7, b"x" * 60)
    elapsed = time.perf_counter() - t0
    assert ok is False
    assert daemon_a.forward_errors == 1
    assert elapsed < 2.0, f"forward blocked {elapsed:.1f}s despite deadline"
    server_b.stop(0)


def test_health_watch_parking_capped(two_nodes):
    """Regression: parked Watch streams must never starve the RPC pool —
    beyond the parking cap, watchers get the status and a clean close,
    and unary RPCs keep being served."""
    import queue
    import threading

    import grpc

    (_, _, _, _, addr_a), _ = two_nodes
    channel = grpc.insecure_channel(addr_a)
    watch = channel.unary_stream(
        "/grpc.health.v1.Health/Watch",
        request_serializer=lambda m: m,
        response_deserializer=lambda b: b)
    calls = [watch(b"") for _ in range(10)]
    got_first: queue.Queue = queue.Queue()
    closed: queue.Queue = queue.Queue()

    def consume(call):
        try:
            it = iter(call)
            got_first.put(next(it))
            for _ in it:
                pass
            closed.put(True)        # server closed the stream (over cap)
        except grpc.RpcError:
            closed.put(False)       # cancelled at teardown (parked)

    for call in calls:
        threading.Thread(target=consume, args=(call,), daemon=True).start()
    firsts = [got_first.get(timeout=10) for _ in range(10)]
    assert all(f == b"\x08\x01" for f in firsts)  # everyone saw SERVING

    # over-cap watchers end promptly, freeing their pool workers
    ended = 0
    deadline = time.time() + 5
    while ended < 6 and time.time() < deadline:
        try:
            closed.get(timeout=0.2)
            ended += 1
        except queue.Empty:
            pass
    assert ended >= 6, f"only {ended} over-cap watchers closed"

    # with the remaining watchers parked, unary RPCs still go through
    check = channel.unary_unary(
        "/grpc.health.v1.Health/Check",
        request_serializer=lambda m: m,
        response_deserializer=lambda b: b)
    assert check(b"", timeout=5) == b"\x08\x01"
    for call in calls:
        call.cancel()
    channel.close()


def test_cross_node_egress_batches_over_sendtostream():
    """Released cross-node frames cross as ONE coalesced SendToBulk
    stream per peer per tick — not one unary RPC per frame (the
    reference's per-packet hot loop, grpcwire.go:452) and not one gRPC
    message per frame either (Python gRPC caps near ~25k messages/s)."""
    from kubedtn_tpu.runtime import WireDataPlane

    class CountingDaemon(Daemon):
        stream_calls = 0
        bulk_calls = 0

        def SendToStream(self, request_iterator, context):
            resp = super().SendToStream(request_iterator, context)
            type(self).stream_calls += 1
            return resp

        def SendToBulk(self, request_iterator, context):
            resp = super().SendToBulk(request_iterator, context)
            type(self).bulk_calls += 1
            return resp

    CountingDaemon.stream_calls = 0
    CountingDaemon.bulk_calls = 0
    store_b = TopologyStore()
    engine_b = SimEngine(store_b, capacity=64)
    daemon_b = CountingDaemon(engine_b)
    server_b, port_b = make_server(daemon_b, port=0, host="127.0.0.1")
    server_b.start()
    addr_b = f"127.0.0.1:{port_b}"

    store_a = TopologyStore()
    engine_a = SimEngine(store_a, capacity=64)
    engine_a.node_ip = "127.0.0.1:1"
    daemon_a = Daemon(engine_a)
    t1, _ = seed(store_a, engine_a.node_ip, addr_b, latency="")
    engine_a.add_links(t1, t1.spec.links)  # A's local row, unshaped

    wire_b = daemon_b._add_wire(pb.WireDef(
        local_pod_name="r2", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip="127.0.0.1:1", peer_intf_id=1))
    wire_a = daemon_a._add_wire(pb.WireDef(
        local_pod_name="r1", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_b,
        peer_intf_id=wire_b.wire_id))

    dp_a = WireDataPlane(daemon_a, max_slots=16)
    n = 6
    for i in range(n):
        wire_a.ingress.append(bytes([i]) * 60)
    dp_a.tick(now_s=5.0)
    dp_a.tick(now_s=5.001)  # unshaped: released immediately
    assert dp_a.flush_peers()
    got = list(wire_b.egress)
    assert len(got) == n, f"only {len(got)}/{n} frames crossed"
    assert CountingDaemon.bulk_calls == 1, \
        f"{CountingDaemon.bulk_calls} bulk calls for one tick's batch"
    assert CountingDaemon.stream_calls == 0  # bulk peer: no fallback
    assert daemon_a.forward_errors == 0
    server_b.stop(0)


def test_cross_node_egress_falls_back_to_stream_for_reference_peer():
    """A peer daemon that doesn't implement the SendToBulk extension (a
    reference-built Go daemon — its IDL stops at SendToStream,
    kube_dtn.proto:171) answers UNIMPLEMENTED once; the egress flush
    remembers that and ships every later batch over the per-frame
    SendToStream, losing nothing."""
    import grpc as _grpc

    from kubedtn_tpu.runtime import WireDataPlane

    class RefDaemon(Daemon):
        stream_calls = 0

        def SendToBulk(self, request_iterator, context):
            context.abort(_grpc.StatusCode.UNIMPLEMENTED,
                          "method SendToBulk not implemented")

        def SendToStream(self, request_iterator, context):
            resp = super().SendToStream(request_iterator, context)
            type(self).stream_calls += 1
            return resp

    RefDaemon.stream_calls = 0
    store_b = TopologyStore()
    engine_b = SimEngine(store_b, capacity=64)
    daemon_b = RefDaemon(engine_b)
    server_b, port_b = make_server(daemon_b, port=0, host="127.0.0.1")
    server_b.start()
    addr_b = f"127.0.0.1:{port_b}"

    store_a = TopologyStore()
    engine_a = SimEngine(store_a, capacity=64)
    engine_a.node_ip = "127.0.0.1:1"
    daemon_a = Daemon(engine_a)
    t1, _ = seed(store_a, engine_a.node_ip, addr_b, latency="")
    engine_a.add_links(t1, t1.spec.links)

    wire_b = daemon_b._add_wire(pb.WireDef(
        local_pod_name="r2", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip="127.0.0.1:1", peer_intf_id=1))
    wire_a = daemon_a._add_wire(pb.WireDef(
        local_pod_name="r1", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_b,
        peer_intf_id=wire_b.wire_id))

    dp_a = WireDataPlane(daemon_a, max_slots=16)
    n = 6
    for i in range(n):
        wire_a.ingress.append(bytes([i]) * 60)
    dp_a.tick(now_s=5.0)
    dp_a.tick(now_s=5.001)
    assert dp_a.flush_peers()
    assert len(wire_b.egress) == n, \
        f"only {len(wire_b.egress)}/{n} frames crossed on fallback"
    assert RefDaemon.stream_calls == 1
    assert daemon_a.peer_bulk_ok.get(addr_b) is False
    assert daemon_a.forward_errors == 0

    # second batch goes straight to the stream, no bulk retry
    for i in range(3):
        wire_a.ingress.append(bytes([0x40 + i]) * 60)
    dp_a.tick(now_s=5.1)
    dp_a.tick(now_s=5.101)
    assert dp_a.flush_peers()
    assert len(wire_b.egress) == n + 3
    assert RefDaemon.stream_calls == 2
    server_b.stop(0)


def test_warm_restart_mid_traffic_completes_cross_node_delivery(
        two_nodes, tmp_path):
    """Node A's daemon restarts WARM while a frame sits in its delay
    line; the restored daemon completes the remaining delay and the
    frame still crosses to node B — checkpoint persistence, orphan-free
    wire re-attach, and peer forwarding composed end to end."""
    from kubedtn_tpu import checkpoint

    (store_a, engine_a, daemon_a, _, addr_a), \
        (store_b, engine_b, daemon_b, _, addr_b) = two_nodes
    t1, _ = seed(store_a, addr_a, addr_b, latency="500ms")
    seed(store_b, addr_a, addr_b, latency="500ms")
    assert engine_a.add_links(t1, t1.spec.links)

    client_b = DaemonClient(addr_b)
    resp = client_b.AddGRPCWireRemote(pb.WireDef(
        local_pod_name="r2", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_a))
    wire_a = daemon_a._add_wire(pb.WireDef(
        local_pod_name="r1", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_b,
        peer_intf_id=resp.peer_intf_id))

    dp_a = WireDataPlane(daemon_a, dt_us=10_000.0)
    client_a = DaemonClient(addr_a)
    frame = b"\x02" * 12 + b"\x08\x06" + b"\x00" * 40
    # pod-origin injection on a cross-daemon wire uses InjectFrame
    assert client_a.InjectFrame(pb.Packet(remot_intf_id=wire_a.wire_id,
                                          frame=frame)).response
    client_a.close()
    dp_a.tick(now_s=0.0)    # shaped: 500ms delay scheduled
    dp_a.tick(now_s=0.1)    # 100ms in; 400ms remain
    wire_b = daemon_b.wires.get_by_key("default/r2", 7)
    assert len(wire_b.egress) == 0

    path = str(tmp_path / "nodeA")
    checkpoint.save(path, store_a, engine_a, dataplane=dp_a)

    # --- node A restarts: everything rebuilt from the checkpoint ---
    store_a2, engine_a2 = checkpoint.load(path)
    engine_a2.node_ip = addr_a
    daemon_a2 = Daemon(engine_a2)
    dp_a2 = WireDataPlane(daemon_a2, dt_us=10_000.0)
    assert checkpoint.load_pending(path, dp_a2, now_s=100.0) == 1
    # the pod re-attaches its wire shortly after boot (reconnect flow)
    daemon_a2._add_wire(pb.WireDef(
        local_pod_name="r1", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_b,
        peer_intf_id=resp.peer_intf_id))

    dp_a2.tick(now_s=100.3)  # 300ms after restore: 100ms still remain
    assert len(wire_b.egress) == 0
    dp_a2.tick(now_s=100.45)  # past the remaining delay: crosses to B
    assert dp_a2.flush_peers()
    assert list(wire_b.egress) == [frame]
    assert dp_a2.undeliverable == 0
    client_b.close()


def test_slow_peer_does_not_stall_local_delivery():
    """Round-5: egress to each peer runs on its own sender thread with a
    bounded queue (the reference's per-wire goroutine role,
    grpcwire.go:386). A SLOW (not blackholed — just slow) peer must cost
    only its own wires: ticks stay fast, local-pair delivery is
    unaffected, the slow peer's frames still arrive, and frames to a
    BLACKHOLED peer are held in that sender's bounded outage buffer
    behind its circuit breaker (round 7: transient failures retry
    instead of dropping) — all without the tick thread ever blocking on
    a peer RPC."""
    from kubedtn_tpu.runtime import WireDataPlane

    class SlowDaemon(Daemon):
        delay_s = 0.6

        def SendToBulk(self, request_iterator, context):
            time.sleep(type(self).delay_s)
            return super().SendToBulk(request_iterator, context)

    class BlackholeDaemon(Daemon):
        def SendToBulk(self, request_iterator, context):
            time.sleep(30)
            return super().SendToBulk(request_iterator, context)

        SendToStream = SendToBulk

    def serve(cls):
        store = TopologyStore()
        engine = SimEngine(store, capacity=16)
        daemon = cls(engine)
        server, port = make_server(daemon, port=0, host="127.0.0.1")
        server.start()
        return daemon, server, f"127.0.0.1:{port}"

    slow_daemon, slow_server, slow_addr = serve(SlowDaemon)
    hole_daemon, hole_server, hole_addr = serve(BlackholeDaemon)
    slow_wire = slow_daemon._add_wire(pb.WireDef(
        local_pod_name="rs", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip="127.0.0.1:1", peer_intf_id=1))

    # node A: one local pair (uid 1) + one wire to the slow peer (uid 7)
    # + one wire to the blackholed peer (uid 8); all links unshaped so
    # releases happen on the next tick
    store_a = TopologyStore()
    engine_a = SimEngine(store_a, capacity=64)
    engine_a.node_ip = "127.0.0.1:1"
    # timeout between the slow peer's 0.6s (succeeds) and the blackhole's
    # 30s (fails on deadline)
    daemon_a = Daemon(engine_a, forward_timeout_s=2.0)
    links = [
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="l2", uid=1),
        Link(local_intf="eth2", peer_intf="eth1",
             peer_pod="physical/" + slow_addr, uid=7),
        Link(local_intf="eth3", peer_intf="eth1",
             peer_pod="physical/" + hole_addr, uid=8),
    ]
    t1 = Topology(name="l1", spec=TopologySpec(links=links))
    t2 = Topology(name="l2", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="l1", uid=1)]))
    for t in (t1, t2):
        t.status.src_ip, t.status.net_ns = "127.0.0.1:1", "/proc/1/ns/net"
        store_a.create(t)
    assert engine_a.add_links(t1, [links[0]])
    assert engine_a.add_links(t2, t2.spec.links)
    assert engine_a.add_links(t1, links[1:])

    wl1 = daemon_a._add_wire(pb.WireDef(
        local_pod_name="l1", kube_ns="default", link_uid=1,
        intf_name_in_pod="eth1"))
    wl2 = daemon_a._add_wire(pb.WireDef(
        local_pod_name="l2", kube_ns="default", link_uid=1,
        intf_name_in_pod="eth1"))
    ws = daemon_a._add_wire(pb.WireDef(
        local_pod_name="l1", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth2", peer_ip=slow_addr,
        peer_intf_id=slow_wire.wire_id))
    wh = daemon_a._add_wire(pb.WireDef(
        local_pod_name="l1", kube_ns="default", link_uid=8,
        intf_name_in_pod="eth3", peer_ip=hole_addr, peer_intf_id=1))

    dp = WireDataPlane(daemon_a, dt_us=1_000.0)
    # warm the batch-kernel compiles outside the timed window
    wl1.ingress.append(b"w" * 60)
    dp.tick(now_s=1.0)
    dp.tick(now_s=1.001)
    wl2.egress.clear()

    n = 4
    for i in range(n):
        ws.ingress.append(bytes([0x10 + i]) * 60)
        wh.ingress.append(bytes([0x20 + i]) * 60)
    dp.tick(now_s=2.0)        # shapes all three rows (pays the one-time
    #                           R=3 bucket compile, excluded from timing)
    t0 = time.perf_counter()
    dp.tick(now_s=2.001)      # releases + hands to the per-peer senders
    #                           (the tick that BLOCKED before round 5)
    # local traffic injected and delivered while both peers are wedged
    for i in range(n):
        wl1.ingress.append(bytes([0x30 + i]) * 60)
        dp.tick(now_s=2.002 + i * 0.001)
    tick_wall = time.perf_counter() - t0
    assert tick_wall < 0.45, (
        f"ticks took {tick_wall:.2f}s — the tick thread blocked on a "
        f"peer RPC (slow peer sleeps 0.6s, blackhole 30s)")
    assert len(wl2.egress) == n, "local delivery stalled behind peers"

    # the slow peer's frames still arrive (its sender waited it out);
    # flush_peers would block on the blackholed sender's retry buffer,
    # so poll the slow wire directly
    deadline = time.monotonic() + 10.0
    while len(slow_wire.egress) < n and time.monotonic() < deadline:
        time.sleep(0.02)
    assert len(slow_wire.egress) == n
    # the blackholed peer's frames failed on ITS sender's deadline and
    # sit in that sender's bounded outage buffer awaiting retry —
    # nobody else paid for them, and nothing was dropped or silently
    # counted away (a recovered peer would still get them)
    stats = dp.peer_fault_stats()[hole_addr]
    assert stats["buffered"] == n
    assert daemon_a.forward_errors == 0
    assert dp.peer_queue_dropped == 0
    hole_sender = dp._peer_senders[hole_addr]
    dp.stop()
    # stop() with the peer still dead gives up the buffer — counted,
    # never silent
    deadline = time.monotonic() + 5.0
    while hole_sender.dropped < n and time.monotonic() < deadline:
        time.sleep(0.02)
    assert hole_sender.dropped == n
    slow_server.stop(0)
    hole_server.stop(0)


def test_plane_restart_recreates_peer_senders(two_nodes):
    """stop()/start() must not black-hole cross-node egress: per-peer
    sender threads are one-shot, so a restarted plane needs FRESH ones —
    a cached dead sender would enqueue frames into a queue with no
    consumer forever (round-5 review finding)."""
    (store_a, engine_a, daemon_a, _, addr_a), \
        (store_b, engine_b, daemon_b, _, addr_b) = two_nodes
    t1, _ = seed(store_a, addr_a, addr_b, latency="")
    seed(store_b, addr_a, addr_b, latency="")
    assert engine_a.add_links(t1, t1.spec.links)
    client_b = DaemonClient(addr_b)
    resp = client_b.AddGRPCWireRemote(pb.WireDef(
        local_pod_name="r2", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_a))
    wire_a = daemon_a._add_wire(pb.WireDef(
        local_pod_name="r1", kube_ns="default", link_uid=7,
        intf_name_in_pod="eth1", peer_ip=addr_b,
        peer_intf_id=resp.peer_intf_id))
    wire_b = daemon_b.wires.get_by_key("default/r2", 7)

    dp = WireDataPlane(daemon_a)
    wire_a.ingress.append(b"\x01" * 60)
    dp.tick(now_s=10.0)
    dp.tick(now_s=10.001)
    assert dp.flush_peers()
    assert len(wire_b.egress) == 1
    assert len(dp._peer_senders) == 1

    # restart the plane: the old sender thread is gone
    dp.stop()
    assert not dp._peer_senders
    wire_a.ingress.append(b"\x02" * 60)
    dp.tick(now_s=10.1)
    dp.tick(now_s=10.101)
    assert dp.flush_peers(), "egress black-holed after restart"
    assert len(wire_b.egress) == 2
    assert daemon_a.forward_errors == 0
    dp.stop()
    client_b.close()
