"""Columnar allocator ≡ historical list allocator.

PR 12 replaced the engine's Python-list free stack with the columnar
``topology.freelist.FreeStack`` (vectorized growth/rebuild/carve).
The contract is BYTE-IDENTITY with the historical semantics: the same
op sequence hands out the same rows in the same order, so row
assignments — and therefore the per-row-keyed delivered streams —
are unchanged. These tests pin that against a verbatim reimplementation
of the historical list allocator (`LegacyFree`), over random
alloc/pair-alloc/free/compact/grow/tenant-block sequences, and then
pin delivered streams through a churned (delete → compact → re-add →
tenant-block) plane at pipeline depths 1 and 2, unsharded and on the
8-device CPU mesh."""

from __future__ import annotations

import random

import numpy as np
import pytest

import jax

from test_pipeline_determinism import _tagged_frames

from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                   TopologySpec)
from kubedtn_tpu.parallel import partition
from kubedtn_tpu.parallel.mesh import make_mesh
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.tenancy import TenantRegistry
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.topology.freelist import FreeStack


class LegacyFree:
    """The pre-PR-12 free-list semantics, op for op — the spec the
    FreeStack must reproduce byte-for-byte."""

    def __init__(self, cap: int) -> None:
        self.l = list(range(cap - 1, -1, -1))

    def pop(self) -> int:
        return self.l.pop()

    def push(self, row: int) -> None:
        self.l.append(row)

    def extend(self, rows) -> None:
        self.l.extend(int(r) for r in rows)

    def grow(self, old_cap: int, new_cap: int) -> None:
        self.l = list(range(new_cap - 1, old_cap - 1, -1)) + self.l

    def compact(self, n_active: int, cap: int) -> None:
        self.l = list(range(cap - 1, n_active - 1, -1))

    def remove_rows(self, rows) -> None:
        taken = {int(r) for r in rows}
        self.l = [r for r in self.l if r not in taken]

    def pick_pair(self, capacity: int, n_shards: int,
                  scan_limit: int = 64) -> tuple[int, int]:
        # verbatim historical pick_pair_rows (engine.py PR 5-11 era)
        free = self.l
        r1 = free.pop()
        if n_shards <= 1:
            return r1, free.pop()
        loc = capacity // n_shards
        blk = r1 // loc
        top = free[-1]
        if top // loc == blk:
            free.pop()
            return r1, top
        lo = max(0, len(free) - scan_limit)
        for i in range(len(free) - 2, lo - 1, -1):
            if free[i] // loc == blk:
                return r1, free.pop(i)
        return r1, free.pop()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_freestack_matches_legacy_op_for_op(seed):
    """Random pop/push/extend/grow/compact/carve/pair sequences: every
    returned row and the full remaining order stay identical."""
    rng = random.Random(seed)
    cap = 64
    fs, legacy = FreeStack.from_range(0, cap), LegacyFree(cap)
    allocated: list[int] = []
    shards = rng.choice([1, 4, 8])
    for _step in range(400):
        assert fs.tolist() == legacy.l
        op = rng.random()
        if op < 0.35 and len(fs) >= 1:
            a, b = fs.pop(), legacy.pop()
            assert a == b
            allocated.append(a)
        elif op < 0.50 and len(fs) >= 2 and cap % shards == 0:
            got = partition.pick_pair_rows(fs, cap, shards)
            want = legacy.pick_pair(cap, shards)
            assert got == want
            allocated.extend(got)
        elif op < 0.75 and allocated:
            r = allocated.pop(rng.randrange(len(allocated)))
            fs.push(r)
            legacy.push(r)
        elif op < 0.85 and len(fs) >= 8:
            # carve a random subset (the tenant-block removal shape)
            k = rng.randrange(1, min(8, len(fs)))
            rows = rng.sample(fs.tolist(), k)
            fs.remove_rows(np.asarray(rows, np.int64))
            legacy.remove_rows(rows)
            allocated.extend(rows)
        elif op < 0.93:
            new_cap = cap * 2
            fs.prepend_range(cap, new_cap)
            legacy.grow(cap, new_cap)
            cap = new_cap
            if cap > 1024:  # keep the walk bounded
                n = len(allocated)
                allocated = list(range(n))
                cap = 1024
                fs = FreeStack.from_range(n, cap)
                legacy.compact(n, cap)
        else:
            # compact: allocated rows renumber to [0, n)
            n = len(allocated)
            allocated = list(range(n))
            fs = FreeStack.from_range(n, cap)
            legacy.compact(n, cap)
    assert fs.tolist() == legacy.l


@pytest.mark.parametrize("seed", [3, 4])
def test_tenant_blocks_matches_list_path(seed):
    """The vectorized FreeStack carve and the historical list-filter
    path pick the same blocks and leave the same remainder order."""
    rng = random.Random(seed)
    cap, shards = 128, 4
    pool = list(range(cap - 1, -1, -1))
    # random fragmentation: drop a third of the rows
    drop = set(rng.sample(range(cap), cap // 3))
    pool = [r for r in pool if r not in drop]
    requests = [rng.randrange(1, 24) for _ in range(5)]
    as_list = list(pool)
    as_stack = FreeStack(pool)
    want = partition.tenant_blocks(as_list, cap, shards, requests)
    got = partition.tenant_blocks(as_stack, cap, shards, requests)
    assert got == want
    assert as_stack.tolist() == as_list


@pytest.mark.parametrize("seed,shard_count", [(0, 1), (1, 4), (2, 8)])
def test_engine_rows_match_legacy_prediction(seed, shard_count):
    """Drive a REAL engine through random pair-alloc/free/compact/grow
    and predict every row assignment with the legacy model — the
    engine-level half of the byte-identity contract."""
    rng = random.Random(seed)
    cap = 64
    store = TopologyStore()
    engine = SimEngine(store, capacity=cap)
    engine.shard_count = shard_count
    legacy = LegacyFree(cap)
    live: list[tuple[str, str, int]] = []
    uid_next = 1
    for _step in range(200):
        assert engine._free.tolist() == legacy.l
        op = rng.random()
        with engine._lock:
            if op < 0.45 and len(legacy.l) >= 2:
                k1, k2 = f"ns/a{uid_next}", f"ns/b{uid_next}"
                got = engine._alloc_link_pair(k1, k2, uid_next)
                if (shard_count > 1
                        and engine._state.capacity % shard_count == 0):
                    want = legacy.pick_pair(engine._state.capacity,
                                            shard_count)
                else:
                    want = (legacy.pop(), legacy.pop())
                assert got == want, (got, want, _step)
                live.append((k1, k2, uid_next))
                uid_next += 1
            elif op < 0.75 and live:
                k1, k2, uid = live.pop(rng.randrange(len(live)))
                for k in (k1, k2):
                    row = engine._rows.pop((k, uid))
                    engine._peer.pop((k, uid), None)
                    engine._row_owner.pop(row, None)
                    engine._free_row(row)
                    legacy.push(row)
            elif op < 0.9:
                old_cap = engine._state.capacity
                if old_cap >= 512:
                    continue  # keep the walk bounded
                engine._ensure_capacity(old_cap + 1)  # force growth
                legacy.grow(old_cap, engine._state.capacity)
                continue
        if op >= 0.9:
            engine.compact()
            legacy.compact(engine.num_active, engine._state.capacity)
            # prediction: sorted-key order re-binds rows 0..n-1
            items = sorted(engine._rows.items())
            for i, (_k, r) in enumerate(items):
                assert r == i
    assert engine._free.tolist() == legacy.l


def test_tenant_block_sequences_keep_pools_and_masks_exact():
    """Random tenant create-with-block/alloc/free/delete/compact:
    the three pools (global free, block reserves, active rows) stay a
    partition of capacity, the O(1) reserved counter matches reality,
    and the incremental columnar accounting masks equal a brute-force
    registry re-derive after every step."""
    rng = random.Random(7)
    store = TopologyStore()
    engine = SimEngine(store, capacity=256)
    reg = TenantRegistry(engine)
    live: list[tuple[str, int]] = []
    uid = 1
    tenants = []
    for step in range(120):
        op = rng.random()
        if op < 0.15 and len(tenants) < 5:
            name = f"t{len(tenants)}"
            reg.create(name, block_edges=rng.choice([0, 8, 16]),
                       namespaces=[name])
            tenants.append(name)
        elif op < 0.25 and tenants and rng.random() < 0.3:
            name = tenants.pop(rng.randrange(len(tenants)))
            reg.delete(name)
        elif op < 0.7 and tenants:
            ns = rng.choice(tenants)
            k = f"{ns}/p{uid}"
            with engine._lock:
                engine._ensure_capacity(1)
                engine._alloc(k, uid)
            live.append((k, uid))
            uid += 1
        elif op < 0.9 and live:
            k, u = live.pop(rng.randrange(len(live)))
            with engine._lock:
                row = engine._rows.pop((k, u))
                engine._row_owner.pop(row, None)
                engine._free_row(row)
        else:
            engine.compact()

        # -- invariants -------------------------------------------
        cap = engine._state.capacity
        gfree = engine._free.tolist()
        reserves = {t: list(reg.get(t).block_free) for t in tenants
                    if reg.get(t) is not None}
        active = list(engine._row_owner)
        everything = gfree + sum(reserves.values(), []) + active
        assert len(everything) == len(set(everything)) == cap, step
        assert reg.reserved_free() == sum(
            len(v) for v in reserves.values()), step
        for t in tenants:
            tn = reg.get(t)
            if tn is None:
                continue
            want = sorted(
                row for (pk, _u), row in engine._rows.items()
                if pk.partition("/")[0] in tn.namespaces)
            got = reg.rows_of(t).tolist()
            assert got == want, (step, t, got, want)


# ---- delivered streams through a churned plane ------------------------

_PROPS = LinkProperties(latency="1ms", loss="7")


def _churned_daemon(pairs: int = 3):
    """Pods reconciled, one topology deleted, a tenant block carved,
    the engine compacted, the topology re-added — the allocator paths
    (pair-alloc, block carve, free fold, compact rebuild) all fire
    before a single frame flows."""
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * pairs + 8)
    reg = TenantRegistry(engine)
    for i in range(pairs):
        a, b = f"a{i}", f"b{i}"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=_PROPS)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=_PROPS)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    # churn: tear one pair down, carve a block, compact, re-add
    topo0 = store.get("default", "a0")
    engine.del_links(topo0, topo0.spec.links)
    reg.create("default", block_edges=4)
    engine.compact()
    engine.add_links(topo0, topo0.spec.links)
    daemon = Daemon(engine)
    win, wout = [], []
    for i in range(pairs):
        win.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"a{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1")))
        wout.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"b{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1")))
    return daemon, win, wout


def _run_churned(depth: int, mesh_n: int | None = None,
                 n_per_wire: int = 120):
    daemon, win, wout = _churned_daemon()
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=depth)
    plane.pipeline_explicit_clock = True
    if mesh_n is not None:
        plane.enable_sharding(make_mesh(mesh_n))
    t = 100.0
    for k, wa in enumerate(win):
        wa.ingress.extend(_tagged_frames(k, n_per_wire))
    for _ in range(60):
        t += 0.002
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert plane.tick_errors == 0
    return [list(w.egress) for w in wout]


def test_churned_stream_depth2_matches_depth1():
    assert _run_churned(2) == _run_churned(1)


@pytest.mark.sharded_plane
@pytest.mark.parametrize("sharded_mesh", [8], indirect=True)
def test_churned_stream_sharded_matches_unsharded(sharded_mesh):
    del sharded_mesh
    base = _run_churned(1, mesh_n=None)
    for depth in (1, 2):
        assert _run_churned(depth, mesh_n=8) == base


def test_checkpoint_roundtrip_keeps_freelist_and_keyids(tmp_path):
    """The FreeStack serializes through the manifest byte-identically,
    and a restored engine re-derives the columnar per-row key ids (a
    restored link must keep its identity-keyed PRNG stream)."""
    from kubedtn_tpu import checkpoint
    from kubedtn_tpu.topology.engine import link_key_id

    daemon, _win, _wout = _churned_daemon()
    engine = daemon.engine
    checkpoint.save(str(tmp_path / "ck"), engine.store, engine)
    _store2, engine2 = checkpoint.load(str(tmp_path / "ck"))
    # the manifest folds tenant-block reserve rows back into the saved
    # free list (a tenancy-less load keeps them in the global pool)
    want = engine._free.tolist() + sorted(
        engine.tenancy.reserved_free_rows(), reverse=True)
    assert engine2._free.tolist() == want
    assert engine2._pod_names == {v: k
                                  for k, v in engine2._pod_ids.items()}
    for (pk, u), r in engine2._rows.items():
        assert int(engine2._row_keyid[r]) == link_key_id(pk, u)


def test_checkpoint_row_out_of_capacity_is_typed_corruption(tmp_path):
    """A manifest row beyond the stated capacity hits the columnar
    key-id write: it must surface as CheckpointCorruptError (so
    load_or_rebuild falls back to reconstruction), never a raw
    IndexError killing the restore."""
    import json

    import pytest as _pytest

    from kubedtn_tpu import checkpoint

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    p = str(tmp_path / "ck")
    checkpoint.save(p, store, engine)
    mpath = tmp_path / "ck" / "manifest.json"
    m = json.loads(mpath.read_text())
    m["engine"]["rows"] = [["ns/x", 1, 999]]
    mpath.write_text(json.dumps(m))
    with _pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.load(p)
    _s, _e, src = checkpoint.load_or_rebuild(p, store=store,
                                             capacity=16)
    assert src == "rebuild"
