"""BASELINE-ladder scenarios at CI scale: the same code paths bench.py
and the CLI drive on hardware, shrunk so the suite exercises them on the
virtual CPU mesh every run."""

import pytest

from kubedtn_tpu import scenarios as S


@pytest.mark.requires_reference_yaml
def test_three_node_reference_sample():
    r = S.three_node()
    assert r["links"] == 3
    assert r["reachable"] is True
    # latency-free sample: RTTs are finite and tiny
    assert all(v >= 0 for v in r["pings"].values())


def test_reconcile_scenario_small_scale():
    """reconcile_100k's full pipeline (store → reconciler → engine →
    device → gRPC round trip) at 40 links."""
    r = S.reconcile_100k(n_spine=4, n_leaf=10, links_per_pair=1,
                         grpc_batch=10)
    assert r["links"] == 40
    assert r["directed_rows"] == 80
    assert r["grpc_ok"] is True
    assert r["teardown_s"] >= 0  # full-lifecycle phase reaches 0 rows
    assert r["spot_check_latency_us"] == 20_000.0
    assert r["meets_target"] is True  # trivially, at this scale
    assert r["device_calls"] <= 6     # coalescing holds at small scale too


def test_churn_scenario_small_scale():
    r = S.churn_1k(n_nodes=50, n_links=120, seconds=2.0)
    assert r["churn_links_total"] == 24
    assert r["updates_per_sec"] > 0


def test_routes_scenario_small_scale():
    r = S.routes_10k(n_nodes=200, n_links=600, events=2, dst_chunk=50)
    assert 0 < r["reachable_frac"] <= 1.0
    assert r["recompute_s_first"] > 0


def test_scale_scenario_small_scale():
    """scale_1m's device pipeline (bulk load → full-fabric contiguous
    update scan → shaping scan) at 80 links."""
    r = S.scale_1m(n_spine=4, n_leaf=10, links_per_pair=2,
                   update_iters=2, shape_iters=2)
    assert r["links"] == 80
    assert r["directed_rows"] == 160
    assert r["updates_per_sec"] > 0
    assert r["shape_pkts_per_sec"] > 0


def test_chaos_scenario_small_scale():
    """chaos_flaps: link flaps under routed traffic — routes reconverge
    and traffic keeps flowing through every outage."""
    r = S.chaos_flaps(n_nodes=40, n_links=140, events=2,
                      flaps_per_event=4, steps_per_event=10)
    assert r["events"] == 2 and len(r["event_results"]) == 2
    assert r["baseline_rx"] > 0
    assert r["traffic_survived_every_outage"] is True
    for ev in r["event_results"]:
        assert ev["down_recompute_s"] >= 0
        assert ev["rx_after_restore"] > 0
