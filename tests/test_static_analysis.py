"""dtnlint: per-pass fixture self-tests, waiver semantics, the
clean-tree tier-1 gate (writes ANALYSIS.json), and the runtime
lock-order harness (kubedtn_tpu.contracts).

Each rule gets at least one triggering and one clean fixture under
tests/fixtures/dtnlint/ — the fixtures are parsed, never imported."""

from __future__ import annotations

import json
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from kubedtn_tpu import contracts
from kubedtn_tpu.analysis import (
    CallGraph,
    Project,
    default_root,
    run_suite,
    summarize,
    write_json,
)
from kubedtn_tpu.analysis.passes import PASSES, host_sync

FIXTURES = Path(__file__).parent / "fixtures" / "dtnlint"
REPO = default_root()


def run_pass(rule: str, *fixture_names: str, hot_roots=None):
    project = Project(FIXTURES, packages=fixture_names)
    graph = CallGraph(project)
    if rule == "sync" and hot_roots is not None:
        from kubedtn_tpu.analysis.core import apply_waivers

        return apply_waivers(project, host_sync.run(
            project, graph, hot_roots=hot_roots))
    from kubedtn_tpu.analysis.core import apply_waivers

    return apply_waivers(project, PASSES[rule](project, graph))


# ---- per-pass fixtures ------------------------------------------------

def test_purity_bad_fixture_fires():
    f = run_pass("purity", "purity_bad.py")
    msgs = "\n".join(x.message for x in f)
    assert len(f) >= 4
    assert "time.time" in msgs
    assert "print" in msgs
    assert "random.random" in msgs
    assert "EVENTS" in msgs  # closed-over mutation, incl. the scan body


def test_purity_scan_body_is_traced():
    f = run_pass("purity", "purity_bad.py")
    # the lax.scan body's mutation is caught even though only the
    # enclosing function is named at the call site
    assert any("body" in x.message and "EVENTS" in x.message for x in f)


def test_purity_clean_fixture_silent():
    assert run_pass("purity", "purity_clean.py") == []


def test_key_bad_fixture_fires():
    f = run_pass("key", "key_bad.py")
    msgs = [x.message for x in f]
    assert any("second sampling call" in m for m in msgs)
    assert any("raw `jax.random.key(...)`" in m and "uniform" in m
               for m in msgs)
    assert any("passed directly into `shape`" in m for m in msgs)
    assert any("loop-invariant" in m for m in msgs)


def test_key_clean_fixture_silent():
    assert run_pass("key", "key_clean.py") == []


def test_sync_bad_fixture_fires():
    f = run_pass("sync", "sync_bad.py",
                 hot_roots=(("sync_bad.py", "hot_tick"),))
    msgs = [x.message for x in f]
    assert any("np.asarray" in m for m in msgs)
    assert any("float()" in m for m in msgs)
    assert any("bool coercion" in m for m in msgs)


def test_sync_clean_fixture_silent():
    f = run_pass("sync", "sync_clean.py",
                 hot_roots=(("sync_clean.py", "hot_tick"),))
    assert f == []


def test_lock_bad_fixture_fires():
    f = run_pass("lock", "lock_bad.py")
    assert len(f) == 2
    assert {x.message.split("`")[1] for x in f} == {"Box.count",
                                                    "Box.items"}


def test_lock_clean_fixture_silent():
    f = run_pass("lock", "lock_clean.py")
    assert [x for x in f if not x.waived] == []


def test_dtype_bad_fixture_fires():
    f = run_pass("dtype", "dtype_bad.py")
    msgs = [x.message for x in f]
    assert any("clock_us" in m and "freeze" in m for m in msgs)
    assert any("clock_us=" in m for m in msgs)
    assert any("f64→f32 downcast" in m for m in msgs)


def test_dtype_clean_fixture_silent():
    assert run_pass("dtype", "dtype_clean.py") == []


def test_hygiene_bad_fixture_fires():
    f = run_pass("hygiene", "hygiene_bad.py")
    msgs = [x.message for x in f]
    assert any("unused import `sys`" in m for m in msgs)
    assert any("bare `except:`" in m for m in msgs)
    assert any("out of group order" in m for m in msgs)
    # both stdlib imports trail the first-party one: each flags
    assert len(f) == 4


def test_hygiene_clean_fixture_silent():
    assert run_pass("hygiene", "hygiene_clean.py") == []


# ---- waiver semantics -------------------------------------------------

def test_waivers_mark_but_do_not_hide():
    f = run_pass("key", "waivered.py")
    assert len(f) >= 2                      # findings still reported
    assert all(x.waived for x in f)         # ...but every one waived
    assert all(x.waiver_reason for x in f)  # ...with a reason


def test_waiver_requires_reason():
    # `key-ok()` without a reason must not parse as a waiver
    from kubedtn_tpu.analysis.core import _WAIVER_RE

    assert _WAIVER_RE.search("# dtnlint: key-ok()") is None
    m = _WAIVER_RE.search("# dtnlint: key-ok(because)")
    assert m and m.group(2) == "because"


def test_stale_waivers_detected():
    """A `<rule>-ok` comment whose line no longer triggers the rule is
    itself a finding on a FULL run (dead waivers rot the audit trail)."""
    from kubedtn_tpu.analysis import run_suite

    _p, f = run_suite(root=FIXTURES, packages=("stale_waiver.py",))
    stale = [x for x in f if x.rule == "waiver"]
    assert len(stale) == 2, [x.format() for x in f]
    msgs = "\n".join(x.message for x in stale)
    assert "hygiene-ok" in msgs and "key-ok" in msgs
    assert all(not x.waived for x in stale)


def test_live_waivers_not_reported_stale():
    from kubedtn_tpu.analysis import run_suite

    _p, f = run_suite(root=FIXTURES, packages=("waivered.py",))
    assert [x for x in f if x.rule == "waiver"] == [], \
        [x.format() for x in f]


def test_subset_run_skips_stale_detection():
    """--rules subset runs cannot judge staleness: the un-run rules'
    waivers would all look dead."""
    from kubedtn_tpu.analysis import run_suite

    _p, f = run_suite(root=FIXTURES, packages=("stale_waiver.py",),
                      rules=("hygiene",))
    assert [x for x in f if x.rule == "waiver"] == []


def test_jaxpr_rule_waiver_reported_unsupported(tmp_path):
    """dtnverify findings are NOT waivable: a `jops-ok(...)` comment is
    reported as targeting an unwaivable layer, not as merely stale."""
    from kubedtn_tpu.analysis import run_suite

    p = tmp_path / "jw.py"
    p.write_text('"""f."""\n'
                 "X = 1  # dtnlint: jops-ok(reviewed the primitive)\n")
    _p, f = run_suite(root=tmp_path, packages=("jw.py",))
    w = [x for x in f if x.rule == "waiver"]
    assert len(w) == 1
    assert "not waivable" in w[0].message


# ---- --fix: hygiene autofixes ----------------------------------------

def _fix_copy(tmp_path, name="hygiene_bad.py"):
    import shutil

    pkg = tmp_path / name
    shutil.copy(FIXTURES / name, pkg)
    return pkg


def test_fix_removes_unused_imports_and_sorts(tmp_path):
    from kubedtn_tpu.analysis import CallGraph, Project
    from kubedtn_tpu.analysis.core import apply_waivers
    from kubedtn_tpu.analysis.fix import fix_tree
    from kubedtn_tpu.analysis.passes import PASSES

    p = _fix_copy(tmp_path)
    project = Project(tmp_path, packages=("hygiene_bad.py",))
    graph = CallGraph(project)
    findings = apply_waivers(project, PASSES["hygiene"](project, graph))
    changed = fix_tree(tmp_path, project, findings)
    assert changed == ["hygiene_bad.py"]
    text = p.read_text()
    assert "import sys" not in text          # unused import dropped
    # groups re-sorted: stdlib now precedes the first-party import
    assert text.index("import os") < text.index(
        "from kubedtn_tpu import contracts")
    # re-lint: only the bare-except remains (not mechanically fixable)
    project2 = Project(tmp_path, packages=("hygiene_bad.py",))
    left = PASSES["hygiene"](project2, CallGraph(project2))
    assert [f for f in left if "bare" not in f.message] == [], \
        [f.format() for f in left]


def test_fix_is_idempotent_and_safe(tmp_path):
    from kubedtn_tpu.analysis import CallGraph, Project
    from kubedtn_tpu.analysis.core import apply_waivers
    from kubedtn_tpu.analysis.fix import fix_tree
    from kubedtn_tpu.analysis.passes import PASSES

    p = _fix_copy(tmp_path)
    for _ in range(2):
        project = Project(tmp_path, packages=("hygiene_bad.py",))
        graph = CallGraph(project)
        findings = apply_waivers(project,
                                 PASSES["hygiene"](project, graph))
        fix_tree(tmp_path, project, findings)
    import ast

    ast.parse(p.read_text())  # still valid python
    second = p.read_text()
    project = Project(tmp_path, packages=("hygiene_bad.py",))
    findings = apply_waivers(
        project, PASSES["hygiene"](project, CallGraph(project)))
    fix_tree(tmp_path, project, findings)
    assert p.read_text() == second  # no further churn


def test_fix_leaves_waived_findings_alone(tmp_path):
    from kubedtn_tpu.analysis import CallGraph, Project
    from kubedtn_tpu.analysis.core import apply_waivers
    from kubedtn_tpu.analysis.fix import fix_tree
    from kubedtn_tpu.analysis.passes import PASSES

    p = tmp_path / "waived_import.py"
    p.write_text(
        '"""f."""\n'
        "import sys  # dtnlint: hygiene-ok(kept for doctest namespace)\n"
        "X = 1\n")
    project = Project(tmp_path, packages=("waived_import.py",))
    findings = apply_waivers(
        project, PASSES["hygiene"](project, CallGraph(project)))
    assert findings and all(f.waived for f in findings)
    changed = fix_tree(tmp_path, project, findings)
    assert changed == []
    assert "import sys" in p.read_text()


def test_fix_import_order_refuses_to_eat_free_comment(tmp_path):
    """A free-standing comment inside the leading import block (blank
    line between it and the next import) belongs to no reorder unit —
    the fixer must refuse rather than silently delete it."""
    from kubedtn_tpu.analysis.fix import fix_import_order

    p = tmp_path / "m.py"
    src = ('"""d."""\n'
           "from kubedtn_tpu import contracts\n"
           "\n"
           "# TODO: revisit this dependency\n"
           "\n"
           "import os\n"
           "\n"
           "X = (os, contracts)\n")
    p.write_text(src)
    assert fix_import_order(p) is False
    assert p.read_text() == src  # untouched, comment intact


# ---- --diff: artifact deltas ------------------------------------------

def test_diff_new_fixed_and_waiver_flip(tmp_path):
    from kubedtn_tpu.analysis.diff import diff_docs, run_diff

    old = {"schema_version": 1, "findings": [
        {"rule": "key", "path": "a.py", "line": 3, "message": "m1",
         "waived": False},
        {"rule": "sync", "path": "b.py", "line": 9, "message": "m2",
         "waived": False}]}
    new = {"schema_version": 2, "findings": [
        {"rule": "key", "path": "a.py", "line": 5, "message": "m1",
         "waived": True},
        {"rule": "dtype", "path": "c.py", "line": 1, "message": "m3",
         "waived": False}],
        "jaxpr": {"findings": [
            {"rule": "jops", "path": "d.py", "line": 1,
             "message": "m4", "waived": False}]}}
    d = diff_docs(old, new)
    assert {f["message"] for f in d["new"]} == {"m3", "m4"}
    assert {f["message"] for f in d["fixed"]} == {"m2"}
    assert len(d["waiver_changes"]) == 1
    assert d["waiver_changes"][0]["now_waived"] is True
    # exit codes: new ACTIVE findings → 1; clean delta → 0
    import json as _json

    po, pn = tmp_path / "old.json", tmp_path / "new.json"
    po.write_text(_json.dumps(old))
    pn.write_text(_json.dumps(new))
    assert run_diff(po, pn) == 1
    pn.write_text(_json.dumps(old))
    assert run_diff(po, pn) == 0


def test_cli_diff(tmp_path):
    """End-to-end: two artifact writes, then --diff in a subprocess."""
    first = tmp_path / "first.json"
    r = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "-q",
         "--root", str(REPO), "--json", str(first)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    second = tmp_path / "second.json"
    r2 = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "-q",
         "--root", str(REPO), "--json", str(second),
         "--diff", str(first)],
        capture_output=True, text=True, timeout=300)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "new findings: 0" in r2.stdout
    assert "fixed findings: 0" in r2.stdout


# ---- the tier-1 gate: the tree itself is clean ------------------------

def test_tree_is_clean_and_artifact_written():
    """Zero unwaivered findings on kubedtn_tpu/, and the machine-
    readable ANALYSIS.json artifact lands at the repo root so benches
    can track the findings-count trajectory."""
    _project, findings = run_suite(root=REPO)
    active = [f for f in findings if not f.waived]
    assert active == [], "\n" + "\n".join(f.format() for f in active)
    # every waiver carries a reason (honesty gate)
    assert all(f.waiver_reason for f in findings if f.waived)
    out = REPO / "ANALYSIS.json"
    write_json(out, findings, REPO)
    doc = json.loads(out.read_text())
    assert doc["summary"]["unwaivered"] == 0
    assert doc["summary"]["total"] == len(findings)
    assert summarize(findings)["total"] == doc["summary"]["total"]


def test_cli_exit_codes(tmp_path):
    env_root = str(REPO)
    r = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "-q",
         "--root", env_root, "--json", str(tmp_path / "a.json")],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert (tmp_path / "a.json").exists()
    # unknown rule → argparse error
    r2 = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "--rules", "nope"],
        capture_output=True, text=True, timeout=300)
    assert r2.returncode == 2


# ---- guarded_by registry ---------------------------------------------

def test_guarded_by_registry_populated():
    import kubedtn_tpu.runtime  # noqa: F401  (applies the decorators)
    import kubedtn_tpu.telemetry  # noqa: F401

    reg = contracts.registry()
    plane = reg.get("kubedtn_tpu.runtime.WireDataPlane", {})
    assert plane.get("_inflight") == "_tick_lock"
    assert plane.get("_pipe_state") == "_tick_lock"
    sender = reg.get("kubedtn_tpu.runtime._PeerSender", {})
    assert sender.get("dropped") == "_lock"
    tel = reg.get("kubedtn_tpu.telemetry.LinkTelemetry", {})
    assert tel.get("_acc") == "_lock"


# ---- runtime lock-order harness ---------------------------------------

def test_lock_order_cycle_detected():
    """The deliberately inverted acquisition: A→B established, then
    B→A must raise LockOrderError at the acquisition that closes the
    cycle."""
    g = contracts.LockOrderGraph()
    a = contracts.InstrumentedLock("A", g)
    b = contracts.InstrumentedLock("B", g)
    with a:
        with b:
            pass
    with pytest.raises(contracts.LockOrderError, match="cycle"):
        with b:
            with a:
                pass
    assert g.violations


def test_lock_order_cycle_across_threads():
    """The classic AB/BA deadlock shape is caught from the ORDER GRAPH
    even when the two inversions happen on different threads (no actual
    deadlock needed to detect it)."""
    g = contracts.LockOrderGraph(raise_on_cycle=False)
    a = contracts.InstrumentedLock("A", g)
    b = contracts.InstrumentedLock("B", g)

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1)
    th1.start()
    th1.join()
    th2 = threading.Thread(target=t2)
    th2.start()
    th2.join()
    assert g.violations
    with pytest.raises(contracts.LockOrderError):
        g.assert_acyclic()


def test_clean_order_passes_and_rlock_reentry_ok():
    g = contracts.LockOrderGraph()
    outer = contracts.InstrumentedLock("outer", g,
                                       lock=threading.RLock())
    inner = contracts.InstrumentedLock("inner", g)
    for _ in range(3):
        with outer:
            with outer:      # re-entrant: no self-edge
                with inner:
                    pass
    g.assert_acyclic()
    assert g.edges() == {"outer": {"inner"}}


def test_live_plane_lock_order_acyclic():
    """Integration: instrument the REAL plane locks (tick lock, engine
    lock, telemetry lock), run live ticks with telemetry on plus
    concurrent queries, and assert the recorded acquisition order has
    no cycles — the runtime half of the lock-discipline contract."""
    from kubedtn_tpu.api.types import (
        Link,
        LinkProperties,
        Topology,
        TopologySpec,
    )
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.topology.engine import SimEngine
    from kubedtn_tpu.topology.reconciler import Reconciler
    from kubedtn_tpu.topology.store import TopologyStore
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    props = LinkProperties(latency="1ms")
    store.create(Topology(name="a", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="b", uid=1,
             properties=props)])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    daemon._add_wire(pb.WireDef(local_pod_name="b", kube_ns="default",
                                link_uid=1, intf_name_in_pod="eth1"))
    plane = WireDataPlane(daemon, dt_us=2_000.0)
    plane.enable_telemetry(window_s=0.05)

    graph = contracts.LockOrderGraph()
    contracts.instrument_locks(plane, graph, ["_tick_lock"])
    contracts.instrument_locks(engine, graph, ["_lock"])
    contracts.instrument_locks(plane.telemetry, graph, ["_lock"])

    stop = threading.Event()
    errors: list[BaseException] = []

    def query():
        try:
            while not stop.is_set():
                plane.telemetry.window_sum()
                plane.telemetry.link_rows(engine)
        except BaseException as e:  # surfaced below
            errors.append(e)

    qt = threading.Thread(target=query)
    qt.start()
    try:
        for i in range(30):
            wa.ingress.extend(bytes([i % 256]) * 60 for _ in range(4))
            plane.tick(now_s=1.0 + i * 0.002)
        plane.flush()
    finally:
        stop.set()
        qt.join(5.0)
    assert not errors
    graph.assert_acyclic()
    # the contract's signature edge: tick lock precedes the telemetry
    # window lock (open_acc under the dispatch)
    edges = graph.edges()
    tick = "WireDataPlane._tick_lock"
    assert any(tick in held and "LinkTelemetry._lock" in str(acq)
               for held, acq in edges.items()), edges
