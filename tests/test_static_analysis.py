"""dtnlint: per-pass fixture self-tests, waiver semantics, the
clean-tree tier-1 gate (writes ANALYSIS.json), and the runtime
lock-order harness (kubedtn_tpu.contracts).

Each rule gets at least one triggering and one clean fixture under
tests/fixtures/dtnlint/ — the fixtures are parsed, never imported."""

from __future__ import annotations

import json
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from kubedtn_tpu import contracts
from kubedtn_tpu.analysis import (
    CallGraph,
    Project,
    default_root,
    run_suite,
    summarize,
    write_json,
)
from kubedtn_tpu.analysis.passes import PASSES, host_sync

FIXTURES = Path(__file__).parent / "fixtures" / "dtnlint"
REPO = default_root()


def run_pass(rule: str, *fixture_names: str, hot_roots=None):
    project = Project(FIXTURES, packages=fixture_names)
    graph = CallGraph(project)
    if rule == "sync" and hot_roots is not None:
        from kubedtn_tpu.analysis.core import apply_waivers

        return apply_waivers(project, host_sync.run(
            project, graph, hot_roots=hot_roots))
    from kubedtn_tpu.analysis.core import apply_waivers

    return apply_waivers(project, PASSES[rule](project, graph))


# ---- per-pass fixtures ------------------------------------------------

def test_purity_bad_fixture_fires():
    f = run_pass("purity", "purity_bad.py")
    msgs = "\n".join(x.message for x in f)
    assert len(f) >= 4
    assert "time.time" in msgs
    assert "print" in msgs
    assert "random.random" in msgs
    assert "EVENTS" in msgs  # closed-over mutation, incl. the scan body


def test_purity_scan_body_is_traced():
    f = run_pass("purity", "purity_bad.py")
    # the lax.scan body's mutation is caught even though only the
    # enclosing function is named at the call site
    assert any("body" in x.message and "EVENTS" in x.message for x in f)


def test_purity_clean_fixture_silent():
    assert run_pass("purity", "purity_clean.py") == []


def test_key_bad_fixture_fires():
    f = run_pass("key", "key_bad.py")
    msgs = [x.message for x in f]
    assert any("second sampling call" in m for m in msgs)
    assert any("raw `jax.random.key(...)`" in m and "uniform" in m
               for m in msgs)
    assert any("passed directly into `shape`" in m for m in msgs)
    assert any("loop-invariant" in m for m in msgs)


def test_key_clean_fixture_silent():
    assert run_pass("key", "key_clean.py") == []


def test_sync_bad_fixture_fires():
    f = run_pass("sync", "sync_bad.py",
                 hot_roots=(("sync_bad.py", "hot_tick"),))
    msgs = [x.message for x in f]
    assert any("np.asarray" in m for m in msgs)
    assert any("float()" in m for m in msgs)
    assert any("bool coercion" in m for m in msgs)


def test_sync_clean_fixture_silent():
    f = run_pass("sync", "sync_clean.py",
                 hot_roots=(("sync_clean.py", "hot_tick"),))
    assert f == []


def test_lock_bad_fixture_fires():
    f = run_pass("lock", "lock_bad.py")
    assert len(f) == 2
    assert {x.message.split("`")[1] for x in f} == {"Box.count",
                                                    "Box.items"}


def test_lock_clean_fixture_silent():
    f = run_pass("lock", "lock_clean.py")
    assert [x for x in f if not x.waived] == []


def test_dtype_bad_fixture_fires():
    f = run_pass("dtype", "dtype_bad.py")
    msgs = [x.message for x in f]
    assert any("clock_us" in m and "freeze" in m for m in msgs)
    assert any("clock_us=" in m for m in msgs)
    assert any("f64→f32 downcast" in m for m in msgs)


def test_dtype_clean_fixture_silent():
    assert run_pass("dtype", "dtype_clean.py") == []


def test_hygiene_bad_fixture_fires():
    f = run_pass("hygiene", "hygiene_bad.py")
    msgs = [x.message for x in f]
    assert any("unused import `sys`" in m for m in msgs)
    assert any("bare `except:`" in m for m in msgs)
    assert any("out of group order" in m for m in msgs)
    # both stdlib imports trail the first-party one: each flags
    assert len(f) == 4


def test_hygiene_clean_fixture_silent():
    assert run_pass("hygiene", "hygiene_clean.py") == []


# ---- waiver semantics -------------------------------------------------

def test_waivers_mark_but_do_not_hide():
    f = run_pass("key", "waivered.py")
    assert len(f) >= 2                      # findings still reported
    assert all(x.waived for x in f)         # ...but every one waived
    assert all(x.waiver_reason for x in f)  # ...with a reason


def test_waiver_requires_reason():
    # `key-ok()` without a reason must not parse as a waiver
    from kubedtn_tpu.analysis.core import _WAIVER_RE

    assert _WAIVER_RE.search("# dtnlint: key-ok()") is None
    m = _WAIVER_RE.search("# dtnlint: key-ok(because)")
    assert m and m.group(2) == "because"


# ---- the tier-1 gate: the tree itself is clean ------------------------

def test_tree_is_clean_and_artifact_written():
    """Zero unwaivered findings on kubedtn_tpu/, and the machine-
    readable ANALYSIS.json artifact lands at the repo root so benches
    can track the findings-count trajectory."""
    _project, findings = run_suite(root=REPO)
    active = [f for f in findings if not f.waived]
    assert active == [], "\n" + "\n".join(f.format() for f in active)
    # every waiver carries a reason (honesty gate)
    assert all(f.waiver_reason for f in findings if f.waived)
    out = REPO / "ANALYSIS.json"
    write_json(out, findings, REPO)
    doc = json.loads(out.read_text())
    assert doc["summary"]["unwaivered"] == 0
    assert doc["summary"]["total"] == len(findings)
    assert summarize(findings)["total"] == doc["summary"]["total"]


def test_cli_exit_codes(tmp_path):
    env_root = str(REPO)
    r = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "-q",
         "--root", env_root, "--json", str(tmp_path / "a.json")],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert (tmp_path / "a.json").exists()
    # unknown rule → argparse error
    r2 = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "--rules", "nope"],
        capture_output=True, text=True, timeout=300)
    assert r2.returncode == 2


# ---- guarded_by registry ---------------------------------------------

def test_guarded_by_registry_populated():
    import kubedtn_tpu.runtime  # noqa: F401  (applies the decorators)
    import kubedtn_tpu.telemetry  # noqa: F401

    reg = contracts.registry()
    plane = reg.get("kubedtn_tpu.runtime.WireDataPlane", {})
    assert plane.get("_inflight") == "_tick_lock"
    assert plane.get("_pipe_state") == "_tick_lock"
    sender = reg.get("kubedtn_tpu.runtime._PeerSender", {})
    assert sender.get("dropped") == "_lock"
    tel = reg.get("kubedtn_tpu.telemetry.LinkTelemetry", {})
    assert tel.get("_acc") == "_lock"


# ---- runtime lock-order harness ---------------------------------------

def test_lock_order_cycle_detected():
    """The deliberately inverted acquisition: A→B established, then
    B→A must raise LockOrderError at the acquisition that closes the
    cycle."""
    g = contracts.LockOrderGraph()
    a = contracts.InstrumentedLock("A", g)
    b = contracts.InstrumentedLock("B", g)
    with a:
        with b:
            pass
    with pytest.raises(contracts.LockOrderError, match="cycle"):
        with b:
            with a:
                pass
    assert g.violations


def test_lock_order_cycle_across_threads():
    """The classic AB/BA deadlock shape is caught from the ORDER GRAPH
    even when the two inversions happen on different threads (no actual
    deadlock needed to detect it)."""
    g = contracts.LockOrderGraph(raise_on_cycle=False)
    a = contracts.InstrumentedLock("A", g)
    b = contracts.InstrumentedLock("B", g)

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1)
    th1.start()
    th1.join()
    th2 = threading.Thread(target=t2)
    th2.start()
    th2.join()
    assert g.violations
    with pytest.raises(contracts.LockOrderError):
        g.assert_acyclic()


def test_clean_order_passes_and_rlock_reentry_ok():
    g = contracts.LockOrderGraph()
    outer = contracts.InstrumentedLock("outer", g,
                                       lock=threading.RLock())
    inner = contracts.InstrumentedLock("inner", g)
    for _ in range(3):
        with outer:
            with outer:      # re-entrant: no self-edge
                with inner:
                    pass
    g.assert_acyclic()
    assert g.edges() == {"outer": {"inner"}}


def test_live_plane_lock_order_acyclic():
    """Integration: instrument the REAL plane locks (tick lock, engine
    lock, telemetry lock), run live ticks with telemetry on plus
    concurrent queries, and assert the recorded acquisition order has
    no cycles — the runtime half of the lock-discipline contract."""
    from kubedtn_tpu.api.types import (
        Link,
        LinkProperties,
        Topology,
        TopologySpec,
    )
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.topology.engine import SimEngine
    from kubedtn_tpu.topology.reconciler import Reconciler
    from kubedtn_tpu.topology.store import TopologyStore
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    props = LinkProperties(latency="1ms")
    store.create(Topology(name="a", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="b", uid=1,
             properties=props)])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    daemon._add_wire(pb.WireDef(local_pod_name="b", kube_ns="default",
                                link_uid=1, intf_name_in_pod="eth1"))
    plane = WireDataPlane(daemon, dt_us=2_000.0)
    plane.enable_telemetry(window_s=0.05)

    graph = contracts.LockOrderGraph()
    contracts.instrument_locks(plane, graph, ["_tick_lock"])
    contracts.instrument_locks(engine, graph, ["_lock"])
    contracts.instrument_locks(plane.telemetry, graph, ["_lock"])

    stop = threading.Event()
    errors: list[BaseException] = []

    def query():
        try:
            while not stop.is_set():
                plane.telemetry.window_sum()
                plane.telemetry.link_rows(engine)
        except BaseException as e:  # surfaced below
            errors.append(e)

    qt = threading.Thread(target=query)
    qt.start()
    try:
        for i in range(30):
            wa.ingress.extend(bytes([i % 256]) * 60 for _ in range(4))
            plane.tick(now_s=1.0 + i * 0.002)
        plane.flush()
    finally:
        stop.set()
        qt.join(5.0)
    assert not errors
    graph.assert_acyclic()
    # the contract's signature edge: tick lock precedes the telemetry
    # window lock (open_acc under the dispatch)
    edges = graph.edges()
    tick = "WireDataPlane._tick_lock"
    assert any(tick in held and "LinkTelemetry._lock" in str(acq)
               for held, acq in edges.items()), edges
