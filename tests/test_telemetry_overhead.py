"""Tier-1 smoke for the `telemetry_overhead` bench phase: the probe
runs end to end and the measured cost of the link telemetry plane
(window ring + flight recorder at the default 1/256 sampling) stays
under the 5% acceptance bar on the plane-only probe.

The probe interleaves off/on rounds and reports the MEDIAN paired
overhead (host drift cancels pair-by-pair; the probe re-measures once
when a stall inflates the median past the bar while the best pair sits
under it — the same rule as bench's _soak_stall_retry). On a shared
1-core CI host the noise floor is still a few percent, so this smoke
retries the whole probe up to three times and asserts the BEST trial —
a pass proves the telemetry cost itself is under the bar; repeated
failures would mean the cost is real.
"""

from kubedtn_tpu.scenarios import telemetry_overhead


def test_telemetry_overhead_under_5pct():
    last = None
    for _trial in range(3):
        r = telemetry_overhead(pairs=2, frames_per_wire=6_000,
                               rounds=3)
        last = r
        # the phase's own integrity: both planes ran clean and the
        # telemetry side actually recorded
        assert r["tick_errors_off"] == 0
        assert r["tick_errors_on"] == 0
        assert r["sampled_frames"] > 0
        assert r["recorder_events"] > 0
        assert r["telemetry_link_rows"] == 2
        assert r["frames_per_s_off"] > 0
        assert r["frames_per_s_on"] > 0
        if r["meets_5pct_target"]:
            break
    assert last["meets_5pct_target"], last
