"""Randomized lifecycle fuzz: the reconcile/engine pair must track the
documented event semantics under ANY interleaving of pod and spec events.

The contract is EVENT-based, exactly like the reference's:
- setup_pod(p) realizes both directions of every link p declares whose
  peer is alive (handler.go:399-418); links to dead peers wait
  (handler.go:389-395).
- destroy_pod(p) removes both directions of every link p declares —
  removing a veth end destroys the pair (handler.go:461-492).
- dropping a link from p's spec deletes both directions on the next
  reconcile; the peer's unchanged spec does NOT re-add it (its status
  still equals its spec, so its reconcile no-ops — the reference's
  DeepEqual short-circuit, topology_controller.go:66-79).
- property churn touches properties only, never the realized set.

The fuzz drives 30 random events through the REAL paths (engine +
reconciler drains) while an oracle applies the same events to a plain
set; after every drain the engine's host registry, the device arrays,
and the oracle must agree exactly.
"""

import dataclasses

import numpy as np
import pytest

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, TopologySpec
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

PODS = [f"p{i}" for i in range(6)]
PROPS = [
    LinkProperties(),
    LinkProperties(latency="5ms"),
    LinkProperties(latency="1ms", jitter="100us", loss="1"),
    LinkProperties(rate="100Mbit"),
]


def mk_linked_specs(rng, uids):
    """Symmetric per-pod link lists over a random pod pairing per uid."""
    per_pod = {p: [] for p in PODS}
    for uid in uids:
        a, b = rng.choice(len(PODS), 2, replace=False)
        props = PROPS[int(rng.integers(len(PROPS)))]
        pa, pb = PODS[a], PODS[b]
        per_pod[pa].append(Link(local_intf=f"e{uid}a", peer_intf=f"e{uid}b",
                                peer_pod=pb, uid=uid, properties=props))
        per_pod[pb].append(Link(local_intf=f"e{uid}b", peer_intf=f"e{uid}a",
                                peer_pod=pa, uid=uid, properties=props))
    return per_pod


class Oracle:
    """Plain-set mirror of the event semantics above."""

    def __init__(self):
        self.alive: dict[str, bool] = {p: False for p in PODS}
        self.rows: set[tuple[str, int]] = set()

    @staticmethod
    def _key(pod):
        return f"default/{pod}"

    def setup(self, store, pod):
        self.alive[pod] = True
        for l in store.get("default", pod).spec.links:
            if self.alive.get(l.peer_pod):
                self.rows.add((self._key(pod), l.uid))
                self.rows.add((self._key(l.peer_pod), l.uid))

    def destroy(self, store, pod):
        for l in store.get("default", pod).spec.links:
            self.rows.discard((self._key(pod), l.uid))
            self.rows.discard((self._key(l.peer_pod), l.uid))
        self.alive[pod] = False

    def drop_link(self, pod, link):
        self.rows.discard((self._key(pod), link.uid))
        self.rows.discard((self._key(link.peer_pod), link.uid))


@pytest.mark.parametrize("seed", [0, 1, 2, 7, 42])
def test_random_lifecycle_converges(seed):
    rng = np.random.default_rng(seed)
    store = TopologyStore()
    engine = SimEngine(store, capacity=256)
    rec = Reconciler(store, engine)
    oracle = Oracle()

    per_pod = mk_linked_specs(rng, uids=range(1, 13))
    for p in PODS:
        store.create(Topology(name=p,
                              spec=TopologySpec(links=per_pod[p])))
    for p in PODS:
        engine.setup_pod(p)  # the CNI path: placement + first realize
        oracle.setup(store, p)
    rec.drain()

    for step in range(30):
        op = rng.integers(4)
        pod = PODS[int(rng.integers(len(PODS)))]
        if op == 0:
            # pod churn: destroy, sometimes bring straight back
            oracle.destroy(store, pod)
            engine.destroy_pod(pod)
            if rng.random() < 0.6:
                engine.setup_pod(pod)
                oracle.setup(store, pod)
        elif op == 1:
            # property churn on every link of one pod: realized set fixed
            t = store.get("default", pod)
            props = PROPS[int(rng.integers(len(PROPS)))]
            t.spec.links = [dataclasses.replace(l, properties=props)
                            for l in t.spec.links]
            store.update(t)
        elif op == 2:
            # drop a random link from one pod's spec: the pair dies, the
            # peer's unchanged spec does not resurrect it
            t = store.get("default", pod)
            if t.spec.links:
                k = int(rng.integers(len(t.spec.links)))
                dropped = t.spec.links[k]
                t.spec.links = (t.spec.links[:k] + t.spec.links[k + 1:])
                store.update(t)
                oracle.drop_link(pod, dropped)
        else:
            # re-setup (idempotent re-plumb, SetupVeth semantics): may
            # resurrect links the PEER dropped but this pod still declares
            engine.setup_pod(pod)
            oracle.setup(store, pod)
        rec.drain()

        got = set(engine._rows.keys())
        assert got == oracle.rows, (
            f"step {step} op {op} pod {pod}: "
            f"missing {sorted(oracle.rows - got)}, "
            f"extra {sorted(got - oracle.rows)}")
        # host registry vs device arrays: active count agrees
        n_dev = int(np.asarray(engine.state.active).sum())
        assert n_dev == len(got), (step, n_dev, len(got))

    # final sanity: full teardown reaches an empty fabric
    for p in PODS:
        engine.destroy_pod(p)
    rec.drain()
    assert engine.num_active == 0
    assert int(np.asarray(engine.state.active).sum()) == 0
