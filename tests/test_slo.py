"""SLO observability plane (ISSUE 15) — kubedtn_tpu.slo.

Pins:

- **Censored-tail estimation**: quantiles past the bucket ladder's
  open top bucket are ESTIMATED by the log-linear survival fit
  (arxiv 2205.01234) instead of clamped — synthetic known
  distributions recover p99.9 beyond the last edge within tolerance,
  and the clamp (flagged, never silent) only returns when the fit
  legitimately refuses.
- **Burn-rate window math** against hand-computed fixtures, and the
  two-window severity rule.
- **Exact fleet merging**: per-plane histogram slices merged on the
  shared reference ladder produce BIT-EQUAL percentiles/attainment to
  the single-plane computation over the pooled rows.
- **Continuity across live migration**: a migrated tenant's fleet
  view stitches the journal's RECONCILE-frozen src window slice with
  the dst's live ring — offered/delivered totals continuous across
  the move, accounting mismatch 0.
- **Live evaluator smoke** (<30s): the rollover-triggered sidecar
  evaluates a real running plane; Local.ObserveSLO serves it.
- Satellites: percentiles_from_hist censored flags + caller routing,
  Guardrails.from_slo, the noisy_neighbor SLO self-verdict.
"""

import tempfile

import numpy as np
import pytest

from kubedtn_tpu import telemetry as tele
from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.slo import (SloEvaluator, SloSpec, evaluate_tenant,
                             fleet_slo, merge_hists, merge_tenant)
from kubedtn_tpu.slo import tail as slo_tail
from kubedtn_tpu.slo.fleet import contribution
from kubedtn_tpu.slo.spec import severity_of
from kubedtn_tpu.tenancy import TenantRegistry
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.server import Daemon

pytestmark = pytest.mark.slo


# -- helpers ------------------------------------------------------------

def _analytic_hist(survival_fn, total=1_000_000.0):
    """Expected ladder bucket counts for a distribution given its
    survival function S(x) = P(X > x)."""
    edges = np.asarray(tele.BUCKET_EDGES_US)
    cdf = 1.0 - np.asarray([survival_fn(e) for e in edges])
    cum = cdf * total
    return np.concatenate([[cum[0]], np.diff(cum), [total - cum[-1]]])


def _row(tx=0.0, delivered=0.0, hist=None, loss=0.0, queue=0.0):
    r = np.zeros(tele.KCOLS)
    r[tele.T_TX] = tx
    r[tele.T_DELIVERED] = delivered
    r[tele.T_DROP_LOSS] = loss
    r[tele.T_DROP_QUEUE] = queue
    if hist is not None:
        r[tele.T_HIST0:] = np.asarray(hist)
    return r


def _one_tenant_plane(ns="t0", pairs=1, latency="2ms", dt_us=2000.0,
                      window_s=0.1, qos="gold"):
    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * pairs + 8)
    reg = TenantRegistry(engine)
    reg.create(ns, qos=qos)
    props = LinkProperties(latency=latency)
    for i in range(pairs):
        a, b = f"{ns}-a{i}", f"{ns}-b{i}"
        store.create(Topology(name=a, namespace=ns,
                              spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=props)])))
        store.create(Topology(name=b, namespace=ns,
                              spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=props)])))
        engine.setup_pod(a, ns)
        engine.setup_pod(b, ns)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=dt_us)
    plane.attach_tenancy(reg)
    plane.enable_telemetry(window_s=window_s)
    win, wout = [], []
    for i in range(pairs):
        win.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"{ns}-a{i}", kube_ns=ns, link_uid=i + 1,
            intf_name_in_pod="eth1")))
        wout.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"{ns}-b{i}", kube_ns=ns, link_uid=i + 1,
            intf_name_in_pod="eth1")))
    return daemon, plane, reg, win, wout


# -- censored-tail estimation ------------------------------------------

def test_tail_fit_recovers_exponential_p999_past_the_edge():
    """The acceptance distribution: exponential(mean 1s) puts p99.9 at
    6.9s — PAST the 5s last edge, where the old code clamped. The fit
    recovers it within 5%; the old clamp is still reported (flagged)
    by percentiles_from_hist."""
    mean = 1e6
    hist = _analytic_hist(lambda x: np.exp(-x / mean))
    last_edge = tele.BUCKET_EDGES_US[-1]
    true_p999 = -np.log(1e-3) * mean        # ≈ 6.91e6 µs > 5e6
    assert true_p999 > last_edge
    # the OLD behavior: clamped to the edge, now at least flagged
    p = tele.percentiles_from_hist(hist, qs=(0.999,))
    assert p["p99_9_us"] == last_edge
    assert p["p99_9_censored"] is True
    est, method = slo_tail.estimate_quantile(hist, 0.999)
    assert method == slo_tail.METHOD_TAIL_FIT
    assert est > last_edge                   # beyond, not clamped
    assert est == pytest.approx(true_p999, rel=0.05)


def test_tail_fit_sampled_distribution_tolerance():
    """Sampled (not analytic) data: 200k exponential draws binned into
    the ladder still land the estimated p99.9 within 20% of the
    sample's true quantile, beyond the edge."""
    rng = np.random.default_rng(7)
    mean = 1.2e6
    lat = rng.exponential(mean, size=200_000)
    edges = np.asarray(tele.BUCKET_EDGES_US)
    bidx = np.minimum(np.searchsorted(edges, lat, side="left"),
                      tele.N_BINS - 1)
    hist = np.bincount(bidx, minlength=tele.N_BINS).astype(float)
    true_q = float(np.quantile(lat, 0.999))
    est, method = slo_tail.estimate_quantile(hist, 0.999)
    assert method == slo_tail.METHOD_TAIL_FIT
    assert est > edges[-1]
    assert est == pytest.approx(true_q, rel=0.2)


def test_tail_fit_refuses_honestly():
    """The clamp is the FALLBACK, flagged as such: all-mass-in-overflow
    gives no survival points to fit (clamp), in-ladder quantiles never
    consult the fit, an empty histogram answers None — and when the
    fit succeeds the clamp is NEVER returned."""
    # degenerate: every sample past the edge → no usable decay
    h = np.zeros(tele.N_BINS)
    h[-1] = 1000.0
    est, method = slo_tail.estimate_quantile(h, 0.999)
    assert method == slo_tail.METHOD_CENSORED
    assert est == tele.BUCKET_EDGES_US[-1]
    # in-ladder: exact interpolation, no fit involved
    h2 = np.zeros(tele.N_BINS)
    h2[1] = 100.0
    est2, m2 = slo_tail.estimate_quantile(h2, 0.5)
    assert m2 == slo_tail.METHOD_INTERP and 1000.0 < est2 <= 5000.0
    # empty
    assert slo_tail.estimate_quantile(np.zeros(tele.N_BINS), 0.99) \
        == (None, slo_tail.METHOD_EMPTY)
    # fit succeeded ⇒ method is tail-fit, never the clamp
    hist = _analytic_hist(lambda x: np.exp(-x / 8e5))
    assert slo_tail.fit_tail(hist) is not None
    _est, m3 = slo_tail.estimate_quantile(hist, 0.999)
    assert m3 == slo_tail.METHOD_TAIL_FIT


def test_fraction_slower_than_matches_analytic():
    mean = 1e6
    hist = _analytic_hist(lambda x: np.exp(-x / mean))
    # in-ladder bound: exact from the histogram
    assert slo_tail.fraction_slower_than(hist, 2e6) == pytest.approx(
        np.exp(-2.0), rel=1e-6)
    # past-the-edge bound: the tail fit extrapolates
    assert slo_tail.fraction_slower_than(hist, 8e6) == pytest.approx(
        np.exp(-8.0), rel=0.05)


def test_percentiles_censored_flags():
    hist = np.zeros(tele.N_BINS)
    hist[1] = 100.0
    p = tele.percentiles_from_hist(hist, qs=(0.5, 0.99))
    assert p["p50_censored"] is False and p["p99_censored"] is False
    hist[-1] = 900.0  # 90% of mass past the edge → p99 censored
    p = tele.percentiles_from_hist(hist, qs=(0.5, 0.99))
    assert p["p99_censored"] is True
    assert p["p99_us"] == tele.BUCKET_EDGES_US[-1]
    assert tele.quantile_label(0.999) == "p99_9"
    assert tele.quantile_label(0.99) == "p99"


# -- burn-rate window math ---------------------------------------------

def test_burn_rate_hand_fixtures():
    """Hand-computed burns: 2% loss against a 1% budget burns 2.0;
    5% of deliveries past the p99 bound burns 5.0 on the latency
    objective; budget_remaining = 1 − slow burn, floored at 0."""
    spec = SloSpec(delivery_ratio_floor=0.99, p99_bound_us=5_000.0,
                   p999_bound_us=0.0)
    hist = np.zeros(tele.N_BINS)
    hist[0] = 980.0                      # fast deliveries (≤1ms)
    v = evaluate_tenant("t", "gold", spec,
                        _row(tx=1000.0, delivered=980.0, hist=hist,
                             loss=20.0),
                        10.0, _row())
    assert v.slow_burn == pytest.approx(0.02 / 0.01)
    assert v.budget_remaining == 0.0     # 1 - 2.0, floored
    assert v.delivery_ratio == pytest.approx(0.98)
    assert not v.attainment_ok
    # latency burn: 950 in (1ms,5ms], 50 in (5ms,10ms] → 5% > 5ms
    hist2 = np.zeros(tele.N_BINS)
    hist2[1] = 950.0
    hist2[2] = 50.0
    v2 = evaluate_tenant("t", "gold", spec,
                         _row(tx=1000.0, delivered=1000.0, hist=hist2),
                         10.0, _row())
    assert v2.slow_burn == pytest.approx(0.05 / 0.01)
    assert v2.attainment_ok  # delivery fine; latency is what burns
    # parked admission backlog is unserved demand on the delivery
    # objective: 900 parked vs 100 served → 90% error frac → burn 90
    v3 = evaluate_tenant("t", "gold", spec,
                         _row(tx=100.0, delivered=100.0),
                         10.0, _row(), parked=900.0)
    assert v3.slow_burn == pytest.approx((900.0 / 1000.0) / 0.01)


def test_two_window_severity_rule():
    spec = SloSpec(warn_burn=1.0, page_burn=4.0)
    assert severity_of(spec, 0.5, 0.5) == "ok"
    assert severity_of(spec, 10.0, 0.5) == "ok"    # slow disagrees
    assert severity_of(spec, 2.0, 1.5) == "warn"
    assert severity_of(spec, 5.0, 4.5) == "page"
    assert severity_of(spec, 4.0, 100.0) == "page"


def test_qos_defaults_and_spec_validation():
    assert SloSpec.for_qos("gold").delivery_ratio_floor \
        > SloSpec.for_qos("bronze").delivery_ratio_floor
    assert SloSpec.for_qos("gold").p99_bound_us \
        < SloSpec.for_qos("bronze").p99_bound_us
    with pytest.raises(ValueError):
        SloSpec(delivery_ratio_floor=1.5)
    with pytest.raises(ValueError):
        SloSpec(fast_windows=5, slow_windows=2)
    rt = SloSpec.from_dict(SloSpec.for_qos("silver").to_dict())
    assert rt == SloSpec.for_qos("silver")


def test_verdict_censoring_tied_to_method():
    """The verdict's p99 flag describes the VALUE reported: a
    successful tail-fit p99 is a point estimate (not flagged), a
    censored clamp is flagged AND excluded from the latency_ok
    comparison — a clamp is a lower bound, so comparing it against a
    bound above the ladder would pass a tail nobody can see."""
    spec = SloSpec(delivery_ratio_floor=0.99,
                   p99_bound_us=10_000_000.0)   # bound PAST the ladder
    # exponential tail: >1% of mass past the edge, fit succeeds
    mean = 1.6e6
    hist = _analytic_hist(lambda x: np.exp(-x / mean))
    row = _row(tx=hist.sum(), delivered=hist.sum(), hist=hist)
    v = evaluate_tenant("t", "gold", spec, row, 10.0, _row())
    est, m = slo_tail.estimate_quantile(hist, 0.99)
    assert m == slo_tail.METHOD_TAIL_FIT
    assert v.p99_us == est and v.p99_censored is False
    # all mass past the edge: the fit refuses, the clamp is flagged,
    # and latency_ok is NOT decided by clamp <= bound (burn owns it)
    h2 = np.zeros(tele.N_BINS)
    h2[-1] = 1000.0
    v2 = evaluate_tenant("t", "gold", spec,
                         _row(tx=1000.0, delivered=1000.0, hist=h2),
                         10.0, _row())
    assert v2.p99_censored is True
    assert v2.p99_us == tele.BUCKET_EDGES_US[-1]
    assert v2.latency_ok  # undecidable by comparison — not a false ok
    assert v2.slow_burn > 1.0  # ...but the burn SEES the bad tail


# -- exact fleet merging -----------------------------------------------

def test_fleet_merge_bit_equal_to_single_plane():
    """Property: per-plane slices merged on the shared ladder give
    BIT-EQUAL percentiles, attainment, and burns to the single-plane
    computation over the pooled rows — for random splits."""
    rng = np.random.default_rng(3)
    spec = SloSpec(delivery_ratio_floor=0.99, p99_bound_us=100_000.0)
    for trial in range(20):
        n_planes = int(rng.integers(2, 5))
        hists = rng.integers(0, 500, size=(n_planes, tele.N_BINS)) \
            .astype(float)
        loss = rng.integers(0, 30, size=n_planes).astype(float)
        delivered = hists.sum(axis=1)
        tx = delivered + loss
        # single-plane truth over the pooled rows
        pooled = _row(tx=tx.sum(), delivered=delivered.sum(),
                      hist=hists.sum(axis=0), loss=loss.sum())
        truth = evaluate_tenant("t", "gold", spec, pooled, 30.0,
                                _row())
        # fleet merge over per-plane contributions
        contribs = [contribution(
            f"p{i}", tx[i], delivered[i], hists[i], 10.0,
            dropped_loss=loss[i]) for i in range(n_planes)]
        merged = merge_tenant("t", contribs, spec=spec)
        assert merged["delivery_ratio"] == truth.delivery_ratio
        assert merged["p99_us"] == truth.p99_us
        assert merged["p999_us"] == truth.p999_us
        assert merged["slow_burn"] == truth.slow_burn
        assert merged["hist"] == [float(x) for x in pooled[
            tele.T_HIST0:]]
        # merged histogram == sum, bitwise
        assert np.array_equal(merge_hists(hists),
                              hists.sum(axis=0))


def test_fleet_slo_merges_frozen_and_live():
    hist_a = np.zeros(tele.N_BINS)
    hist_a[1] = 100.0
    hist_b = np.zeros(tele.N_BINS)
    hist_b[2] = 50.0
    live = {"B": [{
        "tenant": "mig", "qos": "gold",
        "spec": SloSpec.for_qos("gold").to_dict(),
        "tx": 50.0, "delivered": 50.0, "window_seconds": 5.0,
        "hist": list(hist_b), "fast_burn": 0.25,
        "throttle_backlog": 0.0,
    }]}
    frozen = [("A", "mig",
               {"tx": 100.0, "delivered": 100.0,
                "window_seconds": 10.0, "hist": list(hist_a)},
               "gold")]
    out = fleet_slo(live, frozen)
    v = out["mig"]
    assert v["fleet"] is True
    assert v["planes"] == ["B"] and v["frozen_planes"] == ["A"]
    assert v["tx"] == 150.0 and v["delivered"] == 150.0
    assert v["frozen_tx"] == 100.0
    assert v["window_seconds"] == 15.0
    assert v["fast_burn"] == 0.25       # live plane's fast window
    # merged histogram is the exact sum
    assert v["hist"] == list(hist_a + hist_b)


# -- autopilot hook ----------------------------------------------------

def test_guardrails_from_slo():
    from kubedtn_tpu.updates.gate import Guardrails

    spec = SloSpec.for_qos("gold")        # floor 0.999, p99 20ms
    g = Guardrails.from_slo(spec)
    assert g.max_delivery_drop == pytest.approx(0.001)
    assert g.max_p99_us == 20_000.0
    # absolute SLO cap binds regardless of baseline
    ok, why = g.check(1.0, 25_000.0, 1.0, 24_000.0)
    assert not ok and "SLO bound" in why
    ok, _ = g.check(1.0, 15_000.0, 1.0, 14_000.0)
    assert ok
    # a verdict scales the allowed drop by the remaining budget
    v = evaluate_tenant("t", "gold", spec,
                        _row(tx=1000.0, delivered=999.5,
                             hist=np.eye(tele.N_BINS)[0] * 999.5),
                        10.0, _row())
    g2 = Guardrails.from_slo(v)
    assert g2.max_p99_us == 20_000.0
    assert g2.max_delivery_drop \
        == pytest.approx(0.001 * v.budget_remaining)
    # overrides pass through
    assert Guardrails.from_slo(spec, ticks=100).ticks == 100


def test_guardrails_from_slo_property():
    """Property: EVERY SloSpec — the three QoS defaults plus seeded
    random specs, with and without overrides — maps to guardrails
    that ACCEPT a no-op plan (the candidate holds the healthy
    baseline) and REJECT a candidate whose delivery falls through
    the spec's own floor or whose p99 breaks the spec's bound."""
    from kubedtn_tpu.updates.gate import Guardrails

    rng = np.random.default_rng(19)
    specs = [SloSpec.for_qos(q) for q in ("gold", "silver", "bronze")]
    for _ in range(25):
        specs.append(SloSpec(
            delivery_ratio_floor=float(rng.uniform(0.9, 0.9999)),
            p99_bound_us=float(rng.uniform(5_000.0, 1_000_000.0))))
    for spec in specs:
        for overrides in ({}, {"ticks": 123, "seed": 9,
                               "min_p99_slack_us": 250.0}):
            g = Guardrails.from_slo(spec, **overrides)
            for k, val in overrides.items():
                assert getattr(g, k) == val
            # the thresholds ARE the spec's promises
            assert g.max_delivery_drop == pytest.approx(
                1.0 - spec.delivery_ratio_floor, abs=1e-6)
            assert g.max_p99_us == spec.p99_bound_us
            healthy_p99 = spec.p99_bound_us * 0.5
            # a no-op plan (candidate == healthy baseline) passes
            ok, why = g.check(1.0, healthy_p99, 1.0, healthy_p99)
            assert ok, (spec, why)
            # delivery through the spec's floor is rejected
            ok, why = g.check(spec.delivery_ratio_floor - 1e-4,
                              healthy_p99, 1.0, healthy_p99)
            assert not ok and "delivery" in why, (spec, why)
            # the absolute p99 bound binds regardless of baseline
            ok, why = g.check(1.0, spec.p99_bound_us * 1.01,
                              1.0, spec.p99_bound_us * 1.01)
            assert not ok and "SLO bound" in why, (spec, why)


# -- evaluator over a live plane (tier-1 smoke, <30s) -------------------

def test_evaluator_live_plane_smoke():
    """The rollover-triggered sidecar over a REAL running plane: wall
    clock windows close, the evaluator fires per rollover (never per
    tick), and the verdict reads healthy for a lossless tenant."""
    import time as _time

    daemon, plane, reg, win, wout = _one_tenant_plane(
        window_s=0.25, latency="2ms", dt_us=1000.0)
    ev = SloEvaluator(reg, plane).attach(daemon)
    ev.start(poll_s=0.05)
    plane.start()
    try:
        deadline = _time.monotonic() + 20.0
        while (_time.monotonic() < deadline
               and ev.stats.snapshot()["evaluations"] < 3):
            for w in win:
                w.ingress.extend([b"\x00" * 60] * 20)
            _time.sleep(0.05)
        snap = ev.stats.snapshot()
        assert snap["evaluations"] >= 3, snap
        # rollover-triggered, not tick-triggered
        assert snap["evaluations"] <= plane.telemetry.windows_closed + 1
        vs = ev.verdicts()
        assert "t0" in vs
        v = vs["t0"]
        assert v.delivery_ratio == pytest.approx(1.0)
        assert v.severity == "ok" and v.ok
        assert v.p99_us is not None and v.p99_us < 20_000.0
    finally:
        ev.stop()
        plane.stop()


def test_observe_slo_rpc_over_the_wire():
    import grpc  # noqa: F401

    from kubedtn_tpu.wire.client import DaemonClient
    from kubedtn_tpu.wire.server import make_server

    daemon, plane, reg, win, wout = _one_tenant_plane(window_s=0.05)
    ev = SloEvaluator(reg, plane).attach(daemon)
    srv, port = make_server(daemon, port=0, host="127.0.0.1",
                            log_rpcs=False)
    srv.start()
    t = 100.0
    try:
        for _ in range(100):
            for w in win:
                w.ingress.extend([b"\x00" * 60] * 3)
            t += 0.002
            plane.tick(now_s=t)
        plane.flush()
        plane.tick(now_s=t + 1.0)
        client = DaemonClient(f"127.0.0.1:{port}")
        try:
            resp = client.ObserveSLO(pb.ObserveSLORequest(),
                                     timeout=10.0)
        finally:
            client.close()
        assert resp.ok, resp.error
        assert len(resp.tenants) == 1
        row = resp.tenants[0]
        assert row.tenant == "t0" and row.qos == "gold"
        assert row.delivery_ratio == pytest.approx(1.0)
        assert row.severity == "ok"
        assert row.delivery_ratio_floor == pytest.approx(0.999)
        assert list(row.hist)  # the mergeable ladder slice rides along
        assert resp.windows_closed >= 1
        # tenant filter
        resp2 = daemon.ObserveSLO(
            pb.ObserveSLORequest(tenant="nope"), None)
        assert resp2.ok and len(resp2.tenants) == 0
    finally:
        srv.stop(0)
        plane.stop()
        ev.stop()


def test_observe_links_carries_censored_flag():
    daemon, plane, reg, win, wout = _one_tenant_plane(window_s=10.0)
    t = 100.0
    for _ in range(30):
        win[0].ingress.extend([b"\x00" * 60] * 5)
        t += 0.002
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 11.0)
    rows, _secs, _tr = plane.telemetry.link_rows(daemon.engine)
    assert rows and rows[0]["p99_censored"] is False
    resp = daemon.ObserveLinks(pb.ObserveLinksRequest(), None)
    assert resp.ok and resp.links[0].p99_censored is False
    plane.stop()


# -- continuity across live migration ----------------------------------

def _fed_plane(tenants, addr, seed=0, window_s=0.01):
    store = TopologyStore()
    engine = SimEngine(store, capacity=8 * len(tenants) + 8,
                       node_ip=addr)
    reg = TenantRegistry(engine)
    props = LinkProperties(latency="2ms")
    for ti, ns in enumerate(tenants):
        reg.create(ns)
        uid = ti * 10 + 1
        a, b = f"{ns}-a0", f"{ns}-b0"
        store.create(Topology(name=a, namespace=ns,
                              spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=uid, properties=props)])))
        store.create(Topology(name=b, namespace=ns,
                              spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=uid, properties=props)])))
        engine.setup_pod(a, ns)
        engine.setup_pod(b, ns)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=2000.0, seed=seed)
    plane.pipeline_explicit_clock = True
    plane.attach_tenancy(reg)
    plane.enable_telemetry(window_s=window_s)
    for ti, ns in enumerate(tenants):
        uid = ti * 10 + 1
        daemon._add_wire(pb.WireDef(local_pod_name=f"{ns}-a0",
                                    kube_ns=ns, link_uid=uid,
                                    intf_name_in_pod="eth1"))
        daemon._add_wire(pb.WireDef(local_pod_name=f"{ns}-b0",
                                    kube_ns=ns, link_uid=uid,
                                    intf_name_in_pod="eth1"))
    return daemon, plane, reg


def test_fleet_slo_continuous_across_migration():
    """The acceptance pin: a tenant live-migrated A→B keeps a
    CONTINUOUS fleet-level SLO view — the journal's RECONCILE-frozen
    src window slice stitches with the dst's live ring, offered ==
    frozen + live exactly, accounting mismatch 0 — and daemon A
    (which no longer hosts the tenant) serves the frozen slice over
    Local.ObserveSLO for the client-side `kdt slo --fleet` merge."""
    from kubedtn_tpu.federation import (FederationController,
                                        PlaneHandle)
    from kubedtn_tpu.federation.supervisor import FleetSupervisor

    d_a, p_a, r_a = _fed_plane(["mig", "bg"], "10.0.0.1")
    d_b, p_b, r_b = _fed_plane(["bg2"], "10.0.0.2")
    root = tempfile.mkdtemp(prefix="kdt-slo-fed-")
    fed = FederationController(root)
    fed.register(PlaneHandle("A", d_a, p_a, r_a))
    fed.register(PlaneHandle("B", d_b, p_b, r_b))
    dt = 0.002
    k = [0]
    fed_frames = [0]

    # uid = tenant_index*10 + 1 in _fed_plane's per-plane ordering;
    # the migrated wire keeps its (pod_key, uid) identity on B
    uids = {"mig": 1, "bg": 11, "bg2": 1}

    def wire(daemon, ns, side):
        return daemon.wires.get_by_key(f"{ns}/{ns}-{side}0", uids[ns])

    def tick(feed_on=None):
        k[0] += 1
        t = 100.0 + k[0] * dt
        if feed_on is not None:
            w = wire(feed_on, "mig", "a")
            w.ingress.extend([b"\x00" * 60] * 3)
            fed_frames[0] += 3
        for d, p in ((d_a, p_a), (d_b, p_b)):
            bg = "bg" if d is d_a else "bg2"
            wb = wire(d, bg, "a")
            wb.ingress.extend([b"\x00" * 60] * 2)
            p.tick(now_s=t)

    # pre-move traffic on A
    for _ in range(40):
        tick(feed_on=d_a)
    rec = fed.migrate("mig", "A", "B", settle=lambda: tick(),
                      reconcile_timeout_s=10.0)
    assert rec["state"] == "done"
    # the frozen slice exists and carries the mergeable histogram
    frozen = fed.frozen_windows(tenant="mig")
    assert len(frozen) == 1
    src, ten, win_src, _qos = frozen[0]
    assert (src, ten) == ("A", "mig")
    assert win_src["tx"] > 0 and any(win_src["hist"])
    # post-move traffic on B
    for _ in range(40):
        tick(feed_on=d_b)
    for _d, p in ((d_a, p_a), (d_b, p_b)):
        p.flush()
    tick()
    # accounting across the move reconciles exactly
    acct = fed.coordinator(rec["migration_id"]) \
        .check_accounting(fed_frames[0])
    assert acct["mismatch"] == 0.0
    # supervisor-side merge: frozen A slice + live B ring
    sup = FleetSupervisor(fed, tempfile.mkdtemp(prefix="kdt-slo-fl-"))
    sup.attach(resume_orphans=False)
    merged = sup.fleet_slo(tenant="mig")
    v = merged["mig"]
    assert v["planes"] == ["B"]
    assert v["frozen_planes"] == ["A"]
    # CONTINUITY: fleet offered == frozen pre-move + live post-move
    # (the evaluator reads CLOSED windows only — compare like for
    # like by slicing B's ring the same way)
    live_b = r_b.tenant_window(
        p_b, "mig", window=p_b.telemetry.window_sum(
            last=12, include_open=False))
    assert v["tx"] == pytest.approx(win_src["tx"] + live_b["tx"])
    assert v["delivered"] == pytest.approx(
        win_src["delivered"] + live_b["delivered"])
    assert v["frozen_tx"] == pytest.approx(win_src["tx"])
    assert v["tx"] > live_b["tx"] > 0   # both halves contribute
    # the sweep caches the same merge
    sup.sweep()
    assert "mig" in sup.last_fleet_slo()
    # daemon A answers ObserveSLO with the FROZEN row (it no longer
    # hosts the tenant) — what `kdt slo --fleet` stitches client-side
    resp_a = d_a.ObserveSLO(pb.ObserveSLORequest(tenant="mig"), None)
    assert resp_a.ok
    frozen_rows = [t for t in resp_a.tenants if t.frozen]
    assert len(frozen_rows) == 1
    assert frozen_rows[0].plane == "A"
    assert frozen_rows[0].tx == pytest.approx(win_src["tx"])
    resp_b = d_b.ObserveSLO(pb.ObserveSLORequest(tenant="mig"), None)
    assert resp_b.ok
    live_rows = [t for t in resp_b.tenants if not t.frozen]
    assert any(t.tenant == "mig" for t in live_rows)
    # frozen slices AGE OUT of the windowed view: burn/budget are
    # sliding-window quantities, so a fixed pre-move slice must not
    # depress the fleet verdict forever
    assert fed.frozen_windows(tenant="mig", max_age_s=0.0) == []
    assert len(fed.frozen_windows(tenant="mig")) == 1
    p_a.stop()
    p_b.stop()


# -- scenario self-verdict ---------------------------------------------

def test_noisy_neighbor_slo_verdict():
    """The scenario's SLO half: victim's gold objectives met, the
    over-budget aggressor's burn rate >1 while throttled (<30s)."""
    from kubedtn_tpu.scenarios import noisy_neighbor

    out = noisy_neighbor(victim_pairs=1, aggressor_pairs=1,
                         seconds=1.0, victim_rate_fps=800,
                         aggressor_rate_fps=8_000,
                         aggressor_budget_fps=800)
    assert out["victim_slo_met"], out
    assert out["victim_slo"]["severity"] == "ok"
    assert out["victim_slo"]["slow_burn"] < 1.0
    assert out["aggressor_burning"], out
    assert out["aggressor_slo"]["slow_burn"] > 1.0
    assert out["aggressor_slo"]["severity"] in ("warn", "page")
    assert out["in_guardrails"], out
