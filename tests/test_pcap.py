"""pcap capture tap: file format, filters, and the daemon/data-plane
attach points (the observability stand-in for the reference's per-wire
libpcap handles, grpcwire.go:398-409)."""

import struct

import pytest

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, TopologySpec
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.utils.pcap import (
    LINKTYPE_ETHERNET,
    PCAP_MAGIC,
    CaptureManager,
    PcapWriter,
    read_pcap,
)


def test_pcap_format_roundtrip(tmp_path):
    p = str(tmp_path / "t.pcap")
    w = PcapWriter(p)
    w.write(b"\x01" * 60, ts=1000.25)
    w.write(b"\x02" * 1500, ts=1000.5)
    w.close()
    frames = list(read_pcap(p))
    assert [f.frame for f in frames] == [b"\x01" * 60, b"\x02" * 1500]
    assert frames[0].ts == pytest.approx(1000.25, abs=1e-6)
    assert frames[1].orig_len == 1500
    # the raw global header is what external tools check
    with open(p, "rb") as f:
        magic, vmaj, vmin, _tz, _sig, snap, link = struct.unpack(
            "=IHHiIII", f.read(24))
    assert (magic, vmaj, vmin) == (PCAP_MAGIC, 2, 4)
    assert link == LINKTYPE_ETHERNET and snap == 65535


def test_pcap_snaplen_truncation(tmp_path):
    p = str(tmp_path / "s.pcap")
    w = PcapWriter(p, snaplen=100)
    w.write(b"x" * 500)
    w.close()
    (f,) = read_pcap(p)
    assert len(f.frame) == 100 and f.orig_len == 500


def test_pcap_truncated_file_raises(tmp_path):
    p = str(tmp_path / "bad.pcap")
    w = PcapWriter(p)
    w.write(b"y" * 40)
    w.close()
    data = open(p, "rb").read()
    open(p, "wb").write(data[:-10])  # cut into the frame body
    with pytest.raises(ValueError, match="truncated frame body"):
        list(read_pcap(p))


def test_capture_manager_filters(tmp_path):
    cm = CaptureManager()
    w_all = cm.open(str(tmp_path / "all.pcap"))
    w_pod = cm.open(str(tmp_path / "pod.pcap"), pod_key="default/a", uid=7)
    w_in = cm.open(str(tmp_path / "in.pcap"), direction="in")
    cm.record("default/a", 7, b"A", "in")
    cm.record("default/a", 8, b"B", "out")
    cm.record("default/b", 7, b"C", "in")
    cm.close_all()
    assert [f.frame for f in read_pcap(w_all.path)] == [b"A", b"B", b"C"]
    assert [f.frame for f in read_pcap(w_pod.path)] == [b"A"]
    assert [f.frame for f in read_pcap(w_in.path)] == [b"A", b"C"]


def test_capture_direction_validation(tmp_path):
    cm = CaptureManager()
    with pytest.raises(ValueError):
        cm.open(str(tmp_path / "x.pcap"), direction="sideways")


def _two_pod_daemon():
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    props = LinkProperties(latency="5ms")
    for name, peer in (("a", "b"), ("b", "a")):
        t = Topology(name=name, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=peer,
                 uid=1, properties=props)]))
        t.status.src_ip, t.status.net_ns = "10.0.0.1", f"/run/netns/{name}"
        t.status.links = []
        store.create(t)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    wa = daemon._add_wire(pb.WireDef(
        local_pod_name="a", kube_ns="default", link_uid=1,
        intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(
        local_pod_name="b", kube_ns="default", link_uid=1,
        intf_name_in_pod="eth1"))
    return daemon, wa, wb


def test_capture_through_data_plane(tmp_path):
    """Frames injected on pod a's wire are captured 'in' at ingestion and
    'out' when the shaped frame is delivered to pod b after the 5ms netem
    delay (deterministic ticks)."""
    from kubedtn_tpu.runtime import WireDataPlane

    daemon, wa, wb = _two_pod_daemon()
    cm = CaptureManager()
    daemon.capture = cm
    w_in = cm.open(str(tmp_path / "in.pcap"), direction="in")
    w_out = cm.open(str(tmp_path / "out.pcap"), direction="out")
    plane = WireDataPlane(daemon, dt_us=1000.0)

    frame = b"\xaa" * 120
    daemon._frame_in(wa, frame)  # the RPC ingestion path (tap point)
    t = 0.0
    for _ in range(40):
        plane.tick(now_s=t)
        t += 0.001
        if wb.egress:
            break
    assert list(wb.egress) == [frame]
    cm.close_all()
    assert [f.frame for f in read_pcap(w_in.path)] == [frame]
    assert [f.frame for f in read_pcap(w_out.path)] == [frame]


def test_no_capture_is_free(tmp_path):
    """daemon.capture is None by default and the data plane never touches
    pcap machinery (the tap is opt-in)."""
    daemon, wa, wb = _two_pod_daemon()
    assert daemon.capture is None
    from kubedtn_tpu.runtime import WireDataPlane

    plane = WireDataPlane(daemon, dt_us=1000.0)
    wa.ingress.append(b"z" * 60)
    daemon.mark_hot(wa)
    t = 0.0
    for _ in range(40):
        plane.tick(now_s=t)
        t += 0.001
        if wb.egress:
            break
    assert len(wb.egress) == 1
