"""Pause & stall observability plane (kubedtn_tpu/pauses).

- PauseLedger contract: per-cause aggregates, bounded event ring,
  tick-latency-by-cause attribution, the enabled=False dead branch;
- barrier sites report in: stage_update_round, checkpoint save,
  compact(), GC callbacks;
- the kubedtn_pause_* Prometheus surface with its cardinality cap and
  truncation guard, including scrapes racing the tick thread and an
  in-flight checkpoint save;
- Tracer.rotate_out crash-safe trace rotation;
- Local.ObservePauses and the tier-1 smoke of the bench scenario.
"""

import gc
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                   TopologySpec)
from kubedtn_tpu.pauses import CAUSES, N_TICK_BINS, PauseLedger
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

pytestmark = pytest.mark.pauses


class _SpanSink:
    def __init__(self):
        self.spans = []

    def add_span(self, name, dur_s, **meta):
        self.spans.append((name, dur_s, meta))


# -- ledger unit contract ----------------------------------------------

def test_ledger_aggregates_rows_bytes_and_events():
    led = PauseLedger(tracer=_SpanSink())
    led.record("compact", 0.25, rows=100, moved=40)
    led.record("compact", 0.05, rows=10)
    led.record("checkpoint_save", 0.1, rows=7, bytes=4096,
               path="/tmp/ck")
    c = led.causes()
    assert c["compact"]["count"] == 2
    assert c["compact"]["seconds"] == pytest.approx(0.30)
    assert c["compact"]["max_s"] == pytest.approx(0.25)
    assert c["compact"]["last_s"] == pytest.approx(0.05)
    assert c["compact"]["rows"] == 110
    assert c["checkpoint_save"]["bytes"] == 4096
    assert led.total_pause_s() == pytest.approx(0.40)
    evs = led.events()
    assert [e["cause"] for e in evs] == ["compact", "compact",
                                        "checkpoint_save"]
    assert evs[0]["moved"] == 40
    assert evs[2]["path"] == "/tmp/ck"


def test_ledger_pause_context_times_region_and_streams_span():
    sink = _SpanSink()
    led = PauseLedger(tracer=sink)
    with led.pause("staged_update", plan="default/t1", rows=3):
        time.sleep(0.01)
    c = led.causes()["staged_update"]
    assert c["count"] == 1 and c["seconds"] >= 0.01
    assert c["rows"] == 3
    # exactly ONE retro span per pause, named by cause
    assert len(sink.spans) == 1
    name, dur, meta = sink.spans[0]
    assert name == "pause:staged_update" and dur >= 0.01
    assert meta["plan"] == "default/t1"


def test_ledger_disabled_is_a_dead_branch():
    sink = _SpanSink()
    led = PauseLedger(tracer=sink, enabled=False)
    with led.pause("compact", rows=5):
        pass
    led.record("gc", 0.5)
    led.note_tick(0.001)
    assert led.causes() == {}
    assert led.events() == []
    assert led.tick_hist() == {}
    assert sink.spans == []


def test_ledger_event_ring_bounded_with_drop_counter():
    led = PauseLedger(max_events=4, tracer=_SpanSink())
    for i in range(10):
        led.record("gc", 0.001, generation=i)
    assert len(led.events()) == 4
    assert led.dropped_events == 6
    # newest survive
    assert [e["generation"] for e in led.events()] == [6, 7, 8, 9]


def test_tick_attribution_dominant_cause_and_histograms():
    led = PauseLedger(tracer=_SpanSink())
    # clean tick -> "none"
    led.note_tick(0.0005)
    # two causes since last tick: the larger-seconds one wins
    led.record("compact", 0.2)
    led.record("gc", 0.001)
    led.note_tick(0.21)
    # window cleared: next tick is clean again
    led.note_tick(0.002)
    h = led.tick_hist()
    assert set(h) == {"none", "compact"}
    assert h["none"]["count"] == 2
    assert h["compact"]["count"] == 1
    assert h["compact"]["sum_s"] == pytest.approx(0.21)
    assert len(h["compact"]["buckets"]) == N_TICK_BINS
    assert sum(h["compact"]["buckets"]) == 1
    snap = led.snapshot()
    assert snap["enabled"] and snap["tick_edges_s"]
    assert snap["causes"]["compact"]["count"] == 1


def test_cause_taxonomy_is_the_documented_one():
    assert set(CAUSES) == {
        "checkpoint_save", "checkpoint_load", "compact",
        "staged_update", "migration_fork", "migration_restore",
        "migration_cutover", "pipeline_flush", "shm_stall",
        "jit_compile", "gc"}


def test_gc_callback_records_into_registered_ledgers():
    from kubedtn_tpu.runtime import _GCTuner

    led = PauseLedger(tracer=_SpanSink())
    _GCTuner.register_ledger(led)
    _GCTuner.acquire()
    try:
        gc.collect()
    finally:
        _GCTuner.release()
    c = led.causes()
    assert c["gc"]["count"] >= 1
    ev = [e for e in led.events() if e["cause"] == "gc"][0]
    assert "generation" in ev
    # released: further collections no longer land
    n = c["gc"]["count"]
    gc.collect()
    assert led.causes()["gc"]["count"] == n


# -- plane barrier sites -----------------------------------------------

def _tiny_plane(prefix="pz", pairs=1, capacity=16):
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=capacity)
    props = LinkProperties(latency="1ms")
    for i in range(pairs):
        a, b = f"{prefix}-a{i}", f"{prefix}-b{i}"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=props)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=props)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    win, wout = [], []
    for i in range(pairs):
        win.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"{prefix}-a{i}", kube_ns="default",
            link_uid=i + 1, intf_name_in_pod="eth1")))
        wout.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"{prefix}-b{i}", kube_ns="default",
            link_uid=i + 1, intf_name_in_pod="eth1")))
    plane = WireDataPlane(daemon, dt_us=1000.0)
    plane.pipeline_explicit_clock = True
    return store, engine, daemon, plane, win, wout


def test_stage_update_round_and_compact_report_into_ledger():
    store, engine, daemon, plane, win, wout = _tiny_plane()
    plane.pauses._tracer = _SpanSink()
    ok = plane.stage_update_round(lambda: True, plan="default/pz-a0",
                                  rows=3)
    assert ok is True
    c = plane.pauses.causes()
    assert c["staged_update"]["count"] == 1
    assert c["staged_update"]["rows"] == 3
    assert engine.pauses is plane.pauses
    engine.compact()
    c = plane.pauses.causes()
    assert c["compact"]["count"] == 1
    assert c["compact"]["rows"] == 2  # both directed rows stayed live
    # tick latency attributed: next tick blames the barrier causes
    plane.tick(now_s=100.0)
    h = plane.pauses.tick_hist()
    assert sum(v["count"] for v in h.values()) == 1
    assert "none" not in h  # barrier seconds dominate this tick window
    plane.tick(now_s=100.001)
    assert plane.pauses.tick_hist()["none"]["count"] == 1


def test_checkpoint_save_attributes_cause_and_rows(tmp_path):
    from kubedtn_tpu import checkpoint

    store, engine, daemon, plane, win, wout = _tiny_plane(prefix="ck")
    plane.pauses._tracer = _SpanSink()
    checkpoint.save_live(str(tmp_path / "ck"), store, engine, plane)
    c = plane.pauses.causes()
    assert c["checkpoint_save"]["count"] == 1
    assert c["checkpoint_save"]["rows"] == 16  # engine capacity
    assert c["checkpoint_save"]["seconds"] > 0
    ev = [e for e in plane.pauses.events()
          if e["cause"] == "checkpoint_save"][0]
    assert ev["path"].endswith("/ck")


# -- Prometheus surface -------------------------------------------------

def _scrape(registry) -> str:
    from prometheus_client import generate_latest

    return generate_latest(registry).decode()


def test_pause_metrics_series_and_tick_histogram():
    from kubedtn_tpu.metrics.metrics import make_registry

    store, engine, daemon, plane, win, wout = _tiny_plane(prefix="pm")
    plane.pauses._tracer = _SpanSink()
    registry, _ = make_registry(engine, plane.counters_fn,
                                dataplane=plane)
    # no pauses yet: families exist but carry no cause series
    assert 'kubedtn_pause_seconds_total{cause=' not in _scrape(registry)
    plane.pauses.record("compact", 0.125, rows=50, bytes=2048)
    plane.tick(now_s=100.0)
    plane.tick(now_s=100.001)
    text = _scrape(registry)
    assert 'kubedtn_pause_seconds_total{cause="compact"} 0.125' in text
    assert 'kubedtn_pause_events_total{cause="compact"} 1.0' in text
    assert 'kubedtn_pause_rows_total{cause="compact"} 50.0' in text
    assert 'kubedtn_pause_bytes_total{cause="compact"} 2048.0' in text
    assert 'kubedtn_pause_max_seconds{cause="compact"} 0.125' in text
    assert "kubedtn_pause_causes_truncated 0.0" in text
    assert "kubedtn_pause_events_dropped 0.0" in text
    # tick-latency-by-cause histogram: one compact-attributed tick, one
    # clean tick, cumulative buckets with +Inf
    assert 'kubedtn_tick_latency_seconds_bucket{cause="compact",le="+Inf"} 1.0' in text
    assert 'kubedtn_tick_latency_seconds_bucket{cause="none",le="+Inf"} 1.0' in text
    assert 'kubedtn_tick_latency_seconds_count{cause="none"} 1.0' in text


def test_pause_metrics_cardinality_cap_truncation_guard():
    from kubedtn_tpu.metrics.metrics import PauseStatsCollector

    class _Plane:
        pauses = PauseLedger(tracer=_SpanSink())

    for i in range(8):
        _Plane.pauses.record(f"cause_{i:02d}", 0.001)
    fams = {f.name: f for f in
            PauseStatsCollector(_Plane(), max_causes=3).collect()}
    series = [s.labels["cause"] for s in
              fams["kubedtn_pause_seconds"].samples]
    assert len(series) == 3
    assert series == sorted(series)  # name-sorted, deterministic cap
    trunc = fams["kubedtn_pause_causes_truncated"].samples[0]
    assert trunc.value == 5.0


def test_scrape_races_tick_thread_pause_events_and_checkpoint(tmp_path):
    """Satellite: MetricsServer scraping concurrently with pause events
    landing from the tick thread AND a checkpoint save in flight — no
    torn reads (every 200 parses, counters monotonic), and a collector
    raising mid-scrape still costs THAT scrape a 500-with-reason."""
    from kubedtn_tpu import checkpoint
    from kubedtn_tpu.metrics.metrics import MetricsServer, make_registry

    store, engine, daemon, plane, win, wout = _tiny_plane(prefix="rc")
    plane.pauses._tracer = _SpanSink()
    registry, _ = make_registry(engine, plane.counters_fn,
                                dataplane=plane)

    class _Flaky:
        calls = 0

        def collect(self):
            _Flaky.calls += 1
            if _Flaky.calls % 5 == 0:
                raise RuntimeError("collector exploded mid-scrape")
            return iter(())

    registry.register(_Flaky())
    srv = MetricsServer(registry, port=0)
    srv.start()
    url = f"http://127.0.0.1:{srv.port}/metrics"
    stop = threading.Event()
    tick_err: list = []

    def tick_loop():
        t = 100.0
        while not stop.is_set():
            try:
                win[0].ingress.append(b"\x01" * 60)
                plane.tick(now_s=t)
                plane.pauses.record("gc", 0.0001, generation=2)
                t += 0.001
            except Exception as e:  # pragma: no cover
                tick_err.append(e)
                return

    thr = threading.Thread(target=tick_loop, daemon=True)
    thr.start()
    seen_500 = 0
    seconds_seen = []
    try:
        for i in range(12):
            if i == 4:
                checkpoint.save_live(str(tmp_path / f"ck{i}"), store,
                                     engine, plane)
            try:
                with urllib.request.urlopen(url, timeout=10) as resp:
                    body = resp.read().decode()
            except urllib.error.HTTPError as e:
                assert e.code == 500
                assert "scrape failed" in e.read().decode()
                seen_500 += 1
                continue
            line = [l for l in body.splitlines() if l.startswith(
                'kubedtn_pause_seconds_total{cause="gc"}')]
            if line:
                seconds_seen.append(float(line[0].rsplit(" ", 1)[1]))
    finally:
        stop.set()
        thr.join(5)
        srv.stop()
    assert not tick_err
    assert seen_500 >= 1  # the flaky collector fired at least once
    assert len(seconds_seen) >= 3
    # no torn reads: the gc pause-seconds counter is monotonic
    assert seconds_seen == sorted(seconds_seen)
    c = plane.pauses.causes()
    assert c["checkpoint_save"]["count"] == 1


# -- trace rotation -----------------------------------------------------

def test_tracer_rotate_out_appends_valid_array(tmp_path):
    from kubedtn_tpu.utils.tracing import Tracer

    tr = Tracer()
    out = tmp_path / "trace.json"
    out.write_text("")
    assert tr.rotate_out(str(out)) == 0  # nothing buffered: no write
    with tr.span("reconcile"):
        pass
    tr.add_span("pause:compact", 0.25, rows=10)
    assert tr.pending() == 2
    assert tr.rotate_out(str(out)) == 2
    assert tr.pending() == 0  # drained: a crash now loses nothing
    with tr.span("tick"):
        pass
    assert tr.rotate_out(str(out)) == 1
    # array format: valid JSON once the optional "]" is appended, and
    # rotations appended rather than overwrote
    events = json.loads(out.read_text() + "]")
    assert [e["name"] for e in events] == ["reconcile", "pause:compact",
                                          "tick"]
    assert events[1]["args"]["rows"] == 10
    assert events[1]["dur"] == pytest.approx(0.25e6, rel=1e-3)


# -- wire + CLI surface -------------------------------------------------

def test_observe_pauses_wire_roundtrip():
    from kubedtn_tpu.wire import proto as pb

    store, engine, daemon, plane, win, wout = _tiny_plane(prefix="wp")
    plane.pauses._tracer = _SpanSink()
    plane.stage_update_round(lambda: None, plan="default/wp-a0", rows=2)
    plane.pauses.record("compact", 0.5, rows=20)
    plane.tick(now_s=100.0)
    plane.tick(now_s=100.001)
    resp = daemon.ObservePauses(
        pb.ObservePausesRequest(events=10), None)
    assert resp.ok and resp.enabled
    assert resp.total_pause_s == pytest.approx(
        plane.pauses.total_pause_s())
    by_cause = {c.cause: c for c in resp.causes}
    assert by_cause["compact"].rows == 20
    assert by_cause["compact"].seconds == pytest.approx(0.5)
    # clean-tick histogram rides as pseudo-cause "none"
    assert by_cause["none"].tick_count == 1
    assert len(by_cause["none"].tick_buckets) == N_TICK_BINS
    assert list(resp.tick_edges_s)
    evs = [e for e in resp.events if e.cause == "staged_update"]
    assert evs and "plan=default/wp-a0" in evs[0].detail
    # cause filter
    resp2 = daemon.ObservePauses(
        pb.ObservePausesRequest(cause="compact"), None)
    assert [c.cause for c in resp2.causes] == ["compact"]
    # total is over ALL causes, before the filter
    assert resp2.total_pause_s == pytest.approx(resp.total_pause_s)


def test_observe_pauses_without_plane_reports_error():
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    daemon = Daemon(SimEngine(store, capacity=4))
    resp = daemon.ObservePauses(pb.ObservePausesRequest(), None)
    assert not resp.ok and "pause ledger" in resp.error


def test_kdt_pauses_renderer_and_json_payload(capsys):
    from kubedtn_tpu.cli import _pauses_payload, _render_pauses
    from kubedtn_tpu.wire import proto as pb

    resp = pb.ObservePausesResponse(
        ok=True, enabled=True, uptime_s=12.5, total_pause_s=0.75,
        causes=[
            pb.PauseCauseStat(cause="compact", count=2, seconds=0.5,
                              max_s=0.4, last_s=0.1, last_t_s=11.0,
                              rows=128, bytes=0, tick_buckets=[],
                              tick_count=1, tick_sum_s=0.4),
            pb.PauseCauseStat(cause="none", tick_buckets=[3, 1],
                              tick_count=4, tick_sum_s=0.01),
        ],
        events=[pb.PauseEvent(cause="compact", dur_s=0.4, t_s=10.0,
                              detail="moved=60 rows=128")],
        dropped_events=0, tick_edges_s=[0.001, 0.005])
    _render_pauses(resp, "127.0.0.1:51111")
    text = capsys.readouterr().out
    assert "compact" in text and "128" in text
    assert "(clean ticks)" in text
    assert "moved=60" in text
    payload = _pauses_payload(resp)
    assert payload["total_pause_s"] == pytest.approx(0.75)
    compact = [c for c in payload["causes"]
               if c["cause"] == "compact"][0]
    assert compact["seconds"] == pytest.approx(0.5)
    json.dumps(payload)  # --json output is valid JSON


# -- savail budget + scenario smoke ------------------------------------

def test_savail_gate_judges_banked_record(tmp_path):
    from kubedtn_tpu.analysis.scale.runner import _check_availability

    budget = {"availability": {
        "max_share": {"compact": 0.10, "checkpoint_save": 0.15},
        "max_single_pause_s": {"compact": 1.0},
        "hook_overhead_pct": 2.0}}
    # no record: informational, zero findings
    findings: list = []
    rep = _check_availability(tmp_path, budget, findings)
    assert not rep["present"] and findings == []
    # in-budget record
    (tmp_path / "BENCH_pauses.json").write_text(json.dumps({
        "wall_s": 10.0, "hook_overhead_pct": 0.5,
        "causes": {"compact": {"seconds": 0.5, "max_s": 0.5}}}))
    findings = []
    rep = _check_availability(tmp_path, budget, findings)
    assert rep["present"] and findings == []
    assert rep["shares"]["compact"] == pytest.approx(0.05)
    # over-share + over-single + unbudgeted cause + hook overhead
    (tmp_path / "BENCH_pauses.json").write_text(json.dumps({
        "wall_s": 10.0, "hook_overhead_pct": 3.5,
        "causes": {"compact": {"seconds": 2.0, "max_s": 1.5},
                   "mystery": {"seconds": 0.2, "max_s": 0.2}}}))
    findings = []
    _check_availability(tmp_path, budget, findings)
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 4
    assert "ate 20.0%" in msgs
    assert "worst single `compact` pause" in msgs
    assert "`mystery`" in msgs and "no `availability.max_share`" in msgs
    assert "hook overhead 3.50%" in msgs
    assert all(f.rule == "savail" for f in findings)


def test_pause_observability_scenario_smoke():
    """Tier-1 smoke of the bench scenario at tiny sizes: hook overhead
    measured, and the forced checkpoint/compact/staged-update barriers
    each attributed with cause + duration + rows."""
    from kubedtn_tpu.scenarios import pause_observability

    r = pause_observability(pairs=2, frames_per_wire=600, rounds=2,
                            load_frames_per_wire=300)
    assert r["all_attributed"], r["forced"]
    assert r["staged_ok"] and r["staged_rounds"] >= 1
    assert r["compact_moved"] >= 1  # real churn moved live rows
    for cause in ("checkpoint_save", "compact", "staged_update"):
        st = r["causes"][cause]
        assert st["count"] >= 1 and st["seconds"] > 0.0
        assert st["rows"] > 0
    assert r["tick_errors_on"] == 0 and r["tick_errors_off"] == 0
    assert isinstance(r["hook_overhead_pct"], float)
    assert r["dropped_events"] == 0
