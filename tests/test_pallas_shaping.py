"""Parity tests: the fused Pallas shaping kernel vs the vmapped reference
path (kubedtn_tpu.ops.netem.shape_step), interpret mode on CPU.

Given the same PRNG key both paths draw identical uniforms, so every output
— departure times, all six outcome flags, and the full mutable shaping
state — must agree elementwise."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models.topologies import fat_tree, load_edge_list_into_state
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import netem
from kubedtn_tpu.ops.pallas import shaping


def random_state(capacity: int, seed: int, active_frac: float = 0.9):
    """EdgeState with randomized-but-plausible properties and live state."""
    rng = np.random.default_rng(seed)
    E = capacity
    props = np.zeros((E, es.NPROP), np.float32)
    props[:, es.P_LATENCY_US] = rng.integers(0, 100_000, E)
    props[:, es.P_LATENCY_CORR] = rng.choice([0, 25, 75], E)
    props[:, es.P_JITTER_US] = rng.choice([0, 0, 1000, 5000], E)
    props[:, es.P_LOSS] = rng.choice([0, 0, 1, 25, 100], E)
    props[:, es.P_LOSS_CORR] = rng.choice([0, 50], E)
    props[:, es.P_RATE_BPS] = rng.choice([0, 20e6, 1e9, 10e9], E)
    props[:, es.P_GAP] = rng.choice([0, 0, 2, 5], E)
    props[:, es.P_DUPLICATE] = rng.choice([0, 0, 10, 50], E)
    props[:, es.P_DUPLICATE_CORR] = rng.choice([0, 30], E)
    props[:, es.P_REORDER_PROB] = rng.choice([0, 0, 25], E)
    props[:, es.P_REORDER_CORR] = rng.choice([0, 40], E)
    props[:, es.P_CORRUPT_PROB] = rng.choice([0, 0, 5], E)
    props[:, es.P_CORRUPT_CORR] = rng.choice([0, 20], E)

    state = es.init_state(capacity)
    state = dataclasses.replace(
        state,
        uid=jnp.arange(E, dtype=jnp.int32),
        src=jnp.asarray(rng.integers(0, 64, E), jnp.int32),
        dst=jnp.asarray(rng.integers(0, 64, E), jnp.int32),
        active=jnp.asarray(rng.random(E) < active_frac),
        props=jnp.asarray(props),
        tokens=jnp.asarray(rng.uniform(0, 1e6, E).astype(np.float32)),
        t_last=jnp.asarray(rng.uniform(-1e4, 0, E).astype(np.float32)),
        corr=jnp.asarray(rng.random((E, es.NCORR)).astype(np.float32)),
        pkt_count=jnp.asarray(rng.integers(0, 6, E), jnp.int32),
        backlog_until=jnp.asarray(rng.uniform(0, 1e4, E).astype(np.float32)),
    )
    return state


def assert_state_close(a: es.EdgeState, b: es.EdgeState):
    for f in dataclasses.fields(es.EdgeState):
        x = np.asarray(getattr(a, f.name))
        y = np.asarray(getattr(b, f.name))
        np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-3,
                                   err_msg=f.name)


def assert_result_equal(a: netem.ShapeResult, b: netem.ShapeResult):
    for f in dataclasses.fields(netem.ShapeResult):
        x = np.asarray(getattr(a, f.name))
        y = np.asarray(getattr(b, f.name))
        if x.dtype == bool:
            np.testing.assert_array_equal(x, y, err_msg=f.name)
        else:
            np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-2,
                                       err_msg=f.name)


def dcopy(state):
    """Deep-copy an EdgeState: shaping.shape_step donates its input, and
    these parity tests reuse/compare the original afterwards."""
    return jax.tree.map(jnp.copy, state)


@pytest.mark.parametrize("capacity,seed", [(1024, 0), (2048, 1), (8192, 2)])
def test_parity_random_states(capacity, seed):
    state = random_state(capacity, seed)
    rng = np.random.default_rng(seed + 100)
    sizes = jnp.asarray(rng.choice([64, 512, 1500], capacity)
                        .astype(np.float32))
    have = jnp.asarray(rng.random(capacity) < 0.8)
    t_arr = jnp.asarray(rng.uniform(0, 1000, capacity).astype(np.float32))
    key = jax.random.key(seed)

    ref_state, ref_res = netem.shape_step.__wrapped__(
        state, sizes, have, t_arr, key)
    pl_state, pl_res = shaping.shape_step(dcopy(state), sizes, have, t_arr, key,
                                          interpret=True)
    assert_result_equal(ref_res, pl_res)
    assert_state_close(ref_state, pl_state)


def test_parity_capacity_not_tile_multiple():
    """Capacities below / not divisible by the 64x128 tile get padded."""
    for cap in (64, 192, 1536):
        state = random_state(cap, seed=cap)
        sizes = jnp.full((cap,), 1500.0, jnp.float32)
        have = jnp.ones((cap,), bool)
        t_arr = jnp.zeros((cap,), jnp.float32)
        key = jax.random.key(7)
        ref_state, ref_res = netem.shape_step.__wrapped__(
            state, sizes, have, t_arr, key)
        pl_state, pl_res = shaping.shape_step(dcopy(state), sizes, have, t_arr, key,
                                              interpret=True)
        assert_result_equal(ref_res, pl_res)
        assert_state_close(ref_state, pl_state)


def test_parity_on_real_topology():
    """The flagship fat-tree state through both paths."""
    props = LinkProperties(latency="10ms", jitter="1ms", loss="0.5",
                           rate="1Gbit")
    el = fat_tree(8, props)
    state, rows = load_edge_list_into_state(el, capacity=1024)
    E = state.capacity
    sizes = jnp.full((E,), 1500.0, jnp.float32)
    have = jnp.asarray(np.arange(E) < len(rows))
    t_arr = jnp.zeros((E,), jnp.float32)
    key = jax.random.key(3)

    ref_state, ref_res = netem.shape_step.__wrapped__(
        state, sizes, have, t_arr, key)
    pl_state, pl_res = shaping.shape_step(dcopy(state), sizes, have, t_arr, key,
                                          interpret=True)
    assert_result_equal(ref_res, pl_res)
    assert_state_close(ref_state, pl_state)
    assert int(np.asarray(pl_res.delivered).sum()) > 0


def test_inactive_and_no_packet_lanes_untouched():
    state = random_state(1024, seed=9, active_frac=0.5)
    sizes = jnp.full((1024,), 100.0, jnp.float32)
    have = jnp.asarray(np.arange(1024) % 2 == 0)
    t_arr = jnp.zeros((1024,), jnp.float32)
    key = jax.random.key(11)
    new_state, res = shaping.shape_step(dcopy(state), sizes, have, t_arr, key,
                                        interpret=True)
    idle = ~np.asarray(have & state.active)
    assert not np.asarray(res.delivered)[idle].any()
    assert np.isinf(np.asarray(res.depart_us)[idle]).all()
    np.testing.assert_array_equal(np.asarray(new_state.tokens)[idle],
                                  np.asarray(state.tokens)[idle])
    np.testing.assert_array_equal(np.asarray(new_state.pkt_count)[idle],
                                  np.asarray(state.pkt_count)[idle])


@pytest.mark.parametrize("capacity", [1024, 3000])
def test_tiled_step_matches_dropin_with_external_uniforms(capacity):
    """The persistent-tiled kernel with external (threefry) uniforms is
    bit-identical to the drop-in pallas path AND the vmapped path for
    the same key — tiling is pure layout, not semantics."""
    state = random_state(capacity, seed=11)
    E = state.capacity
    sizes = jnp.asarray(
        np.random.default_rng(1).uniform(64, 1500, E).astype(np.float32))
    have = state.active
    t0s = jnp.zeros((E,), jnp.float32)
    key = jax.random.key(99)

    ref_state, ref_res = netem.shape_step.__wrapped__(
        jax.tree.map(lambda x: x.copy(), state), sizes, have, t0s, key)

    tstate = shaping.tile_state(state)
    u = jax.random.uniform(key, (E, netem.NU), dtype=jnp.float32)
    e_pad = tstate.tokens.shape[0] * shaping.LANE
    u_t = shaping._tiles(u, e_pad)
    sizes_t = shaping.tile_vec(sizes, tstate)
    act_t = shaping.tile_vec((have & state.active).astype(jnp.int32),
                             tstate)
    t_arr_t = shaping.tile_vec(t0s, tstate)
    tstate2, depart, flags = shaping.shape_step_tiled(
        tstate, sizes_t, act_t, t_arr_t, 0, u_t, interpret=True)
    got_state = shaping.untile_state(tstate2, state)

    assert_state_close(ref_state, got_state)
    fl = np.asarray(flags).reshape(-1)[:E]
    dep = np.asarray(depart).reshape(-1)[:E]
    ref_dep = np.asarray(ref_res.depart_us)
    fin = np.isfinite(ref_dep)
    assert np.array_equal(np.isfinite(dep), fin)
    # same tolerance as the drop-in parity tests: fused-multiply
    # contraction differs from the vmapped HLO by ~1 ULP on some lanes
    np.testing.assert_allclose(dep[fin], ref_dep[fin], rtol=1e-5,
                               atol=1e-2)
    assert np.array_equal((fl & shaping.FLAG_DELIVERED) > 0,
                          np.asarray(ref_res.delivered))
    assert np.array_equal((fl & shaping.FLAG_DROP_LOSS) > 0,
                          np.asarray(ref_res.dropped_loss))
    assert np.array_equal((fl & shaping.FLAG_DROP_QUEUE) > 0,
                          np.asarray(ref_res.dropped_queue))


def test_tiled_state_roundtrip_and_multi_step_loop():
    """tile -> N tiled steps -> untile equals N drop-in steps (external
    uniforms), i.e. the persistent layout carries the whole mutable
    state correctly across steps."""
    state = random_state(2048, seed=5)
    E = state.capacity
    sizes = jnp.full((E,), 900.0, jnp.float32)
    t0s = jnp.zeros((E,), jnp.float32)
    key = jax.random.key(3)

    ref = jax.tree.map(lambda x: x.copy(), state)
    for i in range(4):
        ref, _ = netem.shape_step.__wrapped__(
            ref, sizes, ref.active, t0s, jax.random.fold_in(key, i))

    tstate = shaping.tile_state(state)
    e_pad = tstate.tokens.shape[0] * shaping.LANE
    sizes_t = shaping.tile_vec(sizes, tstate)
    act_t = shaping.tile_vec(state.active.astype(jnp.int32), tstate)
    t_arr_t = shaping.tile_vec(t0s, tstate)
    for i in range(4):
        u = jax.random.uniform(jax.random.fold_in(key, i), (E, netem.NU),
                               dtype=jnp.float32)
        tstate, _, _ = shaping.shape_step_tiled(
            tstate, sizes_t, act_t, t_arr_t, i,
            shaping._tiles(u, e_pad), interpret=True)
    got = shaping.untile_state(tstate, state)
    assert_state_close(ref, got)


def test_tiled_prng_requires_uniforms_under_interpret():
    state = random_state(1024, seed=2)
    tstate = shaping.tile_state(state)
    z = shaping.tile_vec(jnp.zeros((state.capacity,), jnp.float32), tstate)
    a = shaping.tile_vec(jnp.zeros((state.capacity,), jnp.int32), tstate)
    with pytest.raises(ValueError, match="interpret mode"):
        shaping.shape_step_tiled(tstate, z, a, z, 7, interpret=True)


def test_tiled_prng_on_chip():
    """The on-core-PRNG tiled path on REAL TPU hardware — the one kernel
    variant interpret mode cannot execute (pltpu.prng_random_bits has no
    interpreter). Run with `KUBEDTN_TEST_PLATFORM=tpu pytest -k on_chip`;
    under the default CPU-mesh harness it skips. Pins the Mosaic cast
    route in _bits_to_uniform (uint32→f32 converts are unsupported on
    v5e — the shifted bits go through an int32 bitcast instead) and the
    uniform distribution the kernel draws from it."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU backend (KUBEDTN_TEST_PLATFORM=tpu)")
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # distribution of the in-kernel uniforms: mean ~0.5, [0, 1), and
    # per-tile PRNG streams must be independent (seeded by program_id)
    def kern(seed_ref, out_ref):
        pltpu.prng_seed(seed_ref[0], pl.program_id(0))
        bits = pltpu.prng_random_bits((256, 128))
        out_ref[...] = shaping._bits_to_uniform(bits)

    out = pl.pallas_call(
        kern,
        grid=(4,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((256, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((1024, 128), jnp.float32),
    )(jnp.asarray([1234], jnp.int32))
    u = np.asarray(out)
    assert (u >= 0.0).all() and (u < 1.0).all()
    assert abs(u.mean() - 0.5) < 0.01
    assert not (u[:256] == u[256:512]).all()

    # the full shaping step with PRNG uniforms executes and produces
    # sane outputs (finite state, flag bits within the defined set)
    state = random_state(2048, seed=3)
    sizes = jnp.asarray(
        np.random.default_rng(0).uniform(64, 1500, 2048), jnp.float32)
    tstate = shaping.tile_state(state)
    sizes_t = shaping.tile_vec(sizes, tstate)
    act_t = shaping.tile_vec(state.active.astype(jnp.int32), tstate)
    t_arr_t = shaping.tile_vec(jnp.zeros((2048,), jnp.float32), tstate)
    ts2, depart, flags = shaping.shape_step_tiled(
        tstate, sizes_t, act_t, t_arr_t, 7, interpret=False)
    jax.block_until_ready(ts2.tokens)
    assert bool(jnp.isfinite(ts2.tokens).all())
    fl = np.asarray(flags)
    assert fl.min() >= 0 and fl.max() < 64  # six defined flag bits
    # delivered frames carry a finite departure time
    delivered = (fl & shaping.FLAG_DELIVERED).astype(bool)
    dep = np.asarray(depart)
    assert np.isfinite(dep[delivered]).all()

    # fused multi-step with on-core PRNG (state crosses steps in-kernel)
    ts3, depS, flS = shaping.shape_steps_tiled(
        ts2, sizes_t, act_t, t_arr_t, 11, 8, interpret=False)
    jax.block_until_ready(ts3.tokens)
    assert bool(jnp.isfinite(ts3.tokens).all())
    flS = np.asarray(flS)
    assert flS.shape[0] == 8 and flS.min() >= 0 and flS.max() < 64
    dS = np.asarray(depS)
    dl = (flS & shaping.FLAG_DELIVERED).astype(bool)
    assert np.isfinite(dS[dl]).all()
    # per-step PRNG streams differ (fresh block per step)
    assert not (flS[0] == flS[1]).all()


@pytest.mark.parametrize("S", [2, 4])
def test_fused_multistep_matches_sequential(S):
    """shape_steps_tiled (S steps fused in one pallas_call, state
    carried in-kernel) must equal S sequential shape_step_tiled calls
    given the same per-step uniforms — exact flags, f32-exact departs
    and state."""
    state = random_state(1024, seed=9)
    rng = np.random.default_rng(2)
    sizes = jnp.asarray(rng.uniform(64, 1500, 1024), jnp.float32)
    ts0 = shaping.tile_state(dcopy(state))
    sz = shaping.tile_vec(sizes, ts0)
    ac = shaping.tile_vec(state.active.astype(jnp.int32), ts0)
    ta = shaping.tile_vec(jnp.zeros(1024, jnp.float32), ts0)
    e_pad = ts0.tokens.shape[0] * shaping.LANE
    us = [shaping._tiles(
        jax.random.uniform(jax.random.PRNGKey(100 + s),
                           (1024, netem.NU), dtype=jnp.float32), e_pad)
        for s in range(S)]

    ts_seq = shaping.tile_state(dcopy(state))
    deps, fls = [], []
    for s in range(S):
        ts_seq, d, f = shaping.shape_step_tiled(ts_seq, sz, ac, ta, 0,
                                                us[s], interpret=True)
        deps.append(np.asarray(d))
        fls.append(np.asarray(f))

    ts_fus, dS, fS = shaping.shape_steps_tiled(
        ts0, sz, ac, ta, 0, S, jnp.concatenate(us, axis=0),
        interpret=True)
    dS, fS = np.asarray(dS), np.asarray(fS)
    for s in range(S):
        np.testing.assert_array_equal(fS[s], fls[s],
                                      err_msg=f"flags step {s}")
        np.testing.assert_allclose(dS[s], deps[s], rtol=1e-6, atol=1e-3,
                                   err_msg=f"depart step {s}")
    for name in ("tokens", "t_last", "backlog", "count", "corr"):
        np.testing.assert_allclose(
            np.asarray(getattr(ts_fus, name)),
            np.asarray(getattr(ts_seq, name)),
            rtol=1e-6, atol=1e-3, err_msg=name)


def test_fused_multistep_prng_requires_uniforms_under_interpret():
    state = random_state(1024, seed=2)
    tstate = shaping.tile_state(state)
    z = shaping.tile_vec(jnp.zeros((state.capacity,), jnp.float32), tstate)
    a = shaping.tile_vec(jnp.zeros((state.capacity,), jnp.int32), tstate)
    with pytest.raises(ValueError, match="interpret mode"):
        shaping.shape_steps_tiled(tstate, z, a, z, 7, 4, interpret=True)
