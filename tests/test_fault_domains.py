"""Fault-domain layer tests: circuit breaker state machine, per-peer
sender retry/outage-buffer behavior, dispatch-failure requeue, and the
tick supervisor's degradation ladder + watchdog.

The chaos injector (kubedtn_tpu/chaos.py) drives the in-process faults;
peer faults use a hand-rolled flaky client so each transition is stepped
deterministically (no wall-clock flap schedule needed)."""

import threading
import time

import grpc
import pytest

from kubedtn_tpu import fault
from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.chaos import ChaosError, ChaosInjector
from kubedtn_tpu.runtime import WireDataPlane, _PeerSender
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb


# ---- circuit breaker state machine ----------------------------------

class FakeClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def test_breaker_closed_to_open_to_half_open_to_closed():
    clk = FakeClock()
    b = fault.CircuitBreaker(failure_threshold=3, reset_timeout_s=1.0,
                             clock=clk)
    assert b.state == fault.CLOSED and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == fault.CLOSED  # below threshold
    b.record_failure()
    assert b.state == fault.OPEN and b.opens == 1
    assert not b.allow()            # cooling down
    assert b.time_to_probe() == pytest.approx(1.0)
    clk.t = 1.5
    assert b.allow()                # probe granted
    assert b.state == fault.HALF_OPEN and b.half_opens == 1
    b.record_success()
    assert b.state == fault.CLOSED and b.closes == 1 and b.cycles == 1
    assert b.consecutive_failures == 0


def test_breaker_failed_probe_escalates_timeout():
    clk = FakeClock()
    b = fault.CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0,
                             max_reset_timeout_s=3.0, clock=clk)
    b.record_failure()
    assert b.state == fault.OPEN
    clk.t = 1.0
    assert b.allow() and b.state == fault.HALF_OPEN
    b.record_failure()              # probe failed
    assert b.state == fault.OPEN and b.opens == 2
    # doubled cooldown: not before t=3.0
    clk.t = 2.5
    assert not b.allow()
    clk.t = 3.1
    assert b.allow()
    b.record_failure()
    # capped at max_reset_timeout_s
    assert b.time_to_probe() <= 3.0 + 1e-9
    clk.t = 6.2
    assert b.allow()
    b.record_success()
    # success resets the escalation
    b.record_failure()
    assert b.time_to_probe() == pytest.approx(1.0)


def test_backoff_jitter_bounds_and_reset():
    import random

    bo = fault.Backoff(base_s=0.1, factor=2.0, max_s=0.5,
                       rng=random.Random(1))
    d0 = bo.next_delay()
    d1 = bo.next_delay()
    d2 = bo.next_delay()
    assert 0.05 <= d0 <= 0.1
    assert 0.1 <= d1 <= 0.2
    assert 0.2 <= d2 <= 0.4
    for _ in range(10):
        assert bo.next_delay() <= 0.5
    bo.reset()
    assert bo.next_delay() <= 0.1


def test_backoff_survives_thousands_of_attempts():
    """Regression: `factor ** attempt` must never overflow — a peer
    down for hours reaches thousands of retry attempts, and a dead
    sender thread would black-hole that peer forever."""
    bo = fault.Backoff(base_s=0.05, factor=2.0, max_s=2.0)
    for _ in range(5000):
        assert 0.0 < bo.next_delay() <= 2.0


def test_rate_limited_log_counts_suppressed():
    clk = FakeClock()
    rl = fault.RateLimitedLog(min_interval_s=1.0, clock=clk)
    assert rl.ready() == (True, 0)
    assert rl.ready() == (False, 0)
    assert rl.ready() == (False, 0)
    clk.t = 1.5
    assert rl.ready() == (True, 2)  # two suppressed since last fire


# ---- per-peer sender: retry, outage buffer, bulk re-latch -----------

class _RpcErr(grpc.RpcError):
    def __init__(self, code) -> None:
        self._c = code

    def code(self):
        return self._c


class FakeDaemon:
    forward_timeout_s = 0.2

    def __init__(self, client) -> None:
        self.client = client
        self.peer_bulk_ok: dict = {}
        self.forward_errors = 0
        self._l = threading.Lock()

    def _peer_wire_client(self, addr):
        return self.client

    def count_forward_errors(self, n: int) -> None:
        with self._l:
            self.forward_errors += n

    def reset_peer_bulk(self, addr: str) -> None:
        self.peer_bulk_ok.pop(addr, None)


class FlakyClient:
    """Scripted peer: `down` raises UNAVAILABLE, `bulk_ok` gates
    UNIMPLEMENTED on the bulk transport, counters record transport
    usage."""

    def __init__(self) -> None:
        self.down = False
        self.bulk_ok = True
        self.got = 0
        self.bulk_calls = 0
        self.stream_calls = 0

    def SendToBulk(self, it, timeout=None):
        self.bulk_calls += 1
        if self.down:
            raise _RpcErr(grpc.StatusCode.UNAVAILABLE)
        if not self.bulk_ok:
            raise _RpcErr(grpc.StatusCode.UNIMPLEMENTED)
        self.got += sum(len(b.packets) for b in it)

    def SendToStream(self, it, timeout=None):
        self.stream_calls += 1
        if self.down:
            raise _RpcErr(grpc.StatusCode.UNAVAILABLE)
        self.got += len(list(it))


def _sender(daemon, threshold=3, reset_s=0.05):
    return _PeerSender(
        daemon, "peer:1",
        breaker=fault.CircuitBreaker(failure_threshold=threshold,
                                     reset_timeout_s=reset_s),
        backoff=fault.Backoff(base_s=0.005, max_s=0.02))


def _pkts(n):
    return [pb.Packet(remot_intf_id=1, frame=b"x" * 40) for _ in range(n)]


def test_transient_failure_retries_without_loss():
    cl = FlakyClient()
    cl.down = True
    d = FakeDaemon(cl)
    s = _sender(d)
    try:
        s.enqueue(_pkts(50))
        time.sleep(0.3)
        # outage in progress: nothing lost, breaker open, frames buffered
        assert cl.got == 0
        assert s.buffered == 50 and s.dropped == 0
        assert s.retries > 0 and s.breaker.opens >= 1
        assert d.forward_errors == 0  # transient != failed
        cl.down = False
        assert s.wait_empty(5.0)
        assert cl.got == 50 and s.sent == 50
        assert s.breaker.state == fault.CLOSED and s.breaker.cycles >= 1
    finally:
        s.stop()


def test_outage_buffer_bound_drops_and_counts():
    cl = FlakyClient()
    cl.down = True
    d = FakeDaemon(cl)
    s = _sender(d)
    old = _PeerSender.MAX_QUEUED
    _PeerSender.MAX_QUEUED = 100
    try:
        s.enqueue(_pkts(80))
        time.sleep(0.15)  # sender drains the queue into its retry buffer
        accepted = s.enqueue(_pkts(80))
        # bound covers queued + retry-pending: only the remaining room
        assert accepted == 20
        assert s.dropped == 60
        assert s.buffered == 100
        cl.down = False
        assert s.wait_empty(5.0)
        assert cl.got == 100  # everything accepted was delivered
    finally:
        _PeerSender.MAX_QUEUED = old
        s.stop()


def test_fatal_code_drops_batch_into_forward_errors():
    class FatalClient(FlakyClient):
        def SendToBulk(self, it, timeout=None):
            raise _RpcErr(grpc.StatusCode.INVALID_ARGUMENT)

        def SendToStream(self, it, timeout=None):
            raise _RpcErr(grpc.StatusCode.INVALID_ARGUMENT)

    d = FakeDaemon(FatalClient())
    s = _sender(d)
    try:
        s.enqueue(_pkts(10))
        assert s.wait_empty(5.0)  # dropped counts as settled
        assert d.forward_errors == 10
        assert s.retries == 0  # fatal codes never retry
    finally:
        s.stop()


def test_bulk_path_regained_after_half_open_probe():
    """Satellite: the UNIMPLEMENTED stream-only latch must reset at the
    breaker's recovery probe so an upgraded peer regains SendToBulk."""
    cl = FlakyClient()
    cl.bulk_ok = False  # reference-built peer: bulk unimplemented
    d = FakeDaemon(cl)
    s = _sender(d)
    try:
        s.enqueue(_pkts(10))
        assert s.wait_empty(5.0)
        assert d.peer_bulk_ok.get("peer:1") is False  # latched stream-only
        assert cl.stream_calls >= 1
        # outage; during it the peer is upgraded to speak bulk
        cl.down = True
        s.enqueue(_pkts(10))
        time.sleep(0.3)
        cl.down = False
        cl.bulk_ok = True
        assert s.wait_empty(5.0)
        assert d.peer_bulk_ok.get("peer:1", True) is True
        assert cl.got == 20
    finally:
        s.stop()


# ---- dispatch-failure requeue + degradation ladder ------------------

def _daemon_with_pair(props=LinkProperties(latency="1ms")):
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    store.create(Topology(name="a", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="b", uid=1,
             properties=props)])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a", kube_ns="default",
                                     link_uid=1, intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="b", kube_ns="default",
                                     link_uid=1, intf_name_in_pod="eth1"))
    return daemon, wa, wb


@pytest.mark.chaos
def test_forced_dispatch_failure_requeues_frames():
    """A failed dispatch costs a tick, never the frames: the chaos
    injector forces the fused dispatch to raise, the drained frames
    requeue, and the next tick delivers every one of them."""
    daemon, wa, wb = _daemon_with_pair()
    plane = WireDataPlane(daemon, dt_us=2_000.0)
    chaos = ChaosInjector(seed=3)
    plane.attach_chaos(chaos)
    frames = [bytes([i]) * 60 for i in range(20)]
    wa.ingress.extend(frames)
    chaos.fail_next_dispatches(2)
    for i in range(2):
        with pytest.raises(ChaosError):
            plane.tick(now_s=1.0 + i * 0.002)
    assert len(wa.ingress) == 20  # requeued, FIFO, nothing lost
    plane.tick(now_s=1.004)
    plane.tick(now_s=1.2)  # past the 1ms latency
    assert list(wb.egress) == frames
    assert plane.shaped == 20
    assert chaos.injected["dispatch"] == 2


@pytest.mark.chaos
def test_completion_failure_requeues_frames():
    """The zero-loss invariant holds for ASYNC failures too: a device
    error surfacing at the pipeline's completion sync point requeues
    the job's frames (holdback) instead of dropping the dispatch."""
    daemon, wa, wb = _daemon_with_pair()
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=2)
    plane.pipeline_explicit_clock = True
    frames = [bytes([i]) * 60 for i in range(10)]
    wa.ingress.extend(frames)
    real = plane._complete

    def boom(job):
        raise RuntimeError("injected completion failure")

    plane._complete = boom
    plane.tick(now_s=1.0)  # dispatch rides the ring, not yet completed
    with pytest.raises(RuntimeError, match="injected"):
        plane.tick(now_s=1.002)  # idle tick drains the ring -> boom
    plane._complete = real
    assert plane._holdback  # requeued, not lost
    plane.tick(now_s=1.004)
    plane.tick(now_s=1.006)
    plane.tick(now_s=1.5)  # past the 1ms latency
    assert list(wb.egress) == frames
    assert plane.shaped == 10


def test_slice_retry_budget_drops_poison_slice():
    """A slice failing deterministically with a nominally-transient
    code must not wedge the peer's egress forever: after
    MAX_SLICE_RETRIES it drops into forward_errors and the buffer
    moves on."""
    class AlwaysExhausted(FlakyClient):
        def SendToBulk(self, it, timeout=None):
            raise _RpcErr(grpc.StatusCode.RESOURCE_EXHAUSTED)

        SendToStream = SendToBulk

    d = FakeDaemon(AlwaysExhausted())
    s = _PeerSender(
        d, "peer:1",
        breaker=fault.CircuitBreaker(failure_threshold=100,  # stay closed
                                     reset_timeout_s=0.01),
        backoff=fault.Backoff(base_s=0.001, max_s=0.002))
    old = _PeerSender.MAX_SLICE_RETRIES
    _PeerSender.MAX_SLICE_RETRIES = 4
    try:
        s.enqueue(_pkts(10))
        assert s.wait_empty(10.0)  # gave up within the budget
        assert d.forward_errors == 10
        assert s.retries >= 3
    finally:
        _PeerSender.MAX_SLICE_RETRIES = old
        s.stop()


@pytest.mark.chaos
def test_supervisor_degrades_and_promotes():
    """Repeated tick failures walk the ladder 0 → 1 → 2; a clean
    interval promotes back one rung at a time. (Driven through the
    supervisor entry point the runner loop calls.)"""
    daemon, _wa, _wb = _daemon_with_pair()
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=2)
    plane.degrade_after = 2
    plane.promote_after_s = 0.05
    for _ in range(2):
        plane._supervise(False)
    assert plane.degrade_level == 1 and plane.degradations == 1
    assert plane.effective_pipeline_depth == 1
    for _ in range(2):
        plane._supervise(False)
    assert plane.degrade_level == 2
    # still failing: stays at the bottom rung
    for _ in range(4):
        plane._supervise(False)
    assert plane.degrade_level == 2 and plane.degradations == 2
    time.sleep(0.06)
    plane._supervise(True)
    assert plane.degrade_level == 1 and plane.promotions == 1
    time.sleep(0.06)
    plane._supervise(True)
    assert plane.degrade_level == 0 and plane.promotions == 2
    assert plane.effective_pipeline_depth == 2


@pytest.mark.chaos
def test_runner_survives_dispatch_faults_and_degrades():
    """End to end with the real runner: every 3rd dispatch raises; the
    plane keeps delivering (requeue), tick_errors counts the faults, and
    the supervisor eventually steps the ladder down."""
    daemon, wa, wb = _daemon_with_pair()
    plane = WireDataPlane(daemon, dt_us=1_000.0, pipeline_depth=2)
    plane.degrade_after = 2
    chaos = ChaosInjector(seed=5)
    plane.attach_chaos(chaos)
    plane.start()
    try:
        # warm first (jit compile would coalesce everything into one
        # dispatch and dodge the fault plan)
        wa.ingress.extend([b"w" * 60] * 4)
        deadline = time.monotonic() + 60.0
        while len(wb.egress) < 4 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(wb.egress) == 4
        wb.egress.clear()
        chaos.fail_every_kth_dispatch(3)
        n = 0
        for _ in range(30):  # paced chunks → many separate dispatches
            wa.ingress.extend([b"q" * 60] * 10)
            n += 10
            time.sleep(0.01)
        deadline = time.monotonic() + 60.0
        while len(wb.egress) < n and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(wb.egress) == n, f"lost {n - len(wb.egress)} frames"
        assert plane.tick_errors > 0
        assert chaos.injected["dispatch"] > 0
    finally:
        plane.stop()


def test_bulk_path_regained_after_idle_reprobe():
    """A peer upgraded during a QUIET window (no outage, so no breaker
    cycle) regains the bulk path via the periodic idle re-probe."""
    cl = FlakyClient()
    cl.bulk_ok = False  # latched stream-only on first contact
    d = FakeDaemon(cl)
    s = _sender(d)
    old = _PeerSender.BULK_REPROBE_S
    _PeerSender.BULK_REPROBE_S = 0.05
    try:
        s.enqueue(_pkts(5))
        assert s.wait_empty(5.0)
        assert d.peer_bulk_ok.get("peer:1") is False
        cl.bulk_ok = True  # upgraded while idle; no failures anywhere
        time.sleep(0.1)    # past the re-probe interval
        s.enqueue(_pkts(5))
        assert s.wait_empty(5.0)
        assert d.peer_bulk_ok.get("peer:1", True) is True
        assert cl.got == 10
    finally:
        _PeerSender.BULK_REPROBE_S = old
        s.stop()


def test_new_jit_bucket_disarms_watchdog():
    """A tick dispatching a never-seen (class-mix, shape) bucket traces
    a new executable — the watchdog must treat that window as warm-up,
    not a stall (the runner re-arms after the tick completes)."""
    daemon, wa, _wb = _daemon_with_pair()
    plane = WireDataPlane(daemon, dt_us=2_000.0)
    wa.ingress.extend([b"x" * 60] * 3)
    plane.tick(now_s=1.0)  # first bucket (K pad 4)
    plane._watchdog_armed = True
    wa.ingress.extend([b"x" * 60] * 3)
    plane.tick(now_s=1.01)  # same bucket: no compile, stays armed
    assert plane._watchdog_armed
    wa.ingress.extend([b"x" * 60] * 40)
    plane.tick(now_s=1.02)  # new K bucket (pad 64): compile window
    assert not plane._watchdog_armed


def test_watchdog_counts_stalled_heartbeat():
    daemon, _wa, _wb = _daemon_with_pair()
    plane = WireDataPlane(daemon, dt_us=2_000.0)
    plane.watchdog_timeout_s = 0.1
    # fake a wedged runner: stale heartbeat, watchdog running and armed
    # (arming normally happens at the first completed tick — cold
    # compiles must not count as stalls)
    plane._heartbeat_s = time.monotonic() - 10.0
    plane._watchdog_armed = True
    plane._start_watchdog()
    try:
        deadline = time.monotonic() + 5.0
        while plane.watchdog_stalls == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert plane.watchdog_stalls > 0
        assert plane.heartbeat_age_s > plane.watchdog_timeout_s
    finally:
        plane._watchdog_stop.set()
        plane._watchdog_thread.join(timeout=2)


def test_stage_breakdown_exports_degrade_gauges():
    daemon, _wa, _wb = _daemon_with_pair()
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=2)
    pipe = plane.stage_breakdown()["pipeline"]
    assert pipe["degrade_level"] == 0
    assert pipe["effective_depth"] == 2
    plane.force_degrade(2)
    pipe = plane.stage_breakdown()["pipeline"]
    assert pipe["degrade_level"] == 2
    assert pipe["effective_depth"] == 1


def test_metrics_registry_exports_fault_series():
    """The new breaker/supervision series reach the Prometheus
    exposition (per-peer series appear once a sender exists)."""
    from prometheus_client import generate_latest

    from kubedtn_tpu.metrics.metrics import make_registry
    from kubedtn_tpu.runtime import _PeerSender as PS

    daemon, _wa, _wb = _daemon_with_pair()
    plane = WireDataPlane(daemon, dt_us=2_000.0)
    cl = FlakyClient()
    fd = FakeDaemon(cl)
    plane._peer_senders["10.0.0.9:51111"] = _sender(fd)
    try:
        registry, _ = make_registry(daemon.engine, dataplane=plane)
        body = generate_latest(registry).decode()
        for series in ("kubedtn_peer_breaker_state",
                       "kubedtn_peer_breaker_opens",
                       "kubedtn_peer_breaker_cycles",
                       "kubedtn_peer_forward_retry",
                       "kubedtn_peer_outage_buffered",
                       "kubedtn_dataplane_degrade_level",
                       "kubedtn_dataplane_effective_pipeline_depth",
                       "kubedtn_dataplane_watchdog_stalls",
                       "kubedtn_dataplane_heartbeat_age_seconds",
                       "kubedtn_dataplane_peer_forward_retries",
                       "kubedtn_dataplane_degradations",
                       "kubedtn_dataplane_promotions"):
            assert series in body, series
        assert 'peer="10.0.0.9:51111"' in body
    finally:
        sender = plane._peer_senders.pop("10.0.0.9:51111")
        sender.stop()
        assert isinstance(sender, PS)
