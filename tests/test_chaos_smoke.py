"""Fast chaos smoke (tier-1): the full fault-domain acceptance loop in
a few seconds — two real gRPC daemons, the deterministic chaos injector
flapping the cross-node peer link at 1 Hz under paced live load, zero
frames lost, breaker cycling. The bench's chaos_soak phase runs the
same scenario longer; this is the always-on regression gate."""

import logging

import pytest

from kubedtn_tpu.scenarios import chaos_soak, update_under_flap


@pytest.mark.chaos
def test_chaos_soak_smoke_no_frames_lost():
    logging.disable(logging.WARNING)  # rate-limited peer-send warnings
    try:
        r = chaos_soak(pairs=2, seconds=3.0, flap_period_s=1.0,
                       offered_frames_per_s=6_000, seed=11)
    finally:
        logging.disable(logging.NOTSET)
    assert r["frames_fed"] > 0
    # the flap actually fired and the peer link actually broke
    assert r["injected_faults"]["peer_blackhole"] > 0
    assert r["peer_retries"] > 0
    # acceptance: zero loss, zero tick errors, >=1 full breaker
    # open -> half-open -> closed cycle, nothing dropped at the buffer
    assert r["frames_lost"] == 0, r
    assert r["tick_errors"] == 0, r
    assert r["breaker_cycles"] >= 1, r["breaker"]
    assert r["peer_buffer_dropped"] == 0
    assert r["shaping_dropped"] == 0
    # round 8: the flight recorder survives the fault path — at least
    # one sampled cross-node trace shows ingress → outage-buffered →
    # retried → peer-sent on A and received on B (chaos_soak RAISES
    # when absent; these assertions document the evidence shape)
    assert r["trace_ok"], r
    assert r["trace_hops"] >= 5
    for stage in ("ingress", "outage-buffered", "retried", "peer-sent",
                  "received"):
        assert stage in r["trace_stages"], r["trace_stages"]
    assert len(r["trace_nodes"]) == 2  # both daemons contributed
    assert r["sampled_frames"] > 0


@pytest.mark.chaos
def test_update_under_flap_smoke():
    """Round 10: a planned update staged while the peer breaker is
    cycling must either complete or roll back cleanly — and the
    zero-loss accounting must hold either way (<30 s tier-1 smoke of
    the bench's update_under_flap variant)."""
    logging.disable(logging.WARNING)
    try:
        r = update_under_flap(pairs=2, seconds=3.0, flap_period_s=1.0,
                              offered_frames_per_s=4_000, gate_ticks=60,
                              seed=13)
    finally:
        logging.disable(logging.NOTSET)
    assert r["frames_fed"] > 0
    # the flap actually fired while the update staged
    assert r["injected_faults"]["peer_blackhole"] > 0
    assert r["breaker_cycles"] >= 1, r["breaker"]
    # every staged update either landed or rolled back cleanly — and
    # at least one actually went through the gate + stager
    assert r["stage_results"], r
    assert r["stages_clean"], r["stage_results"]
    # acceptance: zero loss, zero tick errors, either way
    assert r["frames_lost"] == 0, r
    assert r["tick_errors"] == 0, r


@pytest.mark.chaos
@pytest.mark.shm
@pytest.mark.requires_native_shm
def test_shm_producer_crash_smoke():
    """The shm ingest plane's crash gate (<30s tier-1 smoke of the
    LADDER's shm_producer_crash): a real producer subprocess is
    SIGKILLed mid-burst — zero committed-frame loss (delivered indices
    are an exact contiguous prefix covering every progress report),
    the torn tail is skipped only after the pid provably died, the
    dead ring retires, and a producer-minted trace id spans
    received -> ingress -> delivered across the ring."""
    from kubedtn_tpu.scenarios import shm_producer_crash

    r = shm_producer_crash(frames=1_200, kill_after=400,
                           drain_timeout_s=20.0)
    assert r["reported_at_kill"] >= 400, r
    assert r["delivered_prefix_ok"], r
    assert r["committed_lost"] == 0, r
    assert r["delivered"] >= r["reported_at_kill"], r
    assert r["torn_skipped"] > 0, r          # the gap-skip path ran
    assert r["ring_pending_final"] == 0, r
    assert r["rings_retired"] == 1, r        # dead ring retired
    assert r["trace_ok"], r                  # trace spans the ring
    assert r["tick_errors"] == 0 and r["dropped"] == 0, r
    assert r["in_guardrails"], r
