"""Multi-tenant isolation contract — the headline pin (ISSUE 10).

A tenant's delivered byte stream and telemetry totals in a COHABITED
plane (three tenants, three kernel classes, one shared SoA) are
BYTE-IDENTICAL to a SOLO plane running only that tenant's topology
with the same seed — at pipeline depths 1 and 2, unsharded and on the
8-device forced-host mesh. The mechanism is per-row fold_in keys
(ops/netem.row_keys keyed by engine.link_key_id): a row's uniforms
depend on the link's declared identity and its own frame ordinals,
never on which other tenants share the batch or how it pads.

Also here: the tenant-scoped twin fork (what-if on one tenant's slice
sees only that tenant's edges) and the per-tenant WhatIf concurrency
pool (one tenant's sweep no longer parks another's).
"""

import numpy as np
import pytest

import jax

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.tenancy import TenantRegistry
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

pytestmark = pytest.mark.tenancy

# one tenant per kernel class: slot-independent, max-plus TBF, and the
# correlated sequential scan (the classes the fused tick routes)
TENANT_PROPS = {
    "t0": LinkProperties(latency="2ms", jitter="1ms", loss="10"),
    "t1": LinkProperties(rate="2Mbit"),
    "t2": LinkProperties(latency="1ms", loss="10", loss_corr="25"),
}
PAIRS = 2


def _build_plane(tenant_names, depth=1, mesh_n=None, seed=0,
                 props_map=None):
    """One plane hosting `tenant_names`' topologies (uids and pod
    names are GLOBAL — identical between cohabited and solo builds, so
    link identities match). Returns (plane, {tenant: (wins, wouts)})."""
    from kubedtn_tpu.parallel.mesh import make_mesh
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    props_map = props_map or TENANT_PROPS
    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * PAIRS * len(props_map) + 8)
    registry = TenantRegistry(engine)
    all_names = sorted(props_map)
    for ns in tenant_names:
        registry.create(ns)
        props = props_map[ns]
        base_uid = all_names.index(ns) * PAIRS  # global uid space
        for i in range(PAIRS):
            uid = base_uid + i + 1
            a, b = f"{ns}-a{i}", f"{ns}-b{i}"
            store.create(Topology(name=a, namespace=ns,
                                  spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                     uid=uid, properties=props)])))
            store.create(Topology(name=b, namespace=ns,
                                  spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                     uid=uid, properties=props)])))
            engine.setup_pod(a, ns)
            engine.setup_pod(b, ns)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=depth,
                          seed=seed)
    plane.pipeline_explicit_clock = True
    plane.attach_tenancy(registry)
    plane.enable_telemetry(window_s=0.01, sample_period=4)
    if mesh_n is not None:
        plane.enable_sharding(make_mesh(mesh_n))
    wires = {}
    for ns in tenant_names:
        base_uid = all_names.index(ns) * PAIRS
        win, wout = [], []
        for i in range(PAIRS):
            uid = base_uid + i + 1
            win.append(daemon._add_wire(pb.WireDef(
                local_pod_name=f"{ns}-a{i}", kube_ns=ns, link_uid=uid,
                intf_name_in_pod="eth1")))
            wout.append(daemon._add_wire(pb.WireDef(
                local_pod_name=f"{ns}-b{i}", kube_ns=ns, link_uid=uid,
                intf_name_in_pod="eth1")))
        wires[ns] = (win, wout)
    return plane, registry, wires


def _tagged(ns, wire_i, j, size=64):
    tag = f"{ns}/{wire_i}".encode()
    return tag + j.to_bytes(4, "big") + b"\x00" * (size - len(tag) - 4)


def _run(tenant_names, depth=1, mesh_n=None, ticks=40,
         frames_per_tick=3, props_map=None):
    """Deterministic schedule: every tenant's every ingress wire gets
    `frames_per_tick` frames EVERY tick (an int, or a per-tenant dict
    so an aggressor can burst while the victim's schedule stays
    identical to its solo run), so the cohabited and solo planes
    dispatch on the same ticks (same key chain)."""
    fpt = (frames_per_tick if isinstance(frames_per_tick, dict)
           else {ns: frames_per_tick for ns in tenant_names})
    plane, registry, wires = _build_plane(tenant_names, depth=depth,
                                          mesh_n=mesh_n,
                                          props_map=props_map)
    t = 100.0
    dt = 0.002
    j = {ns: 0 for ns in tenant_names}
    for _ in range(ticks):
        for ns in tenant_names:
            win, _ = wires[ns]
            for k, w in enumerate(win):
                w.ingress.extend(_tagged(ns, k, j[ns] + n)
                                 for n in range(fpt[ns]))
            j[ns] += fpt[ns]
        t += dt
        plane.tick(now_s=t)
    # drain the tail deterministically
    for _ in range(60):
        t += dt
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert plane.tick_errors == 0
    delivered = {ns: [list(w.egress) for w in wires[ns][1]]
                 for ns in tenant_names}
    # per-tenant telemetry totals: summed over the tenant's rows
    total, _secs = plane.telemetry.window_sum()
    tel = {}
    for ns in tenant_names:
        rows = registry.rows_of(ns)
        tel[ns] = total[rows[rows < total.shape[0]]].sum(axis=0)
    counters = {ns: registry.tenant_counters(plane, ns)
                for ns in tenant_names}
    return delivered, tel, counters


@pytest.mark.parametrize("depth", [1, 2], ids=["d1", "d2"])
def test_cohabited_vs_solo_byte_identical(depth):
    """Three tenants sharing one plane: each tenant's per-wire
    delivered byte sequences, telemetry ring totals, and counter
    slices equal a solo plane of only its topology, bit for bit."""
    co_del, co_tel, co_cnt = _run(sorted(TENANT_PROPS), depth=depth)
    for ns in sorted(TENANT_PROPS):
        so_del, so_tel, so_cnt = _run([ns], depth=depth)
        assert co_del[ns] == so_del[ns], f"tenant {ns} byte stream"
        np.testing.assert_array_equal(co_tel[ns], so_tel[ns])
        assert co_cnt[ns] == so_cnt[ns]


def test_pad_bucket_crossing_aggressor_keeps_victim_identical():
    """An aggressor in the SAME kernel class bursting across a
    _pad_slots bucket (5 frames/tick pads K to 16; the victim's solo
    plane pads its 3 to 4) must not perturb the victim: each slot's
    uniforms come from a per-(row, slot) fold_in key, never from a
    K-shaped per-row draw whose bits shift with the batch's padded
    slot count. This is the noisy-neighbor case the headline
    byte-identity contract advertises — a constant-K schedule (the
    other tests here) cannot catch a regression in it."""
    props = {"agg": TENANT_PROPS["t0"], "vic": TENANT_PROPS["t0"]}
    for depth in (1, 2):
        co_del, co_tel, co_cnt = _run(
            ["agg", "vic"], depth=depth, props_map=props,
            frames_per_tick={"agg": 5, "vic": 3})
        so_del, so_tel, so_cnt = _run(
            ["vic"], depth=depth, props_map=props,
            frames_per_tick={"vic": 3})
        assert co_del["vic"] == so_del["vic"], f"victim bytes d{depth}"
        np.testing.assert_array_equal(co_tel["vic"], so_tel["vic"])
        assert co_cnt["vic"] == so_cnt["vic"]


def test_cohabited_mesh8_vs_solo_unsharded():
    """The same contract with the cohabited plane's SoA block-sharded
    across the 8-device forced-host mesh (solo stays unsharded — the
    sharded plane is already pinned byte-identical to the unsharded
    one, so this closes cohabited-sharded ≡ solo-unsharded)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    co_del, co_tel, _ = _run(sorted(TENANT_PROPS), depth=1, mesh_n=8,
                             ticks=25)
    for ns in sorted(TENANT_PROPS):
        so_del, so_tel, _ = _run([ns], depth=1, ticks=25)
        assert co_del[ns] == so_del[ns], f"tenant {ns} byte stream"
        np.testing.assert_array_equal(co_tel[ns], so_tel[ns])


def test_cohabited_mesh8_depth2_byte_identical():
    """Depth-2 on the 8-device mesh equals depth-1 unsharded, per
    tenant — overlap and sharding together change nothing."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    base_del, base_tel, _ = _run(sorted(TENANT_PROPS), depth=1,
                                 ticks=25)
    m8_del, m8_tel, _ = _run(sorted(TENANT_PROPS), depth=2, mesh_n=8,
                             ticks=25)
    for ns in sorted(TENANT_PROPS):
        assert m8_del[ns] == base_del[ns]
        np.testing.assert_array_equal(m8_tel[ns], base_tel[ns])


# -- tenant-scoped twin forks + per-tenant WhatIf pool -----------------

def test_tenant_snapshot_scopes_edges():
    plane, registry, _wires = _build_plane(sorted(TENANT_PROPS))
    snap = registry.tenant_snapshot(plane, "t1")
    rows = registry.rows_of("t1")
    active = np.asarray(snap.sim.edges.active)
    assert active[rows].all()
    others = np.setdiff1d(np.arange(active.shape[0]), rows)
    assert not active[others].any()
    plane.stop()


def test_whatif_per_tenant_slots_do_not_share():
    from kubedtn_tpu.twin.query import _sweep_slots

    class Dummy:
        pass

    d = Dummy()
    a = _sweep_slots(d, "t0")
    b = _sweep_slots(d, "t1")
    shared = _sweep_slots(d, "")
    assert a is not b and a is not shared
    # tenant A's slot held: tenant B still acquires immediately
    assert a.acquire(blocking=False)
    try:
        assert b.acquire(blocking=False)
        b.release()
    finally:
        a.release()


def test_whatif_tenant_scoped_sweep():
    from kubedtn_tpu.twin.query import serve_whatif
    from kubedtn_tpu.wire import proto as pb

    plane, _registry, _wires = _build_plane(sorted(TENANT_PROPS))
    daemon = plane.daemon
    resp = serve_whatif(daemon, pb.WhatIfRequest(
        ticks=20, include_baseline=True, tenant="t0"))
    assert resp.ok, resp.error
    assert len(resp.results) == 1
    resp2 = serve_whatif(daemon, pb.WhatIfRequest(
        ticks=20, include_baseline=True, tenant="nope"))
    assert not resp2.ok and "unknown tenant" in resp2.error
    plane.stop()
