"""Tests for Topology API types: schema parity, validation, YAML loading."""

import os

import pytest

from kubedtn_tpu.api.types import (
    Link,
    LinkProperties,
    Topology,
    links_equal_without_properties,
    load_yaml,
)

THREE_NODE_YAML = """
apiVersion: v1
kind: List
items:
  - apiVersion: y-young.github.io/v1
    kind: Topology
    metadata:
      name: r1
    spec:
      links:
        - uid: 1
          peer_pod: r2
          local_intf: eth1
          peer_intf: eth1
          local_ip: 12.12.12.1/24
          peer_ip: 12.12.12.2/24
        - uid: 2
          peer_pod: r3
          local_intf: eth2
          peer_intf: eth1
          local_ip: 13.13.13.1/24
          peer_ip: 13.13.13.3/24
          properties:
            latency: 10ms
            rate: 100Mbit
  - apiVersion: y-young.github.io/v1
    kind: Topology
    metadata:
      name: r2
    spec:
      links:
        - uid: 1
          peer_pod: r1
          local_intf: eth1
          peer_intf: eth1
          local_ip: 12.12.12.2/24
          peer_ip: 12.12.12.1/24
  - apiVersion: v1
    kind: Pod
    metadata:
      name: r1
"""


def test_load_yaml_list():
    topos = load_yaml(THREE_NODE_YAML)
    assert [t.name for t in topos] == ["r1", "r2"]
    r1 = topos[0]
    assert len(r1.spec.links) == 2
    assert r1.spec.links[0].uid == 1
    assert r1.spec.links[1].properties.latency == "10ms"
    assert r1.status.links is None  # first-seen semantics preserved


def test_numeric_conversion():
    props = LinkProperties(latency="10ms", jitter="1ms", loss="25.5",
                           rate="100Mbit", gap=5)
    n = props.to_numeric()
    assert n["latency_us"] == 10_000
    assert n["jitter_us"] == 1_000
    assert n["loss"] == pytest.approx(25.5)
    assert n["rate_bps"] == 100_000_000
    assert n["gap"] == 5


def test_equal_without_properties():
    a = Link(local_intf="eth1", peer_intf="eth1", peer_pod="r2", uid=1,
             properties=LinkProperties(latency="10ms"))
    b = Link(local_intf="eth1", peer_intf="eth1", peer_pod="r2", uid=1,
             properties=LinkProperties(latency="50ms"))
    c = Link(local_intf="eth2", peer_intf="eth1", peer_pod="r2", uid=1)
    assert links_equal_without_properties(a, b)
    assert not links_equal_without_properties(a, c)


def test_validation():
    Link(local_intf="eth1", peer_intf="eth1", peer_pod="r2", uid=1,
         local_ip="10.0.0.1/24", local_mac="00:00:5e:00:53:01").validate()
    with pytest.raises(ValueError):
        Link(local_intf="e", peer_intf="e", peer_pod="p", uid=1,
             local_ip="999.0.0.1").validate()
    with pytest.raises(ValueError):
        Link(local_intf="e", peer_intf="e", peer_pod="p", uid=1,
             local_mac="zz:00:5e:00:53:01").validate()
    with pytest.raises(ValueError):
        LinkProperties(latency="10 ms").validate()
    with pytest.raises(ValueError):
        LinkProperties(loss="101").validate()


def test_special_peers():
    mv = Link(local_intf="eth1", peer_intf="eth0", peer_pod="localhost", uid=1)
    assert mv.is_macvlan()
    ph = Link(local_intf="eth1", peer_intf="eth0",
              peer_pod="physical/10.0.0.5", uid=2)
    assert ph.is_physical()
    assert ph.physical_peer_ip() == "10.0.0.5"


def test_manifest_roundtrip():
    topos = load_yaml(THREE_NODE_YAML)
    r1 = topos[0]
    m = r1.to_manifest()
    r1b = Topology.from_manifest(m)
    assert r1b.spec == r1.spec
    assert r1b.name == r1.name


def test_load_reference_sample_if_present():
    path = "/root/reference/config/samples/3node.yml"
    if not os.path.exists(path):
        pytest.skip("reference samples not mounted")
    topos = load_yaml(path)
    assert [t.name for t in topos] == ["r1", "r2", "r3"]
    # full-mesh: uids {1,2,3}, two links per pod
    assert all(len(t.spec.links) == 2 for t in topos)
    uids = {l.uid for t in topos for l in t.spec.links}
    assert uids == {1, 2, 3}


def test_link_with_properties_matches_replace():
    import dataclasses

    l = Link(local_intf="eth1", peer_intf="eth2", peer_pod="q", uid=9,
             local_ip="10.0.0.1/24", properties=LinkProperties(latency="5ms"))
    p = LinkProperties(rate="1Gbit")
    fast = l.with_properties(p)
    slow = dataclasses.replace(l, properties=p)
    assert fast == slow
    assert fast.properties is p
    assert fast.uid == 9 and fast.local_ip == "10.0.0.1/24"
    assert l.properties.latency == "5ms"  # original untouched
    assert hash(fast) == hash(slow)
