"""CRD rendering + config/ manifest parity tests.

The CRD manifest is rendered from the same regex constants the Python
loader validates with (kubedtn_tpu/api/crd.py), so these tests pin both
directions: the rendered schema matches the reference CRD's shape
(reference cni.yaml:14-280 — group, names, status subresource, validation
patterns from api/v1/topology_types.go:65-175), and every checked-in
sample passes the schema's own patterns.
"""

import os
import re
import subprocess
import sys

import yaml

from kubedtn_tpu.api import crd as C
from kubedtn_tpu.api import types as T
from kubedtn_tpu.api.types import load_yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_crd_identity():
    d = C.render_crd()
    assert d["metadata"]["name"] == "topologies.y-young.github.io"
    spec = d["spec"]
    assert spec["group"] == "y-young.github.io"
    assert spec["names"]["kind"] == "Topology"
    assert spec["names"]["plural"] == "topologies"
    assert spec["scope"] == "Namespaced"
    (ver,) = spec["versions"]
    assert ver["name"] == "v1"
    assert ver["storage"] and ver["served"]
    # status must be a subresource — the CNI-vs-controller status race
    # discipline depends on the split endpoints.
    assert ver["subresources"] == {"status": {}}


def test_crd_patterns_are_the_loader_patterns():
    schema = C.topology_schema()
    link = schema["properties"]["spec"]["properties"]["links"]["items"]
    props = link["properties"]["properties"]["properties"]
    assert link["properties"]["local_ip"]["pattern"] == T.IP_PATTERN.pattern
    assert link["properties"]["local_mac"]["pattern"] == T.MAC_PATTERN.pattern
    assert props["loss"]["pattern"] == T.PERCENTAGE_PATTERN.pattern
    assert props["latency"]["pattern"] == T.DURATION_PATTERN.pattern
    assert props["rate"]["pattern"] == T.RATE_PATTERN.pattern
    assert link["required"] == ["local_intf", "peer_pod", "uid"]
    # every LinkProperties dataclass field appears in the schema
    assert set(props) == set(T.LinkProperties.__dataclass_fields__)


def test_checked_in_crd_is_current():
    """config/crd/topologies.yaml must match `make crd` output."""
    path = os.path.join(REPO, "config", "crd", "topologies.yaml")
    with open(path) as f:
        on_disk = yaml.safe_load(f)
    assert on_disk == C.render_crd(), "run `make crd` to regenerate"


def _validate_against_schema(topo_manifest):
    """Minimal structural check of a manifest against the rendered schema's
    patterns and required fields (no external jsonschema dependency)."""
    link_schema = C.link_schema()
    for link in topo_manifest.get("spec", {}).get("links", []):
        for req in link_schema["required"]:
            assert req in link, (topo_manifest["metadata"]["name"], req)
        for fld, sub in link_schema["properties"].items():
            if fld not in link or fld == "properties":
                continue
            if "pattern" in sub:
                assert re.match(sub["pattern"], str(link[fld])), (fld, link[fld])
        for pfld, pval in (link.get("properties") or {}).items():
            sub = link_schema["properties"]["properties"]["properties"][pfld]
            if "pattern" in sub:
                assert re.match(sub["pattern"], str(pval)), (pfld, pval)


def _sample_paths():
    root = os.path.join(REPO, "config", "samples")
    return [os.path.join(root, f) for f in sorted(os.listdir(root))
            if f.endswith((".yml", ".yaml")) and f != "physical-host.yaml"]


def test_native_samples_load_validate_and_match_schema():
    assert _sample_paths(), "no samples checked in"
    for path in _sample_paths():
        topos = load_yaml(path)
        assert topos, path
        for t in topos:
            t.validate()
            _validate_against_schema(t.to_manifest())


def test_ring4_sample_reconciles_and_pings():
    """End-to-end: apply the ring sample, reconcile, ping around the ring."""
    from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

    topos = load_yaml(os.path.join(REPO, "config", "samples", "ring4.yaml"))
    assert len(topos) == 4
    store = TopologyStore()
    engine = SimEngine(store)
    rec = Reconciler(store, engine)
    for t in topos:
        store.create(t)
        engine.setup_pod(t.name, t.namespace)
    rec.drain()
    # all four links live on device as directed row pairs
    assert engine.num_active == 8
    # ping across the geo hop: RTT at least 2 × the 40ms one-way latency
    out = engine.ping("sat-a", "sat-b", uid=11)
    assert out["reachable"] and out["rtt_us"] >= 2 * 40_000


def test_reference_samples_still_load_unmodified():
    """The reference's own sample files parse through the same loader
    (capability parity — reference config/samples/)."""
    import pytest

    ref = "/root/reference/config/samples"
    if not os.path.isdir(ref):
        pytest.skip("reference tree not present")
    for name in ("3node.yml", "tc/latency.yaml", "tc/bandwidth.yaml"):
        topos = load_yaml(os.path.join(ref, name))
        assert topos
        for t in topos:
            t.validate()


def test_cli_crd_subcommand_roundtrips():
    out = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.cli", "crd"],
        capture_output=True, text=True, cwd=REPO, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert yaml.safe_load(out.stdout) == C.render_crd()
