"""Seeded mutation fixtures for the dtnverify passes.

Each function re-introduces one HISTORICAL bug shape at the IR level —
the exact classes the jaxpr passes exist to catch (ARCHITECTURE.md
"Enforced invariants", lineage column). The test suite traces each
mutant and asserts its pass KILLS it while the real tree stays clean;
a pass that stops killing its mutant has rotted.

Loaded by path (importlib) from tests/test_jaxpr_verify.py — never on
the package import path, so dtnlint's AST passes do not scan it.
"""

import jax
import jax.numpy as jnp
from jax import lax


def mutant_raw_key(x):
    """PR 6's engine.ping bug: a raw `jax.random.key(seed)` minted
    INSIDE the traced program — every call replays the same stream.
    Killed by jkey (random_seed in traced code) and jops (denied
    primitive)."""
    k = jax.random.key(42)
    return x + jax.random.uniform(k, x.shape)


def mutant_unsplit_key(key, x):
    """The PR 3 vmap-drift class: a key ARGUMENT consumed raw by the
    sampler — no split/fold_in between the tick key and the draw, so
    two call sites sharing the key draw identical bits. Killed by
    jkey."""
    return x + jax.random.uniform(key, x.shape)


def clean_key_use(key, x):
    """The contract-conforming shape: fold_in then sample."""
    k = jax.random.fold_in(key, 7)
    return x + jax.random.uniform(k, x.shape)


def mutant_f32_anchor(clock_us, soa):
    """The PR 3 clock-freeze class, at the IR level: an f64 wall-clock
    anchor truncated to f32 inside traced code and scattered into the
    f32 SoA — past ~2.4 h of µs uptime the f32 clock stops advancing.
    Trace under `jax.experimental.enable_x64` with an f64 `clock_us`.
    Killed by jdtype (truncating cast + tainted scatter)."""
    t32 = clock_us.astype(jnp.float32)
    return soa.at[jnp.int32(0)].set(t32[0])


def clean_anchor_use(clock_us, soa):
    """The contract-conforming shape: form the RELATIVE time in f64,
    then narrow the small delta."""
    rel = clock_us - clock_us[0]
    return soa.at[jnp.int32(0)].set(rel[0].astype(jnp.float32))


def make_mutant_mailbox_arith(mesh, axis):
    """The select-combine violation: the ring exchange merges foreign
    mailbox bits with ARITHMETIC (`acc + rf * flag`) instead of the
    ownership select — one FMA rounding and the N-shard plane is no
    longer bit-identical to the 1-shard plane. Killed by jshard."""
    from kubedtn_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    n = int(mesh.devices.size)
    perm = [(s, (s + 1) % n) for s in range(n)]

    def body(fmail, imail):
        acc = fmail
        rf, ri = fmail, imail
        for _ in range(n - 1):
            rf = lax.ppermute(rf, axis, perm)
            ri = lax.ppermute(ri, axis, perm)
            flag = (ri[:, :1] > 0).astype(fmail.dtype)
            acc = acc + rf * flag   # the mutation: arithmetic combine
        return acc

    return shard_map(body, mesh=mesh, in_specs=(P(), P()),
                     out_specs=P())


def make_mutant_mailbox_cast_arith(mesh, axis):
    """The laundered variant: the arithmetic combine hidden behind a
    leading dtype cast (`ri.astype(f32)` then FMA). A taint pass that
    lets `convert_element_type` consume taint misses this; jshard must
    still kill it."""
    from kubedtn_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    n = int(mesh.devices.size)
    perm = [(s, (s + 1) % n) for s in range(n)]

    def body(fmail, imail):
        acc = fmail
        rf, ri = fmail, imail
        for _ in range(n - 1):
            rf = lax.ppermute(rf, axis, perm)
            ri = lax.ppermute(ri, axis, perm)
            flag_f = ri[:, :1].astype(fmail.dtype)  # cast, THEN math
            acc = acc + rf * flag_f
        return acc

    return shard_map(body, mesh=mesh, in_specs=(P(), P()),
                     out_specs=P())


def make_clean_mailbox(mesh, axis):
    """The real exchange's select-combine, for the clean control."""
    from kubedtn_tpu.parallel.exchange import make_ring_exchange

    n = int(mesh.devices.size)
    from kubedtn_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    exch = make_ring_exchange(n, axis, use_dma=False)
    return shard_map(lambda f, i: exch(f, i), mesh=mesh,
                     in_specs=(P(), P()), out_specs=(P(), P()))


# -- cross-tenant scatter (jtenant / tenant-isolation audit) -----------


def mutant_cross_tenant_scatter(soa, rows, updates):
    """The tenant-isolation violation: the write-back scatter lands on
    `rows + stride` — an arithmetic SHIFT of the dispatch's row
    indices, which can relocate one tenant's state write into another
    tenant's edge block while every per-tenant counter still balances.
    Killed by jtenant (index arithmetic with no axis-offset
    provenance reaching a scatter)."""
    shifted = rows + jnp.int32(8)   # the mutation: cross-range shift
    return soa.at[shifted].set(updates, mode="drop")


def clean_tenant_scatter(soa, rows, valid, updates):
    """The contract-conforming shape: padding rows select the
    out-of-bounds sentinel (select, not arithmetic) and the scatter
    drops them."""
    tgt = jnp.where(valid, rows, jnp.int32(soa.shape[0]))
    return soa.at[tgt].set(updates, mode="drop")


# -- the un-fused two-dispatch tick (jcost / dispatch counting) --------

@jax.jit
def _half_tick_a(x):
    return x * 2.0


@jax.jit
def _half_tick_b(x):
    return x + 1.0


def mutant_two_dispatch_tick(x):
    """The fusion regression: what used to be ONE fused device program
    now crosses the host between two jitted dispatches. Killed by the
    jcost dispatch gate (dispatches per tick pinned in
    COST_BUDGET.json)."""
    y = _half_tick_a(x)
    return _half_tick_b(y)
