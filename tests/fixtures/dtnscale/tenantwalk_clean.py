"""dtnscale fixture: the incrementally-maintained counter form of the
reserved-rows accounting — O(1) per read. Silent under an
O(rows_touched) budget. Parsed, never imported."""


def ensure_capacity(self, extra):
    need = self.num_active + extra
    need += self._reserved_free_n
    return need
