"""dtnscale fixture: a seeded O(capacity) walk inside a tick-path
helper — the shape of the historical `set(engine._shaped_rows)`
per-dispatch copy. The capacity-classified loop must be killed under
an O(rows_touched) budget. Parsed, never imported."""


def dispatch_inner(self, inputs):
    batches = []
    for wire, lens in inputs:  # rows_touched: the drained batch
        row = self._rows.get((wire.pod_key, wire.uid))
        if row is not None:
            batches.append((wire, row, lens))
    shaped = set()
    # the seeded offender: host work scaling with plane size on the
    # steady tick
    for row in range(self._state.capacity):
        if self.is_shaped(row):
            shaped.add(row)
    return batches, shaped
