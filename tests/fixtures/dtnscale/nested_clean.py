"""dtnscale fixture: the single-pass reclaim — every journaled row
leaves the free list in ONE vectorized pass after the per-image
replay. Silent under an O(capacity) budget. Parsed, never
imported."""

import numpy as np


def rollback(self, entries):
    doomed = []
    for images in entries:
        doomed.extend(images)
    self._free.remove_rows(np.asarray(doomed, np.int64))
    return len(entries)
