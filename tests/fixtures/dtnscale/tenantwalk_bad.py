"""dtnscale fixture: the historical `reserved_free()` shape — an
O(tenants) registry walk re-derived on a barrier path budgeted
O(rows_touched). Parsed, never imported."""


def ensure_capacity(self, extra):
    need = self.num_active + extra
    need += sum(len(t.block_free) for t in self._tenants.values())
    return need
