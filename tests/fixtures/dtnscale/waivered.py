"""dtnscale fixture: a capacity walk carrying a scost waiver — the
designated-slow-path escape hatch. Reported AND waived, with the
reason in the artifact. Parsed, never imported."""


# dtnlint: scost-ok(namespace-binding slow path: runs once per tenant create/delete, never on the steady tick)
def rebuild_masks(self):
    owners = {}
    for (pod_key, _uid), row in self._rows.items():
        owners[row] = pod_key.partition("/")[0]
    return owners
