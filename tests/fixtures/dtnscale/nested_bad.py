"""dtnscale fixture: a capacity-classified walk nested inside a
per-row loop — the rollback-reclaim shape that made large rollbacks
O(rows × free-list). Superlinear: flagged even under an O(capacity)
budget. Parsed, never imported."""


def rollback(self, entries):
    for images in entries:
        doomed = set(images)
        self._free = [r for r in self._free if r not in doomed]
    return len(entries)
