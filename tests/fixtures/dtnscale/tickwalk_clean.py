"""dtnscale fixture: the batch-scoped form of the tick-path helper —
shaped verdicts resolved only for this dispatch's rows. Must stay
silent under an O(rows_touched) budget. Parsed, never imported."""


def dispatch_inner(self, inputs):
    batches = []
    for wire, lens in inputs:  # rows_touched: the drained batch
        row = self._rows.get((wire.pod_key, wire.uid))
        if row is not None:
            batches.append((wire, row, lens))
    shaped = {row for _w, row, _l in batches if self.is_shaped(row)}
    return batches, shaped
