"""dtnscale fixture: per-element free-list scans — `row in _free`
membership and `_free.remove(row)` are O(capacity) per call, and the
enclosing per-row loop makes the reclaim quadratic. Flagged
regardless of budget. Parsed, never imported."""


def reclaim(self, rows):
    for row in rows:
        if row in self._free:
            self._free.remove(row)
    return len(rows)
