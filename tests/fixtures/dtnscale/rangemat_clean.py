"""dtnscale fixture: the columnar free-list rebuild (one vectorized
arange) — silent at any budget. Parsed, never imported."""

import numpy as np


def compact(self):
    n = self.num_active
    cap = self._state.capacity
    self._free = np.arange(cap - 1, n - 1, -1, dtype=np.int32)
    return n
