"""dtnscale fixture: materializing an O(capacity) Python collection —
the historical free-list rebuild. Flagged REGARDLESS of budget (even
an O(capacity)-budget entry must keep linear passes columnar).
Parsed, never imported."""


def compact(self):
    n = self.num_active
    cap = self._state.capacity
    self._free = list(range(cap - 1, n - 1, -1))
    return n
