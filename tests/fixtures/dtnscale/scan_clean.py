"""dtnscale fixture: the vectorized reclaim — one `remove_rows` mask
pass over the columnar free list. Silent. Parsed, never imported."""

import numpy as np


def reclaim(self, rows):
    self._free.remove_rows(np.asarray(rows, np.int64))
    return len(rows)
