"""Known-bad fixture for the lock-discipline pass."""

import threading

from kubedtn_tpu.contracts import guarded_by


@guarded_by("_lock", "count", "items")
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.items = []

    def bad_inc(self):
        self.count += 1          # guarded write, no lock

    def bad_read(self):
        return len(self.items)   # guarded read, no lock
