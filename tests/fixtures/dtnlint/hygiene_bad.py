"""Known-bad fixture for the hygiene pass."""

from kubedtn_tpu import contracts  # first-party before stdlib: order
import os
import sys  # unused import


def swallow():
    try:
        return os.getpid() + id(contracts)
    except:                      # bare except
        return 0
