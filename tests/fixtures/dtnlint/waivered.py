"""Waiver-syntax fixture: the findings exist but are waived (line,
line-above, and def-level placements)."""

import jax


def line_waiver(key):
    a = jax.random.uniform(key)
    b = jax.random.normal(key)  # dtnlint: key-ok(fixture: documented reuse)
    return a + b


# dtnlint: key-ok(fixture: def-level waiver covers the whole body)
def def_waiver(key):
    a = jax.random.uniform(key)
    b = jax.random.normal(key)
    return a + b
