"""Stale-waiver fixture: every waiver below sits where its rule no
longer fires, so a FULL run must report each as a `waiver` finding."""

import os  # dtnlint: hygiene-ok(dead: os IS used below, nothing to waive)


# dtnlint: key-ok(dead: this function draws no keys anymore)
def no_keys_here():
    return os.getpid()
