"""Clean fixture for the lock-discipline pass: zero findings expected."""

import threading

from kubedtn_tpu.contracts import guarded_by, requires_lock


@guarded_by("_lock", "count", "items")
class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0           # __init__ precedes publication
        self.items = []

    def good_inc(self):
        with self._lock:
            self.count += 1

    @requires_lock("_lock")
    def helper(self):
        self.items.append(1)     # caller holds the lock

    def waivered(self):
        return self.count  # dtnlint: lock-ok(fixture: torn read tolerated)
