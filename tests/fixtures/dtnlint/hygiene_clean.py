"""Clean fixture for the hygiene pass: zero findings expected."""

import os
import sys

from kubedtn_tpu import contracts


def fine():
    try:
        return os.getpid() + id(contracts) + len(sys.argv)
    except (OSError, ValueError):
        return 0
