"""Clean fixture for the key-discipline pass: zero findings expected."""

import jax


def split_then_sample(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.uniform(k1)
    b = jax.random.normal(k2)
    return a + b


def leaf_kernel(state, key):
    # the caller split for us; one sampler consumes the parameter
    return state + jax.random.uniform(key)


def folded_root(seed):
    key = jax.random.key(seed)
    k = jax.random.fold_in(key, 1)
    return jax.random.uniform(k)


def per_iteration(key, n):
    out = 0.0
    for i in range(n):
        out = out + jax.random.uniform(jax.random.fold_in(key, i))
    return out
