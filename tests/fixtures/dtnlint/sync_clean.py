"""Clean fixture for the host-sync pass: hot code that stays
future-shaped (metadata reads, host-list marshalling, identity
tests)."""

import numpy as np


def hot_tick(state, lens):
    e = state.props.shape[0]             # metadata: no transfer
    arr = np.asarray(lens, np.uint64)    # host list → host array
    if state is None:                    # identity test: no coercion
        return None
    return e, arr
