"""Known-bad fixture for the traced-purity pass: every construct here
must produce a finding (tests/test_static_analysis.py pins the count).
Never imported — parsed only."""

import random
import time

import jax

EVENTS = []


@jax.jit
def step(x):
    t = time.time()          # wall clock inside a trace
    print("tick", x)         # host I/O inside a trace
    return x + t


@jax.jit
def jittered(x):
    return x * random.random()   # host RNG inside a trace


@jax.jit
def accum(x):
    EVENTS.append(x)         # closed-over container mutation
    return x


def run_scan(xs):
    def body(carry, x):
        EVENTS.append(x)     # scan body is traced too
        return carry + x, None

    return jax.lax.scan(body, 0.0, xs)
