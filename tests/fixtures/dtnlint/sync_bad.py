"""Known-bad fixture for the host-sync pass (run with hot_roots
pointing at `hot_tick`)."""

import numpy as np


def hot_tick(state):
    mirror = np.asarray(state.props)     # device materialization
    v = float(state.tokens[0])           # scalar coercion
    if state:                            # bool coercion on a device val
        v += 1.0
    return mirror, v
