"""Known-bad fixture for the key-discipline pass."""

import jax


def reuse(key):
    a = jax.random.uniform(key)
    b = jax.random.normal(key)     # same key, second sampler
    return a + b


def raw_root(seed):
    return jax.random.uniform(jax.random.key(seed))  # unsplit root


def root_into_call(seed, state):
    return shape(state, jax.random.key(seed))  # root into sampling path


def loop_invariant(key, n):
    out = 0.0
    for _ in range(n):
        out = out + jax.random.uniform(key)  # same bits every pass
    return out


def shape(state, key):
    return state
