"""Clean fixture for the dtype-drift pass: zero findings expected."""

import numpy as np

import jax.numpy as jnp


def keep_anchor(clock_us):
    return np.float64(clock_us)          # anchors STAY f64


def build(snapshot, clock_us):
    return snapshot.replace(clock_us=np.float64(clock_us))


def column_write(col):
    return col.at[0].set(jnp.float32(1.0))  # explicit f32: intended
