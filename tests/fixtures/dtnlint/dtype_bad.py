"""Known-bad fixture for the dtype-drift pass."""

import numpy as np

import jax.numpy as jnp


def freeze_anchor(clock_us):
    return np.float32(clock_us)          # the clock_us freeze class


def build(snapshot):
    return snapshot.replace(
        clock_us=jnp.zeros((), jnp.float32))  # f32-constructed anchor


def leak_into_column(col):
    return col.at[0].set(np.float64(1.0))  # f64 into an f32 scatter
