"""Clean fixture for the traced-purity pass: zero findings expected.
Host effects OUTSIDE traces and local-container use INSIDE them are
both legal."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    xs = []
    xs.append(x * 2)         # local list: builds the trace, no effect
    return jnp.stack(xs)


def host_driver(x):
    t0 = time.time()         # host side: fine
    y = step(x)
    print("elapsed", time.time() - t0)
    return y
