"""gRPC control-plane tests: full client↔daemon round trips on the
reference's wire protocol, including the CNI and controller call patterns."""

import pytest

from kubedtn_tpu.api.types import load_yaml
from kubedtn_tpu.topology import SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.client import DaemonClient
from kubedtn_tpu.wire.server import Daemon, make_server

REFERENCE_3NODE = "/root/reference/config/samples/3node.yml"


@pytest.fixture()
def daemon_and_client():
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    for t in load_yaml(REFERENCE_3NODE):
        store.create(t)
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0)
    server.start()
    client = DaemonClient(f"127.0.0.1:{port}")
    yield daemon, client, engine, store
    client.close()
    server.stop(0)


def test_proto_roundtrip_bytes():
    # field numbers match the reference IDL: a serialized Link decodes
    # with the same values
    link = pb.Link(peer_pod="r2", local_intf="eth1", peer_intf="eth1",
                   local_ip="12.12.12.1/24", uid=7,
                   properties=pb.LinkProperties(latency="10ms"))
    data = link.SerializeToString()
    back = pb.Link.FromString(data)
    assert back.peer_pod == "r2" and back.uid == 7
    assert back.properties.latency == "10ms"


@pytest.mark.requires_reference_yaml
def test_setup_pod_flow(daemon_and_client):
    daemon, client, engine, store = daemon_and_client
    # CNI cmdAdd: SetupPod for each pod
    for name in ("r1", "r2", "r3"):
        resp = client.SetupPod(pb.SetupPodQuery(
            name=name, kube_ns="default", net_ns=f"/run/netns/{name}"))
        assert resp.response
    assert engine.num_active == 6
    # Get returns status with placement
    pod = client.Get(pb.PodQuery(name="r1", kube_ns="default"))
    assert pod.src_ip == engine.node_ip
    assert len(pod.links) == 2


@pytest.mark.requires_reference_yaml
def test_setup_unknown_pod_delegates(daemon_and_client):
    _, client, engine, _ = daemon_and_client
    resp = client.SetupPod(pb.SetupPodQuery(name="not-in-topology"))
    assert resp.response  # true => CNI delegates to next plugin
    assert engine.num_active == 0


@pytest.mark.requires_reference_yaml
def test_update_links_via_wire(daemon_and_client):
    daemon, client, engine, store = daemon_and_client
    for name in ("r1", "r2", "r3"):
        client.SetupPod(pb.SetupPodQuery(name=name,
                                         net_ns=f"/run/netns/{name}"))
    # controller UpdateLinks: change uid-1 latency
    topo = store.get("default", "r1")
    links = [pb.link_to_proto(l) for l in topo.spec.links if l.uid == 1]
    links[0].properties.latency = "33ms"
    resp = client.UpdateLinks(pb.LinksBatchQuery(
        local_pod=pb.Pod(name="r1", kube_ns="default"), links=links))
    assert resp.response
    assert engine.link_row("default/r1", 1)["latency_us"] == 33_000.0


@pytest.mark.requires_reference_yaml
def test_destroy_pod_flow(daemon_and_client):
    daemon, client, engine, _ = daemon_and_client
    for name in ("r1", "r2", "r3"):
        client.SetupPod(pb.SetupPodQuery(name=name,
                                         net_ns=f"/run/netns/{name}"))
    resp = client.DestroyPod(pb.PodQuery(name="r2"))
    assert resp.response
    assert engine.num_active == 2  # only r1-r3 link remains


@pytest.mark.requires_reference_yaml
def test_remote_update(daemon_and_client):
    daemon, client, engine, _ = daemon_and_client
    resp = client.Update(pb.RemotePod(
        net_ns="/run/netns/r9", intf_name="eth1", intf_ip="9.9.9.9/24",
        peer_vtep="10.1.0.2", vni=5007, kube_ns="default", name="r1",
        properties=pb.LinkProperties(latency="5ms")))
    assert resp.response
    row = engine.link_row("default/r1", 7)  # vni 5007 -> uid 7
    assert row is not None and row["latency_us"] == 5000.0


@pytest.mark.requires_reference_yaml
def test_wire_lifecycle_and_packets(daemon_and_client):
    daemon, client, engine, _ = daemon_and_client
    for name in ("r1", "r2"):
        client.SetupPod(pb.SetupPodQuery(name=name,
                                         net_ns=f"/run/netns/{name}"))
    # name generation parity format: %.5s%.5s-%04d
    gen = client.GenerateNodeInterfaceName(
        pb.GenerateNodeInterfaceNameRequest(pod_intf_name="eth1",
                                            pod_name="router1"))
    assert gen.ok
    assert gen.node_intf_name.startswith("routeeth1-")

    wd = pb.WireDef(link_uid=1, local_pod_name="r1", kube_ns="default",
                    intf_name_in_pod="eth1",
                    veth_name_local_host=gen.node_intf_name)
    exists = client.GRPCWireExists(wd)
    assert not exists.response
    created = client.AddGRPCWireRemote(wd)
    assert created.response
    wire_id = created.peer_intf_id

    # unary per-frame path (the reference's only implemented path)
    resp = client.SendToOnce(pb.Packet(remot_intf_id=wire_id,
                                       frame=b"\x01\x02\x03"))
    assert resp.response
    # streaming path (unimplemented in the reference — implemented here)
    resp = client.SendToStream(iter([
        pb.Packet(remot_intf_id=wire_id, frame=b"aa"),
        pb.Packet(remot_intf_id=wire_id, frame=b"bbbb"),
    ]))
    assert resp.response

    batches = daemon.drain_ingress()
    assert len(batches) == 1
    wire_out, row, sizes, frames = batches[0]
    assert sizes == [3, 2, 4]
    assert row == engine.row_of("default/r1", 1)

    assert client.RemGRPCWire(wd).response
    assert not client.GRPCWireExists(wd).response


@pytest.mark.requires_reference_yaml
def test_send_to_unknown_wire_errors(daemon_and_client):
    import grpc

    _, client, _, _ = daemon_and_client
    with pytest.raises(grpc.RpcError) as ei:
        client.SendToOnce(pb.Packet(remot_intf_id=424242, frame=b"x"))
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND


@pytest.mark.requires_reference_yaml
def test_concurrent_rpcs_race_free(daemon_and_client):
    # 16-thread gRPC pool vs the engine lock: concurrent SetupPod /
    # AddGRPCWireRemote / Update must neither lose links nor reuse wire ids.
    import concurrent.futures

    daemon, client, engine, _ = daemon_and_client

    def setup(name):
        return client.SetupPod(pb.SetupPodQuery(
            name=name, net_ns=f"/run/netns/{name}")).response

    def wire(i):
        return client.AddGRPCWireRemote(pb.WireDef(
            link_uid=100 + i, local_pod_name="r1",
            kube_ns="default")).peer_intf_id

    def remote(i):
        return client.Update(pb.RemotePod(
            vni=6000 + i, name=f"rp{i}", kube_ns="default",
            properties=pb.LinkProperties(latency="1ms"))).response

    with concurrent.futures.ThreadPoolExecutor(16) as ex:
        setups = list(ex.map(setup, ["r1", "r2", "r3"] * 4))
        wire_ids = list(ex.map(wire, range(24)))
        remotes = list(ex.map(remote, range(24)))
    assert all(setups) and all(remotes)
    assert len(set(wire_ids)) == 24          # no duplicate wire ids
    assert engine.num_active == 6 + 24       # 3-node mesh + 24 remote rows
    # every remote row realized
    for i in range(24):
        assert engine.link_row(f"default/rp{i}", 1000 + i) is not None


def test_racing_wire_creates_yield_one_wire():
    """Regression: two concurrent AddGRPCWireRemote calls for the same
    (pod, uid) must de-duplicate into ONE wire (the reference's
    wire-exists guard, grpcwire.go:292-383), both receiving its id."""
    import threading

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0, host="127.0.0.1")
    server.start()
    client = DaemonClient(f"127.0.0.1:{port}")

    n = 8
    barrier = threading.Barrier(n)
    ids = []
    lock = threading.Lock()

    def create():
        barrier.wait()
        resp = client.AddGRPCWireRemote(pb.WireDef(
            local_pod_name="r1", kube_ns="default", link_uid=5,
            intf_name_in_pod="eth1", peer_ip="10.0.0.9"))
        with lock:
            ids.append(resp.peer_intf_id)

    threads = [threading.Thread(target=create) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(ids) == n
    assert len(set(ids)) == 1, f"racing creates split-brained: {set(ids)}"
    assert len(daemon.wires.all()) == 1
    # a DIFFERENT link on the same pod still gets its own wire
    resp2 = client.AddGRPCWireRemote(pb.WireDef(
        local_pod_name="r1", kube_ns="default", link_uid=6,
        intf_name_in_pod="eth2", peer_ip="10.0.0.9"))
    assert resp2.peer_intf_id not in set(ids)
    assert len(daemon.wires.all()) == 2
    client.close()
    server.stop(0)


def test_drain_ingress_visits_only_hot_wires():
    """drain_ingress is O(wires with traffic): untouched wires are never
    visited, residue beyond the per-tick budget stays hot, and a wire
    whose link is not yet realized is retried once it is."""
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    daemon = Daemon(engine)
    wires = [daemon._add_wire(pb.WireDef(
        local_pod_name=f"p{i}", kube_ns="default", link_uid=i,
        intf_name_in_pod="eth0")) for i in range(20)]
    # realize rows for pods 0..19
    from kubedtn_tpu.api.types import Link, Topology, TopologySpec
    for i in range(20):
        t = Topology(name=f"p{i}", spec=TopologySpec(links=[
            Link(local_intf="eth0", peer_intf="e", uid=i,
                 peer_pod="physical/10.0.0.9")]))
        store.create(t)
        engine.setup_pod(f"p{i}")

    visited = []
    real_get = daemon.wires.get_by_id
    daemon.wires.get_by_id = lambda i: (visited.append(i),
                                        real_get(i))[1]
    # traffic on exactly one wire, more than one tick's budget
    for _ in range(70):
        wires[7].ingress.append(b"x" * 60)
    out = daemon.drain_ingress(max_per_wire=64)
    assert len(out) == 1 and len(out[0][3]) == 64
    assert set(visited) == {wires[7].wire_id}  # nobody else visited
    visited.clear()
    out = daemon.drain_ingress(max_per_wire=64)  # residue still hot
    assert len(out) == 1 and len(out[0][3]) == 6
    assert daemon.drain_ingress() == []          # drained -> cold

    # unrealized link: frames wait, wire stays hot until the row exists
    w = daemon._add_wire(pb.WireDef(
        local_pod_name="late", kube_ns="default", link_uid=99,
        intf_name_in_pod="eth0"))
    w.ingress.append(b"y" * 60)
    assert daemon.drain_ingress() == []
    t = Topology(name="late", spec=TopologySpec(links=[
        Link(local_intf="eth0", peer_intf="e", uid=99,
             peer_pod="physical/10.0.0.9")]))
    store.create(t)
    engine.setup_pod("late")
    out = daemon.drain_ingress()
    assert len(out) == 1 and out[0][3] == [b"y" * 60]


def test_directly_constructed_wire_not_starved():
    """A Wire built by an embedder (plain dataclass) and registered via
    WireManager.add must still be drained: the registry installs the
    hot-marking hook on every wire it learns about — including frames
    queued BEFORE registration."""
    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    from kubedtn_tpu.api.types import Link, Topology, TopologySpec
    t = Topology(name="emb", spec=TopologySpec(links=[
        Link(local_intf="eth0", peer_intf="e", uid=3,
             peer_pod="physical/10.0.0.9")]))
    store.create(t)
    engine.setup_pod("emb")

    from kubedtn_tpu.wire.server import Wire
    wire = Wire(wire_id=7777, uid=3, pod_key="default/emb",
                node_iface_name="emb-eth0")
    wire.ingress.append(b"early" + b"\x00" * 55)  # BEFORE registration
    daemon.wires.add(wire)
    out = daemon.drain_ingress()
    assert len(out) == 1 and out[0][3][0].startswith(b"early")
    # post-registration direct appends (and extend) also mark hot
    wire.ingress.extend([b"l" * 60, b"m" * 60])
    out = daemon.drain_ingress()
    assert len(out) == 1 and len(out[0][3]) == 2


def test_iadd_on_ingress_marks_hot():
    """`wire.ingress += [...]` must mark the wire hot (deque's C-level
    __iadd__ would bypass a plain extend override)."""
    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    from kubedtn_tpu.api.types import Link, Topology, TopologySpec
    t = Topology(name="ia", spec=TopologySpec(links=[
        Link(local_intf="eth0", peer_intf="e", uid=4,
             peer_pod="physical/10.0.0.9")]))
    store.create(t)
    engine.setup_pod("ia")
    wire = daemon._add_wire(pb.WireDef(
        local_pod_name="ia", kube_ns="default", link_uid=4,
        intf_name_in_pod="eth0"))
    wire.ingress += [b"a" * 60, b"b" * 60]
    out = daemon.drain_ingress()
    assert len(out) == 1 and len(out[0][3]) == 2
