"""Two-process multi-host smoke test.

Proves the jax.distributed path works end to end: two OS processes (the
stand-ins for two TPU hosts) join one coordinator, build the host-major
multihost mesh, and reduce an edge-sharded array across BOTH processes'
devices — the initialization the reference performs when each node's
daemon joins the cluster and peers over gRPC (reference
daemon/main.go:20-107), re-expressed as jax.distributed + collectives
(kubedtn_tpu/parallel/mesh.py:43-70).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys

pid = int(sys.argv[1])
coord = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, sys.argv[3])

import jax

# the axon TPU-tunnel platform overrides JAX_PLATFORMS; the explicit
# config update is what actually pins the CPU backend (see conftest.py)
jax.config.update("jax_platforms", "cpu")
# CPU multiprocess collectives ride the gloo transport; without this the
# stock CPU client refuses with "Multiprocess computations aren't
# implemented on the CPU backend" (the pre-round-9 env failure)
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kubedtn_tpu.parallel.mesh import (edge_sharding, init_distributed,
                                       make_multihost_mesh)

init_distributed(coordinator_address=coord, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
mesh = make_multihost_mesh()
assert mesh.devices.size == 4, mesh.devices.size
# host-major: this process's two devices hold consecutive shards
sh = edge_sharding(mesh)

E = 8  # 2 rows per device
data = np.arange(E, dtype=np.float32) + 1.0  # 1..8, global
x = jax.make_array_from_callback((E,), sh, lambda idx: data[idx])

total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
# each process also checks its addressable shards carry the right slices
local_rows = sorted(int(s.index[0].start) for s in x.addressable_shards)
print(json.dumps({
    "pid": pid,
    "procs": jax.process_count(),
    "devices": int(mesh.devices.size),
    "total": float(total),
    "local_shard_starts": local_rows,
}), flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_two_workers(script_text, tmp_path, timeout, hang_msg):
    """Launch the worker script as two coordinated processes; return both
    JSON results. Kills the sibling on any failure so a crashed worker
    never leaves the other blocking on the dead coordinator."""
    script = tmp_path / "worker.py"
    script.write_text(script_text)
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), coord, REPO],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                raise AssertionError(hang_msg)
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
    return outs


@pytest.mark.requires_multihost
def test_two_process_multihost_mesh(tmp_path):
    outs = _run_two_workers(WORKER, tmp_path, 180, "multihost worker hung")

    for pid, o in enumerate(sorted(outs, key=lambda o: o["pid"])):
        assert o["pid"] == pid
        assert o["procs"] == 2
        assert o["devices"] == 4
        assert o["total"] == 36.0  # sum(1..8) reduced across BOTH hosts
    # host-major layout: process 0 owns rows [0,2), [2,4); process 1 the rest
    starts = {o["pid"]: o["local_shard_starts"] for o in outs}
    assert starts[0] == [0, 2]
    assert starts[1] == [4, 6]


WORKER_STEP = r"""
import json, os, sys

pid = int(sys.argv[1])
coord = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, sys.argv[3])

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models.topologies import fat_tree, load_edge_list_into_state
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.parallel.mesh import (edge_sharding, init_distributed,
                                       make_multihost_mesh)
from kubedtn_tpu.parallel.sharded import make_sharded_step

init_distributed(coordinator_address=coord, num_processes=2, process_id=pid)
mesh = make_multihost_mesh()
assert mesh.devices.size == 4

# both hosts build the SAME topology deterministically, then globalize
props = LinkProperties(latency="10ms", jitter="1ms", loss="0.5", rate="1Gbit")
el = fat_tree(4, props)
state, rows = load_edge_list_into_state(el, capacity=64)
E = state.capacity
sh_e = edge_sharding(mesh)
sh_r = NamedSharding(mesh, P())


def glob(x, sh):
    a = np.asarray(x)
    return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])


state = jax.tree.map(lambda x: glob(x, sh_e), state)
sizes = glob(np.full((E,), 1500.0, np.float32), sh_e)
have = glob((np.arange(E) < len(rows)), sh_e)
t_arr = glob(np.zeros((E,), np.float32), sh_e)
B = 8
urows = glob(np.arange(B, dtype=np.int32), sh_r)
uprops = glob(np.stack(
    [es.props_row(LinkProperties(latency="20ms", rate="100Mbit")
                  .to_numeric())] * B), sh_r)
uvalid = glob(np.ones(B, dtype=bool), sh_r)
key = jax.random.key(0)  # scalar: implicitly replicated

step = make_sharded_step(mesh, n_nodes=el.n_nodes)
state2, res, stats = step(state, urows, uprops, uvalid, sizes, have,
                          t_arr, key)

lat_col = es.PROP_NAMES.index("latency_us")
check = jax.jit(
    lambda s, d: (jnp.sum(d.astype(jnp.float32)), s.props[0, lat_col]),
    out_shardings=(sh_r, sh_r))
delivered, lat0 = check(state2, res.delivered)

# stats come out replicated (P()): every process can read them whole
tx_total = float(np.asarray(stats.tx_packets).sum())
print(json.dumps({
    "pid": pid,
    "devices": int(mesh.devices.size),
    "delivered": float(delivered),
    "tx_total": tx_total,
    "lat0_after_update": float(lat0),
}), flush=True)
"""


@pytest.mark.requires_multihost
def test_two_process_sharded_step(tmp_path):
    """The FULL sharded sim step (batched updates -> shaping -> psum'd
    node stats) jitted across two OS processes' device meshes — the DCN
    path of SURVEY §5.8, not just an array reduce."""
    outs = _run_two_workers(WORKER_STEP, tmp_path, 240,
                            "sharded-step worker hung")

    a, b = sorted(outs, key=lambda o: o["pid"])
    assert a["devices"] == b["devices"] == 4
    # both processes computed the SAME global result
    assert a["delivered"] == b["delivered"] > 0
    assert a["tx_total"] == b["tx_total"] == a["delivered"]
    # the batched update landed: row 0's latency is the new 20ms
    assert a["lat0_after_update"] == b["lat0_after_update"] == 20_000.0


WORKER_ROUTER = r"""
import json, os, sys

pid = int(sys.argv[1])
coord = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, sys.argv[3])

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from kubedtn_tpu.parallel.mesh import init_distributed, make_multihost_mesh

N_PROCS = 4
# distributed init FIRST: importing modules is fine, but nothing may
# touch the XLA backend before initialize()
init_distributed(coordinator_address=coord, num_processes=N_PROCS,
                 process_id=pid)
assert jax.process_count() == N_PROCS

import dataclasses
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kubedtn_tpu import router as RT
from kubedtn_tpu.models import traffic as TR
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import routing as R
from kubedtn_tpu.parallel.router import (_edge_specs,
                                         make_sharded_router_step)
mesh = make_multihost_mesh()
N_SHARDS = mesh.devices.size
assert N_SHARDS == 8
E = 32
E_LOC = E // N_SHARDS

# deterministic chain 0->1->...->4, one hop per shard: every forward
# crosses a shard boundary and hops 2-3 cross PROCESS boundaries
n_nodes = 5
n_links = n_nodes - 1
rows = np.arange(n_links, dtype=np.int32) * E_LOC
props = np.zeros((n_links, es.NPROP), np.float32)
props[:, es.P_LATENCY_US] = 1000.0
state = es.init_state(E)
state = es.apply_links(
    state, jnp.asarray(rows), jnp.arange(1, n_links + 1, dtype=jnp.int32),
    jnp.arange(n_links, dtype=jnp.int32),
    jnp.arange(1, n_links + 1, dtype=jnp.int32),
    jnp.asarray(props), jnp.ones(n_links, dtype=bool))
_, nh = R.recompute_routes(state, n_nodes, max_hops=8)
rs0 = RT.init_router(state, nh, n_nodes, q=32, k_fwd=8)

mode = np.zeros((E,), np.int32); mode[rows[0]] = TR.MODE_CBR
rate = np.zeros((E,), np.float32); rate[rows[0]] = 8e6
size = np.full((E,), 1000.0, np.float32)
z = np.zeros((E,), np.float32)
spec = TR.TrafficSpec(mode=jnp.asarray(mode), rate_bps=jnp.asarray(rate),
                      pkt_bytes=jnp.asarray(size), on_us=jnp.asarray(z),
                      off_us=jnp.asarray(z))
flow_dst = np.full((E,), -1, np.int32)
flow_dst[rows[0]] = n_nodes - 1

STEPS = 12
# single-device reference, computed identically in every process
rs_ref = jax.tree.map(lambda x: x.copy(), rs0)
for i in range(STEPS):
    rs_ref = RT.router_step(rs_ref, spec, jnp.asarray(flow_dst),
                            jax.random.key(i), 2, 8, jnp.float32(2000.0))
ref_rx = np.asarray(rs_ref.node_rx_packets).tolist()

# globalize onto the 4-process mesh with the step's own shardings
specs = _edge_specs(rs0, N_SHARDS)

def glob(x, p):
    a = np.asarray(x)
    sh = NamedSharding(mesh, p)
    return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])

rs = jax.tree.map(glob, rs0, specs)
spec_g = jax.tree.map(lambda x: glob(x, P("edge")), spec)
flow_g = glob(flow_dst, P("edge"))

step = make_sharded_router_step(mesh, n_nodes, k_slots=2, k_fwd=8)
for i in range(STEPS):
    rs = step(rs, spec_g, flow_g, jax.random.key(i), 2000.0)

got_rx = np.asarray(rs.node_rx_packets).tolist()
print(json.dumps({
    "pid": pid,
    "devices": int(N_SHARDS),
    "ref_rx": ref_rx,
    "got_rx": got_rx,
    "fwd_dropped": float(np.asarray(rs.fwd_dropped)),
    "no_route": float(np.asarray(rs.no_route_dropped)),
}), flush=True)
"""


def _run_workers(script_text, tmp_path, timeout, hang_msg, n_procs):
    script = tmp_path / "worker.py"
    script.write_text(script_text)
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), coord, REPO],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for pid in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                raise AssertionError(hang_msg)
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
    return outs


@pytest.mark.requires_multihost
def test_four_process_sharded_router_steps(tmp_path):
    """FOUR processes x 2 devices run the full sharded ROUTER step
    (generate -> shape -> all_to_all cross-shard exchange -> deliver)
    for 12 steps on a chain whose hops each cross a shard boundary —
    and hops 2-3 cross PROCESS boundaries, so the all_to_all rides the
    distributed backend, not shared memory. Every process must see the
    SAME global result, equal to a single-device reference run.

    This is the strongest multi-chip evidence this environment can
    produce: the v4-8 (and multi-host DCN) story compiled and executed
    with real cross-process collectives, standing in for the reference's
    daemon mesh (common/utils.go:39-68)."""
    outs = _run_workers(WORKER_ROUTER, tmp_path, 420,
                        "4-process router worker hung", 4)
    assert len(outs) == 4
    base = outs[0]
    assert base["got_rx"] == base["ref_rx"], (base["got_rx"],
                                              base["ref_rx"])
    # chain end received traffic across 4 shard hops
    assert base["got_rx"][-1] > 0
    for o in outs[1:]:
        assert o["got_rx"] == base["got_rx"]  # identical on every host
        assert o["fwd_dropped"] == 0 and o["no_route"] == 0
