"""Two-process multi-host smoke test.

Proves the jax.distributed path works end to end: two OS processes (the
stand-ins for two TPU hosts) join one coordinator, build the host-major
multihost mesh, and reduce an edge-sharded array across BOTH processes'
devices — the initialization the reference performs when each node's
daemon joins the cluster and peers over gRPC (reference
daemon/main.go:20-107), re-expressed as jax.distributed + collectives
(kubedtn_tpu/parallel/mesh.py:43-70).
"""

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys

pid = int(sys.argv[1])
coord = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, sys.argv[3])

import jax

# the axon TPU-tunnel platform overrides JAX_PLATFORMS; the explicit
# config update is what actually pins the CPU backend (see conftest.py)
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kubedtn_tpu.parallel.mesh import (edge_sharding, init_distributed,
                                       make_multihost_mesh)

init_distributed(coordinator_address=coord, num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
mesh = make_multihost_mesh()
assert mesh.devices.size == 4, mesh.devices.size
# host-major: this process's two devices hold consecutive shards
sh = edge_sharding(mesh)

E = 8  # 2 rows per device
data = np.arange(E, dtype=np.float32) + 1.0  # 1..8, global
x = jax.make_array_from_callback((E,), sh, lambda idx: data[idx])

total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x)
# each process also checks its addressable shards carry the right slices
local_rows = sorted(int(s.index[0].start) for s in x.addressable_shards)
print(json.dumps({
    "pid": pid,
    "procs": jax.process_count(),
    "devices": int(mesh.devices.size),
    "total": float(total),
    "local_shard_starts": local_rows,
}), flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_multihost_mesh(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), coord, REPO],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("multihost worker hung")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    for pid, o in enumerate(sorted(outs, key=lambda o: o["pid"])):
        assert o["pid"] == pid
        assert o["procs"] == 2
        assert o["devices"] == 4
        assert o["total"] == 36.0  # sum(1..8) reduced across BOTH hosts
    # host-major layout: process 0 owns rows [0,2), [2,4); process 1 the rest
    starts = {o["pid"]: o["local_shard_starts"] for o in outs}
    assert starts[0] == [0, 2]
    assert starts[1] == [4, 6]
