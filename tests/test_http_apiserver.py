"""K8sBridge + KubeLeaseStore against the protocol-level fake apiserver
over REAL HTTP (tests/fake_apiserver.py — the envtest role of reference
controllers/suite_test.go:44-80).

Unlike test_k8s_bridge.py's in-process duck-typed fakes, everything here
crosses a socket: chunked-JSON watch streams, merge-patch content types,
410 Gone expiry via the apiserver's ERROR-event protocol, resourceVersion
CAS on Leases, and the informer loop's 410-vs-transient recovery split.
"""

import threading
import time

import pytest

from fake_apiserver import FakeApiServer
from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.topology.k8s import (
    ApiHttpError,
    HttpKubeApi,
    HttpLeaseApi,
    K8sBridge,
    WatchExpiredError,
)
from kubedtn_tpu.topology.manager import KubeLeaseStore
from kubedtn_tpu.topology.store import TopologyStore


def manifest(name: str, latency: str = "10ms", ns: str = "default",
             uid: int = 1) -> dict:
    t = Topology(name=name, namespace=ns, spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="peer",
             uid=uid, properties=LinkProperties(latency=latency))]))
    return t.to_manifest()


@pytest.fixture()
def server():
    srv = FakeApiServer(event_window=16, watch_timeout_s=5.0)
    host, port = srv.start()
    yield srv, f"http://{host}:{port}"
    srv.stop()


def test_list_and_get_over_http(server):
    srv, url = server
    srv.put_object(manifest("r1"))
    srv.put_object(manifest("r2", ns="other"))
    api = HttpKubeApi(url)
    items, rv = api.list_topologies()
    assert {i["metadata"]["name"] for i in items} == {"r1", "r2"}
    assert int(rv) >= 2
    api_ns = HttpKubeApi(url, namespace="other")
    items, _ = api_ns.list_topologies()
    assert [i["metadata"]["name"] for i in items] == ["r2"]


def test_watch_streams_chunked_events(server):
    srv, url = server
    api = HttpKubeApi(url, timeout_s=10.0)
    _, rv = api.list_topologies()
    got = []

    def watcher():
        for ev_type, obj in api.watch_topologies(rv):
            got.append((ev_type, obj["metadata"]["name"]))
            if len(got) >= 3:
                return

    t = threading.Thread(target=watcher, daemon=True)
    t.start()
    time.sleep(0.2)
    srv.put_object(manifest("a"))
    srv.put_object(manifest("a", latency="50ms"))
    srv.delete_object("default", "a")
    t.join(timeout=10)
    assert not t.is_alive()
    assert got == [("ADDED", "a"), ("MODIFIED", "a"), ("DELETED", "a")]


def test_watch_expired_raises_410(server):
    srv, url = server
    api = HttpKubeApi(url)
    srv.put_object(manifest("r1"))
    _, rv = api.list_topologies()
    # push the retained window past rv, then compact
    for i in range(20):
        srv.put_object(manifest("r1", latency=f"{i + 1}ms"))
    srv.expire_history()
    with pytest.raises(WatchExpiredError):
        for _ in api.watch_topologies(rv):
            pass


def test_status_patch_roundtrip_over_http(server):
    srv, url = server
    srv.put_object(manifest("r1"))
    api = HttpKubeApi(url)
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    bridge.sync_once()
    t = store.get("default", "r1")
    t.status.src_ip = "10.9.9.9"
    t.status.net_ns = "/proc/42/ns/net"
    store.update_status(t)
    assert bridge.push_status(store.get("default", "r1")) is True
    obj = srv.objects[("default", "r1")]
    assert obj["status"]["src_ip"] == "10.9.9.9"
    # PATCH went to the status subresource, not the object
    assert any(p.endswith("/r1/status") and p.startswith("PATCH")
               for p in srv.requests)
    # vanished object reads as False (404), not an exception
    srv.delete_object("default", "r1")
    t.status.src_ip = "10.0.0.1"
    assert bridge.push_status(t) is False


def test_informer_relists_immediately_on_410(server):
    srv, url = server
    api = HttpKubeApi(url, timeout_s=10.0)
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    errors: list[Exception] = []
    stop = threading.Event()
    th = threading.Thread(
        target=lambda: bridge.run(on_error=errors.append, stop=stop),
        daemon=True)
    srv.put_object(manifest("r1"))
    th.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not store.list():
        time.sleep(0.05)
    assert [t.name for t in store.list()] == ["r1"]

    # expire the watch history while more changes land
    srv.expire_history()
    srv.put_object(manifest("r2"))
    t0 = time.monotonic()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(store.list()) < 2:
        time.sleep(0.05)
    recovery_s = time.monotonic() - t0
    assert {t.name for t in store.list()} == {"r1", "r2"}
    # 410 recovery is an immediate re-list: well under the 1s the old
    # fixed sleep imposed, and the error surfaced to on_error
    assert recovery_s < 1.0, f"410 recovery took {recovery_s:.2f}s"
    assert any(getattr(e, "status", None) == 410 or
               isinstance(e, WatchExpiredError) for e in errors)
    n_lists = sum(1 for p in srv.requests
                  if p.startswith("GET") and "watch" not in p
                  and p.endswith("/topologies"))
    assert n_lists >= 2  # initial + post-410
    stop.set()
    th.join(timeout=10)


def test_informer_backs_off_on_transient_and_resumes_without_list(server):
    srv, url = server

    class CountingApi(HttpKubeApi):
        lists = 0
        watch_fail = 0

        def list_topologies(self):
            type(self).lists += 1
            return super().list_topologies()

        def watch_topologies(self, rv):
            if type(self).watch_fail > 0:
                type(self).watch_fail -= 1
                raise ConnectionResetError("transient blip")
            yield from super().watch_topologies(rv)

    CountingApi.lists = 0
    CountingApi.watch_fail = 2
    api = CountingApi(url, timeout_s=10.0)
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    bridge.BACKOFF_INITIAL_S = 0.05  # keep the test fast
    errors: list[Exception] = []
    stop = threading.Event()
    srv.put_object(manifest("r1"))
    th = threading.Thread(
        target=lambda: bridge.run(on_error=errors.append, stop=stop),
        daemon=True)
    th.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not store.list():
        time.sleep(0.05)
    # both transient failures burned, watch established
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and CountingApi.watch_fail > 0:
        time.sleep(0.05)
    srv.put_object(manifest("r2"))
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(store.list()) < 2:
        time.sleep(0.05)
    assert {t.name for t in store.list()} == {"r1", "r2"}
    # transient errors resumed from the last RV: exactly ONE list
    assert CountingApi.lists == 1, f"{CountingApi.lists} LISTs"
    assert len(errors) >= 2
    stop.set()
    th.join(timeout=10)


def test_bridge_spec_sync_full_loop_over_http(server):
    """spec change on the 'cluster' flows to the store via the watch;
    local status flows back via the subresource; the echo of our own
    status push is suppressed."""
    srv, url = server
    api = HttpKubeApi(url, timeout_s=10.0)
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    stop = threading.Event()
    srv.put_object(manifest("r1", latency="10ms"))
    th = threading.Thread(target=lambda: bridge.run(stop=stop),
                          daemon=True)
    th.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not store.list():
        time.sleep(0.05)

    srv.put_object(manifest("r1", latency="99ms"))
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            t = store.get("default", "r1")
            if t.spec.links[0].properties.latency == "99ms":
                break
        except Exception:
            pass
        time.sleep(0.05)
    assert store.get("default", "r1").spec.links[0] \
        .properties.latency == "99ms"

    t = store.get("default", "r1")
    t.status.src_ip = "10.1.2.3"
    store.update_status(t)
    assert bridge.push_status(store.get("default", "r1"))
    time.sleep(0.5)  # let the echo event arrive
    assert srv.objects[("default", "r1")]["status"]["src_ip"] == "10.1.2.3"
    assert bridge.stats["echoes_skipped"] >= 1
    stop.set()
    th.join(timeout=10)


def test_kube_lease_store_cas_over_http(server):
    srv, url = server
    api = HttpLeaseApi(url)
    a = KubeLeaseStore(namespace="kubedtn-tpu", api=api)
    b = KubeLeaseStore(namespace="kubedtn-tpu", api=api)
    assert a.try_acquire("leader", "pod-a", 0.0, 2.0) is True
    assert b.try_acquire("leader", "pod-b", 0.0, 2.0) is False
    assert b.holder("leader") == "pod-a"
    # renewal by the holder succeeds (CAS against current RV)
    assert a.try_acquire("leader", "pod-a", 0.0, 2.0) is True
    # release → immediate takeover
    a.release("leader", "pod-a")
    assert b.try_acquire("leader", "pod-b", 0.0, 2.0) is True
    assert a.holder("leader") == "pod-b"
    lease = srv.leases[("kubedtn-tpu", "leader")]
    assert lease["spec"]["holderIdentity"] == "pod-b"

    # a STALE (unreleased) holder is stolen, and that counts a transition
    t = {"now": time.time()}
    c = KubeLeaseStore(namespace="steal", api=api, clock=lambda: t["now"])
    assert c.try_acquire("l2", "pod-a", 0.0, 2.0) is True
    t["now"] += 10.0  # lease duration elapsed without renewal
    d = KubeLeaseStore(namespace="steal", api=api, clock=lambda: t["now"])
    assert d.try_acquire("l2", "pod-b", 0.0, 2.0) is True
    assert srv.leases[("steal", "l2")]["spec"]["leaseTransitions"] == 1


def test_lease_stale_rv_put_conflicts(server):
    srv, url = server
    api = HttpLeaseApi(url)
    store = KubeLeaseStore(namespace="ns", api=api)
    assert store.try_acquire("l", "a", 0.0, 30.0)
    lease = api.read_namespaced_lease("l", "ns")
    # another writer bumps the RV behind our back
    lease2 = dict(lease)
    lease2["spec"] = dict(lease["spec"], holderIdentity="b")
    api.replace_namespaced_lease("l", "ns", lease2)
    # replaying the FIRST lease body (stale RV) must 409
    with pytest.raises(ApiHttpError) as ei:
        api.replace_namespaced_lease("l", "ns", lease)
    assert ei.value.status == 409
