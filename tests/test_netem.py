"""Numeric tests for the netem + TBF shaping kernels.

Statistical expectations follow the Linux netem/tbf behavior the reference
installs per link (reference common/qdisc.go): loss/duplicate/corrupt rates,
uniform jitter in [latency-jitter, latency+jitter], AR(1) correlation,
reorder-with-gap, token-bucket serialization and the 50ms queue limit.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import netem


def state_with(prop: LinkProperties, n_edges: int = 1, capacity: int = 8):
    s = es.init_state(capacity)
    rows = jnp.arange(n_edges, dtype=jnp.int32)
    props = jnp.stack([es.props_row(prop.to_numeric())] * n_edges)
    return es.apply_links(
        s, rows, rows, jnp.zeros(n_edges, jnp.int32),
        jnp.ones(n_edges, jnp.int32), props,
        jnp.ones(n_edges, dtype=bool),
    )


@jax.jit
def _run_scan(s, sizes, have, arrivals, keys):
    def body(carry, inp):
        st = carry
        t_arr, key = inp
        st, res = netem.shape_step(st, sizes, have, t_arr, key)
        return st, res

    return jax.lax.scan(body, s, (arrivals, keys))


def run_packets(s, n_pkts, size=1000.0, spacing_us=0.0, seed=0):
    """Send n_pkts sequential packets on edge 0 via one lax.scan."""
    E = s.capacity
    sizes = jnp.full((E,), size, jnp.float32)
    have = jnp.zeros((E,), bool).at[0].set(True)
    arrivals = (jnp.arange(n_pkts, dtype=jnp.float32) * spacing_us)[:, None]
    arrivals = jnp.broadcast_to(arrivals, (n_pkts, E))
    keys = jax.random.split(jax.random.key(seed), n_pkts)
    s, stacked = _run_scan(s, sizes, have, arrivals, keys)
    stacked = jax.tree.map(np.asarray, stacked)
    outs = [
        jax.tree.map(lambda x, i=i: x[i, 0], stacked) for i in range(n_pkts)
    ]
    return s, outs


def test_pure_latency():
    s = state_with(LinkProperties(latency="10ms"))
    _, outs = run_packets(s, 5)
    for o in outs:
        assert o.delivered
        assert o.depart_us == pytest.approx(10_000.0)


def test_jitter_uniform_range_and_mean():
    s = state_with(LinkProperties(latency="10ms", jitter="2ms"))
    _, outs = run_packets(s, 2000)
    d = np.array([o.depart_us for o in outs])
    assert d.min() >= 8_000.0 - 1e-3
    assert d.max() <= 12_000.0 + 1e-3
    assert d.mean() == pytest.approx(10_000.0, rel=0.02)
    # uniform distribution: std = (b-a)/sqrt(12) = 4000/3.464 ≈ 1154.7
    assert d.std() == pytest.approx(4000 / np.sqrt(12), rel=0.1)


def test_loss_rate():
    s = state_with(LinkProperties(loss="25"))
    _, outs = run_packets(s, 4000)
    lost = np.mean([o.dropped_loss for o in outs])
    assert lost == pytest.approx(0.25, abs=0.03)


def test_loss_correlation():
    # netem's get_crandom is an AR(1) blend of uniforms, whose stationary
    # law concentrates around 0.5 — a 50% threshold keeps the marginal rate
    # at ~50% while making drops bursty. (This also reproduces the known
    # kernel quirk that correlation skews rates away from nominal for
    # thresholds far from 50%.)
    s = state_with(LinkProperties(loss="50", loss_corr="50"))
    _, outs = run_packets(s, 6000)
    drops = np.array([o.dropped_loss for o in outs], dtype=float)
    assert drops.mean() == pytest.approx(0.5, abs=0.05)
    x = drops - drops.mean()
    ac1 = (x[:-1] * x[1:]).mean() / (x.var() + 1e-12)
    assert ac1 > 0.15  # bursty vs ~0 for uncorrelated

    # the quirk itself: high correlation + low threshold => far fewer drops
    s2 = state_with(LinkProperties(loss="30", loss_corr="90"))
    _, outs2 = run_packets(s2, 4000)
    drops2 = np.mean([o.dropped_loss for o in outs2])
    assert drops2 < 0.10


def test_duplicate_and_corrupt_rates():
    s = state_with(LinkProperties(duplicate="10", corrupt_prob="5"))
    _, outs = run_packets(s, 4000)
    dup = np.mean([o.duplicated for o in outs])
    cor = np.mean([o.corrupted for o in outs])
    assert dup == pytest.approx(0.10, abs=0.02)
    assert cor == pytest.approx(0.05, abs=0.015)


def test_reorder_with_gap():
    # netem: reorder 25% gap 5 — every 5th packet is a candidate to jump
    # the 10ms delay line; candidates jump with p=0.25.
    s = state_with(LinkProperties(latency="10ms", reorder_prob="25", gap=5))
    _, outs = run_packets(s, 4000)
    reo = np.array([o.reordered for o in outs])
    # only candidates can reorder; steady-state candidate fraction with
    # gap=5 and p=.25 is governed by renewal theory: E[cycle] = 4 + 1/p
    # packets per reorder... just sanity-check the rate is between the
    # naive bounds (0.25/5 ≈ 0.05 lower, 0.25 upper) and nonzero.
    assert 0.01 < reo.mean() < 0.25
    d = np.array([o.depart_us for o in outs])
    assert np.all(d[reo] == 0.0)        # reordered packets jump the line
    assert np.all(d[~reo] == 10_000.0)  # everyone else takes full latency


def test_reorder_gap0_rate():
    s = state_with(LinkProperties(latency="10ms", reorder_prob="20"))
    _, outs = run_packets(s, 4000)
    reo = np.mean([o.reordered for o in outs])
    assert reo == pytest.approx(0.20, abs=0.03)


def test_tbf_serialization():
    # 8 Mbit/s = 1 byte/µs; burst = rate/250 = 32000 bytes. After the
    # initial burst is spent, 1000-byte packets serialize at 1000 µs each.
    s = state_with(LinkProperties(rate="8Mbit"))
    _, outs = run_packets(s, 40)
    d = np.array([o.depart_us for o in outs])
    # first 32 packets ride the initial 32000-byte burst: depart immediately
    np.testing.assert_allclose(d[:32], 0.0, atol=1e-2)
    # each subsequent packet waits for 1000 fresh tokens
    np.testing.assert_allclose(np.diff(d[32:]), 1000.0, rtol=1e-3)


def test_tbf_burst_floor():
    # 1 Mbit/s: rate/250 = 4000 < 5000 => the 5000-byte floor applies
    # (common/qdisc.go:364-367). 0.125 B/µs => 8000 µs per 1000-byte packet.
    s = state_with(LinkProperties(rate="1Mbit"))
    _, outs = run_packets(s, 8)
    d = np.array([o.depart_us for o in outs])
    np.testing.assert_allclose(d[:5], 0.0, atol=1e-2)
    np.testing.assert_allclose(np.diff(d[5:]), 8000.0, rtol=1e-3)


def test_tbf_queue_limit_drops():
    # 50ms queue at 1 byte/µs: after the 32-packet burst, queued packets
    # wait (i-31)*1000 µs; waits beyond 50ms are dropped (packet ~83 on).
    s = state_with(LinkProperties(rate="8Mbit"))
    _, outs = run_packets(s, 100)
    dropped = np.array([o.dropped_queue for o in outs])
    assert dropped.any()
    assert not dropped[:80].any()  # early packets fit in burst + queue
    assert dropped[85:].all()
    d = np.array([o.depart_us for o in outs])
    assert np.all(np.isinf(d[dropped]))


def test_netem_then_tbf_composition():
    # latency 10ms + 8Mbit rate: depart = 10ms + serialization.
    s = state_with(LinkProperties(latency="10ms", rate="8Mbit"))
    _, outs = run_packets(s, 40)
    d = np.array([o.depart_us for o in outs])
    assert d[0] == pytest.approx(10_000.0, rel=1e-5)
    np.testing.assert_allclose(np.diff(d[32:]), 1000.0, rtol=1e-3)


def test_loss_does_not_consume_tokens():
    s = state_with(LinkProperties(loss="100", rate="8Mbit"))
    s1, outs = run_packets(s, 20)
    assert all(o.dropped_loss for o in outs)
    # bucket untouched: still full at burst = 8e6/250
    assert float(s1.tokens[0]) == pytest.approx(32000.0)


def test_inactive_edges_untouched():
    s = state_with(LinkProperties(latency="1ms"), n_edges=1, capacity=4)
    sizes = jnp.full((4,), 100.0, jnp.float32)
    have = jnp.ones((4,), bool)  # claim packets everywhere...
    s2, res = netem.shape_step(s, sizes, have,
                               jnp.zeros((4,), jnp.float32),
                               jax.random.key(0))
    r = jax.tree.map(np.asarray, res)
    assert r.delivered[0]
    assert not r.delivered[1:].any()  # ...but only active edges deliver


def test_roll_epoch():
    s = state_with(LinkProperties(rate="8Mbit"))
    s = dataclasses.replace(
        s, t_last=s.t_last.at[0].set(500.0),
        backlog_until=s.backlog_until.at[0].set(700.0))
    s = netem.roll_epoch(s, jnp.float32(300.0))
    assert float(s.t_last[0]) == pytest.approx(200.0)
    assert float(s.backlog_until[0]) == pytest.approx(400.0)


def test_determinism():
    s1 = state_with(LinkProperties(loss="50", latency="1ms", jitter="1ms"))
    s2 = state_with(LinkProperties(loss="50", latency="1ms", jitter="1ms"))
    _, o1 = run_packets(s1, 50, seed=7)
    _, o2 = run_packets(s2, 50, seed=7)
    for a, b in zip(o1, o2):
        assert a.depart_us == b.depart_us
        assert a.dropped_loss == b.dropped_loss


def test_duplicate_loss_interaction_kernel_parity():
    # sch_netem keeps a packet count: duplicate increments, loss decrements.
    # duplicate=100 + loss=100 => every packet triggers both => delivered
    # exactly once, never dropped, never duplicated.
    s = state_with(LinkProperties(duplicate="100", loss="100"))
    _, outs = run_packets(s, 200)
    assert all(o.delivered for o in outs)
    assert not any(o.dropped_loss for o in outs)
    assert not any(o.duplicated for o in outs)


def test_drop_does_not_advance_gap_counter():
    # Kernel early-returns dropped packets before the reorder counter:
    # with loss=50 and gap=1000 (no packet ever reaches the gap window in
    # 100 packets), pkt_count must equal delivered-only count.
    s = state_with(LinkProperties(latency="1ms", loss="50",
                                  reorder_prob="1", gap=1000))
    s1, outs = run_packets(s, 100)
    delivered = sum(int(o.delivered) for o in outs)
    assert int(s1.pkt_count[0]) == delivered
