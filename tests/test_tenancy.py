"""Multi-tenant plane: registry, admission, QoS, observability, and
the jtenant isolation audit.

- block reservation composes with shard blocks (parallel.partition
  .tenant_block) and steers the engine allocator; freed rows return to
  the owning tenant's pool;
- admission token buckets throttle with typed, metered verdicts and
  never drop (noisy_neighbor smoke, <30s tier-1);
- QoS classes scale the per-wire drain budget;
- per-tenant counters PARTITION the plane-global counters exactly —
  property-tested over random multi-tenant specs at both pipeline
  depths, with compact()'s remap carried per tenant;
- kubedtn_tenant_* series + the cardinality truncation guard;
- Local.Tenant* RPC round-trip; reconciler namespace→tenant mapping;
- the jtenant pass kills its seeded cross-tenant-scatter mutant while
  the clean control stays silent.
"""

import importlib.util
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.tenancy import (HostTokenBucket, TenantRegistry)
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

pytestmark = pytest.mark.tenancy

_SPEC = importlib.util.spec_from_file_location(
    "dtnverify_mutants_tenancy",
    Path(__file__).parent / "fixtures" / "dtnverify" / "mutants.py")
mutants = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(mutants)


# -- token bucket -------------------------------------------------------

def test_token_bucket_deterministic():
    b = HostTokenBucket(100.0)  # 100/s, burst 100
    assert b.ok(0.0)
    b.charge(100.0, 0.0)
    assert not b.ok(0.0)   # fill exactly 0: throttled
    assert b.ok(0.5)       # +50 tokens refilled: admits again
    # overdraw into debt (batch-granular charging): throttled until
    # the refill crosses back above zero, then admitting again
    b.charge(200.0, 0.5)
    assert not b.ok(1.0)   # fill = -150 + 50 = -100
    assert b.ok(3.0)       # +200 more since 1.0s

    unlimited = HostTokenBucket(0.0)
    assert unlimited.ok(0.0)
    unlimited.charge(1e9, 0.0)
    assert unlimited.ok(0.0)


# -- registry / blocks --------------------------------------------------

def _engine(capacity=64):
    store = TopologyStore()
    return store, SimEngine(store, capacity=capacity)


def test_tenant_block_composes_with_shard_blocks():
    from kubedtn_tpu.parallel.partition import tenant_block

    free = list(range(63, -1, -1))
    blk = tenant_block(free, 64, 4, 10)  # shard blocks of 16
    lo, hi = blk
    assert hi - lo == 10
    assert lo // 16 == (hi - 1) // 16  # inside ONE shard block
    assert not any(lo <= r < hi for r in free)
    # a second tenant gets a disjoint block
    blk2 = tenant_block(free, 64, 4, 10)
    assert blk2 is not None and (blk2[1] <= lo or blk2[0] >= hi)


def test_block_steers_allocation_and_release():
    _store, engine = _engine()
    reg = TenantRegistry(engine)
    t = reg.create("acme", block_edges=8)
    lo, hi = t.block
    with engine._lock:
        r1 = engine._alloc("acme/p1", 1)
        r2 = engine._alloc("acme/p2", 1)
        other = engine._alloc("else/p1", 1)
    assert lo <= r1 < hi and lo <= r2 < hi
    assert not (lo <= other < hi)
    # freed block rows return to the tenant pool, not the global list
    n_free = len(t.block_free)
    with engine._lock:
        engine._free_row(r1)
    assert len(t.block_free) == n_free + 1
    assert r1 not in engine._free


def test_registry_quota_namespace_and_compact():
    _store, engine = _engine()
    reg = TenantRegistry(engine)
    reg.create("a", qos="gold", frame_budget_per_s=10.0,
               block_edges=4)
    reg.set_quota("a", qos="bronze", frame_budget_per_s=99.0)
    assert reg.get("a").qos == "bronze"
    assert reg.get("a").bucket_frames.rate_per_s == 99.0
    reg.bind_namespace("a-extra", "a")
    assert reg.tenant_of_pod_key("a-extra/pod").name == "a"
    with pytest.raises(ValueError):
        reg.create("bad", qos="platinum")
    # compact re-carves the FULL requested reservation (4 rows, not
    # the 3 unused — the pre-compact row lives outside the new block
    # and returns to the global pool when freed, so an unused-only
    # re-carve would decay the entitlement on every compact/free
    # cycle) and accounting survives the renumbering
    with engine._lock:
        engine._alloc("a/p", 1)
    engine.compact()
    t = reg.get("a")
    assert t.block is not None and len(t.block_free) == 4
    assert reg.rows_of("a").tolist() == [0]
    # the re-carved block keeps steering the tenant's allocations
    with engine._lock:
        r = engine._alloc("a/p2", 1)
    assert t.block[0] <= r < t.block[1]


def test_block_re_reserved_lazily_on_create():
    """The post-compact fallback: a tenant whose block stayed
    dissolved gets it back on the next create(block_edges=...), and
    the idempotent create never moves an existing block."""
    _store, engine = _engine()
    reg = TenantRegistry(engine)
    t = reg.create("acme", block_edges=8)
    blk = t.block
    assert reg.create("acme", block_edges=8).block == blk
    # simulate a failed post-compact re-carve (on_compact's warning
    # path): block dissolved, rows back on the global free list
    with engine._lock:
        engine._free.extend(t.block_free)
    with reg._lock:
        t.block = None
        t.block_free = []
    t2 = reg.create("acme", block_edges=8)
    assert t2 is t and t.block is not None
    assert len(t.block_free) == 8
    # a later compact ALSO heals a dissolved reservation (block_rows
    # survives the dissolve)
    with engine._lock:
        engine._free.extend(t.block_free)
    with reg._lock:
        t.block = None
        t.block_free = []
    engine.compact()
    assert t.block is not None and len(t.block_free) == 8


def test_create_race_loser_namespaces_bind_to_winner(monkeypatch):
    """When two create()s race on one name, the loser's namespaces
    must land in BOTH the winner's ns_map entries (admission) and its
    `namespaces` set (accounting) — a ns_map-only bind would make
    tenant_of_pod_key and rows_of permanently disagree. The race is
    simulated deterministically: the winner publishes while the loser
    is between its existence check and its own publish (tenants are
    published BEFORE any block is carved, so the loser never holds
    rows a concurrent compact could double-free)."""
    import kubedtn_tpu.tenancy.registry as regmod

    _store, engine = _engine()
    reg = TenantRegistry(engine)
    real_tenant = regmod.Tenant

    def racing_tenant(*args, **kw):
        t = real_tenant(*args, **kw)
        if kw.get("name") == "x" and "x" not in reg._tenants:
            with reg._lock:
                reg._tenants["x"] = real_tenant(name="x")
                reg._ns_map.setdefault("x", "x")
        return t

    monkeypatch.setattr(regmod, "Tenant", racing_tenant)
    won = reg.create("x", block_edges=4, namespaces={"x", "extra"})
    assert won is reg.get("x")
    assert reg._ns_map["extra"] == "x"
    assert "extra" in won.namespaces
    # the block the caller asked for lands on the WINNER, carved off
    # the free list exactly once (no duplicate free-list entries)
    assert won.block is not None and len(won.block_free) == 4
    assert len(engine._free) == engine._state.capacity - 4
    assert len(set(engine._free)) == len(engine._free)


def test_link_key_id_two_word_64_bit():
    """link_key_id spans 64 bits (no 31-bit birthday collisions at
    plane scale) and row_keys folds BOTH words: identities that share
    a lo word still get distinct per-row streams."""
    from kubedtn_tpu.ops import netem
    from kubedtn_tpu.topology.engine import link_key_id

    ids = {link_key_id(f"ns/p{i}", i % 7) for i in range(2000)}
    assert len(ids) == 2000
    assert any(k >> 32 for k in ids)
    ks = netem.row_keys(jax.random.key(0),
                        jnp.asarray([[1, 0], [1, 1]], jnp.uint32))
    assert not np.array_equal(np.asarray(jax.random.key_data(ks[0])),
                              np.asarray(jax.random.key_data(ks[1])))


def test_reconciler_maps_namespace_to_tenant():
    store, engine = _engine()
    reg = TenantRegistry(engine)
    store.create(Topology(name="p", namespace="team-x",
                          spec=TopologySpec()))
    Reconciler(store, engine).reconcile("team-x", "p")
    assert reg.get("team-x") is not None
    assert reg.tenant_of_pod_key("team-x/p").name == "team-x"


# -- multi-tenant plane harness ----------------------------------------

PROPS_MENU = [
    LinkProperties(latency="1ms"),
    LinkProperties(latency="2ms", loss="20"),
    LinkProperties(rate="1Mbit"),
    LinkProperties(latency="1ms", loss="15", loss_corr="30"),
]


def _tenant_plane(spec, depth=1, capacity=None, qos=None, budgets=None):
    """spec: {tenant: [(uid, props_idx), ...]} — one link pair per
    entry. Returns (plane, registry, {tenant: (wins, wouts)})."""
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    n_pairs = sum(len(v) for v in spec.values())
    store = TopologyStore()
    engine = SimEngine(store, capacity=capacity or 4 * n_pairs + 8)
    reg = TenantRegistry(engine)
    for ns in spec:
        reg.create(ns, qos=(qos or {}).get(ns),
                   frame_budget_per_s=(budgets or {}).get(ns, 0.0))
    for ns, links in spec.items():
        for uid, pi in links:
            a, b = f"{ns}-a{uid}", f"{ns}-b{uid}"
            props = PROPS_MENU[pi % len(PROPS_MENU)]
            store.create(Topology(name=a, namespace=ns,
                                  spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                     uid=uid, properties=props)])))
            store.create(Topology(name=b, namespace=ns,
                                  spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                     uid=uid, properties=props)])))
            engine.setup_pod(a, ns)
            engine.setup_pod(b, ns)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=depth)
    plane.pipeline_explicit_clock = True
    plane.attach_tenancy(reg)
    wires = {}
    for ns, links in spec.items():
        win, wout = [], []
        for uid, _pi in links:
            win.append(daemon._add_wire(pb.WireDef(
                local_pod_name=f"{ns}-a{uid}", kube_ns=ns,
                link_uid=uid, intf_name_in_pod="eth1")))
            wout.append(daemon._add_wire(pb.WireDef(
                local_pod_name=f"{ns}-b{uid}", kube_ns=ns,
                link_uid=uid, intf_name_in_pod="eth1")))
        wires[ns] = (win, wout)
    return plane, reg, wires


# -- QoS drain weights --------------------------------------------------

def test_qos_budget_weights():
    spec = {"gold": [(1, 0)], "bronze": [(2, 0)]}
    plane, reg, wires = _tenant_plane(
        spec, qos={"gold": "gold", "bronze": "bronze"})
    policy = reg.drain_policy(100, 0.0)
    gw = wires["gold"][0][0]
    bw = wires["bronze"][0][0]
    assert policy(gw) == 100
    assert policy(bw) == 25

    class FakeWire:
        pod_key = "untenanted/p"
        wire_id = 1
        ingress = []

    assert policy(FakeWire()) == 100  # unmapped ns: full budget
    plane.stop()


# -- admission: noisy-neighbor smoke (<30s) -----------------------------

def test_noisy_neighbor_smoke():
    """The tier-1 chaos smoke: the aggressor is throttled at its
    budget with typed metered verdicts and zero dropped frames; the
    victim loses nothing and is never throttled."""
    from kubedtn_tpu.scenarios import noisy_neighbor

    out = noisy_neighbor(victim_pairs=1, aggressor_pairs=1,
                         seconds=1.0, victim_rate_fps=800,
                         aggressor_rate_fps=8_000,
                         aggressor_budget_fps=800)
    assert out["in_guardrails"], out
    assert out["victim_lost"] == 0
    assert out["throttle_events"] > 0
    assert out["aggressor_queued_not_dropped"] > 0
    assert (out["aggressor_admitted"] + out["aggressor_queued_not_dropped"]
            == out["aggressor_fed"])  # throttled, never dropped
    assert out["dropped"] == 0


@pytest.mark.requires_native_shm
def test_noisy_neighbor_shm_aggressor_smoke():
    """Same contract, shm transport: the aggressor feeds through a
    shared-memory ring, so admission lands at the RING HEAD and the
    over-budget backlog parks in the segment — throttled, never
    dropped, victim untouched."""
    from kubedtn_tpu.scenarios import noisy_neighbor

    out = noisy_neighbor(victim_pairs=1, aggressor_pairs=1,
                         seconds=1.0, victim_rate_fps=800,
                         aggressor_rate_fps=8_000,
                         aggressor_budget_fps=800,
                         aggressor_via_shm=True)
    assert out["in_guardrails"], out
    assert out["aggressor_transport"] == "shm"
    assert out["victim_lost"] == 0
    assert out["throttle_events"] > 0
    assert out["shm"]["throttled_events"] > 0  # verdicts at ring head
    # exact accounting: every unadmitted frame is parked in the ring
    # (or the sender's outage buffer), none dropped
    assert (out["aggressor_admitted"] + out["aggressor_queued_not_dropped"]
            == out["aggressor_fed"])
    assert out["dropped"] == 0


def test_throttle_verdicts_are_typed_and_metered():
    spec = {"busy": [(1, 0)]}
    plane, reg, wires = _tenant_plane(spec, budgets={"busy": 10.0})
    win, wout = wires["busy"]
    t = 50.0
    for j in range(40):
        win[0].ingress.extend([b"\x02" * 60] * 5)
        t += 0.002
        plane.tick(now_s=t)
    verds = reg.admission.recent()
    assert verds, "expected throttle verdicts"
    v = verds[-1]
    assert v.tenant == "busy" and v.reason == "frame-budget"
    assert v.queued_frames > 0
    st = reg.admission.stats_for("busy")
    assert st["throttle_events"] == len(
        [x for x in verds if x.tenant == "busy"])
    plane.stop()


# -- per-tenant counters partition the global ones (property test) -----

@pytest.mark.parametrize("depth", [1, 2], ids=["d1", "d2"])
@pytest.mark.parametrize("seed", [0, 1])
def test_tenant_counters_partition_global(depth, seed):
    """Random multi-tenant specs: the per-tenant counter slices sum
    EXACTLY to the plane-global counters over active rows — including
    after a mid-run compact() (remap carried per tenant)."""
    rng = np.random.default_rng(seed)
    n_tenants = int(rng.integers(2, 5))
    uid = 0
    spec = {}
    for i in range(n_tenants):
        links = []
        for _ in range(int(rng.integers(1, 4))):
            uid += 1
            links.append((uid, int(rng.integers(0, len(PROPS_MENU)))))
        spec[f"ten{i}"] = links
    plane, reg, wires = _tenant_plane(spec, depth=depth)
    t = 80.0
    for j in range(25):
        for ns, (win, _wout) in wires.items():
            for w in win:
                n = int(rng.integers(0, 6))
                w.ingress.extend([b"\x02" * int(rng.integers(60, 200))
                                  for _ in range(n)])
        t += 0.002
        plane.tick(now_s=t)
        if j == 12:
            plane.flush()
            plane.engine.compact()
    plane.flush()

    def check():
        per = {ns: reg.tenant_counters(plane, ns) for ns in spec}
        c = plane.counters
        with plane.engine._lock:
            rows = np.fromiter(plane.engine._rows.values(), np.int64,
                               len(plane.engine._rows))
        cap = np.asarray(c.tx_packets).shape[0]
        rows = rows[rows < cap]
        for key, arr in (("tx_packets", c.tx_packets),
                         ("delivered_packets", c.rx_packets),
                         ("delivered_bytes", c.rx_bytes),
                         ("dropped_loss", c.dropped_loss),
                         ("dropped_queue", c.dropped_queue),
                         ("dropped_ring", c.dropped_ring)):
            total = float(np.asarray(arr)[rows].sum())
            got = sum(p[key] for p in per.values())
            assert got == pytest.approx(total), key

    check()
    plane.engine.compact()   # remap again after the run
    check()
    plane.stop()


# -- metrics: kubedtn_tenant_* + truncation guard -----------------------

def test_tenant_metrics_and_truncation_guard():
    from prometheus_client import generate_latest

    from kubedtn_tpu.metrics.metrics import make_registry

    spec = {"m0": [(1, 0)], "m1": [(2, 0)]}
    plane, reg, wires = _tenant_plane(spec)
    t = 60.0
    for _ in range(10):
        for ns, (win, _wout) in wires.items():
            win[0].ingress.extend([b"\x02" * 60] * 4)
        t += 0.002
        plane.tick(now_s=t)
    plane.flush()
    registry, _h = make_registry(plane.engine,
                                 sim_counters_fn=plane.counters_fn,
                                 dataplane=plane, tenancy=reg)
    text = generate_latest(registry).decode()
    assert 'kubedtn_tenant_admitted_frames_total{tenant="m0"}' in text
    assert 'kubedtn_tenant_delivered_packets_total{tenant="m1"}' in text
    assert "kubedtn_tenant_series_truncated 0.0" in text
    # cardinality cap: only max_tenants exported, the guard counts
    registry2, _h2 = make_registry(plane.engine,
                                   sim_counters_fn=plane.counters_fn,
                                   dataplane=plane, tenancy=reg,
                                   max_tenants=1)
    text2 = generate_latest(registry2).decode()
    assert 'tenant="m0"' in text2 and 'tenant="m1"' not in text2
    assert "kubedtn_tenant_series_truncated 1.0" in text2
    plane.stop()


# -- Local.Tenant* RPC surface -----------------------------------------

def test_tenant_rpc_roundtrip():
    from kubedtn_tpu.wire import proto as pb

    spec = {"rpc0": [(1, 0)]}
    plane, reg, wires = _tenant_plane(spec)
    daemon = plane.daemon
    resp = daemon.TenantCreate(pb.TenantSpec(
        name="newt", qos="gold", frame_budget_per_s=123.0,
        block_edges=4), None)
    assert resp.ok, resp.error
    assert resp.tenant.qos == "gold"
    assert resp.tenant.block_lo >= 0
    lst = daemon.TenantList(pb.TenantQuery(), None)
    assert lst.ok and {t.name for t in lst.tenants} == {"rpc0", "newt"}
    q = daemon.TenantQuota(pb.TenantSpec(name="newt", qos="silver"),
                           None)
    assert q.ok and q.tenant.qos == "silver"
    missing = daemon.TenantQuota(pb.TenantSpec(name="ghost"), None)
    assert not missing.ok
    t = 42.0
    wires["rpc0"][0][0].ingress.extend([b"\x02" * 60] * 8)
    plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 1.0)
    st = daemon.TenantStats(pb.TenantQuery(name="rpc0"), None)
    assert st.ok, st.error
    assert st.admitted_frames == 8
    assert st.tx_packets == 8.0
    plane.stop()

    # a daemon without tenancy answers loudly, not with a crash
    from kubedtn_tpu.wire.server import Daemon

    _store2, engine2 = _engine()
    bare = Daemon(engine2)
    r = bare.TenantCreate(pb.TenantSpec(name="x"), None)
    assert not r.ok and "not enabled" in r.error


# -- jtenant: the cross-tenant-scatter mutant ---------------------------

def test_cross_tenant_scatter_mutant_killed():
    from kubedtn_tpu.analysis.verify.entrypoints import EntryPoint
    from kubedtn_tpu.analysis.verify.tenant_audit import \
        check_tenant_isolation

    soa = jnp.zeros((16,))
    rows = jnp.zeros((4,), jnp.int32)
    upd = jnp.ones((4,))
    ep = EntryPoint("mutant_cross_tenant_scatter",
                    "tests/fixtures/dtnverify/mutants.py", 1)
    ep.jaxpr = jax.make_jaxpr(mutants.mutant_cross_tenant_scatter)(
        soa, rows, upd)
    found: list = []
    check_tenant_isolation(ep, found)
    assert any("another tenant's edge range" in f.message
               for f in found), found


def test_clean_tenant_scatter_control_silent():
    from kubedtn_tpu.analysis.verify.entrypoints import EntryPoint
    from kubedtn_tpu.analysis.verify.tenant_audit import \
        check_tenant_isolation

    soa = jnp.zeros((16,))
    rows = jnp.zeros((4,), jnp.int32)
    valid = jnp.ones((4,), bool)
    upd = jnp.ones((4,))
    ep = EntryPoint("clean_tenant_scatter",
                    "tests/fixtures/dtnverify/mutants.py", 1)
    ep.jaxpr = jax.make_jaxpr(mutants.clean_tenant_scatter)(
        soa, rows, valid, upd)
    found: list = []
    check_tenant_isolation(ep, found)
    assert found == []


def test_no_scatter_program_is_harness_drift():
    from kubedtn_tpu.analysis.verify.entrypoints import EntryPoint
    from kubedtn_tpu.analysis.verify.tenant_audit import \
        check_tenant_isolation

    ep = EntryPoint("scatterless", "x", 1)
    ep.jaxpr = jax.make_jaxpr(lambda x: x * 2.0)(jnp.zeros((4,)))
    found: list = []
    check_tenant_isolation(ep, found)
    assert any("harness drift" in f.message for f in found)


# -- per-row keyed draws: the kernel-level mechanism --------------------

def test_keyed_draws_are_batch_composition_independent():
    """A row's uniforms with key_ids depend only on its key id — the
    same row alone and in a mixed batch draws identical bits (the
    netem-level statement of the tenant byte-identity contract)."""
    import dataclasses

    from kubedtn_tpu.ops import edge_state as es
    from kubedtn_tpu.ops import netem

    state = es.init_state(8)
    props = np.zeros((8, es.NPROP), np.float32)
    props[:, es.P_LATENCY_US] = 500.0
    props[:, es.P_LOSS] = 30.0
    state = dataclasses.replace(state, props=jnp.asarray(props),
                                active=jnp.ones((8,), bool))
    key = jax.random.key(7)
    sizes = jnp.full((2, 4), 100.0, jnp.float32)
    valid = jnp.ones((2, 4), bool)
    kids = jnp.asarray([5, 9], jnp.int32)
    res_pair, _ = netem.shape_slots_indep_nodonate(
        state, jnp.asarray([1, 3], jnp.int32), sizes, valid, key, kids)
    res_solo, _ = netem.shape_slots_indep_nodonate(
        state, jnp.asarray([3], jnp.int32), sizes[1:], valid[1:], key,
        kids[1:])
    np.testing.assert_array_equal(np.asarray(res_pair.delivered[1]),
                                  np.asarray(res_solo.delivered[0]))
    np.testing.assert_array_equal(np.asarray(res_pair.depart_us[1]),
                                  np.asarray(res_solo.depart_us[0]))
