"""Cross-subsystem concurrency stress: control-plane RPCs, data-plane
ticks, frame ingestion, and metrics scrapes all hammer one daemon at
once; afterwards the host registries, device arrays, and counters must
be consistent and no thread may have died.

The reference's concurrency discipline is hand-rolled per structure
(per-uid mutexes, sync.Map, RetryOnConflict — SURVEY §5.2); here the
engine lock + lock-free tick snapshot + generation-cached placements
carry the same load, and this test is the standing proof they compose.
"""

import threading
import time

import numpy as np

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, TopologySpec
from kubedtn_tpu.metrics.metrics import make_registry
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.client import DaemonClient
from kubedtn_tpu.wire.server import Daemon, make_server
from prometheus_client import generate_latest

PODS = 8
UIDS_PER_POD = 4


def _cluster():
    store = TopologyStore()
    engine = SimEngine(store, capacity=256, node_ip="10.0.0.1")
    props = LinkProperties(latency="1ms")
    names = [f"s{i}" for i in range(PODS)]
    specs = {n: [] for n in names}
    uid = 0
    for i, a in enumerate(names):
        b = names[(i + 1) % PODS]
        for _ in range(UIDS_PER_POD):
            uid += 1
            specs[a].append(Link(local_intf=f"e{uid}a", peer_intf=f"e{uid}b",
                                 peer_pod=b, uid=uid, properties=props))
            specs[b].append(Link(local_intf=f"e{uid}b", peer_intf=f"e{uid}a",
                                 peer_pod=a, uid=uid, properties=props))
    for n in names:
        t = Topology(name=n, spec=TopologySpec(links=specs[n]))
        store.create(t)
    for n in names:
        engine.setup_pod(n)
    Reconciler(store, engine).drain()
    return store, engine, names


def test_concurrent_rpc_ticks_and_scrapes():
    store, engine, names = _cluster()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=2000.0)
    registry, hist = make_registry(engine,
                                   sim_counters_fn=plane.counters_fn)
    engine.stats.observer = hist
    daemon.hist = hist
    server, port = make_server(daemon, port=0, host="127.0.0.1")
    server.start()
    plane.start()

    stop = threading.Event()
    errors: list[BaseException] = []

    def guard(fn):
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — surface anything
                errors.append(e)
        return run

    def updater():
        c = DaemonClient(f"127.0.0.1:{port}")
        props_cycle = [pb.props_to_proto(LinkProperties(latency=l))
                       for l in ("1ms", "5ms", "")]
        i = 0
        while not stop.is_set():
            name = names[i % PODS]
            links = [pb.link_to_proto(l)
                     for l in store.get("default", name).spec.links]
            for l in links:
                l.properties.CopyFrom(props_cycle[i % 3])
            c.UpdateLinks(pb.LinksBatchQuery(
                local_pod=pb.Pod(name=name, kube_ns="default"),
                links=links))
            i += 1
        c.close()

    def churner():
        # destroy/re-setup one pod over and over through the engine
        i = 0
        while not stop.is_set():
            pod = names[i % PODS]
            engine.destroy_pod(pod)
            engine.setup_pod(pod)
            i += 1
            time.sleep(0.002)

    def injector():
        c = DaemonClient(f"127.0.0.1:{port}")
        r = c.AddGRPCWireRemote(pb.WireDef(
            local_pod_name=names[0], kube_ns="default", link_uid=1,
            intf_name_in_pod="eth1"))
        wid = int(r.peer_intf_id)
        while not stop.is_set():
            c.InjectFrame(pb.Packet(remot_intf_id=wid, frame=b"x" * 120))
            time.sleep(0.001)
        c.close()

    def scraper():
        while not stop.is_set():
            out = generate_latest(registry)
            assert b"kubedtnd_request_duration" in out
            time.sleep(0.005)

    threads = [threading.Thread(target=guard(f), daemon=True)
               for f in (updater, churner, injector, scraper)]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "stress worker hung"
    plane.stop()
    server.stop(0)

    assert not errors, errors
    assert plane.tick_errors == 0
    # final consistency: re-setup everything, host registry == device mask
    for n in names:
        engine.setup_pod(n)
    Reconciler(store, engine).drain()
    n_host = engine.num_active
    n_dev = int(np.asarray(engine.state.active).sum())
    assert n_host == n_dev
    # every declared link is realized again (all pods alive)
    assert n_host == 2 * PODS * UIDS_PER_POD
