"""CNI shim protocol tests (against a live daemon on a loopback port) and
tracing subsystem tests."""

import json

import pytest

from kubedtn_tpu import cni
from kubedtn_tpu.api.types import load_yaml
from kubedtn_tpu.topology import SimEngine, TopologyStore
from kubedtn_tpu.utils import tracing
from kubedtn_tpu.wire.server import Daemon, make_server

THREE_NODE = "/root/reference/config/samples/3node.yml"


@pytest.fixture()
def daemon_port():
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    for t in load_yaml(THREE_NODE):
        store.create(t)
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0)
    server.start()
    yield port, engine
    server.stop(0)


def conf(port: int, prev=None) -> str:
    d = {"cniVersion": "1.0.0", "name": "k8s-pod-network",
         "type": "kubedtn", "daemonPort": port}
    if prev is not None:
        d["prevResult"] = prev
    return json.dumps(d)


def env_for(cmd: str, pod: str, ns: str = "default") -> dict:
    return {
        "CNI_COMMAND": cmd,
        "CNI_ARGS": f"IgnoreUnknown=1;K8S_POD_NAMESPACE={ns};"
                    f"K8S_POD_NAME={pod}",
        "CNI_NETNS": f"/var/run/netns/{pod}",
        "CNI_CONTAINERID": "abc123",
    }


@pytest.mark.requires_reference_yaml
def test_cmd_add_realizes_pod(daemon_port, capsys):
    port, engine = daemon_port
    prev = {"cniVersion": "1.0.0", "ips": [{"address": "10.244.0.7/24"}]}
    rc = cni.main(stdin_text=conf(port, prev), env=env_for("ADD", "r1"))
    assert rc == 0
    # chained prevResult is passed through on stdout
    out = json.loads(capsys.readouterr().out)
    assert out == prev
    assert engine.is_alive("default/r1")


@pytest.mark.requires_reference_yaml
def test_add_then_peer_plumbs_links(daemon_port, capsys):
    port, engine = daemon_port
    cni.main(stdin_text=conf(port), env=env_for("ADD", "r1"))
    cni.main(stdin_text=conf(port), env=env_for("ADD", "r2"))
    capsys.readouterr()
    # r1<->r2 link realized by whichever pod came up last
    assert engine.num_active >= 2


@pytest.mark.requires_reference_yaml
def test_non_topology_pod_errors_but_del_is_silent(daemon_port, capsys):
    port, engine = daemon_port
    # SetupPod returns True for unknown pods (delegate), so ADD succeeds
    rc = cni.main(stdin_text=conf(port), env=env_for("ADD", "not-a-twin"))
    assert rc == 0
    capsys.readouterr()
    # DEL of an unknown pod must never fail pod teardown
    rc = cni.main(stdin_text=conf(port), env=env_for("DEL", "not-a-twin"))
    assert rc == 0


@pytest.mark.requires_reference_yaml
def test_cmd_del(daemon_port, capsys):
    port, engine = daemon_port
    cni.main(stdin_text=conf(port), env=env_for("ADD", "r1"))
    cni.main(stdin_text=conf(port), env=env_for("ADD", "r2"))
    rc = cni.main(stdin_text=conf(port), env=env_for("DEL", "r1"))
    capsys.readouterr()
    assert rc == 0
    assert not engine.is_alive("default/r1")


def test_version(capsys):
    rc = cni.main(stdin_text="", env={"CNI_COMMAND": "VERSION"})
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert "1.0.0" in out["supportedVersions"]


@pytest.mark.requires_reference_yaml
def test_check_noop(daemon_port):
    port, _ = daemon_port
    assert cni.main(stdin_text=conf(port), env=env_for("CHECK", "r1")) == 0


def test_conflist_install_merge_and_remove(tmp_path):
    primary = {"cniVersion": "0.3.1", "name": "cbr0",
               "plugins": [{"type": "flannel"}]}
    (tmp_path / "10-flannel.conflist").write_text(json.dumps(primary))

    out = cni.install_conflist(str(tmp_path), inter_node_link_type="GRPC",
                               daemon_port=5151)
    merged = json.loads(open(out).read())
    types = [p["type"] for p in merged["plugins"]]
    assert types == ["flannel", "kubedtn"]   # chained after the primary
    assert merged["plugins"][1]["daemonPort"] == 5151
    assert cni.inter_node_link_type(str(tmp_path)) == "GRPC"

    # idempotent: re-install doesn't duplicate the plugin
    cni.install_conflist(str(tmp_path))
    merged = json.loads(open(out).read())
    assert [p["type"] for p in merged["plugins"]].count("kubedtn") == 1

    cni.remove_conflist(str(tmp_path))
    assert not (tmp_path / cni.CONFLIST_NAME).exists()
    assert cni.inter_node_link_type(str(tmp_path)) == "VXLAN"  # default


def test_wrap_bare_conf(tmp_path):
    (tmp_path / "05-bridge.conf").write_text(json.dumps(
        {"cniVersion": "0.4.0", "name": "bridge", "type": "bridge"}))
    out = cni.install_conflist(str(tmp_path))
    merged = json.loads(open(out).read())
    assert [p["type"] for p in merged["plugins"]] == ["bridge", "kubedtn"]


# ---- tracing --------------------------------------------------------

def test_spans_nest_and_aggregate():
    tr = tracing.Tracer()
    with tr.span("reconcile"):
        with tr.span("add-links", n=3):
            pass
        with tr.span("status-copy"):
            pass
    spans = tr.spans()
    by_name = {s.name: s for s in spans}
    assert by_name["add-links"].depth == 1
    assert by_name["reconcile"].depth == 0
    assert by_name["add-links"].meta == {"n": 3}
    stats = tr.stats()
    assert stats["reconcile"]["count"] == 1
    assert stats["reconcile"]["total_ms"] >= stats["add-links"]["total_ms"]


def test_traced_decorator_and_export(tmp_path):
    tr = tracing.Tracer()

    @tr.traced("work")
    def work(x):
        return x * 2

    assert work(21) == 42
    path = str(tmp_path / "trace.json")
    tr.export_chrome(path)
    data = json.load(open(path))
    assert data["traceEvents"][0]["name"] == "work"
    assert data["traceEvents"][0]["ph"] == "X"


def test_disabled_tracer_is_free():
    tr = tracing.Tracer(enabled=False)
    with tr.span("x"):
        pass
    assert tr.spans() == []
