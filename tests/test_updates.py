"""Planned-update engine (kubedtn_tpu/updates/): planner ordering +
static check, twin verification gate, stager equivalence/rollback, the
reconciler's planned path, and the PlanUpdate/ApplyPlan wire surface.

The two acceptance pins (ISSUE 8):

- a CLEAN planned update staged through the live plane is byte-identical
  to a direct `update_links` apply — edge-state SoA and telemetry ring
  totals — at pipeline depths 1 and 2, unsharded and on the 8-device
  forced-host CPU mesh;
- a REGRESSING delta is rejected by the twin gate before touching the
  live plane, and a mid-staging regression rolls back through the
  journal: configuration state (uid/active/props, and src/dst on every
  row active in either state) plus the host registries restore
  bit-exactly, with dead-row residue exactly matching the engine's own
  delete semantics.
"""

import numpy as np
import pytest

import jax

from test_pipeline_determinism import _daemon_with_pairs, _tagged_frames

from kubedtn_tpu.api.types import Link, LinkProperties
from kubedtn_tpu.parallel.mesh import make_mesh
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.updates import (
    Guardrails,
    PlanError,
    UpdateRound,
    check_plan,
    inverse_round,
    plan_update,
    verify_plan,
)
from kubedtn_tpu.updates.stager import UpdateStats
from kubedtn_tpu.twin.snapshot import snapshot_from_engine


def _link(uid, peer="b0", intf="eth1", props=None):
    return Link(local_intf=intf, peer_intf=intf, peer_pod=peer, uid=uid,
                properties=props or LinkProperties())


# ---- planner ----------------------------------------------------------

class TestPlanner:
    def test_make_before_break_order(self):
        old = [_link(1), _link(2, props=LinkProperties(latency="1ms"))]
        new = [_link(2, props=LinkProperties(latency="9ms")), _link(3)]
        plan = plan_update(old, new, name="a")
        kinds = [("add" if r.adds else "change" if r.changes else "del")
                 for r in plan.rounds]
        assert kinds == ["add", "change", "del"]
        assert plan.checked
        assert plan.n_edits == 3

    def test_round_chunking(self):
        old = []
        new = [_link(i, peer=f"b{i}") for i in range(5)]
        plan = plan_update(old, new, name="a", max_round_edits=2)
        assert [len(r.adds) for r in plan.rounds] == [2, 2, 1]
        assert [r.index for r in plan.rounds] == [0, 1, 2]

    def test_empty_diff_empty_plan(self):
        links = [_link(1), _link(2)]
        plan = plan_update(links, list(links), name="a")
        assert plan.rounds == ()
        assert plan.n_edits == 0

    def test_changes_carry_old_props(self):
        old = [_link(1, props=LinkProperties(latency="1ms"))]
        new = [_link(1, props=LinkProperties(latency="9ms"))]
        plan = plan_update(old, new, name="a")
        (rnd,) = plan.rounds
        assert rnd.changes[0].properties.latency == "9ms"
        assert rnd.changes_old[0].properties.latency == "1ms"

    def test_inverse_round(self):
        old = [_link(1, props=LinkProperties(latency="1ms")), _link(2)]
        new = [_link(1, props=LinkProperties(latency="9ms")), _link(3)]
        plan = plan_update(old, new, name="a")
        for rnd in plan.rounds:
            inv = inverse_round(rnd)
            assert inv.adds == rnd.dels
            assert inv.dels == rnd.adds
            assert inv.changes == rnd.changes_old
            assert inv.changes_old == rnd.changes

    def test_check_rejects_delete_before_add(self):
        # identity change: a<->b0 connectivity moves from uid 1 to uid 2.
        # Deleting first blackholes the pair transiently — the planner
        # never emits this order; the check must refuse it.
        old = [_link(1, intf="eth1")]
        new = [_link(2, intf="eth2")]
        plan = plan_update(old, new, name="a")
        assert plan.checked  # planner's own order passes
        bad = (UpdateRound(index=0, dels=tuple(old)),
               UpdateRound(index=1, adds=tuple(new)))
        with pytest.raises(PlanError, match="blackhole"):
            check_plan(plan, rounds=bad)

    def test_check_rejects_mixed_state_transient_loop(self):
        """A transition whose OLD and NEW next-hops can mix into a
        cycle must be refused: adding x-v reroutes y's traffic to v
        through x (the tie-break picks x) while x, still on the old
        round, forwards to v through y — nodes straddling the round
        barrier would bounce x -> y -> x. The planner cannot split a
        single add to fix this, so the delta is refused outright (the
        reconciler's planned path then falls back to direct apply)."""
        # fabric: x-y (uid "10"), y-w (uid "5"), w-v (uid "6").
        # old: x reaches v via y->w->v (x's next hop: y).
        # new: link x-v (uid 1) — y's next hops to v tie between x and
        # w at distance 1; the deterministic tie-break (str(uid):
        # "10" < "5") picks x. Union: x->y (old) + y->x (new) = loop.
        fabric = [("default/x", "default/y", 10),
                  ("default/y", "default/w", 5),
                  ("default/w", "default/v", 6)]
        plan = plan_update([], [_link(1, peer="v")], name="x",
                           check=False)
        with pytest.raises(PlanError, match="transient loop"):
            check_plan(plan, fabric_edges=fabric)

    def test_check_fabric_detour_allows_delete_first(self):
        # same delta, but the surrounding fabric already connects the
        # endpoints — no transient blackhole even delete-first
        old = [_link(1, intf="eth1")]
        new = [_link(2, intf="eth2")]
        plan = plan_update(old, new, name="a")
        bad = (UpdateRound(index=0, dels=tuple(old)),
               UpdateRound(index=1, adds=tuple(new)))
        reports = check_plan(plan, rounds=bad,
                             fabric_edges=[("default/a", "default/b0")])
        assert len(reports) == 2

    def test_pair_disconnected_in_endpoint_is_not_a_demand(self):
        # the END state drops the link entirely: operator intent, so the
        # intermediate states owe that pair nothing
        old = [_link(1)]
        plan = plan_update(old, [], name="a")
        assert plan.checked


# ---- verification gate ------------------------------------------------

def _realized_cluster(pairs=2, props=None):
    props = props or LinkProperties(latency="2ms")
    daemon, engine, win, wout = _daemon_with_pairs(pairs, props)
    return daemon, engine, win, wout


GATE_GUARDS = Guardrails(ticks=60, dt_us=1000.0)


class TestGate:
    def test_clean_plan_verified(self):
        _d, engine, _wi, _wo = _realized_cluster()
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        new = [l.with_properties(LinkProperties(latency="3ms"))
               for l in old]
        plan = plan_update(old, new, name="a0")
        v = verify_plan(plan, snapshot_from_engine(engine),
                        guardrails=Guardrails(ticks=60, dt_us=1000.0,
                                              max_p99_factor=4.0))
        assert v.ok, v.reason
        assert len(v.rounds) == plan.n_rounds
        assert v.baseline["delivery_ratio"] is not None
        assert v.gate_s > 0

    def test_regressing_plan_rejected(self):
        _d, engine, _wi, _wo = _realized_cluster()
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        new = [l.with_properties(LinkProperties(loss="80"))
               for l in old]
        plan = plan_update(old, new, name="a0")
        v = verify_plan(plan, snapshot_from_engine(engine),
                        guardrails=GATE_GUARDS)
        assert not v.ok
        assert "delivery" in v.reason
        assert any(not r["ok"] for r in v.rounds)

    def test_link_failure_rejected_via_fail_vocabulary(self):
        # deleting a live link tanks that edge's delivery in the sweep —
        # the DELETE round replays as a `fail` perturbation
        _d, engine, _wi, _wo = _realized_cluster()
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        plan = plan_update(old, [], name="a0")
        v = verify_plan(plan, snapshot_from_engine(engine),
                        guardrails=GATE_GUARDS)
        assert not v.ok

    def test_gate_degrade_targets_local_row_only(self):
        """With pod_ids resolving the plan topology, a CHANGE degrades
        only the LOCAL directed row — `update_links` semantics — so an
        asymmetric peer configuration (loss on the reverse row) stays
        in the replica and the gate verifies the exact end state
        staging will produce."""
        _d, engine, _wi, _wo = _realized_cluster(pairs=1)
        # make the PEER direction lossy (it keeps shaping that way
        # regardless of what the local end's update changes)
        peer = engine.store.get("default", "b0")
        assert engine.update_links(
            peer, [l.with_properties(LinkProperties(loss="50"))
                   for l in peer.spec.links])
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        new = [l.with_properties(LinkProperties(latency="3ms"))
               for l in old]
        plan = plan_update(old, new, name="a0")
        with engine._lock:
            pod_ids = dict(engine._pod_ids)
        v = verify_plan(plan, snapshot_from_engine(engine),
                        guardrails=Guardrails(ticks=60, dt_us=1000.0,
                                              max_p99_factor=8.0),
                        pod_ids=pod_ids)
        assert v.ok, v.reason
        # the peer row's 50% loss is still shaping in the round replica
        # (a uid-wide degrade would have wiped it and shown ~baseline-
        # with-no-loss delivery); baseline carries the same loss, so
        # the round's delivery must sit near the LOSSY baseline, well
        # below a loss-free one
        b = v.baseline["delivery_ratio"]
        r = v.rounds[-1]["delivery_ratio"]
        assert b < 0.9  # the peer loss shows in the baseline
        assert abs(r - b) < 0.05, (r, b)

    def test_adds_only_plan_trivially_verified(self):
        _d, engine, _wi, _wo = _realized_cluster()
        plan = plan_update([], [_link(9, peer="b0")], name="a0")
        v = verify_plan(plan, snapshot_from_engine(engine),
                        guardrails=GATE_GUARDS)
        assert v.ok
        assert v.skipped_adds == 1

    def test_cumulative_rounds(self):
        # round k's scenario carries rounds 1..k: a benign change in
        # round 1 plus a killer delete in round 2 must show round 1
        # clean and round 2 failing
        _d, engine, _wi, _wo = _realized_cluster()
        t0 = engine.store.get("default", "a0")
        t1 = engine.store.get("default", "a1")
        old = list(t0.status.links) + list(t1.status.links)
        new = [old[0].with_properties(LinkProperties(latency="3ms"))]
        plan = plan_update(old, new, name="a0")
        v = verify_plan(plan, snapshot_from_engine(engine),
                        guardrails=Guardrails(ticks=60, dt_us=1000.0,
                                              max_p99_factor=4.0))
        assert not v.ok
        assert v.rounds[0]["ok"]          # change round alone: fine
        assert not v.rounds[-1]["ok"]     # + delete round: regression


# ---- stager: staged ≡ direct ------------------------------------------

PROPS = LinkProperties(latency="3ms", jitter="1ms", loss="5")
NEW_PROPS = LinkProperties(latency="5ms", jitter="1ms", loss="2")


def _staged_or_direct(depth, mesh_n, staged, *, observe_ticks=2,
                      n_per_wire=120, ticks_before=25, ticks_after=25):
    """Drive one fresh plane through an identical deterministic
    schedule; apply the same delta staged (plan → rounds → barriers)
    or direct (one update_links). Returns (delivery, SoA columns,
    telemetry totals, plane)."""
    daemon, engine, win, wout = _daemon_with_pairs(2, PROPS)
    plane = WireDataPlane(daemon, dt_us=2000.0, pipeline_depth=depth)
    plane.pipeline_explicit_clock = True
    plane.enable_telemetry(window_s=0.01, sample_period=4)
    if mesh_n is not None:
        plane.enable_sharding(make_mesh(mesh_n))
    t = [100.0]

    def ticks(n):
        for _ in range(n):
            t[0] += 0.002
            plane.tick(now_s=t[0])

    for k, wa in enumerate(win):
        wa.ingress.extend(_tagged_frames(k, n_per_wire))
    ticks(ticks_before)
    topo = engine.store.get("default", "a0")
    old = list(topo.status.links)
    new = [l.with_properties(NEW_PROPS) for l in old]
    if staged:
        plan = plan_update(old, new, namespace="default", name="a0",
                           max_round_edits=1)
        res = plane.update_stager().stage(
            plan, topo, observe_ticks=observe_ticks, tick_driver=ticks,
            guardrails=Guardrails(max_p99_factor=8.0))
        assert res.ok, res
        assert res.rounds_applied == plan.n_rounds
    else:
        assert engine.update_links(topo, new)
        # match the staged run's tick schedule exactly: its watch
        # windows are idle ticks (no ingress), so the same idle ticks
        # here keep both runs byte-comparable
        ticks(observe_ticks * len(old))
    for k, wa in enumerate(win):
        wa.ingress.extend(_tagged_frames(k, n_per_wire))
    ticks(ticks_after)
    plane.flush()
    plane.tick(now_s=t[0] + 10.0)
    assert plane.tick_errors == 0
    st = engine.state
    cols = {n: np.asarray(getattr(st, n))
            for n in ("uid", "src", "dst", "active", "props")}
    tel, _secs = plane.telemetry.window_sum()
    return [list(w.egress) for w in wout], cols, tel, plane


@pytest.mark.parametrize("mesh_n,depth", [
    (None, 1), (None, 2), (8, 1), (8, 2),
], ids=["unsharded-d1", "unsharded-d2", "mesh8-d1", "mesh8-d2"])
def test_staged_end_state_byte_identical_to_direct(mesh_n, depth):
    """ISSUE 8 acceptance: staged apply ≡ direct update_links apply —
    per-wire delivery bytes, the full edge-state SoA configuration
    columns, and the telemetry window-ring totals — at depths 1 and 2,
    unsharded and on the 8-device CPU mesh."""
    if mesh_n is not None and len(jax.devices()) < mesh_n:
        pytest.skip(f"needs {mesh_n} devices")
    d_out, d_cols, d_tel, dp = _staged_or_direct(depth, mesh_n, False)
    s_out, s_cols, s_tel, sp = _staged_or_direct(depth, mesh_n, True)
    assert s_out == d_out
    assert sp.shaped == dp.shaped
    assert sp.dropped == dp.dropped
    for name in d_cols:
        np.testing.assert_array_equal(s_cols[name], d_cols[name],
                                      err_msg=name)
    np.testing.assert_array_equal(s_tel, d_tel)
    assert sum(len(w) for w in d_out) > 0  # guards a vacuous pass


# ---- stager: rollback --------------------------------------------------

def _registry_state(engine):
    return (dict(engine._rows), dict(engine._peer),
            dict(engine._row_owner), set(engine._shaped_rows))


def _fail_after(n):
    calls = [0]

    def health(_plane, _base):
        calls[0] += 1
        if calls[0] >= n:
            return False, "injected regression", {}
        return True, "", {}

    return health


class TestRollback:
    def _plane(self):
        daemon, engine, win, wout = _daemon_with_pairs(2, PROPS)
        plane = WireDataPlane(daemon, dt_us=2000.0, pipeline_depth=1)
        plane.pipeline_explicit_clock = True
        plane.enable_telemetry(window_s=0.01, sample_period=4)
        t = [100.0]

        def ticks(n):
            for _ in range(n):
                t[0] += 0.002
                plane.tick(now_s=t[0])

        return daemon, engine, win, wout, plane, ticks

    def test_changes_only_rollback_bit_exact(self):
        """A regression mid-staging rolls the applied rounds back: for
        a property-change plan EVERY configuration column (uid, src,
        dst, active, props) and every registry restores bit-exactly."""
        daemon, engine, win, wout, plane, ticks = self._plane()
        for k, wa in enumerate(win):
            wa.ingress.extend(_tagged_frames(k, 80))
        ticks(25)
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        new = [l.with_properties(NEW_PROPS) for l in old]
        plan = plan_update(old, new, name="a0", max_round_edits=1)
        st0 = engine.state
        pre = {n: np.asarray(getattr(st0, n)).copy()
               for n in ("uid", "src", "dst", "active", "props")}
        pre_reg = _registry_state(engine)
        res = plane.update_stager().stage(
            plan, topo, observe_ticks=1, tick_driver=ticks,
            health_check=_fail_after(1), guardrails=Guardrails())
        assert not res.ok and res.rolled_back
        assert res.rounds_applied == 0
        st1 = engine.state
        for name, a in pre.items():
            np.testing.assert_array_equal(
                np.asarray(getattr(st1, name)), a, err_msg=name)
        assert _registry_state(engine) == pre_reg
        # status was never copied: the delta remains pending
        assert engine.store.get("default", "a0").status.links == old

    def test_add_del_rollback_restores_config(self):
        """Adds/deletes roll back to the exact pre-plan rows: uid,
        active, props restore bit-exactly on every row; src/dst on
        every row that is active in either state (rows freed by the
        rolled-back add keep the engine's normal delete residue — the
        same bytes a direct add-then-delete leaves)."""
        daemon, engine, win, wout, plane, ticks = self._plane()
        ticks(5)
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        new = ([old[0].with_properties(NEW_PROPS)]
               + [_link(9, peer="b0", intf="eth7",
                        props=LinkProperties(latency="1ms"))])
        plan = plan_update(old, new, name="a0", max_round_edits=1)
        st0 = engine.state
        pre = {n: np.asarray(getattr(st0, n)).copy()
               for n in ("uid", "src", "dst", "active", "props")}
        pre_reg = _registry_state(engine)
        res = plane.update_stager().stage(
            plan, topo, observe_ticks=1, tick_driver=ticks,
            health_check=_fail_after(plan.n_rounds),
            guardrails=Guardrails())
        assert not res.ok and res.rolled_back
        st1 = engine.state
        post = {n: np.asarray(getattr(st1, n))
                for n in ("uid", "src", "dst", "active", "props")}
        for name in ("uid", "active", "props"):
            np.testing.assert_array_equal(post[name], pre[name],
                                          err_msg=name)
        live = pre["active"] | post["active"]
        np.testing.assert_array_equal(post["src"][live],
                                      pre["src"][live])
        np.testing.assert_array_equal(post["dst"][live],
                                      pre["dst"][live])
        assert _registry_state(engine) == pre_reg

    def test_rollback_then_traffic_matches_untouched_plane(self):
        """After a rollback the plane shapes EXACTLY like one that was
        never staged: identical subsequent delivery bytes (INDEP
        kernel class — no persistent row state involved)."""
        def run(staged):
            daemon, engine, win, wout, plane, ticks = self._plane()
            for k, wa in enumerate(win):
                wa.ingress.extend(_tagged_frames(k, 60))
            ticks(20)
            if staged:
                topo = engine.store.get("default", "a0")
                old = list(topo.status.links)
                new = [l.with_properties(NEW_PROPS) for l in old]
                plan = plan_update(old, new, name="a0")
                res = plane.update_stager().stage(
                    plan, topo, observe_ticks=2, tick_driver=ticks,
                    health_check=_fail_after(1),
                    guardrails=Guardrails())
                assert res.rolled_back
            else:
                ticks(2)  # the staged run's watch window, idle here
            for k, wa in enumerate(win):
                wa.ingress.extend(_tagged_frames(k, 60))
            ticks(25)
            plane.flush()
            plane.tick(now_s=1000.0)
            return [list(w.egress) for w in wout]

        assert run(True) == run(False)

    def test_engine_op_failure_rolls_back(self):
        """A mid-round engine failure (the dispatch-failure hook) rolls
        back instead of leaving a half-applied round."""
        daemon, engine, win, wout, plane, ticks = self._plane()
        ticks(3)
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        new = [l.with_properties(NEW_PROPS) for l in old]
        plan = plan_update(old, new, name="a0")
        st0 = engine.state
        pre_props = np.asarray(st0.props).copy()
        real = engine.update_links
        engine.update_links = lambda *_a, **_k: False
        try:
            res = plane.update_stager().stage(
                plan, topo, observe_ticks=0, guardrails=Guardrails())
        finally:
            engine.update_links = real
        assert not res.ok and res.rolled_back
        assert "dispatch failure" in res.reason
        np.testing.assert_array_equal(np.asarray(engine.state.props),
                                      pre_props)

    def test_ladder_signal_triggers_rollback(self):
        """The PR 2 fault-domain hook: a tick_errors rise during the
        watch window is a regression — the built-in health check rolls
        the round back."""
        daemon, engine, win, wout, plane, ticks = self._plane()
        ticks(3)
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        new = [l.with_properties(NEW_PROPS) for l in old]
        plan = plan_update(old, new, name="a0")

        def failing_driver(n):
            plane.tick_errors += 1  # what the runner does on a failure
            ticks(n)

        res = plane.update_stager().stage(
            plan, topo, observe_ticks=1, tick_driver=failing_driver,
            guardrails=Guardrails())
        assert not res.ok and res.rolled_back
        assert "tick_errors" in res.reason

    def test_one_staging_at_a_time(self):
        daemon, engine, win, wout, plane, ticks = self._plane()
        topo = engine.store.get("default", "a0")
        old = list(topo.status.links)
        plan = plan_update(
            old, [l.with_properties(NEW_PROPS) for l in old], name="a0")
        stager = plane.update_stager()
        with stager._tick_lock:
            stager._staging_key = "default/other"
        try:
            with pytest.raises(RuntimeError, match="in progress"):
                stager.stage(plan, topo, observe_ticks=0)
        finally:
            with stager._tick_lock:
                stager._staging_key = None


# ---- reconciler planned path ------------------------------------------

class TestPlannedReconcile:
    def _cluster(self):
        from kubedtn_tpu.topology import Reconciler

        daemon, engine, win, wout = _daemon_with_pairs(2, PROPS)
        plane = WireDataPlane(daemon, dt_us=2000.0, pipeline_depth=1)
        plane.pipeline_explicit_clock = True
        stats = UpdateStats()
        rec = Reconciler(
            engine.store, engine, plane=plane, planned=True,
            guardrails=Guardrails(ticks=60, dt_us=1000.0,
                                  max_p99_factor=8.0),
            observe_ticks=0, update_stats=stats)
        return engine, plane, rec, stats

    def test_clean_delta_routes_through_planner(self):
        engine, plane, rec, stats = self._cluster()
        topo = engine.store.get("default", "a0")
        topo.spec.links = [l.with_properties(NEW_PROPS)
                           for l in topo.spec.links]
        engine.store.update(topo)
        results = [r for r in rec.drain() if r.action != "noop"]
        assert [r.action for r in results] == ["planned"]
        assert results[0].ok
        assert "gate" in results[0].phase_ms
        fresh = engine.store.get("default", "a0")
        assert fresh.status.links == fresh.spec.links
        row = engine.link_row("default/a0", 1)
        assert row["latency_us"] == pytest.approx(5000.0)
        assert stats.snapshot()["plans_verified"] == 1

    def test_regressing_delta_rejected_before_live_plane(self):
        """ISSUE 8 acceptance: the gate blocks a regressing delta
        BEFORE it touches the live plane — device state unchanged,
        status stale, no requeue spin."""
        engine, plane, rec, stats = self._cluster()
        pre_props = np.asarray(engine.state.props).copy()
        topo = engine.store.get("default", "a0")
        old_status = list(topo.status.links)
        topo.spec.links = [l.with_properties(LinkProperties(loss="80"))
                           for l in topo.spec.links]
        engine.store.update(topo)
        results = [r for r in rec.drain() if r.action != "noop"]
        assert [r.action for r in results] == ["plan-rejected"]
        assert not results[0].ok
        np.testing.assert_array_equal(np.asarray(engine.state.props),
                                      pre_props)
        fresh = engine.store.get("default", "a0")
        assert fresh.status.links == old_status  # delta NOT recorded
        assert rec._requeue == set()  # deterministic verdict: no spin
        assert stats.snapshot()["plans_rejected"] == 1

    def test_direct_path_still_default(self):
        from kubedtn_tpu.topology import Reconciler

        daemon, engine, _wi, _wo = _daemon_with_pairs(1, PROPS)
        rec = Reconciler(engine.store, engine)
        assert rec.planned is False
        topo = engine.store.get("default", "a0")
        topo.spec.links = [l.with_properties(NEW_PROPS)
                           for l in topo.spec.links]
        engine.store.update(topo)
        results = [r for r in rec.drain() if r.action != "noop"]
        assert [r.action for r in results] == ["changed"]


# ---- wire surface ------------------------------------------------------

class TestWireSurface:
    def _daemon(self):
        daemon, engine, win, wout = _daemon_with_pairs(2, PROPS)
        plane = WireDataPlane(daemon, dt_us=2000.0, pipeline_depth=1)
        plane.pipeline_explicit_clock = True
        return daemon, engine, plane

    def _request(self, pb, engine, name, props, **kw):
        topo = engine.store.get("default", name)
        desired = [l.with_properties(props) for l in topo.spec.links]
        return pb.PlanUpdateRequest(
            name=name, kube_ns="default",
            links=[pb.link_to_proto(l) for l in desired],
            ticks=60, max_p99_factor=8.0, **kw)

    def test_plan_then_apply(self):
        from kubedtn_tpu.wire import proto as pb

        daemon, engine, plane = self._daemon()
        resp = daemon.PlanUpdate(
            self._request(pb, engine, "a0", NEW_PROPS), None)
        assert resp.ok, resp.error
        assert resp.verified
        assert resp.plan_id > 0
        assert len(resp.rounds) == 1
        assert resp.rounds[0].changes == 1
        assert resp.baseline_delivery_ratio > 0
        apply_resp = daemon.ApplyPlan(
            pb.ApplyPlanRequest(plan_id=resp.plan_id), None)
        assert apply_resp.ok, apply_resp
        assert apply_resp.rounds_applied == 1
        assert not apply_resp.rolled_back
        fresh = engine.store.get("default", "a0")
        assert fresh.spec.links[0].properties.latency == "5ms"
        assert fresh.status.links == fresh.spec.links
        # consumed: a second apply of the same id fails loudly
        again = daemon.ApplyPlan(
            pb.ApplyPlanRequest(plan_id=resp.plan_id), None)
        assert not again.ok
        assert "unknown or expired" in again.error

    def test_regressing_plan_gets_no_id(self):
        from kubedtn_tpu.wire import proto as pb

        daemon, engine, plane = self._daemon()
        resp = daemon.PlanUpdate(
            self._request(pb, engine, "a0",
                          LinkProperties(loss="80")), None)
        assert resp.ok
        assert not resp.verified
        assert resp.plan_id == 0
        assert "delivery" in resp.reject_reason

    def test_apply_conflict_on_moved_topology(self):
        from kubedtn_tpu.wire import proto as pb

        daemon, engine, plane = self._daemon()
        resp = daemon.PlanUpdate(
            self._request(pb, engine, "a0", NEW_PROPS), None)
        assert resp.verified
        # the topology moves between plan and apply
        topo = engine.store.get("default", "a0")
        topo.status.links = [
            l.with_properties(LinkProperties(latency="7ms"))
            for l in topo.status.links]
        engine.store.update_status(topo)
        apply_resp = daemon.ApplyPlan(
            pb.ApplyPlanRequest(plan_id=resp.plan_id), None)
        assert not apply_resp.ok
        assert "conflict" in apply_resp.error

    def test_apply_does_not_clobber_newer_spec(self):
        """A desired state posted AFTER the plan was built must survive
        the apply: status records what was realized, the newer spec is
        left for the next reconcile to converge toward."""
        from kubedtn_tpu.wire import proto as pb

        daemon, engine, plane = self._daemon()
        resp = daemon.PlanUpdate(
            self._request(pb, engine, "a0", NEW_PROPS), None)
        assert resp.verified
        # operator posts a NEWER desired state via the normal spec path
        v2 = LinkProperties(latency="8ms")
        topo = engine.store.get("default", "a0")
        topo.spec.links = [l.with_properties(v2)
                           for l in topo.spec.links]
        engine.store.update(topo)
        apply_resp = daemon.ApplyPlan(
            pb.ApplyPlanRequest(plan_id=resp.plan_id), None)
        assert apply_resp.ok, apply_resp
        fresh = engine.store.get("default", "a0")
        # v2's intent preserved; the realized state is the plan's
        assert fresh.spec.links[0].properties.latency == "8ms"
        assert fresh.status.links[0].properties.latency == "5ms"
        assert fresh.spec.links != fresh.status.links  # reconcilable

    def test_unrealized_topology_is_an_error(self):
        from kubedtn_tpu.wire import proto as pb
        from kubedtn_tpu.api.types import Topology, TopologySpec

        daemon, engine, plane = self._daemon()
        engine.store.create(Topology(
            name="fresh", spec=TopologySpec(links=[_link(1)])))
        resp = daemon.PlanUpdate(pb.PlanUpdateRequest(
            name="fresh", kube_ns="default",
            links=[pb.link_to_proto(_link(1))]), None)
        assert not resp.ok
        assert "not realized" in resp.error

    def test_empty_diff_is_verified_noop(self):
        from kubedtn_tpu.wire import proto as pb

        daemon, engine, plane = self._daemon()
        topo = engine.store.get("default", "a0")
        resp = daemon.PlanUpdate(pb.PlanUpdateRequest(
            name="a0", kube_ns="default",
            links=[pb.link_to_proto(l) for l in topo.status.links]),
            None)
        assert resp.ok and resp.verified
        assert resp.plan_id == 0
        assert len(resp.rounds) == 0


# ---- metrics -----------------------------------------------------------

def test_update_stats_collector_series():
    from kubedtn_tpu.metrics.metrics import UpdateStatsCollector

    stats = UpdateStats()

    class _V:
        ok = True
        gate_s = 0.25

    stats.record_plan(_V())
    fams = UpdateStatsCollector(stats).collect()
    names = {f.name for f in fams}
    assert "kubedtn_update_plans_built" in names
    assert "kubedtn_update_rollbacks" in names
    by_name = {f.name: f for f in fams}
    assert by_name["kubedtn_update_plans_built"].samples[0].value == 1.0
    assert by_name["kubedtn_update_gate_seconds"].samples[0].value \
        == pytest.approx(0.25)


def test_guarded_by_registry_covers_stager():
    """ISSUE 8 satellite: the stager's shared state is declared under
    the plane's tick lock for dtnlint's lock pass."""
    from kubedtn_tpu import contracts
    import kubedtn_tpu.updates.stager  # noqa: F401  (applies decorator)

    reg = contracts.registry()
    stager = reg.get("kubedtn_tpu.updates.stager.UpdateStager", {})
    assert stager.get("_journal") == "_tick_lock"
    assert stager.get("_staging_key") == "_tick_lock"
