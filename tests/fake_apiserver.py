"""A protocol-level fake Kubernetes apiserver for bridge tests.

The reference boots etcd + a real apiserver in its controller tests
(reference controllers/suite_test.go:44-80, envtest). This is the same
role at the HTTP layer this repo actually exercises: a ThreadingHTTPServer
speaking the CustomObjects REST surface for Topology CRs —

- LIST  GET  /apis/{g}/{v}/{plural}                       (cluster scope)
        GET  /apis/{g}/{v}/namespaces/{ns}/{plural}
- WATCH same paths with ?watch=true&resourceVersion=N — a streaming
        response of JSON-lines watch events. A resourceVersion older than
        the retained event window answers with the apiserver's actual
        protocol for expiry: HTTP 200 + an ERROR event carrying a
        `Status` object with code 410 ("Expired"), which clients must
        turn into a fresh LIST.
- PATCH .../{name}/status   (application/merge-patch+json)
- PATCH .../{name}          (metadata merge — finalizers)
- POST/PUT/DELETE on objects so tests can drive spec changes like a
  controller-manager would.

Plus the coordination.k8s.io/v1 Lease surface (GET/POST/PUT with
resourceVersion CAS → 409 on mismatch) so KubeLeaseStore runs against it
over real HTTP.

Deliberately faithful bits: a single global, monotonically increasing
resourceVersion; watch events replayed from an in-memory log with a
bounded window (so 410 is reachable); optimistic-concurrency on Lease
replace; JSON-lines chunk framing.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from kubedtn_tpu import GROUP, VERSION

PLURAL = "topologies"


class FakeApiServer:
    """In-memory cluster state + the HTTP server around it."""

    def __init__(self, event_window: int = 64,
                 watch_timeout_s: float = 30.0) -> None:
        self._lock = threading.Condition()
        self._rv = 0
        self.objects: dict[tuple[str, str], dict] = {}  # (ns, name) -> obj
        self.leases: dict[tuple[str, str], dict] = {}
        # retained watch log: list of (rv:int, type:str, object:dict)
        self._events: list[tuple[int, str, dict]] = []
        self.event_window = event_window
        self.watch_timeout_s = watch_timeout_s
        self.requests: list[str] = []  # "<METHOD> <path>" log for tests
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        # fault injection: when >0, the next N non-watch requests answer
        # HTTP 500 (transient-error path testing)
        self.fail_next = 0
        # bumped by expire_history: active watch streams terminate so
        # clients must reconnect (and discover their RV is now stale),
        # like an apiserver closing watches on etcd compaction
        self._generation = 0

    # -- state helpers (lock held) ------------------------------------

    def _bump(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _record(self, ev_type: str, obj: dict) -> None:
        self._events.append((self._rv, ev_type, json.loads(json.dumps(obj))))
        if len(self._events) > self.event_window:
            del self._events[: len(self._events) - self.event_window]
        self._lock.notify_all()

    # -- test-driver conveniences -------------------------------------

    def put_object(self, manifest: dict) -> dict:
        """Create or replace a Topology object (spec changes from 'the
        controller-manager'); status is preserved on replace."""
        meta = manifest.setdefault("metadata", {})
        ns = meta.setdefault("namespace", "default")
        name = meta["name"]
        with self._lock:
            old = self.objects.get((ns, name))
            if old is not None and "status" not in manifest:
                manifest = dict(manifest)
                if "status" in old:
                    manifest["status"] = old["status"]
            meta["resourceVersion"] = self._bump()
            self.objects[(ns, name)] = manifest
            self._record("ADDED" if old is None else "MODIFIED", manifest)
        return manifest

    def delete_object(self, ns: str, name: str) -> None:
        with self._lock:
            obj = self.objects.pop((ns, name), None)
            if obj is not None:
                obj["metadata"]["resourceVersion"] = self._bump()
                self._record("DELETED", obj)

    def expire_history(self) -> None:
        """Drop the whole retained watch log (simulates compaction): any
        watch resuming from an old RV now gets 410 Gone."""
        with self._lock:
            self._events.clear()
            # burn some versions so stale RVs are unambiguously old
            self._rv += 100
            self._generation += 1
            self._lock.notify_all()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> tuple[str, int]:
        state = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            # ---- helpers ----
            def _json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _status(self, code: int, reason: str, message: str) -> None:
                self._json(code, {
                    "kind": "Status", "apiVersion": "v1", "metadata": {},
                    "status": "Failure", "message": message,
                    "reason": reason, "code": code,
                })

            def _read_body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def _fail_injected(self) -> bool:
                with state._lock:
                    if state.fail_next > 0:
                        state.fail_next -= 1
                        fail = True
                    else:
                        fail = False
                if fail:
                    self._status(500, "InternalError", "injected fault")
                return fail

            # ---- topology routes ----
            def _topo_path(self, path: str):
                """(ns | None, name | None, subresource | None) for a
                CustomObjects path, else None."""
                base = f"/apis/{GROUP}/{VERSION}"
                if not path.startswith(base + "/"):
                    return None
                rest = path[len(base) + 1:].strip("/").split("/")
                if rest[0] == "namespaces":
                    if len(rest) < 3 or rest[2] != PLURAL:
                        return None
                    ns = rest[1]
                    name = rest[3] if len(rest) > 3 else None
                    sub = rest[4] if len(rest) > 4 else None
                    return ns, name, sub
                if rest[0] != PLURAL:
                    return None
                name = rest[1] if len(rest) > 1 else None
                sub = rest[2] if len(rest) > 2 else None
                return None, name, sub

            def _lease_path(self, path: str):
                base = "/apis/coordination.k8s.io/v1/namespaces/"
                if not path.startswith(base):
                    return None
                rest = path[len(base):].strip("/").split("/")
                if len(rest) < 2 or rest[1] != "leases":
                    return None
                return rest[0], rest[2] if len(rest) > 2 else None

            def _serve_list(self, ns):
                with state._lock:
                    items = [o for (ons, _n), o in
                             sorted(state.objects.items())
                             if ns is None or ons == ns]
                    rv = str(state._rv)
                self._json(200, {
                    "apiVersion": f"{GROUP}/{VERSION}",
                    "kind": "TopologyList",
                    "metadata": {"resourceVersion": rv},
                    "items": json.loads(json.dumps(items)),
                })

            def _serve_watch(self, ns, rv_from: int):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def send_event(ev: dict) -> None:
                    line = json.dumps(ev).encode() + b"\n"
                    self.wfile.write(hex(len(line))[2:].encode() + b"\r\n"
                                     + line + b"\r\n")
                    self.wfile.flush()

                with state._lock:
                    oldest = state._events[0][0] if state._events \
                        else state._rv + 1
                # resuming before the retained window: the apiserver's
                # 410 protocol is an ERROR event, not an HTTP error
                if rv_from + 1 < oldest and rv_from < state._rv:
                    send_event({
                        "type": "ERROR",
                        "object": {
                            "kind": "Status", "apiVersion": "v1",
                            "metadata": {}, "status": "Failure",
                            "reason": "Expired", "code": 410,
                            "message": f"too old resource version: "
                                       f"{rv_from}",
                        },
                    })
                    self.wfile.write(b"0\r\n\r\n")
                    return
                cursor = rv_from
                import time as _t
                with state._lock:
                    gen0 = state._generation
                deadline = _t.monotonic() + state.watch_timeout_s
                try:
                    while _t.monotonic() < deadline:
                        with state._lock:
                            if state._generation != gen0:
                                break  # compaction: close the stream
                            pending = [
                                (rv, t, o) for (rv, t, o) in state._events
                                if rv > cursor and (
                                    ns is None or
                                    o.get("metadata", {})
                                    .get("namespace", "default") == ns)]
                            if not pending:
                                state._lock.wait(0.1)
                        for rv, t, o in pending:
                            send_event({"type": t, "object": o})
                            cursor = rv
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass

            # ---- verbs ----
            def do_GET(self):
                u = urlparse(self.path)
                state.requests.append(f"GET {u.path}")
                lease = self._lease_path(u.path)
                if lease is not None:
                    ns, name = lease
                    with state._lock:
                        obj = state.leases.get((ns, name))
                    if obj is None:
                        return self._status(404, "NotFound",
                                            f"lease {name} not found")
                    return self._json(200, obj)
                topo = self._topo_path(u.path)
                if topo is None:
                    return self._status(404, "NotFound", "no such route")
                ns, name, _sub = topo
                if name is None:
                    q = parse_qs(u.query)
                    if q.get("watch", ["false"])[0] in ("true", "1"):
                        rv = int(q.get("resourceVersion", ["0"])[0] or 0)
                        return self._serve_watch(ns, rv)
                    if self._fail_injected():
                        return
                    return self._serve_list(ns)
                with state._lock:
                    obj = state.objects.get((ns or "default", name))
                if obj is None:
                    return self._status(404, "NotFound",
                                        f"{name} not found")
                return self._json(200, obj)

            def do_POST(self):
                u = urlparse(self.path)
                state.requests.append(f"POST {u.path}")
                body = self._read_body()
                lease = self._lease_path(u.path)
                if lease is not None:
                    ns, _ = lease
                    name = body.get("metadata", {}).get("name")
                    with state._lock:
                        if (ns, name) in state.leases:
                            return self._status(409, "AlreadyExists",
                                                f"lease {name} exists")
                        body.setdefault("metadata", {})
                        body["metadata"]["namespace"] = ns
                        body["metadata"]["resourceVersion"] = state._bump()
                        state.leases[(ns, name)] = body
                    return self._json(201, body)
                topo = self._topo_path(u.path)
                if topo is None:
                    return self._status(404, "NotFound", "no such route")
                ns = topo[0] or body.get("metadata", {}) \
                    .get("namespace", "default")
                name = body.get("metadata", {}).get("name")
                with state._lock:
                    if (ns, name) in state.objects:
                        return self._status(409, "AlreadyExists",
                                            f"{name} exists")
                body.setdefault("metadata", {})["namespace"] = ns
                state.put_object(body)
                return self._json(201, body)

            def do_PUT(self):
                u = urlparse(self.path)
                state.requests.append(f"PUT {u.path}")
                body = self._read_body()
                lease = self._lease_path(u.path)
                if lease is not None:
                    ns, name = lease
                    with state._lock:
                        cur = state.leases.get((ns, name))
                        if cur is None:
                            return self._status(404, "NotFound",
                                                f"lease {name} not found")
                        want = body.get("metadata", {}) \
                            .get("resourceVersion")
                        have = cur["metadata"]["resourceVersion"]
                        if want is not None and want != have:
                            return self._status(
                                409, "Conflict",
                                f"resourceVersion mismatch: {want}!={have}")
                        body.setdefault("metadata", {})
                        body["metadata"]["namespace"] = ns
                        body["metadata"]["resourceVersion"] = state._bump()
                        state.leases[(ns, name)] = body
                    return self._json(200, body)
                topo = self._topo_path(u.path)
                if topo is None or topo[1] is None:
                    return self._status(404, "NotFound", "no such route")
                body.setdefault("metadata", {})["namespace"] = \
                    topo[0] or "default"
                state.put_object(body)
                return self._json(200, body)

            def do_PATCH(self):
                u = urlparse(self.path)
                state.requests.append(f"PATCH {u.path}")
                if self._fail_injected():
                    return
                topo = self._topo_path(u.path)
                if topo is None or topo[1] is None:
                    return self._status(404, "NotFound", "no such route")
                ns, name, sub = topo
                ns = ns or "default"
                patch = self._read_body()
                with state._lock:
                    obj = state.objects.get((ns, name))
                    if obj is None:
                        return self._status(404, "NotFound",
                                            f"{name} not found")
                    if sub == "status":
                        obj["status"] = patch.get("status", {})
                    else:
                        meta_patch = patch.get("metadata", {})
                        if "finalizers" in meta_patch:
                            obj["metadata"]["finalizers"] = \
                                meta_patch["finalizers"]
                    obj["metadata"]["resourceVersion"] = state._bump()
                    state._record("MODIFIED", obj)
                return self._json(200, obj)

            def do_DELETE(self):
                u = urlparse(self.path)
                state.requests.append(f"DELETE {u.path}")
                topo = self._topo_path(u.path)
                if topo is None or topo[1] is None:
                    return self._status(404, "NotFound", "no such route")
                ns, name, _ = topo
                state.delete_object(ns or "default", name)
                return self._json(200, {"kind": "Status",
                                        "status": "Success"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="fake-apiserver")
        self._thread.start()
        host, port = self._httpd.server_address
        return host, port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
