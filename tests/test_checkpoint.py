"""Checkpoint/resume + elastic recovery tests.

Covers both recovery modes of SURVEY.md §5.3-5.4: reconstruction from the
store alone (the reference's daemon-restart resync) and full checkpoint
restore (store + registries + device arrays, incl. mutable shaping state)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu import checkpoint
from kubedtn_tpu.api.types import Link, LinkProperties, load_yaml
from kubedtn_tpu.ops import netem
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

THREE_NODE = "/root/reference/config/samples/3node.yml"


def build_three_node():
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    topos = load_yaml(THREE_NODE)
    for t in topos:
        store.create(t)
    for t in topos:
        engine.setup_pod(t.name, t.namespace)
    return store, engine, topos


def engine_fingerprint(engine: SimEngine):
    return {
        "rows": dict(engine._rows),
        "peer": dict(engine._peer),
        "pod_ids": dict(engine._pod_ids),
        "alive": set(engine._topology_manager),
        "num_active": engine.num_active,
    }


@pytest.mark.requires_reference_yaml
def test_rebuild_engine_reconstruction():
    """Daemon restart: device arrays are rebuildable from the store."""
    store, engine, _ = build_three_node()
    before = engine_fingerprint(engine)

    rebuilt = checkpoint.rebuild_engine(store, capacity=64)
    after = engine_fingerprint(rebuilt)

    assert after["alive"] == before["alive"]
    assert after["num_active"] == before["num_active"]
    assert set(after["rows"]) == set(before["rows"])
    # realized properties survive reconstruction
    for (pod, uid) in before["rows"]:
        a = engine.link_row(pod, uid)
        b = rebuilt.link_row(pod, uid)
        for k in a:
            if k != "row":  # row placement may differ; semantics may not
                assert a[k] == b[k], (pod, uid, k)


@pytest.mark.requires_reference_yaml
def test_rebuild_skips_dead_pods():
    store, engine, topos = build_three_node()
    engine.destroy_pod(topos[0].name, topos[0].namespace)
    rebuilt = checkpoint.rebuild_engine(store, capacity=64)
    dead_key = f"{topos[0].namespace or 'default'}/{topos[0].name}"
    assert all(pod != dead_key for pod, _ in rebuilt._rows)


@pytest.mark.requires_reference_yaml
def test_checkpoint_roundtrip(tmp_path):
    store, engine, topos = build_three_node()
    # advance mutable shaping state so restore has something to preserve
    E = engine.state.capacity
    sizes = jnp.full((E,), 1500.0, jnp.float32)
    have = engine.state.active.copy()  # donated below; alias would dangle
    engine.state, _ = netem.shape_step(engine.state, sizes, have,
                                       jnp.zeros((E,), jnp.float32),
                                       jax.random.key(0))
    before = engine_fingerprint(engine)
    state_before = jax.tree.map(np.asarray, engine.state)

    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    store2, engine2 = checkpoint.load(path)

    assert engine_fingerprint(engine2) == before
    for f in dataclasses.fields(engine.state):
        np.testing.assert_array_equal(
            np.asarray(getattr(engine2.state, f.name)),
            getattr(state_before, f.name), err_msg=f.name)
    # store round-trips spec+status+metadata
    for t in store.list():
        t2 = store2.get(t.namespace, t.name)
        assert t2.spec.links == t.spec.links
        assert t2.status.src_ip == t.status.src_ip
        assert t2.finalizers == t.finalizers
        assert t2.resource_version == t.resource_version


@pytest.mark.requires_reference_yaml
def test_restored_engine_keeps_working(tmp_path):
    """Resume then mutate: the restored engine accepts new reconciles."""
    store, engine, topos = build_three_node()
    # reach steady state (status.links populated) before checkpointing, so
    # the post-restore reconcile is a real diff, not the first-seen rule
    rec0 = Reconciler(store, engine)
    for t in topos:
        rec0.reconcile(t.namespace, t.name)
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    store2, engine2 = checkpoint.load(path)

    # update link properties through the reconciler on the restored pair
    rec = Reconciler(store2, engine2)
    t = store2.get(topos[0].namespace, topos[0].name)
    new_links = [dataclasses.replace(
        l, properties=LinkProperties(latency="42ms")) for l in t.spec.links]
    t.spec.links = new_links
    store2.update(t)
    rec.reconcile(t.namespace, t.name)

    row = engine2.link_row(t.key, t.spec.links[0].uid)
    assert row is not None and row["latency_us"] == 42000.0


@pytest.mark.requires_reference_yaml
def test_checkpoint_with_sim_state(tmp_path):
    from kubedtn_tpu.models.traffic import cbr_everywhere
    from kubedtn_tpu import sim as S

    store, engine, _ = build_three_node()
    spec = cbr_everywhere(engine.state.capacity, engine.num_active,
                          rate_bps=1e6, pkt_bytes=500.0)
    sim = S.init_sim(engine.state)
    sim = S.run(sim, spec, steps=5, dt_us=1000.0, k_slots=2)
    engine.state = sim.edges  # run() donates; re-adopt the live arrays

    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine, sim=sim)
    _, engine2 = checkpoint.load(path)
    sim2 = checkpoint.load_sim(path, engine2)

    assert sim2 is not None
    np.testing.assert_array_equal(np.asarray(sim2.counters.tx_packets),
                                  np.asarray(sim.counters.tx_packets))
    clock2 = float(sim2.clock_us)
    assert clock2 == float(sim.clock_us)
    # and it still steps (sim_step donates sim2)
    sim3, _ = S.sim_step(sim2, spec, jax.random.key(1), 2,
                         jnp.float32(1000.0))
    assert float(sim3.clock_us) > clock2


def test_restored_engine_rebuilds_shaped_rows(tmp_path):
    """Regression: a restored shaped link must still read as shaped to the
    TCP-bypass guard — otherwise same-node TCP flows would skip its
    netem/TBF chain entirely after a daemon restart."""
    from kubedtn_tpu import checkpoint as cp
    from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                       TopologySpec)

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    t = Topology(name="s", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth9",
             peer_pod="physical/10.0.0.9", uid=1,
             properties=LinkProperties(latency="10ms")),
        Link(local_intf="eth2", peer_intf="eth8",
             peer_pod="physical/10.0.0.8", uid=2),  # unshaped
    ]))
    store.create(t)
    engine.setup_pod("s")
    shaped_row = engine.row_of("default/s", 1)
    plain_row = engine.row_of("default/s", 2)
    assert engine.is_shaped(shaped_row) and not engine.is_shaped(plain_row)

    path = str(tmp_path / "ckpt")
    cp.save(path, store, engine)
    store2, engine2 = cp.load(path)
    assert engine2.is_shaped(engine2.row_of("default/s", 1))
    assert not engine2.is_shaped(engine2.row_of("default/s", 2))


@pytest.mark.requires_reference_yaml
def test_daemon_restart_resumes_shaping_e2e(tmp_path):
    """Full daemon-restart story (the reference's restart rescan,
    SURVEY §5.3-5.4): checkpoint a live daemon's store+engine, 'crash'
    it, restore into a NEW daemon, re-attach wires, and verify traffic
    still shapes with the original link properties."""
    from kubedtn_tpu import checkpoint as cp
    from kubedtn_tpu.api.types import load_yaml
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon, make_server

    LATENCY = "/root/reference/config/samples/tc/latency.yaml"
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    for t in load_yaml(LATENCY):
        store.create(t)
        engine.setup_pod(t.name, t.namespace)
    n_active = engine.num_active
    assert n_active > 0

    path = str(tmp_path / "daemon-ckpt")
    cp.save(path, store, engine)
    del store, engine  # the 'crash'

    store2, engine2 = cp.load(path)
    assert engine2.num_active == n_active
    daemon2 = Daemon(engine2)
    server2, port2 = make_server(daemon2, port=0, host="127.0.0.1")
    server2.start()
    try:
        # wires re-attach (pods reconnect after a daemon restart)
        w1 = daemon2._add_wire(pb.WireDef(
            local_pod_name="r1", kube_ns="default", link_uid=1,
            intf_name_in_pod="eth1"))
        w2 = daemon2._add_wire(pb.WireDef(
            local_pod_name="r2", kube_ns="default", link_uid=1,
            intf_name_in_pod="eth1"))
        dp = WireDataPlane(daemon2)
        frame = b"\x02" * 12 + b"\x08\x06" + b"\x00" * 50
        w1.ingress.append(frame)
        assert dp.tick(now_s=10.0) == 1
        assert not w2.egress          # 10ms latency survived the restart
        dp.tick(now_s=10.011)
        assert list(w2.egress) == [frame]
    finally:
        server2.stop(0)


def test_pending_frames_survive_daemon_restart(tmp_path):
    """In the reference, in-flight packets live in kernel qdisc queues
    and survive a daemon restart; here the delay line checkpoints: a
    restored frame completes its REMAINING delay, not a fresh one."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    def build_cluster():
        store = TopologyStore()
        engine = SimEngine(store, capacity=64)
        props = LinkProperties(latency="500ms")
        from kubedtn_tpu.api.types import Topology, TopologySpec
        for name, peer in (("a", "b"), ("b", "a")):
            t = Topology(name=name, spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1", peer_pod=peer,
                     uid=1, properties=props)]))
            t.status.src_ip, t.status.net_ns = "10.0.0.1", f"/ns/{name}"
            t.status.links = []
            store.create(t)
        Reconciler(store, engine).drain()
        daemon = Daemon(engine)
        wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                         kube_ns="default", link_uid=1,
                                         intf_name_in_pod="eth1"))
        wb = daemon._add_wire(pb.WireDef(local_pod_name="b",
                                         kube_ns="default", link_uid=1,
                                         intf_name_in_pod="eth1"))
        return store, engine, daemon, wa, wb

    store, engine, daemon, wa, wb = build_cluster()
    plane = WireDataPlane(daemon, dt_us=10_000.0)
    frame = b"\xee" * 77
    daemon._frame_in(wa, frame)
    plane.tick(now_s=0.0)       # shaped: 500ms of delay scheduled
    plane.tick(now_s=0.2)       # 200ms elapsed, 300ms remain
    assert len(wb.egress) == 0

    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine, dataplane=plane)
    exported = plane.export_pending()
    assert len(exported) == 1
    assert exported[0][3] == pytest.approx(300_000.0, abs=11_000)

    # "restart": brand-new daemon + plane, restore from disk
    store2, engine2, daemon2, wa2, wb2 = build_cluster()
    plane2 = WireDataPlane(daemon2, dt_us=10_000.0)
    n = checkpoint.load_pending(path, plane2, now_s=100.0)
    assert n == 1
    # 200ms later: still held (remaining was ~300ms)
    plane2.tick(now_s=100.2)
    assert len(wb2.egress) == 0
    # past the remaining delay: delivered
    plane2.tick(now_s=100.35)
    assert list(wb2.egress) == [frame]


def test_pending_checkpoint_guards(tmp_path):
    """save() refuses a live runner (non-atomic cut) and a dataplane-less
    re-save removes a stale pending file instead of resurrecting it."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon)
    path = str(tmp_path / "ckpt")

    plane.start()
    try:
        with pytest.raises(RuntimeError, match="stop"):
            checkpoint.save(path, store, engine, dataplane=plane)
    finally:
        plane.stop()

    checkpoint.save(path, store, engine, dataplane=plane)
    import os
    assert os.path.exists(os.path.join(path, "pending_frames.npz"))
    checkpoint.save(path, store, engine)  # no dataplane: stale file goes
    assert not os.path.exists(os.path.join(path, "pending_frames.npz"))
    plane2 = WireDataPlane(Daemon(engine))
    assert checkpoint.load_pending(path, plane2) == 0


def test_restored_frames_wait_for_wire_reattach(tmp_path):
    """A restored frame released before its pod re-attaches a wire waits
    in the orphan queue (grace window) and delivers once the wire
    re-registers; an expired wait is counted, never silently dropped."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=10_000.0)
    # restore a frame due in 50ms for a (pod, uid) with NO wire yet
    plane.restore_pending([("default/a", 1, b"\xab" * 64, 50_000.0)],
                          now_s=0.0)
    plane.tick(now_s=0.1)  # due, but no wire: orphaned, not dropped
    assert plane.undeliverable == 0
    # the pod re-attaches its wire (the reconnect flow after restart)
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a", kube_ns="default",
                                     link_uid=1, intf_name_in_pod="eth1"))
    plane.tick(now_s=0.2)
    assert list(wa.egress) == [b"\xab" * 64]
    assert plane.undeliverable == 0

    # expiry path: grace elapses with no wire -> counted
    plane.restore_pending([("default/ghost", 9, b"\xcd" * 32, 10_000.0)],
                          now_s=1.0)
    plane.orphan_grace_s = 0.05
    plane.tick(now_s=1.1)   # due, orphaned with 50ms grace
    plane.tick(now_s=1.3)   # grace expired
    assert plane.undeliverable == 1


def test_restore_pending_rejects_mixed_clocks():
    """A plane driven by a synthetic clock must not accept a default
    (monotonic) now_s in restore_pending — deadlines would be skewed by
    the epoch difference between the two clocks (ADVICE r3)."""
    import pytest

    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    plane = WireDataPlane(Daemon(engine), dt_us=10_000.0)
    plane.tick(now_s=5.0)  # synthetic clock: origin=5.0, _clock_ext set
    with pytest.raises(ValueError, match="explicit clock"):
        plane.restore_pending([("default/a", 1, b"\x00" * 32, 1_000.0)])
    # the explicit-clock path still works
    assert plane.restore_pending(
        [("default/a", 1, b"\x00" * 32, 1_000.0)], now_s=5.1) == 1


def _small_cluster():
    """Reference-sample-free store/engine pair for the corruption tests
    (one shaped physical link, row realized)."""
    from kubedtn_tpu.api.types import Topology, TopologySpec

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    t = Topology(name="s", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="e",
             peer_pod="physical/10.0.0.9", uid=1,
             properties=LinkProperties(latency="10ms"))]))
    store.create(t)
    engine.setup_pod("s")
    return store, engine


def test_checkpoint_atomic_save_layout(tmp_path):
    """save() swaps a fully-written staging directory into place: the
    final dir carries the manifest with per-file checksums, and neither
    the staging dir nor a .prev generation survives a clean save."""
    import json
    import os

    store, engine = _small_cluster()
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["format_version"] == checkpoint.FORMAT_VERSION
    assert "edge_state.npz" in manifest["checksums"]
    leftovers = [d for d in os.listdir(tmp_path)
                 if d.startswith(".ckpt-tmp-") or d.endswith(".prev")]
    assert leftovers == []
    # a second save over the same path replaces wholesale, same contract
    checkpoint.save(path, store, engine)
    store2, engine2 = checkpoint.load(path)
    assert engine2.row_of("default/s", 1) is not None


def test_missing_checkpoint_is_distinct_from_damage(tmp_path):
    """A fresh daemon's first start: load raises the MISSING subtype,
    and load_pending/load_sim quietly report nothing to restore —
    while an unsupported format version raises the base error (a
    rolled-back daemon must not silently cold-start over a
    newer-format checkpoint)."""
    import json
    import os

    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire.server import Daemon

    path = str(tmp_path / "never-written")
    with pytest.raises(checkpoint.CheckpointMissingError):
        checkpoint.load(path)
    store, engine = _small_cluster()
    plane = WireDataPlane(Daemon(engine), dt_us=10_000.0)
    assert checkpoint.load_pending(path, plane) == 0
    assert checkpoint.load_sim(path, engine) is None

    checkpoint.save(path, store, engine)
    mpath = os.path.join(path, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["format_version"] = checkpoint.FORMAT_VERSION + 1
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(checkpoint.CheckpointError, match="unsupported"):
        checkpoint.load(path)
    with pytest.raises(checkpoint.CheckpointError, match="unsupported"):
        checkpoint.load_pending(path, plane)


def test_checkpoint_refuses_non_checkpoint_dir(tmp_path):
    store, engine = _small_cluster()
    path = str(tmp_path / "precious")
    import os

    os.makedirs(path)
    with open(os.path.join(path, "notes.txt"), "w") as f:
        f.write("not a checkpoint")
    with pytest.raises(checkpoint.CheckpointError, match="refusing"):
        checkpoint.save(path, store, engine)


def test_manifestless_debris_is_corrupt_and_replaceable(tmp_path):
    """A dir holding ONLY checkpoint data files but no manifest is
    DAMAGE: load surfaces it (never a silent fresh start), and the next
    save may replace it (a crash-looped daemon must not be wedged out
    of checkpointing forever)."""
    import os

    store, engine = _small_cluster()
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    os.remove(os.path.join(path, "manifest.json"))
    with pytest.raises(checkpoint.CheckpointCorruptError,
                       match="no manifest"):
        checkpoint.load(path)
    checkpoint.save(path, store, engine)  # debris replaced, not refused
    checkpoint.load(path)


def test_save_sweeps_leaked_staging_dirs(tmp_path):
    """kill -9 mid-save leaves a .ckpt-tmp-* staging dir; the next save
    sweeps it (dead pids only — a live pid is another process's
    staging, and a sibling checkpoint's staging never matches)."""
    import os
    import subprocess
    import sys

    store, engine = _small_cluster()
    path = str(tmp_path / "ckpt")
    proc = subprocess.Popen([sys.executable, "-c", ""])
    proc.wait()  # a pid guaranteed dead
    leaked = str(tmp_path / f".ckpt-tmp-ckpt-{proc.pid}")
    os.makedirs(leaked)
    with open(os.path.join(leaked, "edge_state.npz"), "w") as f:
        f.write("junk from a crashed save")
    # a SIBLING checkpoint's staging must never match the sweep pattern
    sibling = str(tmp_path / f".ckpt-tmp-ckpt-b-{proc.pid}")
    os.makedirs(sibling)
    checkpoint.save(path, store, engine)
    assert not os.path.exists(leaked)
    assert os.path.exists(sibling)
    checkpoint.load(path)


def test_truncated_manifest_raises_typed_error(tmp_path):
    import os

    store, engine = _small_cluster()
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    with open(os.path.join(path, "manifest.json"), "r+b") as f:
        f.truncate(25)
    with pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.load(path)


def test_truncated_npz_raises_typed_error(tmp_path):
    import os

    store, engine = _small_cluster()
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    p = os.path.join(path, "edge_state.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.load(path)


def test_checksum_mismatch_raises_typed_error(tmp_path):
    """Garbled-but-well-formed damage (flipped byte, size unchanged) is
    caught by the manifest checksums, not by np.load luck."""
    import os

    from kubedtn_tpu.chaos import ChaosInjector

    store, engine = _small_cluster()
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    ChaosInjector(seed=2).corrupt_file(
        os.path.join(path, "edge_state.npz"), n_bytes=1)
    with pytest.raises(checkpoint.CheckpointCorruptError,
                       match="checksum mismatch"):
        checkpoint.load(path)


def test_load_or_rebuild_falls_back_on_corruption(tmp_path):
    """The documented recovery: a damaged checkpoint falls back cleanly
    to rebuild_engine from the store — the reference's reconstruction
    path — instead of raising mid-restore."""
    import os

    store, engine = _small_cluster()
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine)
    s, e, src = checkpoint.load_or_rebuild(path, store)
    assert src == "checkpoint"
    with open(os.path.join(path, "manifest.json"), "r+b") as f:
        f.truncate(10)
    s2, e2, src2 = checkpoint.load_or_rebuild(path, store, capacity=16)
    assert src2 == "rebuild"
    # the rebuilt engine carries the realized link with its properties
    row = e2.link_row("default/s", 1)
    assert row is not None and row["latency_us"] == 10_000.0
    # without a fallback store the typed error propagates
    with pytest.raises(checkpoint.CheckpointError):
        checkpoint.load_or_rebuild(path)


def test_crash_between_renames_restores_previous_generation(tmp_path):
    """kill -9 between save()'s two renames leaves `path` absent and
    `<path>.prev` holding the previous complete checkpoint: load (and
    load_pending, same resolution) restore that generation."""
    import os

    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire.server import Daemon

    store, engine = _small_cluster()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=10_000.0)
    plane.restore_pending([("default/s", 1, b"\xaa" * 40, 80_000.0)],
                          now_s=0.0)
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine, dataplane=plane)
    # emulate the crash window: new tmp never landed, old moved aside
    os.rename(path, path + ".prev")
    store2, engine2 = checkpoint.load(path)
    assert engine2.row_of("default/s", 1) is not None
    plane2 = WireDataPlane(Daemon(engine2), dt_us=10_000.0)
    assert checkpoint.load_pending(path, plane2, now_s=100.0) == 1
    assert len(plane2.export_pending()) == 1
    # ... and the next successful save supersedes the .prev generation
    checkpoint.save(path, store, engine)
    assert not os.path.exists(path + ".prev")
    checkpoint.load(path)


def test_resave_without_sim_drops_stale_sim_state(tmp_path):
    """Satellite: a reused checkpoint directory must not resurrect an
    earlier save's sim_state.npz (mirror of the pending_frames rule) —
    the wholesale directory swap guarantees it."""
    import os

    from kubedtn_tpu.models.traffic import cbr_everywhere
    from kubedtn_tpu import sim as S

    store, engine = _small_cluster()
    spec = cbr_everywhere(engine.state.capacity, engine.num_active,
                          rate_bps=1e6, pkt_bytes=500.0)
    sim = S.init_sim(engine.state)
    sim = S.run(sim, spec, steps=2, dt_us=1000.0, k_slots=2)
    engine.state = sim.edges
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine, sim=sim)
    assert os.path.exists(os.path.join(path, "sim_state.npz"))
    _, engine2 = checkpoint.load(path)
    assert checkpoint.load_sim(path, engine2) is not None

    checkpoint.save(path, store, engine)  # sim is None this time
    assert not os.path.exists(os.path.join(path, "sim_state.npz"))
    _, engine3 = checkpoint.load(path)
    assert checkpoint.load_sim(path, engine3) is None


@pytest.mark.chaos
def test_kill9_mid_save_never_yields_corrupt_load(tmp_path):
    """The acceptance contract, with a REAL SIGKILL: a subprocess
    checkpoints the same cluster in a tight loop, killed -9 at an
    arbitrary instant; load() must then return a complete generation
    (new or previous) — never torn state — and load_or_rebuild must
    always produce a working engine."""
    import os
    import signal
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = str(tmp_path / "ckpt")
    src = f"""
import sys
sys.path.insert(0, {repo!r})
import os
os.environ["JAX_PLATFORMS"] = "cpu"
from kubedtn_tpu import checkpoint
from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \\
    TopologySpec
from kubedtn_tpu.topology import SimEngine, TopologyStore

store = TopologyStore()
engine = SimEngine(store, capacity=16)
t = Topology(name="s", spec=TopologySpec(links=[
    Link(local_intf="eth1", peer_intf="e",
         peer_pod="physical/10.0.0.9", uid=1,
         properties=LinkProperties(latency="10ms"))]))
store.create(t)
engine.setup_pod("s")
print("READY", flush=True)
while True:
    checkpoint.save({path!r}, store, engine)
"""
    store, _engine = _small_cluster()
    for attempt, delay_s in enumerate((0.25, 0.6)):
        proc = subprocess.Popen([sys.executable, "-c", src],
                                stdout=subprocess.PIPE, text=True)
        try:
            assert proc.stdout.readline().strip() == "READY"
            time.sleep(delay_s)  # several saves deep, mid-save likely
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
        s2, e2, src2 = checkpoint.load_or_rebuild(path, store,
                                                  capacity=16)
        # whichever generation (or fallback) won, the link is intact
        row = e2.link_row("default/s", 1)
        assert row is not None and row["latency_us"] == 10_000.0, \
            (attempt, src2)
        # a torn directory must never satisfy a plain load() — it either
        # loads a complete generation or raises the typed error
        try:
            _s3, e3 = checkpoint.load(path)
        except checkpoint.CheckpointError:
            pass
        else:
            assert e3.link_row("default/s", 1) is not None


def test_corrupt_pending_frames_is_typed_not_silent(tmp_path):
    import os

    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire.server import Daemon

    store, engine = _small_cluster()
    plane = WireDataPlane(Daemon(engine), dt_us=10_000.0)
    plane.restore_pending([("default/s", 1, b"\xbb" * 64, 40_000.0)],
                          now_s=0.0)
    path = str(tmp_path / "ckpt")
    checkpoint.save(path, store, engine, dataplane=plane)
    p = os.path.join(path, "pending_frames.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 3)
    plane2 = WireDataPlane(Daemon(engine), dt_us=10_000.0)
    with pytest.raises(checkpoint.CheckpointCorruptError):
        checkpoint.load_pending(path, plane2, now_s=1.0)


def test_restore_pending_rejects_synthetic_now_on_monotonic_plane():
    """Mirror direction of the clock guard: an obviously-synthetic now_s
    against a monotonic-derived origin must raise, not silently release
    every restored frame immediately."""
    import time

    import pytest

    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    plane = WireDataPlane(Daemon(engine), dt_us=10_000.0)
    plane.tick()  # monotonic clock: origin = time.monotonic()
    with pytest.raises(ValueError, match="monotonic"):
        plane.restore_pending([("default/a", 1, b"\x00" * 32, 1_000.0)],
                              now_s=100.0)
    # an explicit now_s on the same (monotonic) clock is accepted
    assert plane.restore_pending(
        [("default/a", 1, b"\x00" * 32, 1_000.0)],
        now_s=time.monotonic()) == 1
