"""Wire-compatibility golden tests for the dynamic proto schema.

`kubedtn_tpu/wire/proto.py` hand-builds its FileDescriptorProto and claims
byte-compatibility with the reference IDL (reference proto/v1/kube_dtn.proto:8-172,
from which the reference's Go stubs proto/v1/*.pb.go are generated). These
tests make that claim checkable instead of asserted:

- `tests/data/kube_dtn_ref.desc` is the protoc-compiled FileDescriptorSet of
  the reference's kube_dtn.proto (libprotoc 3.21.12). It is checked in so the
  comparison runs without the reference tree or a protoc toolchain.
- When the reference tree AND protoc are both present, the blob is
  regenerated and byte-compared so it can never silently go stale.
- Every reference message is compared field-by-field (number, wire type,
  label) against the dynamic descriptors, fully-populated messages are
  serialized through BOTH descriptor sets and byte-compared in both
  directions, and every reference service method is checked for identical
  request/response types and streaming mode.

A single field-number or wire-type slip in proto.py breaks these tests —
which is exactly the failure that would otherwise silently break a
reference-built Go client talking to this daemon.
"""

import os
import shutil
import subprocess

import pytest
from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from kubedtn_tpu.wire import proto as dyn

DESC_PATH = os.path.join(os.path.dirname(__file__), "data",
                         "kube_dtn_ref.desc")
REF_PROTO = "/root/reference/proto/v1/kube_dtn.proto"

# proto3 scalar defaults are never serialized, so every field below is set
# to a non-default value — otherwise a wrong field NUMBER could hide behind
# an empty payload.
_FULL_VALUES = {
    "LinkProperties": dict(
        latency="5ms", latency_corr="10%", jitter="1ms", loss="0.5%",
        loss_corr="25%", rate="1Gbit", gap=3, duplicate="1%",
        duplicate_corr="5%", reorder_prob="2%", reorder_corr="50%",
        corrupt_prob="0.1%", corrupt_corr="12%"),
    "PodQuery": dict(name="r1", kube_ns="dtn"),
    "SetupPodQuery": dict(name="r1", kube_ns="dtn", net_ns="/proc/7/ns/net"),
    "BoolResponse": dict(response=True),
    "WireDef": dict(
        peer_intf_id=77, peer_ip="10.1.0.2", intf_name_in_pod="eth1",
        local_pod_net_ns="/proc/9/ns/net", link_uid=42,
        local_pod_name="r1", veth_name_local_host="host-eth-7",
        kube_ns="dtn", local_pod_ip="10.0.0.1"),
    "WireCreateResponse": dict(response=True, peer_intf_id=77),
    "Packet": dict(remot_intf_id=77, frame=b"\x01\x02\x03\xff" * 16),
    "GenerateNodeInterfaceNameRequest": dict(
        pod_intf_name="eth1", pod_name="r1"),
    "GenerateNodeInterfaceNameResponse": dict(
        ok=True, node_intf_name="eth-r1-eth1"),
}


def _ref_file() -> descriptor_pb2.FileDescriptorProto:
    fds = descriptor_pb2.FileDescriptorSet()
    with open(DESC_PATH, "rb") as fh:
        fds.ParseFromString(fh.read())
    (f,) = fds.file
    return f


@pytest.fixture(scope="module")
def ref_messages():
    """Message classes compiled from the reference's own descriptor set."""
    pool = descriptor_pool.DescriptorPool()
    fd = _ref_file()
    pool.Add(fd)
    out = {}
    for m in fd.message_type:
        out[m.name] = message_factory.GetMessageClass(
            pool.FindMessageTypeByName(f"{fd.package}.{m.name}"))
    return out, fd


def test_checked_in_descriptor_matches_reference_proto(tmp_path):
    """Freshness guard: the blob must equal a live protoc run whenever the
    reference tree and protoc are both available."""
    protoc = shutil.which("protoc")
    if protoc is None or not os.path.exists(REF_PROTO):
        pytest.skip("protoc or reference proto not available")
    shutil.copy(REF_PROTO, tmp_path / "kube_dtn.proto")
    out = tmp_path / "fresh.desc"
    subprocess.run(
        [protoc, f"--descriptor_set_out={out}", "--include_imports",
         "-I.", "kube_dtn.proto"],
        cwd=tmp_path, check=True)
    with open(DESC_PATH, "rb") as fh:
        golden = fh.read()
    assert out.read_bytes() == golden, (
        "tests/data/kube_dtn_ref.desc is stale — regenerate with protoc")


# Framework extension FIELDS inside reference messages — numbers past
# the reference's, carried as unknown fields by reference peers (proto3
# skips them): Packet.trace_id=3 (flight-recorder cross-node trace id,
# wire/proto.py). Anything not listed here is a silent wire break.
EXTENSION_FIELDS = {"Packet": {3}}


def test_every_reference_field_matches(ref_messages):
    """Every reference field must match ours number-for-number (wire
    types and labels included); extra fields are allowed ONLY from the
    documented EXTENSION_FIELDS allowlist."""
    _, fd = ref_messages
    assert fd.package == dyn.PACKAGE
    for ref_msg in fd.message_type:
        ours = dyn._MESSAGES[ref_msg.name].DESCRIPTOR
        ref_by_num = {f.number: f for f in ref_msg.field}
        ours_by_num = {f.number: f for f in ours.fields}
        assert set(ref_by_num) <= set(ours_by_num), (
            f"{ref_msg.name}: reference fields missing")
        extra = set(ours_by_num) - set(ref_by_num)
        assert extra <= EXTENSION_FIELDS.get(ref_msg.name, set()), (
            f"{ref_msg.name}: undocumented extension fields {extra}")
        for num, rf in ref_by_num.items():
            of = ours_by_num[num]
            assert of.name == rf.name, f"{ref_msg.name}.{num}"
            assert of.type == rf.type, (
                f"{ref_msg.name}.{rf.name}: wire type "
                f"{of.type} != {rf.type}")
            ref_repeated = (rf.label ==
                            descriptor_pb2.FieldDescriptorProto
                            .LABEL_REPEATED)
            assert of.is_repeated == ref_repeated, (
                f"{ref_msg.name}.{rf.name}: repeated-ness")
            if rf.type == descriptor_pb2.FieldDescriptorProto.TYPE_MESSAGE:
                # message-typed fields must point at the same nested type
                assert f".{fd.package}.{of.message_type.name}" \
                    == rf.type_name, f"{ref_msg.name}.{rf.name}"


def _build(cls, name, ref_cls_map):
    """Fully-populated instance of `name` built with class map `cls`."""
    if name == "Link":
        return cls["Link"](
            peer_pod="r2", local_intf="eth1", peer_intf="eth2",
            local_ip="10.0.0.1/24", peer_ip="10.0.0.2/24", uid=42,
            local_mac="aa:bb:cc:dd:ee:01", peer_mac="aa:bb:cc:dd:ee:02",
            properties=cls["LinkProperties"](
                **_FULL_VALUES["LinkProperties"]))
    if name == "Pod":
        return cls["Pod"](
            name="r1", src_ip="192.168.1.10", net_ns="/proc/7/ns/net",
            kube_ns="dtn",
            links=[_build(cls, "Link", ref_cls_map),
                   _build(cls, "Link", ref_cls_map)])
    if name == "LinksBatchQuery":
        return cls["LinksBatchQuery"](
            local_pod=_build(cls, "Pod", ref_cls_map),
            links=[_build(cls, "Link", ref_cls_map)])
    if name == "RemotePod":
        return cls["RemotePod"](
            net_ns="/proc/7/ns/net", intf_name="eth1",
            intf_ip="10.0.0.1/24", peer_vtep="192.168.1.20",
            kube_ns="dtn", vni=5042, name="r1",
            properties=cls["LinkProperties"](
                **_FULL_VALUES["LinkProperties"]))
    return cls[name](**_FULL_VALUES[name])


def test_serialized_bytes_roundtrip_both_directions(ref_messages):
    """Every message type, fully populated, must serialize to the SAME
    bytes through our dynamic classes and the reference's compiled
    classes, and each side must parse the other's bytes losslessly."""
    ref_cls, fd = ref_messages
    for name in [m.name for m in fd.message_type]:
        ours = _build(dyn._MESSAGES, name, ref_cls)
        theirs = _build(ref_cls, name, ref_cls)
        b_ours = ours.SerializeToString(deterministic=True)
        b_theirs = theirs.SerializeToString(deterministic=True)
        assert b_ours == b_theirs, f"{name}: serialized bytes differ"
        assert len(b_ours) > 0, f"{name}: test value serialized empty"
        # cross-parse: their bytes through our class and vice versa
        back_ours = dyn._MESSAGES[name]()
        back_ours.ParseFromString(b_theirs)
        assert back_ours.SerializeToString(deterministic=True) == b_theirs
        back_theirs = ref_cls[name]()
        back_theirs.ParseFromString(b_ours)
        assert back_theirs.SerializeToString(deterministic=True) == b_ours


def test_every_reference_service_method_matches(ref_messages):
    """Service names, method names, request/response types and streaming
    modes must cover the reference's exactly; extensions (InjectFrame)
    are allowed but reference methods may not drift."""
    _, fd = ref_messages
    tables = {"Local": dyn.LOCAL_METHODS, "Remote": dyn.REMOTE_METHODS,
              "WireProtocol": dyn.WIRE_METHODS}
    assert {s.name for s in fd.service} == set(tables)
    for svc in fd.service:
        table = tables[svc.name]
        for m in svc.method:
            assert m.name in table, f"{svc.name}.{m.name} missing"
            req_cls, resp_cls, streaming = table[m.name]
            assert f".{fd.package}.{req_cls.DESCRIPTOR.name}" \
                == m.input_type, f"{svc.name}.{m.name} request type"
            assert f".{fd.package}.{resp_cls.DESCRIPTOR.name}" \
                == m.output_type, f"{svc.name}.{m.name} response type"
            assert m.client_streaming == streaming, (
                f"{svc.name}.{m.name} streaming mode")
            assert not m.server_streaming


# -- method-path-level interop -----------------------------------------
#
# The message-level tests above prove encodings match; these prove the
# SERVER actually answers on the byte-identical full method strings a
# reference-built Go client dials (protoc derives them from the package/
# service/method names in kube_dtn.proto:145-172 into proto/v1/*_grpc.pb.go,
# e.g. "/proto.v1.Local/AddLinks"). A handler-registration slip — wrong
# package constant, renamed service — would pass every message test and
# still answer UNIMPLEMENTED to every real client; these tests fail on it.

import grpc

_DATA_DIR = os.path.join(os.path.dirname(__file__), "data")


def _identity(b):
    return b


@pytest.fixture()
def live_server():
    from kubedtn_tpu.topology import SimEngine, TopologyStore
    from kubedtn_tpu.wire.server import Daemon, make_server

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0, host="127.0.0.1",
                               log_rpcs=False)
    server.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")
    yield daemon, channel
    channel.close()
    server.stop(0)


def test_registered_method_paths_match_protoc_derivation(ref_messages,
                                                         live_server):
    """Dial every reference service method on the exact path string the
    reference's generated stubs use — derived here from the protoc
    descriptor, NOT from our proto module — with a syntactically valid
    request. Any status except UNIMPLEMENTED proves the server registered
    a handler under the byte-identical path (no daemon handler in this
    codebase returns UNIMPLEMENTED itself)."""
    ref_cls, fd = ref_messages
    _daemon, channel = live_server
    for svc in fd.service:
        for m in svc.method:
            path = f"/{fd.package}.{svc.name}/{m.name}"
            req_name = m.input_type.rsplit(".", 1)[1]
            payload = _build(ref_cls, req_name, ref_cls) \
                .SerializeToString(deterministic=True)
            try:
                if m.client_streaming:
                    call = channel.stream_unary(
                        path, request_serializer=_identity,
                        response_deserializer=_identity)
                    call(iter([payload]), timeout=10)
                else:
                    call = channel.unary_unary(
                        path, request_serializer=_identity,
                        response_deserializer=_identity)
                    call(payload, timeout=10)
            except grpc.RpcError as e:
                assert e.code() != grpc.StatusCode.UNIMPLEMENTED, (
                    f"{path}: not registered (UNIMPLEMENTED) — a "
                    f"reference-built client dialing this path gets no "
                    f"service")
                # NOT_FOUND etc. for a dummy payload still proves the
                # path resolved to our handler


def _golden(name: str, kind: str) -> bytes:
    with open(os.path.join(_DATA_DIR, f"golden_{name}.{kind}.hex")) as f:
        return bytes.fromhex(f.read().strip())


def test_captured_bytes_goldens_per_service(live_server):
    """Replay one captured request per service as RAW BYTES against a
    fresh live server and byte-compare the raw response to the captured
    golden. The goldens were serialized through message classes built
    from the checked-in protoc descriptor (reference-derived), so a
    regression in our dynamic encodings OR our handler registration
    cannot hide behind message-level tests that use our own classes on
    both sides. Order matters: the Remote call creates the wire the
    WireProtocol call targets (ids are deterministic on a fresh daemon).
    """
    _daemon, channel = live_server
    seq = [
        ("local_generate_node_interface_name",
         "/proto.v1.Local/GenerateNodeInterfaceName"),
        ("remote_add_grpc_wire_remote",
         "/proto.v1.Remote/AddGRPCWireRemote"),
        ("wire_send_to_once",
         "/proto.v1.WireProtocol/SendToOnce"),
    ]
    for name, path in seq:
        call = channel.unary_unary(path, request_serializer=_identity,
                                   response_deserializer=_identity)
        resp = call(_golden(name, "req"), timeout=10)
        assert resp == _golden(name, "resp"), (
            f"{path}: response bytes differ from captured golden")
