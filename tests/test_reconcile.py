"""Engine + reconciler tests, ending in the 3-node e2e.

Mirrors the reference's validation story (SURVEY.md §4) but executable
without a cluster: the 3-node full-mesh sample (reference
config/samples/3node.yml + hack/test-3node.sh ping smoke test) is loaded
as-is, pods come up through the CNI-equivalent setup path, and reachability
is asserted via ping-equivalent probes through the shaping kernels.
"""

import time

import numpy as np
import pytest

from kubedtn_tpu.api.types import (
    Link,
    LinkProperties,
    Topology,
    TopologySpec,
    load_yaml,
)
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore, calc_diff


REFERENCE_3NODE = "/root/reference/config/samples/3node.yml"
REFERENCE_LATENCY = "/root/reference/config/samples/tc/latency.yaml"


def cluster(yaml_path_or_topos):
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    if isinstance(yaml_path_or_topos, str):
        topos = load_yaml(yaml_path_or_topos)
    else:
        topos = yaml_path_or_topos
    for t in topos:
        store.create(t)
    return store, engine, [t.name for t in topos]


class TestCalcDiff:
    def test_add_del_change(self):
        a = Link(local_intf="eth1", peer_intf="eth1", peer_pod="x", uid=1)
        b = Link(local_intf="eth2", peer_intf="eth2", peer_pod="y", uid=2,
                 properties=LinkProperties(latency="10ms"))
        b2 = Link(local_intf="eth2", peer_intf="eth2", peer_pod="y", uid=2,
                  properties=LinkProperties(latency="50ms"))
        c = Link(local_intf="eth3", peer_intf="eth3", peer_pod="z", uid=3)
        add, dele, changed = calc_diff([a, b], [b2, c])
        assert add == [c]
        assert dele == [a]
        assert changed == [b2]

    def test_matches_reference_on_identity_fields(self):
        # a changed IP is a delete+add, not an update (EqualWithoutProperties
        # compares all identity fields — topology_controller.go:342-351)
        a = Link(local_intf="eth1", peer_intf="eth1", peer_pod="x", uid=1,
                 local_ip="10.0.0.1/24")
        a2 = Link(local_intf="eth1", peer_intf="eth1", peer_pod="x", uid=1,
                  local_ip="10.0.0.2/24")
        add, dele, changed = calc_diff([a], [a2])
        assert (add, dele, changed) == ([a2], [a], [])


class TestEngineLifecycle:
    def test_setup_pod_unknown_delegates(self):
        store = TopologyStore()
        engine = SimEngine(store)
        assert engine.setup_pod("ghost") is True  # delegate, not error
        assert engine.num_active == 0

    @pytest.mark.requires_reference_yaml
    def test_peer_alive_gating(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        engine.setup_pod("r1")
        # r2, r3 not alive: nothing realized yet
        assert engine.num_active == 0
        engine.setup_pod("r2")
        # r1-r2 link (uid 1) realized in both directions
        assert engine.num_active == 2
        assert engine.row_of("default/r1", 1) is not None
        assert engine.row_of("default/r2", 1) is not None
        engine.setup_pod("r3")
        # full mesh: uids 1,2,3 × 2 directions
        assert engine.num_active == 6

    @pytest.mark.requires_reference_yaml
    def test_finalizer_set_on_alive(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        engine.setup_pod("r1")
        assert store.get("default", "r1").finalizers == ["y-young.github.io/v1"]
        engine.destroy_pod("r1")
        assert store.get("default", "r1").finalizers == []

    @pytest.mark.requires_reference_yaml
    def test_destroy_pod_tears_down_both_directions(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        for n in ("r1", "r2", "r3"):
            engine.setup_pod(n)
        engine.destroy_pod("r2")
        # r2's links (uids 1,3) die in both directions; uid 2 (r1-r3) lives
        assert engine.num_active == 2
        assert engine.row_of("default/r1", 2) is not None
        assert engine.row_of("default/r3", 2) is not None
        assert engine.row_of("default/r1", 1) is None

    def test_macvlan_no_shaping(self):
        store = TopologyStore()
        engine = SimEngine(store)
        t = Topology(name="m", spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eno1", peer_pod="localhost",
                 uid=9, properties=LinkProperties(latency="10ms"))]))
        store.create(t)
        engine.setup_pod("m")
        row = engine.link_row("default/m", 9)
        assert row["active"]
        assert row["latency_us"] == 0.0  # reference applies no qdiscs here

    def test_physical_link_realized_immediately(self):
        store = TopologyStore()
        engine = SimEngine(store)
        t = Topology(name="gw", spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth9",
                 peer_pod="physical/192.168.1.5", uid=4,
                 properties=LinkProperties(latency="5ms"))]))
        store.create(t)
        engine.setup_pod("gw")
        row = engine.link_row("default/gw", 4)
        assert row["active"] and row["latency_us"] == 5000.0

    def test_capacity_growth(self):
        store = TopologyStore()
        engine = SimEngine(store, capacity=8)
        links = [Link(local_intf=f"e{u}", peer_intf=f"e{u}",
                      peer_pod=f"physical/10.0.0.{u}", uid=u)
                 for u in range(1, 30)]
        store.create(Topology(name="big", spec=TopologySpec(links=links)))
        engine.setup_pod("big")
        assert engine.num_active == 29
        assert engine.state.capacity >= 29


class TestReconciler:
    @pytest.mark.requires_reference_yaml
    def test_first_seen_copies_status_without_plumbing(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        rec = Reconciler(store, engine)
        r = rec.reconcile("default", "r1")
        assert r.action == "first-seen"
        assert engine.num_active == 0  # no plumbing on first sight
        topo = store.get("default", "r1")
        assert topo.status.links == topo.spec.links

    @pytest.mark.requires_reference_yaml
    def test_noop_when_steady(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        rec = Reconciler(store, engine)
        rec.reconcile("default", "r1")
        assert rec.reconcile("default", "r1").action == "noop"

    @pytest.mark.requires_reference_yaml
    def test_property_change_flows_to_device(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        for n in ("r1", "r2", "r3"):
            engine.setup_pod(n)
        rec = Reconciler(store, engine)
        rec.reconcile_all()  # first-seen for all

        t = store.get("default", "r1")
        links = list(t.spec.links)
        links[0] = Link(local_intf=links[0].local_intf,
                        peer_intf=links[0].peer_intf,
                        peer_pod=links[0].peer_pod, uid=links[0].uid,
                        local_ip=links[0].local_ip, peer_ip=links[0].peer_ip,
                        properties=LinkProperties(latency="25ms"))
        t.spec.links = links
        store.update(t)
        r = rec.reconcile("default", "r1")
        assert r.action == "changed" and r.updated == 1
        assert engine.link_row("default/r1", 1)["latency_us"] == 25_000.0
        # update touches only the local end (handler.go:649-658)
        assert engine.link_row("default/r2", 1)["latency_us"] == 0.0

    @pytest.mark.requires_reference_yaml
    def test_link_remove_via_spec(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        for n in ("r1", "r2", "r3"):
            engine.setup_pod(n)
        rec = Reconciler(store, engine)
        rec.reconcile_all()
        t = store.get("default", "r1")
        t.spec.links = [l for l in t.spec.links if l.uid != 2]
        store.update(t)
        r = rec.reconcile("default", "r1")
        assert r.deleted == 1
        assert engine.row_of("default/r1", 2) is None
        assert engine.row_of("default/r3", 2) is None  # pair destroyed

    @pytest.mark.requires_reference_yaml
    def test_drain_watch_loop(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        rec = Reconciler(store, engine)
        results = rec.drain()
        # 3 ADDED events -> 3 first-seen reconciles; the status writes
        # re-trigger the watch, which settles as noops (the reference
        # controller behaves identically via its DeepEqual guard).
        first_seen = [r for r in results if r.action == "first-seen"]
        assert sorted(r.key for r in first_seen) == [
            "default/r1", "default/r2", "default/r3"]
        assert all(r.action in ("first-seen", "noop") for r in results)
        assert rec.drain() == []  # steady


@pytest.mark.skipif(not __import__("os").path.exists(REFERENCE_3NODE),
                    reason="reference samples not mounted")
class TestThreeNodeE2E:
    """The reference's 3-node ping smoke test, virtualized."""

    def test_full_mesh_ping(self):
        store, engine, names = cluster(REFERENCE_3NODE)
        for n in names:
            engine.setup_pod(n)
        rec = Reconciler(store, engine)
        rec.drain()
        for a, b, uid in [("r1", "r2", 1), ("r1", "r3", 2), ("r2", "r3", 3)]:
            out = engine.ping(a, b, uid)
            assert out["reachable"], (a, b)
            assert out["rtt_us"] == 0.0  # no shaping configured

    def test_latency_scenario_rtts(self):
        store, engine, names = cluster(REFERENCE_LATENCY)
        for n in names:
            engine.setup_pod(n)
        Reconciler(store, engine).drain()
        # whoever plumbs last imposes its props on both ends: r2 comes up
        # after r1 and redoes uid-1 (10ms both ways); r3 plumbs uid 3 last
        # (r3's declared latency for uid 3 is 50ms per the sample).
        out12 = engine.ping("r1", "r2", 1)
        assert out12["rtt_us"] == pytest.approx(20_000.0)
        out23 = engine.ping("r2", "r3", 3)
        assert out23["rtt_us"] == pytest.approx(100_000.0)
        # uid 2 (r1-r3): no properties declared on either side
        out13 = engine.ping("r1", "r3", 2)
        assert out13["rtt_us"] == pytest.approx(0.0)

    def test_steady_state_after_churn(self):
        store, engine, names = cluster(REFERENCE_3NODE)
        for n in names:
            engine.setup_pod(n)
        rec = Reconciler(store, engine)
        rec.drain()
        # kill and revive r2
        engine.destroy_pod("r2")
        assert not engine.ping("r1", "r2", 1)["reachable"]
        engine.setup_pod("r2")
        rec.drain()
        assert engine.ping("r1", "r2", 1)["reachable"]
        assert engine.num_active == 6


@pytest.mark.requires_reference_yaml
def test_destroy_pod_with_pending_deletion():
    # Deleting the CR while the pod is alive leaves it held by the
    # finalizer; DestroyPod must still tear down links even though
    # clearing the finalizer completes the deletion mid-call
    # (reference handler.go:559-586 reads links before SetAlive).
    store, engine, names = cluster(REFERENCE_3NODE)
    for n in names:
        engine.setup_pod(n)
    store.delete("default", "r3")
    held = store.get("default", "r3")
    assert held.deletion_requested and held.finalizers
    assert engine.destroy_pod("r3")
    with pytest.raises(KeyError):
        store.get("default", "r3")
    # r3's links (uids 2, 3) died in both directions; uid 1 survives
    assert engine.num_active == 2
    assert engine.row_of("default/r1", 1) is not None


class TestEngineFailurePropagation:
    """Regression: a failed engine op (e.g. a rejected cross-node
    completion RPC) must not be recorded as realized — the reference
    returns the error to controller-runtime so the request requeues
    (reference daemon/kubedtn/handler.go:524-532,
    controllers/topology_controller.go:120-122)."""

    class FlakyEngine(SimEngine):
        def __init__(self, *a, fail_times=1, **kw):
            super().__init__(*a, **kw)
            self.fail_times = fail_times

        def add_links(self, topo, links):
            if links and self.fail_times > 0:
                self.fail_times -= 1
                return False  # e.g. peer daemon unreachable; nothing realized
            return super().add_links(topo, links)

    def topo(self):
        link = Link(local_intf="eth1", peer_intf="eth1", peer_pod="r2",
                    uid=1, properties=LinkProperties(latency="10ms"))
        t = Topology(name="r1", spec=TopologySpec(links=[link]))
        t.status.links = []  # already seen: reconcile must plumb the add
        return t

    def test_setup_pod_propagates_add_failure(self):
        store = TopologyStore()
        engine = self.FlakyEngine(store, capacity=16)
        t = self.topo()
        t.status.links = None
        store.create(t)
        assert engine.setup_pod("r1") is False
        engine.fail_times = 0
        assert engine.setup_pod("r1") is True

    def test_failed_reconcile_keeps_status_stale_and_requeues(self):
        store = TopologyStore()
        engine = self.FlakyEngine(store, capacity=16)
        store.create(self.topo())
        rec = Reconciler(store, engine)
        results = rec.drain()
        # pass 1: add fails -> status NOT copied; pass 2 (requeue): add
        # succeeds -> status copied; pass 3: MODIFIED event -> noop
        assert [r.ok for r in results] == [False, True, True]
        assert results[0].action == "changed"
        assert results[-1].action == "noop"
        fresh = store.get("default", "r1")
        assert fresh.status.links == fresh.spec.links

    def test_failed_reconcile_does_not_copy_status(self):
        store = TopologyStore()
        engine = self.FlakyEngine(store, capacity=16, fail_times=10**9)
        store.create(self.topo())
        rec = Reconciler(store, engine)
        res = rec.reconcile("default", "r1")
        assert res.ok is False
        assert store.get("default", "r1").status.links == []  # still stale


class TestWorkQueue:
    """client-go workqueue semantics: dedup, per-key exclusivity, no lost
    re-adds during processing (the discipline behind the reference's 32
    concurrent reconcile workers, topology_controller.go:336)."""

    def test_dedup_queued_key(self):
        from kubedtn_tpu.topology.reconciler import WorkQueue

        q = WorkQueue()
        q.add("a")
        q.add("a")
        assert q.get(timeout=0.1) == "a"
        q.done("a")
        assert q.get(timeout=0.05) is None  # second add coalesced

    def test_readd_during_processing_requeues_on_done(self):
        from kubedtn_tpu.topology.reconciler import WorkQueue

        q = WorkQueue()
        q.add("a")
        key = q.get(timeout=0.1)
        q.add("a")                          # update arrives mid-reconcile
        assert q.get(timeout=0.05) is None  # NOT handed out concurrently
        q.done(key)
        assert q.get(timeout=0.1) == "a"    # ...but never lost
        q.done("a")
        assert q.idle()

    def test_no_two_workers_same_key(self):
        import threading as th

        from kubedtn_tpu.topology.reconciler import WorkQueue

        q = WorkQueue()
        active: dict[str, int] = {}
        overlaps = []
        lock = th.Lock()

        def worker():
            while True:
                key = q.get(timeout=0.05)
                if key is None:
                    return
                with lock:
                    active[key] = active.get(key, 0) + 1
                    if active[key] > 1:
                        overlaps.append(key)
                time.sleep(0.001)
                with lock:
                    active[key] -= 1
                q.done(key)

        threads = [th.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for i in range(200):
            q.add(f"k{i % 5}")  # heavy contention on 5 keys
        for t in threads:
            t.join(timeout=10)
        assert not overlaps


class TestConcurrentReconcile:
    N = 24

    def seed(self, store):
        for i in range(self.N):
            link = Link(local_intf="eth1", peer_intf="eth0",
                        peer_pod="physical/10.9.9.9", uid=i,
                        properties=LinkProperties(latency="1ms"))
            t = Topology(name=f"p{i}", spec=TopologySpec(links=[link]))
            t.status.links = []
            store.create(t)

    def test_two_writers_plus_workers_no_lost_updates(self):
        """Two spec writers race a concurrent reconciler; afterwards every
        topology's status AND its realized device row must equal the final
        spec — an update arriving mid-reconcile must never be lost."""
        import random
        import threading as th

        from kubedtn_tpu.topology.store import retry_on_conflict

        store = TopologyStore()
        engine = SimEngine(store, capacity=64)
        self.seed(store)
        rec = Reconciler(store, engine)
        writers_done = th.Event()

        def writer(seed):
            # Link/LinkProperties are frozen: spec changes REPLACE the Link
            # (mutating in place would raise FrozenInstanceError)
            from dataclasses import replace

            rng = random.Random(seed)
            for v in range(2, 12):
                for i in rng.sample(range(self.N), self.N // 2):
                    def txn():
                        t = store.get("default", f"p{i}")
                        t.spec.links = [replace(
                            t.spec.links[0],
                            properties=LinkProperties(latency=f"{v}ms"))]
                        store.update(t)
                    retry_on_conflict(txn, retries=50)
                    time.sleep(0.0005)

        ws = [th.Thread(target=writer, args=(s,)) for s in (1, 2)]
        for w in ws:
            w.start()
        while not writers_done.is_set():
            rec.drain(workers=8)
            if all(not w.is_alive() for w in ws):
                writers_done.set()
        for w in ws:
            w.join()
        rec.drain(workers=8)  # settle the tail

        for i in range(self.N):
            t = store.get("default", f"p{i}")
            assert t.status.links == t.spec.links, f"p{i} status lost update"
            want = t.spec.links[0].properties.to_numeric()["latency_us"]
            row = engine.link_row(f"default/p{i}", i)
            assert row is not None
            assert row["latency_us"] == want, \
                f"p{i} device row stale: {row['latency_us']} != {want}"

    def test_concurrent_drain_matches_serial(self):
        store = TopologyStore()
        engine = SimEngine(store, capacity=64)
        self.seed(store)
        rec = Reconciler(store, engine)
        results = rec.drain(workers=8)
        assert all(r.ok for r in results)
        for i in range(self.N):
            t = store.get("default", f"p{i}")
            assert t.status.links == t.spec.links
            assert engine.link_row(f"default/p{i}", i) is not None


def test_concurrent_drain_surfaces_worker_exception():
    """Regression: an exception inside a reconcile worker must raise out
    of drain(workers>1) — not strand the key in the workqueue's
    processing set and hang the drain forever."""

    class ExplodingEngine(SimEngine):
        def add_links(self, topo, links):
            raise RuntimeError("boom")

    store = TopologyStore()
    engine = ExplodingEngine(store, capacity=16)
    link = Link(local_intf="eth1", peer_intf="eth0",
                peer_pod="physical/10.9.9.9", uid=1)
    t = Topology(name="p0", spec=TopologySpec(links=[link]))
    t.status.links = []
    store.create(t)
    rec = Reconciler(store, engine)

    done = {}

    def run():
        try:
            rec.drain(workers=4)
            done["outcome"] = "returned"
        except RuntimeError as e:
            done["outcome"] = f"raised:{e}"

    import threading as th
    worker = th.Thread(target=run, daemon=True)
    worker.start()
    worker.join(timeout=20)
    assert not worker.is_alive(), "drain hung on worker exception"
    assert done["outcome"] == "raised:boom"
    # the key requeues so a later (healthy) drain can converge
    assert ("default", "p0") in rec._requeue


class TestPlacementGeneration:
    """The engine caches (src_ip, net_ns) answers against the store's
    placement generation; these pin the generation's bump/no-bump rules
    and the cache's cross-drain invalidation."""

    @pytest.mark.requires_reference_yaml
    def test_spec_update_and_status_copyback_keep_generation(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        engine.setup_pod("r1")
        gen = store.placement_generation
        # spec-only update: no placement movement
        t = store.get("default", "r1")
        store.update(t)
        assert store.placement_generation == gen
        # status copy-back (links only, same src_ip/net_ns): no bump —
        # this is what keeps the cache warm across a reconcile drain
        t = store.get("default", "r1")
        t.status.links = list(t.spec.links)
        store.update_status(t)
        assert store.placement_generation == gen

    @pytest.mark.requires_reference_yaml
    def test_placement_write_and_delete_bump_generation(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        gen = store.placement_generation
        engine.set_alive("r1", "default", "10.0.0.9", "/run/netns/r1")
        assert store.placement_generation > gen
        gen = store.placement_generation
        engine.destroy_pod("r1")  # clears placement (src_ip="")
        assert store.placement_generation > gen

    @pytest.mark.requires_reference_yaml
    def test_cache_invalidated_when_peer_comes_alive(self):
        store, engine, _ = cluster(REFERENCE_3NODE)
        rec = Reconciler(store, engine)
        engine.set_alive("r1", "default", "10.0.0.1", "/run/netns/r1")
        rec.drain()
        # r1 alive, peers not: nothing realized; peer absence is cached
        assert engine.num_active == 0
        # r2 gains placement -> generation bumps -> the next drain must
        # NOT reuse the cached "r2 not alive" answer
        engine.set_alive("r2", "default", "10.0.0.1", "/run/netns/r2")
        # force a re-reconcile of r1 (its status == spec after drain 1
        # would no-op; clear status links to re-diff)
        t = store.get("default", "r1")
        t.status.links = []
        store.update_status(t)
        rec.drain()
        assert engine.row_of("default/r1", 1) is not None
        assert engine.row_of("default/r2", 1) is not None


class TestTrace:
    def test_multihop_line(self):
        from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
            TopologySpec

        store = TopologyStore()
        engine = SimEngine(store, capacity=64)
        props = LinkProperties(latency="5ms")
        names = ["n0", "n1", "n2", "n3"]
        specs = {n: [] for n in names}
        for uid, (a, b) in enumerate(zip(names, names[1:]), start=1):
            specs[a].append(Link(local_intf=f"e{uid}a", peer_intf=f"e{uid}b",
                                 peer_pod=b, uid=uid, properties=props))
            specs[b].append(Link(local_intf=f"e{uid}b", peer_intf=f"e{uid}a",
                                 peer_pod=a, uid=uid, properties=props))
        for n in names:
            store.create(Topology(name=n, spec=TopologySpec(links=specs[n])))
        for n in names:
            engine.setup_pod(n)
        Reconciler(store, engine).drain()

        out = engine.trace("n0", "n3")
        assert out["reachable"] is True
        assert [h["to"] for h in out["hops"]] == [
            "default/n1", "default/n2", "default/n3"]
        assert [h["uid"] for h in out["hops"]] == [1, 2, 3]
        assert out["total_latency_us"] == 15_000.0

        # reverse direction works and unknown pods don't
        back = engine.trace("n3", "n0")
        assert back["reachable"] and len(back["hops"]) == 3
        assert engine.trace("n0", "ghost")["reachable"] is False

    def test_unreachable_after_cut(self):
        from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
            TopologySpec

        store = TopologyStore()
        engine = SimEngine(store, capacity=64)
        props = LinkProperties(latency="1ms")
        specs = {"x": [Link(local_intf="e1a", peer_intf="e1b", peer_pod="y",
                            uid=1, properties=props)],
                 "y": [Link(local_intf="e1b", peer_intf="e1a", peer_pod="x",
                            uid=1, properties=props)]}
        for n in ("x", "y"):
            store.create(Topology(name=n, spec=TopologySpec(links=specs[n])))
            engine.setup_pod(n)
        rec = Reconciler(store, engine)
        rec.drain()
        assert engine.trace("x", "y")["reachable"] is True
        # cut: drop the link from x's spec
        t = store.get("default", "x")
        t.spec.links = []
        store.update(t)
        rec.drain()
        out = engine.trace("x", "y")
        assert out["reachable"] is False and out["hops"] == []

    def test_path_of_exactly_max_hops_is_reachable(self):
        """Regression: a path of exactly max_hops edges must report
        reachable (reachability comes from the dist matrix, not from
        exhausting the walk loop)."""
        from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
            TopologySpec

        n = 17  # 16 hops end to end
        store = TopologyStore()
        engine = SimEngine(store, capacity=128)
        names = [f"m{i}" for i in range(n)]
        specs = {p: [] for p in names}
        for uid, (a, b) in enumerate(zip(names, names[1:]), start=1):
            props = LinkProperties(latency="1ms")
            specs[a].append(Link(local_intf=f"e{uid}a", peer_intf=f"e{uid}b",
                                 peer_pod=b, uid=uid, properties=props))
            specs[b].append(Link(local_intf=f"e{uid}b", peer_intf=f"e{uid}a",
                                 peer_pod=a, uid=uid, properties=props))
        for p in names:
            store.create(Topology(name=p, spec=TopologySpec(links=specs[p])))
            engine.setup_pod(p)
        Reconciler(store, engine).drain()
        out = engine.trace("m0", "m16", max_hops=16)
        assert out["reachable"] is True
        assert len(out["hops"]) == 16
        assert out["total_latency_us"] == 16_000.0


class TestCalcDiffEdgeCases:
    """ISSUE 8 satellite: calc_diff round-trip/duplicate-uid/shaping-
    only edge cases, property-tested over seeded random link sets."""

    @staticmethod
    def _rand_links(rng, n):
        links = []
        used = set()
        for _ in range(n):
            # duplicate uids are LEGAL (identity is the 8-field tuple);
            # only exact-duplicate identities are avoided, since the
            # reference's status list cannot hold two identical links
            while True:
                uid = rng.randrange(4)       # few uids => collisions
                intf = f"eth{rng.randrange(3)}"
                peer = f"p{rng.randrange(3)}"
                if (uid, intf, peer) not in used:
                    used.add((uid, intf, peer))
                    break
            links.append(Link(
                local_intf=intf, peer_intf=intf, peer_pod=peer, uid=uid,
                properties=LinkProperties(
                    latency=f"{rng.randrange(1, 9)}ms",
                    loss=rng.choice(["", "5", "10"]))))
        return links

    @staticmethod
    def _apply(old, add, delete, changed):
        from kubedtn_tpu.topology.reconciler import _identity

        dead = {_identity(d) for d in delete}
        ch = {_identity(c): c for c in changed}
        out = [ch.get(_identity(l), l) for l in old
               if _identity(l) not in dead]
        return out + list(add)

    @staticmethod
    def _norm(links):
        from kubedtn_tpu.topology.reconciler import _identity

        return sorted(links, key=lambda l: (_identity(l),
                                            repr(l.properties)))

    def test_roundtrip_property(self):
        import random

        for seed in range(20):
            rng = random.Random(seed)
            old = self._rand_links(rng, rng.randrange(0, 8))
            new = self._rand_links(rng, rng.randrange(0, 8))
            fwd = calc_diff(old, new)
            applied = self._apply(old, *fwd)
            assert self._norm(applied) == self._norm(new), seed
            # applying the diff converges: nothing left to do
            add2, del2, ch2 = calc_diff(applied, new)
            assert (add2, del2, ch2) == ([], [], []), seed
            # and the reverse diff takes you back — old -> new -> old
            # round-trips to an EMPTY diff
            back = calc_diff(applied, old)
            restored = self._apply(applied, *back)
            assert self._norm(restored) == self._norm(old), seed
            assert calc_diff(restored, old) == ([], [], []), seed

    def test_self_diff_is_empty(self):
        import random

        for seed in range(5):
            links = self._rand_links(random.Random(seed), 6)
            assert calc_diff(links, list(links)) == ([], [], [])

    def test_duplicate_uid_links_tracked_independently(self):
        # two links sharing a uid (distinct interfaces): changing one's
        # properties must classify exactly that one as changed
        a1 = Link(local_intf="eth1", peer_intf="eth1", peer_pod="x",
                  uid=7, properties=LinkProperties(latency="1ms"))
        a2 = Link(local_intf="eth2", peer_intf="eth2", peer_pod="x",
                  uid=7, properties=LinkProperties(latency="1ms"))
        a2_new = a2.with_properties(LinkProperties(latency="9ms"))
        add, dele, changed = calc_diff([a1, a2], [a1, a2_new])
        assert (add, dele) == ([], [])
        assert changed == [a2_new]

    def test_shaping_only_change_is_changed_not_add_del(self):
        # a link whose ONLY delta is shaping properties (here: rate) is
        # an update, never a delete+add — identity excludes properties
        a = Link(local_intf="eth1", peer_intf="eth1", peer_pod="x",
                 uid=1, properties=LinkProperties(latency="2ms"))
        a_new = a.with_properties(LinkProperties(rate="5Mbit"))
        add, dele, changed = calc_diff([a], [a_new])
        assert add == [] and dele == []
        assert changed == [a_new]


def test_direct_reconcile_failure_requeues_for_next_drain():
    """ISSUE 8 satellite (partial-apply leak): a failed DIRECT
    reconcile() — e.g. during reconcile_all's startup resync, with no
    watch event pending — must requeue the key itself, so the next
    drain retries the half-applied delta instead of leaving it stale
    until an unrelated event."""
    store = TopologyStore()
    engine = TestEngineFailurePropagation.FlakyEngine(store, capacity=16)
    link = Link(local_intf="eth1", peer_intf="eth1", peer_pod="r2",
                uid=1, properties=LinkProperties(latency="10ms"))
    t = Topology(name="r1", spec=TopologySpec(links=[link]))
    t.status.links = []
    store.create(t)
    rec = Reconciler(store, engine)
    # swallow the CREATE watch event so the later drain has NO events —
    # only the requeue can drive the retry
    list(rec._watch.poll())
    res = rec.reconcile("default", "r1")
    assert res.ok is False
    assert ("default", "r1") in rec._requeue
    results = rec.drain()
    assert any(r.ok for r in results)
    fresh = store.get("default", "r1")
    assert fresh.status.links == fresh.spec.links
