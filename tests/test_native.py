"""Native runtime library tests: frame decoder parity with the reference's
grpc-wire decoders (grpcwire.go:465-613), the eBPF-bypass flow-table state
machine (bpf/lib/sockops.c, redir.c, redir_disable.c), and the SPSC frame
ring. Builds native/libkubedtn_native.so with g++ on first use."""

import struct
import threading

import pytest

from kubedtn_tpu import native

pytestmark = pytest.mark.skipif(not native.have_native(),
                                reason="native toolchain unavailable")


# ---- frame builders -------------------------------------------------

def eth(src="\x02\x00\x00\x00\x00\x01", dst="\x02\x00\x00\x00\x00\x02",
        ethertype=0x0800, payload=b""):
    return (dst.encode("latin1") + src.encode("latin1")
            + struct.pack(">H", ethertype) + payload)


def ipv4(src="10.0.0.1", dst="10.0.0.2", proto=6, payload=b""):
    total = 20 + len(payload)
    ver_ihl = 0x45
    hdr = struct.pack(">BBHHHBBH4s4s", ver_ihl, 0, total, 0, 0, 64, proto, 0,
                      bytes(int(x) for x in src.split(".")),
                      bytes(int(x) for x in dst.split(".")))
    return hdr + payload


def tcp(sport=12345, dport=80, payload=b""):
    return struct.pack(">HHIIBBHHH", sport, dport, 0, 0, 0x50, 0, 8192, 0,
                       0) + payload


def arp():
    return struct.pack(">HHBBH", 1, 0x0800, 6, 4, 1) + b"\x00" * 20


# ---- decoder parity -------------------------------------------------

def test_ipv4_tcp_bgp():
    frame = eth(payload=ipv4(proto=6, payload=tcp(dport=179)))
    s = native.decode_frame(frame)
    assert s == ("Pkt no 1: Ethernet:IPv4[s:10.0.0.1, d:10.0.0.2]:TCP:BGP"), s
    assert native.classify_frame(frame) == "BGP"


def test_ipv4_tcp_port():
    frame = eth(payload=ipv4(payload=tcp(dport=8080)))
    s = native.decode_frame(frame)
    assert ":TCP:[Port:8080]" in s
    assert native.classify_frame(frame) == "TCP"


def test_ipv4_icmp():
    frame = eth(payload=ipv4(proto=1, payload=b"\x08\x00" + b"\x00" * 6))
    assert ":ICMP" in native.decode_frame(frame)
    assert native.classify_frame(frame) == "ICMP"


def test_ipv4_udp_protocol_text():
    frame = eth(payload=ipv4(proto=17, payload=b"\x00" * 8))
    s = native.decode_frame(frame)
    # the reference prints the raw protocol number for non-ICMP/TCP
    assert "IPv4 with protocol : 17" in s
    assert native.classify_frame(frame) == "UDP"


def test_arp():
    frame = eth(ethertype=0x0806, payload=arp())
    s = native.decode_frame(frame)
    assert s == "Pkt no 1: Ethernet:ARP"
    assert native.classify_frame(frame) == "ARP"


def test_vlan_ipv4():
    inner = ipv4(payload=tcp(dport=179))
    vlan = struct.pack(">HH", 100, 0x0800) + inner
    frame = eth(ethertype=0x8100, payload=vlan)
    s = native.decode_frame(frame)
    assert ":VLAN:IPv4" in s and ":BGP" in s
    assert native.classify_frame(frame) == "BGP"


def test_llc_isis():
    # 802.3 length-typed frame, LLC DSAP/SSAP 0xFE control 0x03, NLPID 0x83
    payload = b"\xfe\xfe\x03\x83" + b"\x00" * 30
    frame = eth(ethertype=len(payload), payload=payload)
    s = native.decode_frame(frame)
    assert ":LLC:ISIS" in s
    assert native.classify_frame(frame) == "ISIS"


def test_ipv6_tcp():
    # minimal IPv6 header: ver=6, payload len, next=6 (TCP), hop=64
    seg = tcp(dport=179)
    hdr = struct.pack(">IHBB", 0x60000000, len(seg), 6, 64)
    hdr += bytes(16) + bytes(15) + b"\x01"
    frame = eth(ethertype=0x86DD, payload=hdr + seg)
    s = native.decode_frame(frame)
    assert ":IPv6" in s and ":TCP:BGP" in s
    assert native.classify_frame(frame) == "BGP"


def test_multi_packet_frame():
    one = eth(payload=ipv4(payload=tcp(dport=179)))
    frame = one + one
    s = native.decode_frame(frame)
    assert s.startswith("Multi Pkts: ")
    assert s.count("Ethernet") == 2
    assert "Pkt no 2:" in s


def test_classify_batch():
    frames = [
        eth(ethertype=0x0806, payload=arp()),
        eth(payload=ipv4(payload=tcp(dport=179))),
        eth(payload=ipv4(proto=1, payload=b"\x00" * 8)),
    ]
    assert native.classify_batch(frames) == ["ARP", "BGP", "ICMP"]


def test_short_frame_unknown():
    assert native.classify_frame(b"\x00" * 5) == "UNKNOWN"


# ---- bypass flow table ----------------------------------------------

A = ("10.0.0.1", 40000)
B = ("10.0.0.2", 80)


def establish(ft):
    """Same-node TCP establishment: active on A, passive on B."""
    ft.active_established(*A, *B)
    assert ft.passive_established(*B, *A)


def test_bypass_state_machine():
    ft = native.FlowTable()
    establish(ft)
    # both directions tracked, INIT
    assert ft.flag(*A, *B) == native.PROXY_INIT
    assert ft.flag(*B, *A) == native.PROXY_INIT
    # first message passes normally and flips to ENABLED (redir.c:33-38)
    assert ft.msg_redirect(*A, *B) is False
    assert ft.flag(*A, *B) == native.PROXY_ENABLED
    # subsequent messages bypass
    assert ft.msg_redirect(*A, *B) is True
    assert ft.msg_redirect(*A, *B) is True
    assert ft.bypassed == 2 and ft.passed == 1
    ft.close()


def test_shaped_egress_disables_bypass_forever():
    """redir_disable.c: flows crossing a shaped veth must not cheat
    emulation."""
    ft = native.FlowTable()
    establish(ft)
    ft.msg_redirect(*A, *B)  # INIT -> ENABLED
    assert ft.msg_redirect(*A, *B) is True
    ft.shaped_egress(*A, *B)
    assert ft.flag(*A, *B) == native.PROXY_DISABLED
    assert ft.msg_redirect(*A, *B) is False
    assert ft.msg_redirect(*A, *B) is False  # stays disabled
    ft.close()


def test_unknown_flow_passes():
    ft = native.FlowTable()
    assert ft.msg_redirect(*A, *B) is False
    assert ft.flag(*A, *B) is None
    ft.close()


def test_cross_node_flow_never_paired():
    """No active record on this node ⇒ passive establish is a no-op."""
    ft = native.FlowTable()
    assert not ft.passive_established(*B, *A)
    assert len(ft) == 0
    ft.close()


def test_close_cleans_up():
    ft = native.FlowTable()
    establish(ft)
    assert len(ft) == 2
    ft.on_close(*A, *B)
    ft.on_close(*B, *A)
    assert len(ft) == 0
    ft.close()


# ---- frame ring -----------------------------------------------------

def test_ring_fifo():
    rb = native.FrameRing(4096)
    frames = [bytes([i]) * (i + 1) for i in range(10)]
    for f in frames:
        assert rb.push(f)
    assert len(rb) == 10
    out = [rb.pop() for _ in range(10)]
    assert out == frames
    assert rb.pop() is None
    rb.close()


def test_ring_overflow_drops():
    rb = native.FrameRing(64)
    big = b"x" * 40
    assert rb.push(big)
    assert not rb.push(big)  # full
    assert rb.dropped == 1
    assert rb.pop() == big
    assert rb.push(big)      # space reclaimed
    rb.close()


def test_ring_wraparound():
    rb = native.FrameRing(128)
    for i in range(100):
        f = bytes([i % 256]) * 50
        assert rb.push(f)
        assert rb.pop() == f
    rb.close()


def test_ring_spsc_threads():
    rb = native.FrameRing(64 * 1024)
    n = 5000
    got = []

    def consumer():
        while len(got) < n:
            f = rb.pop()
            if f is not None:
                got.append(f)

    t = threading.Thread(target=consumer)
    t.start()
    for i in range(n):
        while not rb.push(struct.pack(">I", i)):
            pass
    t.join(timeout=30)
    assert len(got) == n
    assert [struct.unpack(">I", f)[0] for f in got] == list(range(n))


# ---- timing wheel ---------------------------------------------------

def test_wheel_basic_order_and_due_time():
    tw = native.TimingWheel(tick_us=100)
    tw.schedule(5_000, 1)
    tw.schedule(1_000, 2)
    tw.schedule(9_000, 3)
    assert len(tw) == 3
    assert tw.advance(500) == []
    assert tw.advance(1_100) == [2]
    assert tw.advance(10_000) == [1, 3]   # time-ordered
    assert len(tw) == 0
    assert tw.next_due_us() is None


def test_wheel_immediate_and_past_deadlines():
    tw = native.TimingWheel(tick_us=1000)
    tw.advance(50_000)
    tw.schedule(10_000, 7)      # already past
    tw.schedule(50_000, 8)      # exactly now
    assert tw.advance(50_000) == [7, 8]


def test_wheel_levels_cascade():
    """Deadlines spanning all wheel levels release exactly once, never
    early (beyond tick granularity), across random advance steps."""
    import random

    rng = random.Random(7)
    tick = 1000
    tw = native.TimingWheel(tick_us=tick, bits=4, levels=3)  # tiny wheels
    events = {tok: rng.randint(0, 3_000_000) for tok in range(2000)}
    for tok, when in events.items():
        tw.schedule(when, tok)
    released = {}
    now = 0
    while now < 3_100_000:
        now += rng.randint(1, 50_000)
        for tok in tw.advance(now):
            assert tok not in released
            assert events[tok] <= now + tick - 1, (events[tok], now)
            released[tok] = now
    assert len(released) == 2000
    assert len(tw) == 0


def test_wheel_next_due_is_lower_bound():
    tw = native.TimingWheel(tick_us=100, bits=4, levels=3)
    tw.schedule(250, 1)
    nd = tw.next_due_us()
    assert nd is not None and nd <= 300   # slot granularity upper slack
    assert tw.advance(nd - 1) == [] or nd == 0
    # far-future deadline: bound must still make progress (cascade point)
    tw2 = native.TimingWheel(tick_us=100, bits=4, levels=3)
    tw2.schedule(10_000_000, 9)
    nd2 = tw2.next_due_us()
    assert nd2 is not None and 0 < nd2 <= 10_000_000
    assert tw2.advance(nd2) == []         # not due yet, just a checkpoint


def test_wheel_interleaved_schedule_advance():
    tw = native.TimingWheel(tick_us=1000)
    out = []
    for step in range(1, 101):
        now = step * 10_000
        tw.schedule(now + 25_000, step)
        out.extend(tw.advance(now))
    out.extend(tw.advance(10_000_000))
    assert sorted(out) == list(range(1, 101))


def test_wheel_past_deadlines_release_in_deadline_order():
    """Past-due tokens come out in deadline order even when time does not
    move forward between schedule and advance."""
    tw = native.TimingWheel(tick_us=1000)
    tw.advance(50_000)
    tw.schedule(50_000, 8)
    tw.schedule(10_000, 7)
    assert tw.advance(50_000) == [7, 8]


def test_wheel_never_releases_before_deadline_within_tick():
    """A deadline inside the current tick quantum is held until reached —
    the wheel must not undershoot emulated latency."""
    tw = native.TimingWheel(tick_us=1000)
    tw.advance(50_500)
    tw.schedule(50_900, 1)       # current tick, 400us in the future
    assert tw.advance(50_500) == []
    assert tw.advance(50_899) == []
    assert tw.advance(50_900) == [1]


def test_wheel_strict_no_early_release_randomized():
    import random

    rng = random.Random(11)
    tw = native.TimingWheel(tick_us=1000, bits=4, levels=3)
    events = {tok: rng.randint(0, 500_000) for tok in range(500)}
    for tok, when in events.items():
        tw.schedule(when, tok)
    released = set()
    now = 0
    while now < 600_000:
        now += rng.randint(1, 7_000)
        for tok in tw.advance(now):
            assert events[tok] <= now, (events[tok], now)
            assert tok not in released
            released.add(tok)
    assert len(released) == 500


def test_wheel_advance_clamps_negative_time():
    """Regression: a negative elapsed time must NOT wrap through c_uint64
    into ~1.8e19 µs — that would release every scheduled token early and
    permanently fast-forward the wheel."""
    tw = native.TimingWheel(tick_us=1000)
    tw.schedule(5_000, 42)
    assert tw.advance(-1) == []          # clamped to 0, nothing due
    assert tw.advance(-10_000_000) == []
    assert len(tw) == 1                  # token survived
    assert tw.advance(6_000) == [42]     # wheel time not fast-forwarded
    tw.schedule(2_000, 7)                # still schedulable after the clamp
    assert tw.advance(2_500) == [7]


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
class TestParsePacketBatch:
    def test_roundtrip_matches_protobuf(self):
        from kubedtn_tpu.wire import proto as pb

        b = pb.PacketBatch(packets=[
            pb.Packet(remot_intf_id=7, frame=b"hello"),
            pb.Packet(remot_intf_id=1 << 40, frame=b"x" * 300),
            pb.Packet(remot_intf_id=7, frame=b""),
        ])
        blob = b.SerializeToString()
        ids, offs, lens = native.parse_packet_batch(blob)
        assert ids.tolist() == [7, 1 << 40, 7]
        frames = [blob[int(o):int(o) + int(n)]
                  for o, n in zip(offs, lens)]
        assert frames == [b"hello", b"x" * 300, b""]

    def test_unknown_fields_skipped(self):
        # a future PacketBatch with an extra field 2 (varint) per the
        # wire format must still parse the known packets
        from kubedtn_tpu.wire import proto as pb

        core = pb.PacketBatch(packets=[
            pb.Packet(remot_intf_id=3, frame=b"f")]).SerializeToString()
        blob = core + bytes([0x10, 0x05])  # field 2, varint 5
        ids, offs, lens = native.parse_packet_batch(blob)
        assert ids.tolist() == [3]

    def test_overflow_length_varints_rejected(self):
        """Regression (round-5 review): a length varint near 2^64 must
        be REJECTED, not wrap the cursor backward into an infinite loop
        — this parser eats raw network bytes (remote-DoS surface)."""
        huge = b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01"
        with pytest.raises(ValueError):
            native.parse_packet_batch(b"\x0a" + huge)  # outer length
        with pytest.raises(ValueError):
            # inner frame length inside a well-formed packet envelope
            native.parse_packet_batch(
                bytes([0x0a, 12, 0x12]) + huge + b"xx")
        with pytest.raises(ValueError):
            native.parse_packet_batch(b"\xff\xff\xff")  # garbage tag

    def test_truncated_rejected(self):
        from kubedtn_tpu.wire import proto as pb

        blob = pb.PacketBatch(packets=[
            pb.Packet(remot_intf_id=3, frame=b"abcdef")]) \
            .SerializeToString()
        with pytest.raises(ValueError):
            native.parse_packet_batch(blob[:-3])
