"""Sharded live data plane: determinism, exchange, partitioner,
checkpoint round-trip.

The contract that keeps the sharded plane honest (ISSUE 6 /
ARCHITECTURE.md "Sharded live plane"):

- mesh size 1 is byte-identical to the unsharded plane;
- an N-shard plane is byte-identical to mesh-1 (and hence to the
  unsharded plane) at small scale — delivery order, drop causes,
  telemetry window-ring totals — across every kernel class (including
  the TBF 50ms-queue fallback re-shape) and at pipeline depths 1 and 2;
- `twin/snapshot.snapshot_from_plane` captures bit-exact state from a
  sharded live plane;
- a checkpoint written under an 8-way forced-host mesh restores
  bit-exact on a 1-device plane, and vice versa.

Tier-1 runs the whole suite on the CPU backend's 8 forced host devices
(tests/conftest.py), with the Pallas remote-DMA exchange swapped for
the lax.ppermute ring — same mailbox layout, same bits.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from test_pipeline_determinism import (INDEP, SEQ, TBF, TBF_OVERLOAD,
                                       _daemon_with_pairs,
                                       _tagged_frames)

from kubedtn_tpu.parallel import partition
from kubedtn_tpu.parallel.exchange import make_ring_exchange
from kubedtn_tpu.parallel.mesh import (EDGE_AXIS, edge_sharding,
                                       make_mesh, shard_map)
from kubedtn_tpu.runtime import WireDataPlane

pytestmark = pytest.mark.sharded_plane


def _run_plane(props, n_per_wire, depth=1, mesh_n=None, pairs=2,
               ticks=40, dt=0.002, seq_slots=64, telemetry=True):
    """One fresh plane through a deterministic schedule; returns
    (per-wire delivered byte sequences, plane)."""
    daemon, _engine, win, wout = _daemon_with_pairs(pairs, props)
    plane = WireDataPlane(daemon, dt_us=dt * 1e6, pipeline_depth=depth)
    plane.pipeline_explicit_clock = True
    plane.seq_slots = seq_slots
    if telemetry:
        plane.enable_telemetry(window_s=0.01, sample_period=4)
    if mesh_n is not None:
        plane.enable_sharding(make_mesh(mesh_n))
    t = 100.0
    for k, wa in enumerate(win):
        wa.ingress.extend(_tagged_frames(k, n_per_wire))
    for _ in range(ticks):
        t += dt
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert plane.tick_errors == 0
    assert not plane._inflight
    return [list(w.egress) for w in wout], plane


def _tel_totals(plane) -> np.ndarray:
    total, _secs = plane.telemetry.window_sum()
    return total


CASES = [
    (INDEP, 200, {}),
    (TBF, 200, {}),
    (TBF_OVERLOAD, 300, {}),
    (SEQ, 150, dict(seq_slots=16)),
]
CASE_IDS = ["indep", "tbf", "tbf-fallback", "seq-holdback"]


@pytest.mark.parametrize("props,n,kwargs", CASES, ids=CASE_IDS)
@pytest.mark.parametrize("mesh_n,depth", [
    (1, 1), (2, 1), (8, 1), (2, 2), (8, 2),
], ids=["mesh1-d1", "mesh2-d1", "mesh8-d1", "mesh2-d2", "mesh8-d2"])
def test_sharded_byte_identical(props, n, kwargs, mesh_n, depth):
    """mesh-1 ≡ unsharded and mesh-N ≡ mesh-1: delivery order, shaped/
    dropped counts, and telemetry ring totals, byte-for-byte, with the
    window ring + flight recorder ON — per kernel class (including the
    TBF fallback re-shape) at both pipeline depths."""
    if len(jax.devices()) < mesh_n:
        pytest.skip(f"needs {mesh_n} devices")
    base, pb = _run_plane(props, n, depth=1, mesh_n=None, **kwargs)
    got, pg = _run_plane(props, n, depth=depth, mesh_n=mesh_n, **kwargs)
    assert got == base
    assert pg.shaped == pb.shaped
    assert pg.dropped == pb.dropped
    tb, tg = _tel_totals(pb), _tel_totals(pg)
    np.testing.assert_array_equal(tg[:tb.shape[0]], tb)
    assert float(tg[tb.shape[0]:].sum()) == 0.0  # padded rows stay empty


def test_cross_shard_frames_and_mailbox(sharded_mesh):
    """Pairs whose directed rows straddle a shard boundary count as
    cross-shard traffic; delivery stays byte-identical regardless.
    pairs=3 → capacity 20 padded to 24 on an 8-way mesh → E_loc=3, so
    link rows (2,3) split across blocks 0|1."""
    del sharded_mesh  # the fixture provisions/validates the device mesh
    base, _pb = _run_plane(INDEP, 120, pairs=3, mesh_n=None)
    got, pg = _run_plane(INDEP, 120, pairs=3, mesh_n=8)
    assert got == base
    assert pg.shard_xfrm > 0
    assert pg.shard_mailbox_hwm > 0
    s = pg.shard_summary()
    assert s["enabled"] and s["n_shards"] == 8
    assert s["xshard_frames"] == pg.shard_xfrm
    assert 0.0 <= s["colocated_frac"] <= 1.0


@pytest.mark.parametrize("sharded_mesh", [8], indirect=True)
def test_snapshot_from_sharded_plane_bit_exact(sharded_mesh):
    """twin/snapshot.snapshot_from_plane from a sharded live plane is
    bit-identical to the capture from an unsharded plane that ran the
    same schedule."""
    from kubedtn_tpu.checkpoint import flatten_sim_arrays
    from kubedtn_tpu.twin.snapshot import snapshot_from_plane

    _base, pb = _run_plane(SEQ, 150, mesh_n=None, seq_slots=16)
    _got, pg = _run_plane(SEQ, 150, mesh_n=None, seq_slots=16)
    # sanity: two identical unsharded runs snapshot identically
    sb = flatten_sim_arrays(snapshot_from_plane(pb).sim,
                            include_edges=True)
    sg = flatten_sim_arrays(snapshot_from_plane(pg).sim,
                            include_edges=True)
    for k in sb:
        np.testing.assert_array_equal(np.asarray(sb[k]),
                                      np.asarray(sg[k]), err_msg=k)
    _shard, ps = _run_plane(SEQ, 150, mesh_n=int(
        sharded_mesh.devices.size), seq_slots=16)
    ss = flatten_sim_arrays(snapshot_from_plane(ps).sim,
                            include_edges=True)
    for k in sb:
        a, b = np.asarray(sb[k]), np.asarray(ss[k])
        # the sharded plane padded capacity to a mesh multiple: the
        # common prefix must be bit-equal, the padding rows zero/fresh
        n = min(a.shape[0], b.shape[0]) if a.ndim else None
        if a.ndim == 0:
            np.testing.assert_array_equal(a, b, err_msg=k)
        else:
            np.testing.assert_array_equal(a[:n], b[:n], err_msg=k)


def test_checkpoint_roundtrip_8way_to_1device(tmp_path):
    """A checkpoint written under an 8-way forced-host mesh restores
    bit-exact on a 1-device (unsharded) engine, and an unsharded
    checkpoint restores bit-exact re-sharded onto the mesh."""
    import dataclasses

    from kubedtn_tpu import checkpoint as ckpt

    _got, pg = _run_plane(TBF, 150, mesh_n=8)
    store = pg.daemon.engine.store
    engine = pg.engine
    path = str(tmp_path / "ckpt")
    ckpt.save(path, store, engine)
    # 1-device restore: loaded arrays are plain host→default-device
    s2, e2 = ckpt.load(path)
    ref = engine.state
    got = e2.state
    for f in dataclasses.fields(type(ref)):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f.name)),
            np.asarray(getattr(got, f.name)), err_msg=f.name)
    # and back onto the mesh: load_or_rebuild(mesh=) re-shards
    mesh = make_mesh(8)
    s3, e3, src = ckpt.load_or_rebuild(path, store=s2, mesh=mesh)
    assert src == "checkpoint"
    st3 = e3.state
    assert st3.tokens.sharding.is_equivalent_to(
        edge_sharding(mesh), st3.tokens.ndim)
    for f in dataclasses.fields(type(ref)):
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, f.name)),
            np.asarray(getattr(st3, f.name)), err_msg=f.name)
    assert e3.shard_count == 8


def test_fast_forward_on_sharded_plane():
    """Virtual-time advance works unchanged on a sharded plane."""
    daemon, _e, win, _wout = _daemon_with_pairs(2, INDEP)
    plane = WireDataPlane(daemon, dt_us=2000.0)
    plane.enable_sharding(make_mesh(2))
    for k, wa in enumerate(win):
        wa.ingress.extend(_tagged_frames(k, 50))
    plane.tick(now_s=0.0)
    r = plane.fast_forward(1.0)
    assert r["ticks"] > 0
    assert plane.tick_errors == 0


# -- exchange unit --------------------------------------------------------

def test_ring_exchange_assembles_owner_payload():
    """The select-combine ring delivers every row's OWNER payload to
    every shard, bit-verbatim, for both the float and int mailboxes."""
    S = 4
    if len(jax.devices()) < S:
        pytest.skip("needs 4 devices")
    mesh = make_mesh(S)
    R, W = 16, 5
    rng = np.random.default_rng(0)
    owner = rng.integers(0, S, size=R).astype(np.int32)
    fvals = rng.standard_normal((R, W)).astype(np.float32)
    ivals = rng.integers(1, 1 << 30, size=(R,)).astype(np.int32)
    exch = make_ring_exchange(S, EDGE_AXIS)

    def body():
        sid = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
        owned = jnp.asarray(owner) == sid
        fmail = jnp.where(owned[:, None], jnp.asarray(fvals), 0.0)
        imail = jnp.stack(
            [owned.astype(jnp.int32),
             jnp.where(owned, jnp.asarray(ivals), 0)], axis=1)
        return exch(fmail, imail)

    from jax.sharding import PartitionSpec as P

    fg, ig = jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                               out_specs=(P(), P())))()
    np.testing.assert_array_equal(np.asarray(fg), fvals)
    np.testing.assert_array_equal(np.asarray(ig)[:, 1], ivals)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="Pallas remote DMA needs real TPU devices")
def test_ring_exchange_dma_matches_ppermute():
    """On TPU the remote-DMA ring must move the same bits the ppermute
    ring moves (the backend switch must be invisible)."""
    S = min(len(jax.devices()), 4)
    if S < 2:
        pytest.skip("needs >= 2 TPU devices")
    mesh = make_mesh(S)
    R, W = 8, 128
    rng = np.random.default_rng(1)
    owner = rng.integers(0, S, size=R).astype(np.int32)
    fvals = rng.standard_normal((R, W)).astype(np.float32)

    def run(use_dma):
        exch = make_ring_exchange(S, EDGE_AXIS, use_dma=use_dma)

        def body():
            sid = jax.lax.axis_index(EDGE_AXIS).astype(jnp.int32)
            owned = jnp.asarray(owner) == sid
            fmail = jnp.where(owned[:, None], jnp.asarray(fvals), 0.0)
            imail = jnp.stack([owned.astype(jnp.int32),
                               jnp.zeros_like(owned, jnp.int32)], axis=1)
            return exch(fmail, imail)

        from jax.sharding import PartitionSpec as P

        return jax.jit(shard_map(body, mesh=mesh, in_specs=(),
                                 out_specs=(P(), P())))()

    f_pp, _ = run(False)
    f_dma, _ = run(True)
    np.testing.assert_array_equal(np.asarray(f_pp), np.asarray(f_dma))


def test_shard_metrics_exported(sharded_mesh):
    """kubedtn_plane_shard_* series appear (only) while the plane is
    sharded, carrying the mailbox/cross-shard counters."""
    from prometheus_client import generate_latest

    from kubedtn_tpu.metrics.metrics import make_registry

    del sharded_mesh
    _got, plane = _run_plane(INDEP, 60, pairs=3, mesh_n=8, ticks=10)
    registry, _ = make_registry(plane.engine, plane.counters_fn,
                                dataplane=plane)
    text = generate_latest(registry).decode()
    assert "kubedtn_plane_shard_count 8.0" in text
    assert 'kubedtn_plane_shard_edges{shard="0"}' in text
    assert "kubedtn_plane_shard_xshard_frames_total" in text
    assert "kubedtn_plane_shard_mailbox_high_water" in text
    assert "kubedtn_plane_shard_exchange_seconds_total" in text
    # and absent on an unsharded plane
    _got2, plane2 = _run_plane(INDEP, 60, pairs=3, mesh_n=None, ticks=10)
    registry2, _ = make_registry(plane2.engine, plane2.counters_fn,
                                 dataplane=plane2)
    assert "kubedtn_plane_shard_count" not in \
        generate_latest(registry2).decode()


# -- partitioner ----------------------------------------------------------

def test_pick_pair_rows_colocates():
    # fresh engine-style descending stack: consecutive pops = same block
    free = list(range(23, -1, -1))
    r1, r2 = partition.pick_pair_rows(free, 24, 8)
    assert (r1, r2) == (0, 1)
    assert r1 // 3 == r2 // 3
    # no other free row in r1's block anywhere in scan reach: plain pop
    free = [10, 4, 2]  # 2 → block 0; 4 → block 1; 10 → block 3
    r1, r2 = partition.pick_pair_rows(free, 24, 8)
    assert (r1, r2) == (2, 4)


def test_pick_pair_rows_repairs_boundary():
    # after popping 3 (block 1), the stack top is 1 (block 0) but 4
    # (block 1) sits deeper: the scan pulls it out to keep the pair
    # colocated
    free = [9, 4, 1, 3]
    r1, r2 = partition.pick_pair_rows(free, 24, 8)
    assert (r1, r2) == (3, 4)
    assert free == [9, 1]


def test_mailbox_layout_counts_cross_pairs():
    src = np.asarray([0, 3, 6, 7])
    dst = np.asarray([1, 4, 7, -1])
    out = partition.mailbox_layout(src, dst, 24, 8)
    # 0→1 colocated (block 0); 3→4 colocated (block 1); 6→7 colocated
    # (block 2); -1 unknown
    assert out["cross_rows"] == 0
    out2 = partition.mailbox_layout(np.asarray([2, 5]),
                                    np.asarray([3, 4]), 24, 8)
    assert out2["cross_rows"] == 1  # 2→3 straddles blocks 0|1
    assert out2["pairs"] == {(0, 1): 1}


def test_colocation_stats_on_engine():
    _base, plane = _run_plane(INDEP, 10, pairs=3, mesh_n=8, ticks=5)
    stats = partition.colocation_stats(plane.engine, 8)
    assert stats["total_edges"] == 6
    assert sum(stats["edges_per_shard"]) == 6
    assert stats["links_paired"] == 3
