"""Tests for the review fixes: the real-time wire data plane, corruption
persistence across multi-hop forwarding, and concurrent metrics scrapes."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu import router as RT
from kubedtn_tpu.api.types import load_yaml
from kubedtn_tpu.metrics.metrics import make_registry
from kubedtn_tpu.models import traffic as TR
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import routing as R
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.topology import SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.server import Daemon, make_server

THREE_NODE = "/root/reference/config/samples/3node.yml"
LATENCY = "/root/reference/config/samples/tc/latency.yaml"


def make_daemon(yaml_path=THREE_NODE):
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    for t in load_yaml(yaml_path):
        store.create(t)
        engine.setup_pod(t.name, t.namespace)
    return Daemon(engine), engine


def add_wire(daemon, pod, uid, wire_id_hint=0):
    return daemon._add_wire(pb.WireDef(
        local_pod_name=pod, kube_ns="default", link_uid=uid,
        intf_name_in_pod=f"eth{uid}", peer_intf_id=wire_id_hint))


@pytest.mark.requires_reference_yaml
def test_wire_frames_shaped_and_delivered_to_peer():
    """Frames entering r1's wire exit r2's wire after the netem delay."""
    daemon, engine = make_daemon(LATENCY)  # r1<->r2 uid 1 has 10ms latency
    w1 = add_wire(daemon, "r1", 1)
    w2 = add_wire(daemon, "r2", 1)
    dp = WireDataPlane(daemon)

    frame = b"\x02" * 12 + b"\x08\x06" + b"\x00" * 50
    w1.ingress.append(frame)
    shaped = dp.tick(now_s=100.0)
    assert shaped == 1
    # not yet due: 10ms netem delay
    assert len(w2.egress) == 0
    dp.tick(now_s=100.005)
    assert len(w2.egress) == 0
    dp.tick(now_s=100.011)
    assert list(w2.egress) == [frame]
    # counters are live
    c = dp.counters
    assert float(np.asarray(c.tx_packets).sum()) == 1.0
    assert float(np.asarray(c.rx_packets).sum()) == 1.0


@pytest.mark.requires_reference_yaml
def test_wire_dataplane_thread_runs():
    daemon, engine = make_daemon(THREE_NODE)
    w1 = add_wire(daemon, "r1", 1)
    w2 = add_wire(daemon, "r2", 1)
    dp = WireDataPlane(daemon, dt_us=2000.0)
    dp.start()
    try:
        for _ in range(5):
            w1.ingress.append(b"x" * 64)
        deadline = threading.Event()
        for _ in range(100):
            if len(w2.egress) == 5:
                break
            deadline.wait(0.05)
        assert len(w2.egress) == 5
    finally:
        dp.stop()
    assert dp.ticks > 0


@pytest.mark.requires_reference_yaml
def test_metrics_scrape_concurrent_with_mutation():
    """The collector's locked snapshot never races engine mutators."""
    from prometheus_client import generate_latest

    daemon, engine = make_daemon(THREE_NODE)
    registry, _ = make_registry(engine, sim_counters_fn=lambda: None)
    stop = threading.Event()
    errors = []

    def scraper():
        while not stop.is_set():
            try:
                generate_latest(registry)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    t = threading.Thread(target=scraper)
    t.start()
    try:
        topos = load_yaml(THREE_NODE)
        for _ in range(30):
            for tp in topos:
                engine.destroy_pod(tp.name, tp.namespace)
            for tp in topos:
                engine.setup_pod(tp.name, tp.namespace)
    finally:
        stop.set()
        t.join(timeout=10)
    assert errors == []


def chain_state_local(n_nodes, corrupt_first_hop=False):
    E = 64
    n_links = n_nodes - 1
    rows = np.arange(n_links, dtype=np.int32)
    props = np.zeros((n_links, es.NPROP), np.float32)
    props[:, es.P_LATENCY_US] = 100.0
    if corrupt_first_hop:
        props[0, es.P_CORRUPT_PROB] = 100.0  # every packet corrupted on hop 1
    state = es.init_state(E)
    state = es.apply_links(
        state, jnp.asarray(rows), jnp.arange(1, n_links + 1, dtype=jnp.int32),
        jnp.arange(n_links, dtype=jnp.int32),
        jnp.arange(1, n_links + 1, dtype=jnp.int32),
        jnp.asarray(props), jnp.ones(n_links, dtype=bool))
    return state, rows, E


def test_corruption_persists_across_hops():
    """A packet corrupted on hop 1 must arrive corrupt at the chain end."""
    n_nodes = 3
    state, rows, E = chain_state_local(n_nodes, corrupt_first_hop=True)
    dist, nh = R.recompute_routes(state, n_nodes, max_hops=8)
    rs = RT.init_router(state, nh, n_nodes, q=16, k_fwd=4)

    mode = np.zeros((E,), np.int32)
    rate = np.zeros((E,), np.float32)
    mode[rows[0]] = TR.MODE_CBR
    rate[rows[0]] = 8e6
    z = np.zeros((E,), np.float32)
    spec = TR.TrafficSpec(mode=jnp.asarray(mode), rate_bps=jnp.asarray(rate),
                          pkt_bytes=jnp.full((E,), 500.0, jnp.float32),
                          on_us=jnp.asarray(z), off_us=jnp.asarray(z))
    flow_dst = np.full((E,), -1, np.int32)
    flow_dst[rows[0]] = n_nodes - 1
    fd = jnp.asarray(flow_dst)

    for i in range(8):
        rs = RT.router_step(rs, spec, fd, jax.random.key(i), 2, 4,
                            jnp.float32(2000.0))

    counters = rs.sim.counters
    # hop-2 edge (row 1) delivered packets, every one still corrupt-flagged
    hop2_rx = float(np.asarray(counters.rx_packets)[rows[1]])
    hop2_corrupt = float(np.asarray(counters.rx_corrupted)[rows[1]])
    assert hop2_rx > 0
    assert hop2_corrupt == hop2_rx
    assert float(np.asarray(rs.node_rx_packets)[n_nodes - 1]) > 0


@pytest.mark.requires_reference_yaml
def test_dataplane_uses_native_wheel_when_available():
    """The delay line rides the native timing wheel (Python heap only as
    fallback); pending frames drain through it and nothing leaks."""
    from kubedtn_tpu import native

    if not native.have_native():
        import pytest
        pytest.skip("native toolchain unavailable")
    daemon, engine = make_daemon(LATENCY)
    w1 = add_wire(daemon, "r1", 1)
    w2 = add_wire(daemon, "r2", 1)
    dp = WireDataPlane(daemon)
    assert dp._wheel is not None
    for i in range(5):
        w1.ingress.append(b"\x02" * 64)
        dp.tick(now_s=10.0 + i * 0.001)
    assert len(dp._wheel) + len(w2.egress) == 5
    dp.tick(now_s=10.5)  # all 10ms deadlines long past
    assert len(w2.egress) == 5
    assert len(dp._wheel) == 0 and not dp._pending


# ---- TCP/IP bypass fast path (eBPF sockops/redir equivalent) ---------

def tcp_frame(sip="10.0.0.1", sport=4321, dip="10.0.0.2", dport=80,
              payload=b"x" * 32):
    """Minimal ethernet/IPv4/TCP frame for the bypass flow table."""
    import struct as st

    def ip(s):
        a = [int(x) for x in s.split(".")]
        return (a[0] << 24) | (a[1] << 16) | (a[2] << 8) | a[3]

    eth = b"\x02" * 6 + b"\x04" * 6 + b"\x08\x00"
    tcp = st.pack(">HHIIBBHHH", sport, dport, 1, 0, 0x50, 0x18, 8192, 0, 0)
    total = 20 + len(tcp) + len(payload)
    ipv4 = st.pack(">BBHHHBBHII", 0x45, 0, total, 7, 0, 64, 6, 0,
                   ip(sip), ip(dip))
    return eth + ipv4 + tcp + payload


native_only = pytest.mark.skipif(
    not __import__("kubedtn_tpu.native", fromlist=["have_native"])
    .have_native(), reason="native library unavailable")


@pytest.mark.requires_reference_yaml
@native_only
def test_bypass_unshaped_tcp_flow_skips_shaping():
    """Same-node TCP flow over an UNSHAPED link: after the first message
    (which falls through, eBPF parity), frames skip the shaping kernels
    entirely and cross in the same tick."""
    daemon, engine = make_daemon(THREE_NODE)  # no shaping props
    w1 = add_wire(daemon, "r1", 1)
    w2 = add_wire(daemon, "r2", 1)
    dp = WireDataPlane(daemon)
    assert not engine.is_shaped(engine.row_of("default/r1", 1))

    f = tcp_frame()
    w1.ingress.append(f)
    assert dp.tick(now_s=10.0) == 1          # first message: shaped path
    assert dp.bypassed == 0
    w1.ingress.append(f)
    shaped = dp.tick(now_s=10.001)
    assert shaped == 0                        # second message: bypassed
    assert dp.bypassed == 1
    assert f in w2.egress                     # delivered in the SAME tick
    assert dp.flow_stats["bypassed"] >= 1


@pytest.mark.requires_reference_yaml
@native_only
def test_bypass_disabled_forever_on_shaped_link():
    """A flow crossing a shaped row is DISABLED permanently — even after
    the link's shaping is later removed (redir_disable.c:44-48)."""
    daemon, engine = make_daemon(LATENCY)  # uid 1 shaped (10ms)
    w1 = add_wire(daemon, "r1", 1)
    add_wire(daemon, "r2", 1)
    dp = WireDataPlane(daemon)
    row = engine.row_of("default/r1", 1)
    assert engine.is_shaped(row)

    f = tcp_frame(dport=443)
    for i in range(3):
        w1.ingress.append(f)
        dp.tick(now_s=20.0 + i * 0.001)
    assert dp.bypassed == 0                   # never bypassed while shaped

    # strip the shaping: row no longer shaped, but the flow stays disabled
    topo = engine.get_pod("r1")
    from kubedtn_tpu.api.types import LinkProperties
    from dataclasses import replace as _replace
    topo.spec.links = [_replace(l, properties=LinkProperties())
                       for l in topo.spec.links]
    engine.update_links(topo, topo.spec.links)
    assert not engine.is_shaped(row)
    w1.ingress.append(f)
    dp.tick(now_s=21.0)
    assert dp.bypassed == 0                   # DISABLED is forever
    from kubedtn_tpu import native as _n
    sip, sport, dip, dport = 0x0A000001, 4321, 0x0A000002, 443
    assert dp._flowtable.flag(sip, sport, dip, dport) == _n.PROXY_DISABLED


@pytest.mark.requires_reference_yaml
def test_addlinks_not_blocked_by_busy_dataplane():
    """Control-plane ops must not wait for a data-plane device dispatch:
    the tick holds the engine lock only for snapshot and write-back."""
    daemon, engine = make_daemon(THREE_NODE)
    w1 = add_wire(daemon, "r1", 1)
    add_wire(daemon, "r2", 1)
    dp = WireDataPlane(daemon, dt_us=500.0, max_slots=64)
    dp.start()
    try:
        stop = threading.Event()

        def feeder():
            while not stop.is_set():
                if len(w1.ingress) < 256:
                    for _ in range(64):
                        w1.ingress.append(b"q" * 200)
                stop.wait(0.001)

        t = threading.Thread(target=feeder, daemon=True)
        t.start()
        deadline = threading.Event()
        deadline.wait(0.2)  # let the plane get busy
        import time as _t

        topo = engine.get_pod("r3")
        worst = 0.0
        for _ in range(5):
            t0 = _t.perf_counter()
            engine.update_links(topo, topo.spec.links)
            worst = max(worst, _t.perf_counter() - t0)
        stop.set()
        assert worst < 2.0, f"control op blocked {worst:.2f}s by data plane"
        # the first tick may still be inside the one-time jit compile of
        # the batch kernels; wait for it rather than sampling instantly
        deadline = _t.monotonic() + 30
        while dp.shaped == 0 and _t.monotonic() < deadline:
            _t.sleep(0.05)
        assert dp.shaped > 0
    finally:
        dp.stop()


@pytest.mark.requires_reference_yaml
@native_only
def test_bypass_never_for_cross_node_wires():
    """sockops redirection is socket-to-socket on ONE node: a flow whose
    peer wire crosses to another daemon must take the shaped+streamed
    path, never the in-tick bypass."""
    daemon, engine = make_daemon(THREE_NODE)  # unshaped links
    w1 = add_wire(daemon, "r1", 1)
    # peer end is a cross-daemon wire (peer_ip set)
    daemon._add_wire(pb.WireDef(
        local_pod_name="r2", kube_ns="default", link_uid=1,
        intf_name_in_pod="eth1", peer_ip="127.0.0.1:1", peer_intf_id=3))
    dp = WireDataPlane(daemon)
    f = tcp_frame(dport=7777)
    for i in range(3):
        w1.ingress.append(f)
        dp.tick(now_s=30.0 + i * 0.001)
    assert dp.bypassed == 0
    assert dp.shaped == 3


@pytest.mark.requires_reference_yaml
def test_wheel_wakes_early_for_due_releases():
    """With a coarse tick period, a short netem delay still releases near
    its deadline: the runner sleeps only until the wheel's next due time,
    not a full period (the qdisc-watchdog precision of the reference)."""
    from kubedtn_tpu import native

    if not native.have_native():
        import pytest
        pytest.skip("native toolchain unavailable")
    daemon, engine = make_daemon(LATENCY)  # r1<->r2 uid1: 10ms
    w1 = add_wire(daemon, "r1", 1)
    w2 = add_wire(daemon, "r2", 1)
    dp = WireDataPlane(daemon, dt_us=200_000.0)  # 200ms period
    # warm the shaping compile OUTSIDE the timed window
    w1.ingress.append(b"w" * 60)
    dp.tick()
    import time as _t

    _t.sleep(0.02)
    dp.tick()
    w2.egress.clear()

    dp.start()
    try:
        t0 = _t.monotonic()
        w1.ingress.append(b"z" * 64)
        deadline = t0 + 2.0
        while not w2.egress and _t.monotonic() < deadline:
            _t.sleep(0.002)
        elapsed = _t.monotonic() - t0
        assert w2.egress, "frame never delivered"
        # 10ms delay + scheduling slack must beat the 200ms period
        assert elapsed < 0.15, f"release waited a full period: {elapsed:.3f}s"
    finally:
        dp.stop()


@pytest.mark.requires_reference_yaml
def test_unrealized_hot_wire_does_not_busy_spin():
    """A wire with frames but no realized link must NOT wake the runner
    in a tight loop — it stays hot for scheduled ticks only."""
    daemon, engine = make_daemon(THREE_NODE)
    w = daemon._add_wire(pb.WireDef(
        local_pod_name="ghost-pod", kube_ns="default", link_uid=77,
        intf_name_in_pod="eth0"))
    dp = WireDataPlane(daemon, dt_us=20_000.0)  # 20ms period
    w.ingress.append(b"x" * 60)
    dp.start()
    try:
        import time as _t
        _t.sleep(0.5)
        # ~25 scheduled ticks in 0.5s at 20ms; a busy spin would be 1000s
        assert dp.ticks < 100, f"busy spin: {dp.ticks} ticks in 0.5s"
        assert len(w.ingress) == 1  # frame still waiting, not lost
    finally:
        dp.stop()


def test_parse_tcp_flow_never_crashes_on_garbage():
    """The bypass parser faces arbitrary wire bytes: any input must parse
    to a tuple or None, never raise."""
    import random

    from kubedtn_tpu.runtime import parse_tcp_flow

    rng = random.Random(42)
    for n in (0, 1, 13, 14, 17, 18, 33, 34, 53, 54, 60, 200):
        for _ in range(50):
            frame = bytes(rng.randrange(256) for _ in range(n))
            out = parse_tcp_flow(frame)
            assert out is None or (len(out) == 4
                                   and all(isinstance(x, int) for x in out))


def test_parse_tcp_flow_variants():
    from kubedtn_tpu.runtime import parse_tcp_flow

    base = tcp_frame()
    assert parse_tcp_flow(base) == (0x0A000001, 4321, 0x0A000002, 80)

    # 802.1Q VLAN tag shifts the IP header by 4
    vlan = base[:12] + b"\x81\x00\x00\x2a\x08\x00" + base[14:]
    assert parse_tcp_flow(vlan) == (0x0A000001, 4321, 0x0A000002, 80)

    # fragmented packets (MF or offset) never parse
    frag_mf = bytearray(base)
    frag_mf[14 + 6] = 0x20  # MF flag
    assert parse_tcp_flow(bytes(frag_mf)) is None
    frag_off = bytearray(base)
    frag_off[14 + 7] = 0x10  # offset 16
    assert parse_tcp_flow(bytes(frag_off)) is None

    # UDP (proto 17) and IPv6 never parse
    udp = bytearray(base)
    udp[14 + 9] = 17
    assert parse_tcp_flow(bytes(udp)) is None
    v6 = base[:12] + b"\x86\xdd" + base[14:]
    assert parse_tcp_flow(v6) is None


def test_lossy_link_drops_frames_statistically():
    """Daemon-level impairment e2e: a 50%-loss link drops roughly half
    the wire frames (fixed seed — deterministic), and the loss shows in
    both the plane's counter and the per-edge counters."""
    from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                       TopologySpec)

    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    t = Topology(name="lossy", spec=TopologySpec(links=[
        Link(local_intf="eth0", peer_intf="e", uid=1,
             peer_pod="physical/10.0.0.9",
             properties=LinkProperties(loss="50"))]))
    store.create(t)
    engine.setup_pod("lossy")
    daemon = Daemon(engine)
    w = add_wire(daemon, "lossy", 1)
    dp = WireDataPlane(daemon, seed=5)

    n = 200
    for i in range(n):
        w.ingress.append(b"\x02" * 64)
        dp.tick(now_s=1.0 + i * 0.001)
    dp.tick(now_s=5.0)
    delivered = dp.shaped
    dropped = dp.dropped
    assert delivered + dropped == n
    assert 60 <= dropped <= 140, f"loss=50% dropped {dropped}/{n}"
    loss_count = float(np.asarray(dp.counters.dropped_loss).sum())
    assert loss_count == dropped


def test_rate_capped_link_paces_frames_e2e():
    """Daemon-level bandwidth parity (the reference's bandwidth.yaml
    scenario): steady-state inter-arrival spacing on a rate-limited link
    matches the configured TBF rate once the initial token burst drains."""
    from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                       TopologySpec)

    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    rate_bps = 1_000_000  # 1Mbit -> 1500B frame every 12ms
    t = Topology(name="slow", spec=TopologySpec(links=[
        Link(local_intf="eth0", peer_intf="e", uid=1,
             peer_pod="physical/10.0.0.9",
             properties=LinkProperties(rate="1Mbit"))]))
    store.create(t)
    engine.setup_pod("slow")
    daemon = Daemon(engine)
    w = add_wire(daemon, "slow", 1)
    dp = WireDataPlane(daemon, max_slots=64)

    # Offer well OVER rate (one 1500B frame per 4ms vs the 12ms service
    # time) so the 5000B token burst drains after ~4 frames; after that
    # the queue absorbs the excess without hitting the 50ms TBF limit
    # (tc `latency 50ms` parity — a big enough burst would correctly
    # DROP the tail), and delivery spacing shows the shaper's 12ms pace,
    # not the 4ms input pace.
    n = 10
    arrivals = []
    now = 1.0
    tick_i = 0
    # 2ms tick grid (fine release granularity); one frame per 4ms
    while len(arrivals) < n and now < 3.0:
        if tick_i % 2 == 0 and tick_i // 2 < n:
            w.ingress.append(b"\x02" * 1500)
        before = len(w.egress)
        dp.tick(now_s=now)
        arrivals += [now] * (len(w.egress) - before)
        tick_i += 1
        now += 0.002
    assert len(arrivals) == n, f"only {len(arrivals)}/{n} delivered"
    # burst = max(rate/250, 5000B) = 5000B -> first ~3 frames ride the
    # initial tokens; steady state is service-paced at 12ms
    spacing = np.diff(arrivals[5:])
    expect = 1500 * 8 / rate_bps
    med = float(np.median(spacing))
    assert abs(med - expect) < 0.0015, \
        f"median spacing {med:.4f}s != ~{expect}s (shaper not pacing)"


def _half_second_daemon():
    """Two pods joined by a 500ms-latency link, wires attached."""
    from dataclasses import replace as _rp

    from kubedtn_tpu.api.types import LinkProperties

    daemon, engine = make_daemon(LATENCY)  # r1<->r2 uid 1
    topo = engine.get_pod("r1")
    topo.spec.links = [_rp(l, properties=LinkProperties(latency="500ms"))
                       for l in topo.spec.links if l.uid == 1]
    engine.update_links(topo, topo.spec.links)
    wa = add_wire(daemon, "r1", 1)
    wb = add_wire(daemon, "r2", 1)
    return daemon, wa, wb


@pytest.mark.requires_reference_yaml
def test_fast_forward_virtual_time():
    """A 500ms-latency link delivers in milliseconds of wall time under
    fast_forward — virtual-time replay the real-time reference can't do."""
    import time as _time

    daemon, wa, wb = _half_second_daemon()
    dp = WireDataPlane(daemon)
    frame = b"\xbb" * 100
    daemon._frame_in(wa, frame)
    wall0 = _time.monotonic()
    out = dp.fast_forward(2.0, dt_s=0.01)
    wall = _time.monotonic() - wall0
    assert list(wb.egress) == [frame]
    assert out["shaped"] == 1
    assert out["ticks"] == 200
    assert out["virtual_clock_s"] >= 2.0
    assert wall < out["sim_seconds"], (wall, out)  # faster than real time

    # a second fast_forward continues from the advanced virtual clock
    daemon._frame_in(wa, b"\xcc" * 60)
    dp.fast_forward(1.0, dt_s=0.01)
    assert len(wb.egress) == 2


@pytest.mark.requires_reference_yaml
def test_fast_forward_rejects_live_runner():
    daemon, _, _ = _half_second_daemon()
    dp = WireDataPlane(daemon)
    dp.start()
    try:
        with pytest.raises(RuntimeError, match="real-time runner"):
            dp.fast_forward(0.1)
    finally:
        dp.stop()


@pytest.mark.requires_reference_yaml
def test_fast_forward_then_realtime_keeps_remaining_latency():
    """Pending virtual-time releases survive a switch to the real-time
    runner with their REMAINING latency, not an instant release (the
    epoch is rebased onto the monotonic clock in start())."""
    import time as _time

    daemon, wa, wb = _half_second_daemon()
    dp = WireDataPlane(daemon, dt_us=5_000.0)
    daemon._frame_in(wa, b"\xdd" * 90)
    out = dp.fast_forward(0.2, dt_s=0.01)  # 300ms of latency remains
    assert out["shaped"] == 1 and len(wb.egress) == 0
    dp.start()
    try:
        _time.sleep(0.1)
        assert len(wb.egress) == 0, "released early after clock switch"
        deadline = _time.monotonic() + 2.0
        while not wb.egress and _time.monotonic() < deadline:
            _time.sleep(0.02)
        assert len(wb.egress) == 1, "never released after clock switch"
    finally:
        dp.stop()
