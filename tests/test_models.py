"""Tests for topology model generators."""

import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models import topologies as T
from kubedtn_tpu.ops import edge_state as es


def test_line_ring_star_mesh_counts():
    assert T.line(5).n_links == 4
    assert T.ring(5).n_links == 5
    assert T.star(6).n_links == 6
    assert T.full_mesh(4).n_links == 6


def test_fat_tree_k8():
    el = T.fat_tree(8)
    assert el.n_nodes == 80          # 16 core + 32 agg + 32 edge
    assert el.n_links == 8 * 4 * 8   # k pods x half aggs x (half+half)


def test_clos_100k():
    el = T.clos(100, 500, 0, links_per_pair=2)
    assert el.n_links == 100_000
    assert el.n_nodes == 600


def test_random_mesh_no_self_loops():
    el = T.random_mesh(50, 500, seed=3)
    assert el.n_links == 500
    assert not np.any(el.a == el.b)
    assert len(np.unique(el.uid)) == 500


def test_directed_expansion():
    el = T.line(3, LinkProperties(latency="1ms"))
    src, dst, uid, props = el.directed()
    assert len(src) == 4  # 2 links x 2 directions
    assert set(zip(src.tolist(), dst.tolist())) == {(0, 1), (1, 0), (1, 2), (2, 1)}
    assert np.all(props[:, es.P_LATENCY_US] == 1000.0)


def test_to_topologies_roundtrip_validates():
    el = T.fat_tree(4, LinkProperties(latency="30m", loss="0.00001",
                                      rate="1Gbit"))
    topos = el.to_topologies()
    for t in topos:
        t.validate()  # no scientific-notation strings sneak through
    # numeric round trip preserved
    some = [l for t in topos for l in t.spec.links][0]
    n = some.properties.to_numeric()
    assert n["latency_us"] == 30 * 60 * 1_000_000
    assert n["loss"] == pytest.approx(1e-5)
    assert n["rate_bps"] == 1_000_000_000
    # every uid appears exactly twice (once per endpoint view)
    uids = [l.uid for t in topos for l in t.spec.links]
    from collections import Counter
    assert all(c == 2 for c in Counter(uids).values())


def test_load_edge_list_into_state():
    el = T.clos(4, 8, 2)
    state, rows = T.load_edge_list_into_state(el)
    assert int(state.num_active) == 2 * el.n_links
    assert state.capacity >= 2 * el.n_links
