"""Tests for topology model generators."""

import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models import topologies as T
from kubedtn_tpu.ops import edge_state as es


def test_line_ring_star_mesh_counts():
    assert T.line(5).n_links == 4
    assert T.ring(5).n_links == 5
    assert T.star(6).n_links == 6
    assert T.full_mesh(4).n_links == 6


def test_fat_tree_k8():
    el = T.fat_tree(8)
    assert el.n_nodes == 80          # 16 core + 32 agg + 32 edge
    assert el.n_links == 8 * 4 * 8   # k pods x half aggs x (half+half)


def test_clos_100k():
    el = T.clos(100, 500, 0, links_per_pair=2)
    assert el.n_links == 100_000
    assert el.n_nodes == 600


def test_random_mesh_no_self_loops():
    el = T.random_mesh(50, 500, seed=3)
    assert el.n_links == 500
    assert not np.any(el.a == el.b)
    assert len(np.unique(el.uid)) == 500


def test_directed_expansion():
    el = T.line(3, LinkProperties(latency="1ms"))
    src, dst, uid, props = el.directed()
    assert len(src) == 4  # 2 links x 2 directions
    assert set(zip(src.tolist(), dst.tolist())) == {(0, 1), (1, 0), (1, 2), (2, 1)}
    assert np.all(props[:, es.P_LATENCY_US] == 1000.0)


def test_to_topologies_roundtrip_validates():
    el = T.fat_tree(4, LinkProperties(latency="30m", loss="0.00001",
                                      rate="1Gbit"))
    topos = el.to_topologies()
    for t in topos:
        t.validate()  # no scientific-notation strings sneak through
    # numeric round trip preserved
    some = [l for t in topos for l in t.spec.links][0]
    n = some.properties.to_numeric()
    assert n["latency_us"] == 30 * 60 * 1_000_000
    assert n["loss"] == pytest.approx(1e-5)
    assert n["rate_bps"] == 1_000_000_000
    # every uid appears exactly twice (once per endpoint view)
    uids = [l.uid for t in topos for l in t.spec.links]
    from collections import Counter
    assert all(c == 2 for c in Counter(uids).values())


def test_load_edge_list_into_state():
    el = T.clos(4, 8, 2)
    state, rows = T.load_edge_list_into_state(el)
    assert int(state.num_active) == 2 * el.n_links
    assert state.capacity >= 2 * el.n_links


# ---- new families ---------------------------------------------------

def _connected(el):
    """Union-find connectivity over the edge list."""
    parent = list(range(el.n_nodes))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in zip(el.a.tolist(), el.b.tolist()):
        parent[find(a)] = find(b)
    return len({find(i) for i in range(el.n_nodes)}) == 1


def _degrees(el):
    deg = np.zeros(el.n_nodes, np.int64)
    np.add.at(deg, el.a, 1)
    np.add.at(deg, el.b, 1)
    return deg


def test_torus_2d_counts_and_regularity():
    el = T.torus((4, 4))
    assert el.n_nodes == 16 and el.n_links == 32
    assert (_degrees(el) == 4).all()
    assert _connected(el)


def test_torus_3d_and_dim2_no_double_link():
    el = T.torus((4, 4, 4))
    assert el.n_nodes == 64 and el.n_links == 192
    assert (_degrees(el) == 6).all()
    # a size-2 dimension contributes ONE link per wrap pair, not two
    el2 = T.torus((2, 3))
    assert el2.n_links == 3 + 6  # 3 cross-links + two 3-rings
    assert _connected(el2)


def test_hypercube():
    el = T.hypercube(4)
    assert el.n_nodes == 16 and el.n_links == 32
    assert (_degrees(el) == 4).all()
    assert _connected(el)


def test_dragonfly():
    g, a, h = 4, 3, 2
    el = T.dragonfly(g, a, h)
    assert el.n_nodes == g * a
    intra = g * a * (a - 1) // 2
    glob = g * (g - 1) // 2 * h
    assert el.n_links == intra + glob
    assert _connected(el)


def test_barabasi_albert_scale_free():
    el = T.barabasi_albert(200, m=2, seed=3)
    assert el.n_nodes == 200
    assert el.n_links == (200 - 2) * 2
    assert _connected(el)
    deg = _degrees(el)
    # heavy tail: max degree far above the mean
    assert deg.max() >= 4 * deg.mean()


def test_watts_strogatz():
    el = T.watts_strogatz(100, k=4, beta=0.2, seed=5)
    assert el.n_nodes == 100
    assert el.n_links <= 200
    assert _connected(el)
    # no duplicate undirected pairs
    keys = set(zip(np.minimum(el.a, el.b).tolist(),
                   np.maximum(el.a, el.b).tolist()))
    assert len(keys) == el.n_links


def test_geo_wan_distance_latencies():
    el = T.geo_wan(50, degree=3, seed=9)
    assert el.n_nodes == 50
    lat = el.props[:, es.PROP_NAMES.index("latency_us")]
    assert (lat >= 1).all()
    # 5000 km plane diagonal => at most ~ 7071 km * 5 us/km
    assert lat.max() <= 7071 * 5 + 1
    # heterogeneous: not all links share one latency
    assert len(np.unique(lat)) > 5
    # per-link props survive the CR round trip
    topos = el.to_topologies()
    for t in topos:
        t.validate()


def test_new_families_reachable_on_device():
    """Load a torus into edge state and check full device-side
    reachability via the routing kernel."""
    from kubedtn_tpu.ops import routing as R

    el = T.torus((3, 3))
    state, rows = T.load_edge_list_into_state(el)
    reach = R.reachability(state, n_nodes=el.n_nodes)
    assert bool(np.asarray(reach).all())


def test_gen_cli_families():
    import os
    import subprocess
    import sys
    import tempfile

    import yaml

    from kubedtn_tpu.api.types import load_yaml

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "t.yaml")
        r = subprocess.run(
            [sys.executable, "-m", "kubedtn_tpu.cli", "gen", "torus",
             "-p", "dims=3x3", "-o", out],
            capture_output=True, text=True, cwd=repo, check=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        meta = yaml.safe_load(r.stdout)
        assert meta["nodes"] == 9 and meta["links"] == 18
        topos = load_yaml(out)
        assert len(topos) == 9
        for t in topos:
            t.validate()


def test_geo_wan_always_connected_and_guarded():
    for seed in range(20):
        assert _connected(T.geo_wan(50, degree=3, seed=seed)), seed
    with pytest.raises(AssertionError):
        T.geo_wan(4, degree=4)


def test_gen_cli_bad_params_fail_cleanly():
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for argv in (["gen", "ring"],                      # missing required n
                 ["gen", "torus", "-p", "dims=4xa"],   # malformed dims
                 ["gen", "geo_wan", "-p", "n=4", "-p", "degree=4"]):
        r = subprocess.run([sys.executable, "-m", "kubedtn_tpu.cli"] + argv,
                           capture_output=True, text=True, cwd=repo, env=env)
        assert r.returncode == 1, argv
        assert "Traceback" not in r.stderr, argv
        assert "signature" in r.stderr, argv
    # numeric-looking rate param stays a string
    r = subprocess.run([sys.executable, "-m", "kubedtn_tpu.cli", "gen",
                        "geo_wan", "-p", "n=5", "-p", "rate=100Mbit"],
                       capture_output=True, text=True, cwd=repo, env=env)
    assert r.returncode == 0, r.stderr
