"""What-if plane tests: snapshot-fork consistency, replica bit-exactness,
perturbation semantics, the daemon-served WhatIf query (live runner, zero
frame loss), sharded replica meshes, and the bench-phase smoke.

The heavy sweeps share ONE (N, T, capacity) shape via module-scope
fixtures so the engine's executable cache compiles each program once for
the whole module.
"""

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu import router as RT
from kubedtn_tpu import sim as S
from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models import topologies as T
from kubedtn_tpu.models.traffic import cbr_everywhere
from kubedtn_tpu.ops import routing as R
from kubedtn_tpu.twin import (
    Perturbation,
    Scenario,
    compile_scenarios,
    rank_results,
    render_report,
    run_sweep,
    run_sweep_routed,
    snapshot_from_router,
    snapshot_from_sim,
)
from kubedtn_tpu.twin.snapshot import load_snapshot, save_snapshot

STEPS = 30
DT_US = 1000.0
K_SLOTS = 4
N_NODES = 20


def _bitwise_equal(ref_obj, batched_obj, lane, fields_of):
    """Compare every leaf of `ref_obj` against lane `lane` of the
    batched object, bitwise. Returns the list of mismatched leaves."""
    bad = []
    for name in fields_of:
        ref_sub = getattr(ref_obj, name)
        bat_sub = getattr(batched_obj, name)
        for f in dataclasses.fields(ref_sub):
            a = np.asarray(getattr(ref_sub, f.name))
            b = np.asarray(getattr(bat_sub, f.name))[lane]
            if a.tobytes() != b.tobytes():
                bad.append(f"{name}.{f.name}")
    return bad


@pytest.fixture(scope="module")
def base():
    el = T.random_mesh(N_NODES, 40, seed=3,
                       props=LinkProperties(latency="2ms", jitter="1ms",
                                            loss="1"))
    state, rows = T.load_edge_list_into_state(el)
    spec = cbr_everywhere(state.capacity, len(rows), rate_bps=2e6,
                          pkt_bytes=400.0)
    sim = S.init_sim(state, q=16)
    # warm prefix: fork mid-run so the snapshot carries non-trivial
    # shaping state (token clocks, correlation memory, in-flight slots)
    sim = S.run(sim, spec, steps=20, dt_us=DT_US, k_slots=K_SLOTS, seed=7)
    return el, state, rows, spec, snapshot_from_sim(sim, n_nodes=N_NODES)


SCENARIOS = [
    Scenario("baseline"),
    Scenario("degrade-lat", (Perturbation(
        "degrade", uid=1, props=LinkProperties(latency="50ms")),)),
    Scenario("degrade-loss", (Perturbation(
        "degrade", uid=3, props=LinkProperties(latency="2ms",
                                               loss="30")),)),
    Scenario("fail", (Perturbation("fail", uid=2),)),
    Scenario("blackhole", (Perturbation("blackhole", node=0),)),
    Scenario("halve-load", (Perturbation("scale", factor=0.5),)),
]


@pytest.fixture(scope="module")
def sweep(base):
    _el, _state, _rows, spec, snap = base
    return run_sweep(snap, SCENARIOS, steps=STEPS, dt_us=DT_US,
                     spec=spec, k_slots=K_SLOTS, seed=11,
                     keep_final=True)


def test_replica0_unperturbed_bit_identical_to_sim_run(base, sweep):
    """The fork contract: an empty perturbation continues the forked
    SimState EXACTLY as the unbatched engine would — every leaf of
    replica 0's final state matches sim.run bit for bit."""
    _el, _state, _rows, spec, snap = base
    ref = S.run(snap.sim, spec, steps=STEPS, dt_us=DT_US,
                k_slots=K_SLOTS, seed=11)
    bad = _bitwise_equal(ref, sweep.final, 0,
                         ("edges", "inflight", "counters", "traffic"))
    assert not bad, f"replica 0 diverged from sim.run on: {bad}"
    assert (np.asarray(ref.clock_us).tobytes()
            == np.asarray(sweep.final.clock_us)[0].tobytes())


def test_same_seed_same_spec_reproducible(base, sweep):
    _el, _state, _rows, spec, snap = base
    again = run_sweep(snap, SCENARIOS, steps=STEPS, dt_us=DT_US,
                      spec=spec, k_slots=K_SLOTS, seed=11)
    assert again.metrics == sweep.metrics
    assert again.compile_s == 0.0  # executable cache hit


def test_padding_replicas_do_not_perturb_results(base, sweep):
    """N=6 and N=16 (10 padding lanes) sweeps return identical
    per-scenario results: padding replicas share the PRNG schedule
    instead of splitting it, so they cannot shift any real replica's
    streams."""
    _el, _state, _rows, spec, snap = base
    edits16 = compile_scenarios(SCENARIOS, snap.sim.edges,
                                pad_replicas_to=16)
    res16 = run_sweep(snap, SCENARIOS, steps=STEPS, dt_us=DT_US,
                      spec=spec, k_slots=K_SLOTS, seed=11, edits=edits16)
    assert res16.replicas == 16
    assert res16.metrics == sweep.metrics


def test_perturbations_change_the_future(sweep):
    by = dict(zip(sweep.names, sweep.metrics))
    base_m = by["baseline"]
    # 50ms degrade on one link pushes its packets into the 50ms bucket
    assert by["degrade-lat"]["p99_us"] > base_m["p99_us"]
    # heavy loss on a link lowers the delivery ratio
    assert (by["degrade-loss"]["delivery_ratio"]
            < base_m["delivery_ratio"])
    # a failed link stops sourcing traffic: fewer tx packets
    assert by["fail"]["tx_packets"] < base_m["tx_packets"]
    # a blackholed node kills every adjacent edge
    assert by["blackhole"]["tx_packets"] < by["fail"]["tx_packets"]
    # halving offered bytes ~halves delivered bytes (packets unchanged;
    # the snapshot's pre-fork in-flight packets deliver at full size, so
    # the ratio is bounded, not exact)
    assert (0.4 * base_m["delivered_bytes"]
            < by["halve-load"]["delivered_bytes"]
            < 0.7 * base_m["delivered_bytes"])
    assert (by["halve-load"]["delivered_packets"]
            == base_m["delivered_packets"])


def test_ranking_and_report(sweep):
    ranked = rank_results(sweep)
    assert [r for _n, _m, r in ranked] == list(range(1, len(ranked) + 1))
    # worst delivery ranks first
    ratios = [m["delivery_ratio"] for _n, m, _r in ranked]
    assert ratios[0] == min(r for r in ratios if r is not None)
    text = render_report(sweep)
    for name in sweep.names:
        assert name in text
    assert "replica-steps/s" in text


def test_sharded_replica_mesh_matches_unsharded(base, sweep, devices8):
    """The replica axis shards over a device mesh with identical
    results — replicas are embarrassingly parallel."""
    from kubedtn_tpu.parallel.mesh import make_replica_mesh

    _el, _state, _rows, spec, snap = base
    mesh = make_replica_mesh(4, devices=devices8)
    res = run_sweep(snap, SCENARIOS, steps=STEPS, dt_us=DT_US,
                    spec=spec, k_slots=K_SLOTS, seed=11, mesh=mesh)
    assert res.replicas % 4 == 0
    assert res.metrics == sweep.metrics


def test_routed_replica0_bit_identical_to_run_routed(base):
    el, state, rows, spec, _snap = base
    _, nh = R.recompute_routes(state, N_NODES, max_hops=8)
    rs = RT.init_router(state, nh, N_NODES, q=16, k_fwd=4)
    rng = np.random.default_rng(5)
    fdst = np.full((state.capacity,), -1, np.int32)
    fdst[:len(rows)] = rng.integers(0, N_NODES, len(rows))
    flow_dst = jnp.asarray(fdst)
    rs = RT.run_routed(rs, spec, flow_dst, steps=15, dt_us=DT_US,
                       k_slots=K_SLOTS, k_fwd=4, seed=3)
    snap = snapshot_from_router(rs, n_nodes=N_NODES)
    ref = RT.run_routed(snap.router, spec, flow_dst, steps=20,
                        dt_us=DT_US, k_slots=K_SLOTS, k_fwd=4, seed=9)
    res = run_sweep_routed(snap, SCENARIOS[:3], steps=20, dt_us=DT_US,
                           spec=spec, flow_dst=flow_dst,
                           k_slots=K_SLOTS, k_fwd=4, seed=9,
                           keep_final=True)
    bad = _bitwise_equal(ref.sim, res.final.sim, 0,
                         ("edges", "inflight", "counters", "traffic"))
    for f in ("next_edge", "pend_size", "pend_dst", "pend_corr",
              "node_rx_packets", "node_rx_bytes", "fwd_dropped",
              "no_route_dropped"):
        a = np.asarray(getattr(ref, f))
        b = np.asarray(getattr(res.final, f))[0]
        if a.tobytes() != b.tobytes():
            bad.append(f)
    assert not bad, f"routed replica 0 diverged on: {bad}"
    assert res.metrics[0]["node_rx_packets"] > 0


def test_routed_rejects_traffic_scale(base):
    el, state, rows, spec, _snap = base
    _, nh = R.recompute_routes(state, N_NODES, max_hops=8)
    rs = RT.init_router(state, nh, N_NODES, q=16, k_fwd=4)
    snap = snapshot_from_router(rs, n_nodes=N_NODES)
    with pytest.raises(ValueError, match="traffic scale"):
        run_sweep_routed(
            snap, [Scenario("s", (Perturbation("scale", factor=2.0),))],
            steps=5, dt_us=DT_US, spec=spec,
            flow_dst=jnp.full((state.capacity,), -1, jnp.int32))


def test_spec_validation():
    with pytest.raises(ValueError, match="unknown perturbation"):
        Perturbation("melt", uid=1)
    with pytest.raises(ValueError, match="needs a link uid"):
        Perturbation("fail")
    with pytest.raises(ValueError, match="needs LinkProperties"):
        Perturbation("degrade", uid=1)
    with pytest.raises(ValueError, match="needs a node"):
        Perturbation("blackhole")


def test_compile_unknown_uid_raises(base):
    _el, _state, _rows, _spec, snap = base
    sc = [Scenario("x", (Perturbation("fail", uid=999_999),))]
    with pytest.raises(ValueError, match="no active rows"):
        compile_scenarios(sc, snap.sim.edges)


def test_blackhole_resolves_node_names(base):
    _el, _state, _rows, _spec, snap = base
    pod_ids = {"default/left": 0, "default/right": 1}
    sc = [Scenario("bh", (Perturbation("blackhole", node="left"),))]
    edits = compile_scenarios(sc, snap.sim.edges, pod_ids=pod_ids)
    assert edits.dvalid[0].any()
    with pytest.raises(ValueError, match="not found"):
        compile_scenarios(
            [Scenario("bh", (Perturbation("blackhole", node="ghost"),))],
            snap.sim.edges, pod_ids=pod_ids)


def test_snapshot_save_load_roundtrip(tmp_path, base):
    _el, _state, _rows, _spec, snap = base
    p = str(tmp_path / "twin" / "snap.npz")
    save_snapshot(p, snap)
    back = load_snapshot(p)
    assert back.n_nodes == snap.n_nodes
    bad = _bitwise_equal(snap.sim, _Lane0Wrap(back.sim), 0,
                         ("edges", "inflight", "counters", "traffic"))
    assert not bad, bad


class _Lane0Wrap:
    """Adapter so _bitwise_equal's [lane] indexing works on an
    unbatched state: wraps each leaf as a one-element batch."""

    def __init__(self, sim):
        self._sim = sim

    def __getattr__(self, name):
        sub = getattr(self._sim, name)

        class _Sub:
            pass

        w = _Sub()
        for f in dataclasses.fields(sub):
            setattr(w, f.name, np.asarray(getattr(sub, f.name))[None])
        return w


# -- live daemon end-to-end --------------------------------------------

def test_whatif_served_live_zero_frame_loss():
    """Acceptance: a LIVE daemon (real-time runner ACTIVE, traffic
    flowing) serves a WhatIf sweep end-to-end over gRPC — snapshot →
    sweep → ranked report — and afterwards every frame fed during the
    sweep has been delivered: zero live-frame loss."""
    from kubedtn_tpu.metrics.metrics import make_registry
    from kubedtn_tpu.scenarios import _live_plane_setup
    from kubedtn_tpu.twin.query import stats_for
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient
    from prometheus_client import generate_latest

    pairs = 2
    daemon, server, port, plane, wires_in, wires_out = _live_plane_setup(
        pairs, "2ms", 2000.0, "tw")
    frame = b"\x02" * 12 + b"\x07\x77" + b"\x00" * 50  # non-IP: no bypass
    fed = [0]
    stop = threading.Event()

    def feeder():
        while not stop.is_set():
            for w in wires_in:
                w.ingress.extend([frame] * 50)
            fed[0] += 50 * pairs
            stop.wait(0.02)

    delivered = [0]

    def drain() -> int:
        c = 0
        for w in wires_out:
            dq = w.egress
            while True:
                try:
                    dq.popleft()
                except IndexError:
                    break
                c += 1
        delivered[0] += c
        return c

    client = DaemonClient(f"127.0.0.1:{port}")
    t = threading.Thread(target=feeder, daemon=True)
    t.start()
    try:
        # live traffic must be flowing before the sweep starts
        deadline = time.monotonic() + 30.0
        while delivered[0] == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
            drain()
        assert delivered[0] > 0, "live plane never delivered"

        req = pb.WhatIfRequest(ticks=200, dt_us=1000.0,
                               traffic_rate_bps=1e6, seed=5,
                               include_baseline=True)
        sc = req.scenarios.add()
        sc.name = "uid1-slow"
        p = sc.perturbations.add()
        p.kind = "degrade"
        p.uid = 1
        p.properties.CopyFrom(pb.props_to_proto(
            LinkProperties(latency="100ms")))
        sc2 = req.scenarios.add()
        sc2.name = "b0-dead"
        p2 = sc2.perturbations.add()
        p2.kind = "blackhole"
        p2.node = "tw-b0"
        resp = client.WhatIf(req, timeout=300.0)
        assert resp.ok, resp.error
        assert len(resp.results) == 3  # baseline + 2 scenarios
        names = {m.name for m in resp.results}
        assert names == {"baseline", "uid1-slow", "b0-dead"}
        ranks = sorted(m.rank for m in resp.results)
        assert ranks == [1, 2, 3]
        by = {m.name: m for m in resp.results}
        assert by["uid1-slow"].p99_us > by["baseline"].p99_us
        assert by["b0-dead"].tx_packets < by["baseline"].tx_packets
        assert resp.replicas >= 3 and resp.ticks == 200

        # runner stayed live THROUGH the sweep
        assert plane.running
        # keep feeding a moment longer, then drain to zero loss
        time.sleep(0.2)
    finally:
        stop.set()
        t.join(timeout=5)
    deadline = time.monotonic() + 60.0
    while delivered[0] < fed[0] and time.monotonic() < deadline:
        time.sleep(0.02)
        drain()
    try:
        assert delivered[0] == fed[0], \
            f"live frames lost during sweep: {fed[0] - delivered[0]}"
        assert plane.tick_errors == 0
        assert plane.dropped == 0

        # satellite: kubedtn_whatif_* series flow through the registry
        registry, _h = make_registry(daemon.engine,
                                     dataplane=plane,
                                     whatif_stats=stats_for(daemon))
        text = generate_latest(registry).decode()
        assert "kubedtn_whatif_sweeps_served" in text
        assert "kubedtn_whatif_replicas_run" in text
        assert "kubedtn_whatif_run_seconds" in text
        assert stats_for(daemon).sweeps == 1
        assert stats_for(daemon).replicas >= 3
    finally:
        client.close()
        plane.stop()
        server.stop(0)


def test_whatif_request_budget_rejected():
    """scenarios × ticks (and × edge capacity) are bounded per request:
    one in-limit-per-factor query must not pin a gRPC worker for hours
    or broadcast the daemon into an OOM."""
    from kubedtn_tpu.topology import SimEngine, TopologyStore
    from kubedtn_tpu.twin.query import serve_whatif
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    daemon = Daemon(SimEngine(TopologyStore(), capacity=16))
    req = pb.WhatIfRequest(ticks=200_000, include_baseline=True)
    for i in range(64):
        sc = req.scenarios.add()
        sc.name = f"s{i}"
    resp = serve_whatif(daemon, req)
    assert not resp.ok
    assert "budget" in resp.error
    assert daemon.whatif_stats.errors == 1

    # concurrency guard: with the single sweep slot held, an in-budget
    # request is refused loudly instead of parking a gRPC worker
    from kubedtn_tpu.twin import query as Q

    small = pb.WhatIfRequest(ticks=10, include_baseline=True)
    slots = Q._sweep_slots(daemon)
    assert slots.acquire(blocking=False)
    old_wait = Q.SWEEP_WAIT_S
    Q.SWEEP_WAIT_S = 0.05
    try:
        resp2 = serve_whatif(daemon, small)
    finally:
        Q.SWEEP_WAIT_S = old_wait
        slots.release()
    assert not resp2.ok and "in progress" in resp2.error


def test_fast_forward_reports_virtual_speedup():
    """Satellite: fast_forward's result dict carries the effective
    virtual speedup and tick rate, comparable to twin bench figures."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.topology import SimEngine, TopologyStore
    from kubedtn_tpu.wire.server import Daemon

    engine = SimEngine(TopologyStore(), capacity=16)
    plane = WireDataPlane(Daemon(engine), dt_us=10_000.0)
    out = plane.fast_forward(2.0)
    assert out["sim_seconds"] == 2.0
    assert out["wall_s"] >= 0.0
    assert out["virtual_speedup"] is not None and out["virtual_speedup"] > 0
    assert out["ticks_per_s"] is not None and out["ticks_per_s"] > 0


TOPO_YAML = """\
apiVersion: y-young.github.io/v1
kind: Topology
metadata: {name: p1}
spec:
  links:
    - {uid: 1, peer_pod: p2, local_intf: eth1, peer_intf: eth1,
       properties: {latency: 5ms}}
---
apiVersion: y-young.github.io/v1
kind: Topology
metadata: {name: p2}
spec:
  links:
    - {uid: 1, peer_pod: p1, local_intf: eth1, peer_intf: eth1,
       properties: {latency: 5ms}}
"""


def test_cli_whatif_local(tmp_path, capsys):
    """`kdt whatif --file` end to end: spec YAML → sweep → ranked JSON,
    plus loud failure on a malformed spec."""
    import json

    from kubedtn_tpu import cli

    topo = tmp_path / "topo.yml"
    topo.write_text(TOPO_YAML)
    spec = tmp_path / "sweep.yml"
    spec.write_text(
        "- name: slow\n  perturbations:\n"
        "    - {kind: degrade, uid: 1, properties: {latency: 50ms}}\n")
    rc = cli.main(["whatif", "--file", str(topo), "--spec", str(spec),
                   "--ticks", "30", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    rep = json.loads(out)
    ranked = {r["name"]: r for r in rep["ranked"]}
    assert set(ranked) == {"baseline", "slow"}
    assert ranked["slow"]["rank"] == 1
    assert ranked["slow"]["p99_us"] > ranked["baseline"]["p99_us"]

    # table mode renders both scenario names
    rc = cli.main(["whatif", "--file", str(topo), "--spec", str(spec),
                   "--ticks", "30"])
    out = capsys.readouterr().out
    assert rc == 0 and "slow" in out and "baseline" in out

    # malformed spec entries are a clean CLI error, not a traceback
    bad = tmp_path / "bad.yml"
    bad.write_text("- just-a-string\n")
    rc = cli.main(["whatif", "--file", str(topo), "--spec", str(bad)])
    err = capsys.readouterr().err
    assert rc == 1 and "must be a mapping" in err


def test_whatif_sweep_scenario_smoke():
    """Tier-1 smoke of the bench phase (small N×T): the subsystem's
    whole path — topology → snapshot → mixed perturbation set → one
    compiled sweep → report fields — can't silently rot."""
    from kubedtn_tpu.scenarios import whatif_sweep

    r = whatif_sweep(replicas=6, steps=40, n_nodes=12, n_links=24,
                     k_slots=2)
    assert r["replicas"] == 6
    assert r["steps"] == 40
    assert r["replicas_steps_per_s"] > 0
    assert r["virtual_speedup"] > 0
    assert 0 < r["baseline_delivery_ratio"] <= 1.0
    assert ((r["worst_delivery_ratio"] or 0.0)
            <= r["baseline_delivery_ratio"])
    assert r["compile_s"] >= 0.0 and r["run_s"] > 0.0
