"""Test harness: run everything on a virtual 8-device CPU mesh.

The simulation core is pure JAX, so the whole framework — including the
multi-chip sharded paths — is testable on CPU with virtual devices. Real-TPU
behavior is exercised by bench.py and the driver's dryrun (__graft_entry__.py).
"""

import os
import sys

# Must be set before jax initializes its backends. Note: the env var alone
# is not enough under the axon TPU-tunnel platform, which overrides
# JAX_PLATFORMS — the explicit config.update below is what sticks.
#
# KUBEDTN_TEST_PLATFORM=tpu keeps the real backend instead, for the few
# on-chip-only tests (kernel paths interpret mode cannot execute, e.g.
# the tiled Pallas on-core PRNG). Everything else skips or fails off the
# 8-device mesh under that mode — select the on-chip tests explicitly:
#   KUBEDTN_TEST_PLATFORM=tpu pytest tests -k on_chip
_TEST_PLATFORM = os.environ.get("KUBEDTN_TEST_PLATFORM", "cpu")
if _TEST_PLATFORM not in ("cpu", "tpu"):
    raise RuntimeError(
        f"KUBEDTN_TEST_PLATFORM={_TEST_PLATFORM!r}: expected 'cpu' or "
        f"'tpu' (exact, lowercase)")
if _TEST_PLATFORM == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if _TEST_PLATFORM == "cpu":
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# -- reference-checkout gating ----------------------------------------
#
# Convention: tests that read the reference repo's sample files
# (config/samples/*.yml, proto/v1/kube_dtn.proto) carry
# `@pytest.mark.requires_reference_yaml`. The reference checkout is an
# ENVIRONMENT dependency, not a code one — CI images without
# /root/reference used to fail ~50 tests with a misleading
# AttributeError (load_yaml treats a missing path as literal YAML
# text), polluting every tier-1 failure-set diff against the seed.
# Marked tests auto-skip below with a reason naming the missing env, so
# the failure set stays exactly "real regressions".
REFERENCE_ROOT = "/root/reference"


def _multihost_supported() -> bool:
    """Can this jaxlib run multi-PROCESS computations on the CPU
    backend? Needs the gloo TCP collectives transport (the workers set
    jax_cpu_collectives_implementation=gloo); a jaxlib built without it
    fails every multihost test with "Multiprocess computations aren't
    implemented on the CPU backend" — an environment gap, not a
    regression."""
    try:
        import jaxlib.xla_extension as xe

        return hasattr(xe, "make_gloo_tcp_collectives")
    except Exception:
        return False


def _native_shm_supported() -> bool:
    """Can this host run the shared-memory ingest ring? Needs the
    native library (prebuilt .so, or a C++ toolchain for `make -C
    native`) with the kdt_shm_* entry points — an ENVIRONMENT
    dependency, same policy as the reference checkout above: marked
    tests skip with an honest reason instead of failing."""
    try:
        from kubedtn_tpu import native

        return native.have_native()
    except Exception:
        return False


def pytest_collection_modifyitems(config, items):
    if any("requires_native_shm" in item.keywords for item in items) \
            and not _native_shm_supported():
        skip_shm = pytest.mark.skip(
            reason="requires_native_shm: libkubedtn_native.so with the "
                   "kdt_shm_* ring entry points is not available (no "
                   "prebuilt .so and no C++ toolchain to build one)")
        for item in items:
            if "requires_native_shm" in item.keywords:
                item.add_marker(skip_shm)
    if not _multihost_supported():
        skip_mh = pytest.mark.skip(
            reason="requires_multihost: this jaxlib lacks the gloo CPU "
                   "collectives transport, so multi-process CPU "
                   "computations cannot run in this environment")
        for item in items:
            if "requires_multihost" in item.keywords:
                item.add_marker(skip_mh)
    if os.path.exists(REFERENCE_ROOT):
        return
    skip = pytest.mark.skip(
        reason=f"requires_reference_yaml: reference checkout missing at "
               f"{REFERENCE_ROOT} (this environment ships without the "
               f"dtn-dslab/kube-dtn sample files)")
    for item in items:
        if "requires_reference_yaml" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture
def sharded_mesh(request):
    """Edge mesh over the forced-host virtual devices for
    @pytest.mark.sharded_plane tests. Size comes from indirect
    parametrization (`@pytest.mark.parametrize("sharded_mesh", [2, 8],
    indirect=True)`), default 2; skips honestly when the environment
    exposes fewer devices than requested."""
    import jax

    from kubedtn_tpu.parallel.mesh import make_mesh

    n = int(getattr(request, "param", 2))
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"sharded_plane: needs {n} devices, environment "
                    f"exposes {len(devs)}")
    return make_mesh(n)
