"""Test harness: run everything on a virtual 8-device CPU mesh.

The simulation core is pure JAX, so the whole framework — including the
multi-chip sharded paths — is testable on CPU with virtual devices. Real-TPU
behavior is exercised by bench.py and the driver's dryrun (__graft_entry__.py).
"""

import os
import sys

# Must be set before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]
