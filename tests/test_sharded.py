"""Sharding tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import netem
from kubedtn_tpu.parallel import mesh as meshlib
from kubedtn_tpu.parallel.sharded import make_sharded_step


N_NODES = 16
CAPACITY = 256  # 32 rows per device on the 8-device mesh


def build_state(capacity=CAPACITY, n_edges=100):
    rng = np.random.default_rng(0)
    s = es.init_state(capacity)
    src = rng.integers(0, N_NODES, n_edges).astype(np.int32)
    dst = (src + 1 + rng.integers(0, N_NODES - 1, n_edges)).astype(np.int32) % N_NODES
    props = np.stack([
        es.props_row(LinkProperties(latency="1ms").to_numeric())
    ] * n_edges)
    s = es.apply_links(
        s, jnp.arange(n_edges, dtype=jnp.int32),
        jnp.arange(n_edges, dtype=jnp.int32),
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(props),
        jnp.ones(n_edges, dtype=bool))
    return s, src, dst


def test_mesh_creation(devices8):
    m = meshlib.make_mesh(8)
    assert m.devices.shape == (8,)
    assert m.axis_names == (meshlib.EDGE_AXIS,)


def test_sharded_state_placement(devices8):
    m = meshlib.make_mesh(8)
    s, _, _ = build_state()
    sh = meshlib.shard_edge_state(s, m)
    assert len(sh.props.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(sh.uid), np.asarray(s.uid))


def test_sharded_step_matches_unsharded(devices8):
    m = meshlib.make_mesh(8)
    s, src, dst = build_state()
    s_sh = meshlib.shard_edge_state(s, m)

    B = 32
    urows = jnp.arange(B, dtype=jnp.int32)
    uprops = jnp.stack(
        [es.props_row(LinkProperties(latency="5ms").to_numeric())] * B)
    uvalid = jnp.ones(B, dtype=bool)
    sizes = jnp.full((CAPACITY,), 1000.0, jnp.float32)
    have = jnp.ones((CAPACITY,), dtype=bool)
    t_arr = jnp.zeros((CAPACITY,), jnp.float32)
    key = jax.random.key(3)

    step = make_sharded_step(m, N_NODES)
    s2, res, stats = step(s_sh, urows, uprops, uvalid, sizes, have, t_arr, key)

    # unsharded reference run
    s_ref = es.update_links(s, urows, uprops, uvalid)
    s_ref, res_ref = netem.shape_step(s_ref, sizes, have, t_arr, key)

    np.testing.assert_allclose(np.asarray(res.depart_us),
                               np.asarray(res_ref.depart_us), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(res.delivered),
                                  np.asarray(res_ref.delivered))
    np.testing.assert_allclose(np.asarray(s2.props),
                               np.asarray(s_ref.props), rtol=1e-6)

    # stats replicate and agree with a numpy reduction
    delivered = np.asarray(res_ref.delivered)
    tx_ref = np.bincount(src[delivered[:100]], minlength=N_NODES).astype(
        np.float32)
    active_src = np.asarray(s_ref.src)[:100]
    expect_tx = np.zeros(N_NODES, np.float32)
    for sidx, d in zip(active_src, delivered[:100]):
        if d:
            expect_tx[sidx] += 1
    np.testing.assert_allclose(np.asarray(stats.tx_packets), expect_tx)
    assert float(np.asarray(stats.rx_packets).sum()) == delivered.sum()


def test_updated_props_visible_after_sharded_step(devices8):
    m = meshlib.make_mesh(8)
    s, _, _ = build_state()
    s_sh = meshlib.shard_edge_state(s, m)
    step = make_sharded_step(m, N_NODES)

    B = 8
    urows = jnp.arange(B, dtype=jnp.int32)
    uprops = jnp.stack(
        [es.props_row(LinkProperties(latency="7ms").to_numeric())] * B)
    sizes = jnp.full((CAPACITY,), 100.0, jnp.float32)
    s2, res, _ = step(s_sh, urows, uprops, jnp.ones(B, bool), sizes,
                      jnp.ones((CAPACITY,), bool),
                      jnp.zeros((CAPACITY,), jnp.float32), jax.random.key(0))
    # the scatter landed across shards and the same step shaped with it
    np.testing.assert_allclose(np.asarray(res.depart_us)[:B], 7000.0,
                               rtol=1e-6)
