"""Per-cause drop accounting: the partition invariant, end to end.

Every shaping kernel computes its drop causes separately (netem loss in
netem_packet, TBF 50ms-queue overflow in tbf_packet) and the outcomes
are mutually exclusive BY CONSTRUCTION — including at the
duplicate/loss interaction (netem.py `loss_hit & ~dup_hit`: a packet
that hits BOTH duplicate and loss transmits exactly once, counting in
neither cause). These property tests pin, over random specs:

- kernel level: delivered + dropped_loss + dropped_queue == offered,
  exactly, for all three batch kernels (slot-independent, max-plus TBF
  incl. its fallback flag, sequential scan), and `cause_codes` encodes
  the same partition;
- plane level: the live plane's total `dropped` equals the per-edge
  dropped_loss + dropped_queue counter sums exactly (no double count,
  no uncounted drop), with the window ring agreeing when telemetry is
  on — through mixed kernel classes and the TBF fallback re-shape.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from kubedtn_tpu import telemetry as tele
from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.ops import netem
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore


def _rand_props(rng) -> LinkProperties:
    """A random spec drawn from the whole shaping vocabulary: loss /
    duplicate / corrupt / reorder (with correlations), jitter, and TBF
    rates low enough to force queue drops."""
    kw = {}
    if rng.random() < 0.8:
        kw["latency"] = f"{rng.integers(0, 5000)}us"
    if rng.random() < 0.5:
        kw["jitter"] = f"{rng.integers(1, 1000)}us"
    if rng.random() < 0.6:
        kw["loss"] = str(round(float(rng.uniform(0, 40)), 1))
        if rng.random() < 0.5:
            kw["loss_corr"] = str(rng.integers(1, 80))
    if rng.random() < 0.4:
        kw["duplicate"] = str(round(float(rng.uniform(0, 30)), 1))
    if rng.random() < 0.4:
        kw["corrupt_prob"] = str(round(float(rng.uniform(0, 20)), 1))
    if rng.random() < 0.3:
        kw["reorder_prob"] = str(round(float(rng.uniform(0, 30)), 1))
        kw["gap"] = int(rng.integers(0, 4))
    if rng.random() < 0.5:
        # 256Kbit..4Mbit: burst ~5KB, so dense 64-1500B batches
        # regularly overflow the 50ms queue → dropped_queue exercised
        kw["rate"] = f"{int(rng.integers(256, 4000))}Kbit"
    return LinkProperties(**kw)


def _plane_with_links(specs, prefix):
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * len(specs) + 8)
    for i, props in enumerate(specs):
        a, b = f"{prefix}a{i}", f"{prefix}b{i}"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=props)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=props)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    win = [daemon._add_wire(pb.WireDef(
        local_pod_name=f"{prefix}a{i}", kube_ns="default",
        link_uid=i + 1, intf_name_in_pod="eth1"))
        for i in range(len(specs))]
    for i in range(len(specs)):
        daemon._add_wire(pb.WireDef(
            local_pod_name=f"{prefix}b{i}", kube_ns="default",
            link_uid=i + 1, intf_name_in_pod="eth1"))
    return daemon, engine, win


# -- kernel-level partition --------------------------------------------

def _assert_partition(res, act):
    deliv = np.asarray(res.delivered)
    loss = np.asarray(res.dropped_loss)
    queue = np.asarray(res.dropped_queue)
    act = np.asarray(act)
    # mutually exclusive and exhaustive over active lanes
    assert not (deliv & loss).any()
    assert not (deliv & queue).any()
    assert not (loss & queue).any()
    assert ((deliv | loss | queue) == act).all()
    # cause_codes is the same partition, encoded
    codes = np.asarray(netem.cause_codes(res))
    assert ((codes == 0) == ~act).all()
    assert ((codes == 1) == deliv).all()
    assert ((codes == 2) == loss).all()
    assert ((codes == 3) == queue).all()


def test_kernel_partition_property():
    """delivered + dropped_loss + dropped_queue == offered, exactly,
    for each batch kernel over random states and random specs."""
    from kubedtn_tpu.models import topologies as T

    rng = np.random.default_rng(7)
    for trial in range(6):
        el = T.random_mesh(8, 12, seed=int(rng.integers(1 << 30)))
        state, rows = T.load_edge_list_into_state(el)
        # randomize the props columns directly: loss/corr/dup/rate mixes
        props = np.asarray(state.props).copy()
        E = props.shape[0]
        from kubedtn_tpu.ops import edge_state as es

        props[:, es.P_LOSS] = rng.uniform(0, 40, E)
        props[:, es.P_DUPLICATE] = rng.uniform(0, 30, E)
        props[:, es.P_CORRUPT_PROB] = rng.uniform(0, 20, E)
        props[:, es.P_RATE_BPS] = np.where(
            rng.random(E) < 0.5, rng.uniform(2e5, 4e6, E), 0.0)
        corr_on = rng.random(E) < 0.3
        props[:, es.P_LOSS_CORR] = np.where(corr_on,
                                            rng.uniform(0, 80, E), 0.0)
        state = dataclasses.replace(state,
                                    props=jnp.asarray(props,
                                                      jnp.float32))
        R, K = 6, 32
        row_idx = jnp.asarray(rng.choice(len(rows), R, replace=False)
                              .astype(np.int32))
        sizes = jnp.asarray(rng.integers(64, 1500, (R, K))
                            .astype(np.float32))
        valid = jnp.asarray(rng.random((R, K)) < 0.9)
        key = jax.random.key(trial)
        act = np.asarray(valid) & np.asarray(
            state.active)[np.asarray(row_idx)][:, None]

        # sequential scan (handles every spec)
        _st, res = netem.shape_slots_nodonate(state, row_idx, sizes,
                                              valid, key)
        _assert_partition(res, act)

        # slot-independent kernel on its eligible rows
        indep = np.asarray(netem.slot_independent_rows(
            np.asarray(state.props)[np.asarray(row_idx)]))
        if indep.any():
            sub_rows = row_idx[jnp.asarray(np.nonzero(indep)[0])]
            res2, _cnt = netem.shape_slots_indep_nodonate(
                state, sub_rows, sizes[jnp.asarray(
                    np.nonzero(indep)[0])],
                valid[jnp.asarray(np.nonzero(indep)[0])], key)
            _assert_partition(res2, act[indep])

        # max-plus TBF kernel on its eligible rows (fallback rows are
        # flagged, not mis-partitioned)
        tbfb = np.asarray(netem.tbf_batch_rows(
            np.asarray(state.props)[np.asarray(row_idx)]))
        if tbfb.any():
            sel = jnp.asarray(np.nonzero(tbfb)[0])
            res3, _tok, _dep, _dl, _ha, _fb = \
                netem.shape_slots_tbf_nodonate(
                    state, row_idx[sel], sizes[sel], valid[sel], key)
            _assert_partition(res3, act[tbfb])


# -- plane-level accounting --------------------------------------------

def test_plane_drop_causes_sum_to_total_property():
    """Random spec mix through the LIVE plane: per-edge cause counters
    sum exactly to the plane's `dropped` total, per-edge tx equals
    delivered + causes, and the telemetry window ring agrees — over
    both pipeline depths (the TBF fallback path included)."""
    rng = np.random.default_rng(23)
    for depth in (1, 2):
        specs = [_rand_props(rng) for _ in range(5)]
        daemon, engine, win = _plane_with_links(specs, f"pc{depth}")
        plane = WireDataPlane(daemon, dt_us=2000.0,
                              pipeline_depth=depth)
        plane.pipeline_explicit_clock = True
        tel, _rec = plane.enable_telemetry(window_s=10.0,
                                           sample_period=16)
        fed = 0
        t = 100.0
        for burst in range(3):
            for k, w in enumerate(win):
                n = int(rng.integers(20, 200))
                w.ingress.extend([bytes([k]) + b"\x00" * 63] * n)
                fed += n
            for _ in range(15):
                t += 0.002
                plane.tick(now_s=t)
        plane.flush()
        plane.tick(now_s=t + 10.0)
        assert plane.tick_errors == 0
        c = plane.counters
        tx = np.asarray(c.tx_packets)
        rx = np.asarray(c.rx_packets)
        loss = np.asarray(c.dropped_loss)
        queue = np.asarray(c.dropped_queue)
        # global: every fed frame shaped or dropped, causes exact
        assert tx.sum() == fed
        assert rx.sum() == plane.shaped
        assert loss.sum() + queue.sum() == plane.dropped
        assert plane.shaped + plane.dropped == fed
        # per-edge: delivered + causes == offered on every row
        np.testing.assert_array_equal(rx + loss + queue, tx)
        # the window ring tells the same story
        total, _secs = tel.window_sum()
        assert total[:, tele.T_TX].sum() == fed
        assert total[:, tele.T_DELIVERED].sum() == plane.shaped
        np.testing.assert_allclose(total[:, tele.T_DROP_LOSS], loss)
        np.testing.assert_allclose(total[:, tele.T_DROP_QUEUE], queue)
        assert total[:, tele.T_HIST0:].sum() == plane.shaped
