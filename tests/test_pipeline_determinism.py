"""Pipelined tick engine: determinism and flush-barrier contracts.

The data plane's depth-2 software pipeline overlaps tick N's host work
with tick N-1's device shaping (runtime._dispatch / _complete). Overlap
must never change WHAT the plane computes — these tests pin:

- depth 1 vs depth 2 deliver byte-identical per-wire frame sequences for
  every kernel class (slot-independent, max-plus TBF incl. its 50ms
  queue-drop fallback re-shape, and the correlated sequential scan with
  seq_slots holdback);
- every reader/rewriter of shared state crosses the flush() barrier:
  export_pending / restore_pending see in-flight frames, fast_forward's
  epilogue lands the last dispatch, stop() never strands one;
- the adaptive drain budget reacts to the backlog signal in both
  directions and stays inside [adapt_min_slots, max_slots].

Explicit-clock ticks default to the synchronous (depth-1) path;
`pipeline_explicit_clock = True` opts a deterministic-clock plane into
the in-flight ring, which is what makes these comparisons possible.
"""

import gc

import pytest

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.runtime import WireDataPlane, _GCTuner
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore


def _daemon_with_pairs(pairs, props):
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * pairs + 8)
    for i in range(pairs):
        a, b = f"a{i}", f"b{i}"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=props)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=props)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    win, wout = [], []
    for i in range(pairs):
        win.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"a{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1")))
        wout.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"b{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1")))
    return daemon, engine, win, wout


def _tagged_frames(wire_i: int, n: int, size: int = 64):
    """Frames whose bytes encode (wire, sequence) so delivery ORDER is
    byte-comparable, not just delivery count."""
    return [bytes([wire_i]) + i.to_bytes(4, "big")
            + b"\x00" * (size - 5) for i in range(n)]


def _run_plane(depth: int, props, n_per_wire: int, pairs: int = 2,
               ticks: int = 40, dt: float = 0.002, seq_slots: int = 64,
               feed_every: int | None = None, telemetry: bool = False):
    """Drive one freshly-built plane through an identical deterministic
    schedule; returns the per-wire delivered frame sequences."""
    daemon, _engine, win, wout = _daemon_with_pairs(pairs, props)
    plane = WireDataPlane(daemon, dt_us=dt * 1e6, pipeline_depth=depth)
    plane.pipeline_explicit_clock = True
    plane.seq_slots = seq_slots
    if telemetry:
        # window ring + flight recorder ON: the telemetry reductions
        # ride the fused dispatch and must not perturb delivery
        plane.enable_telemetry(window_s=0.01, sample_period=4)
    t = 100.0
    for k, wa in enumerate(win):
        wa.ingress.extend(_tagged_frames(k, n_per_wire))
    for j in range(ticks):
        if feed_every and j and j % feed_every == 0:
            for k, wa in enumerate(win):
                wa.ingress.extend(_tagged_frames(k, n_per_wire))
        t += dt
        plane.tick(now_s=t)
    # drain the ring and release everything scheduled: deadlines are
    # bounded by the props' latency + TBF horizon, far below +10s
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert plane.tick_errors == 0
    assert not plane._inflight
    return [list(w.egress) for w in wout], plane


INDEP = LinkProperties(latency="3ms", jitter="1ms", loss="5")
TBF = LinkProperties(rate="2Gbit")
# ~1ms service/frame at 64B: a 300-frame burst blows the 50ms TBF queue
# limit, forcing the max-plus kernel's exact-scan fallback re-shape
TBF_OVERLOAD = LinkProperties(rate="512Kbit")
SEQ = LinkProperties(latency="2ms", loss="10", loss_corr="25")


@pytest.mark.parametrize("props,n,kwargs", [
    (INDEP, 200, {}),
    (TBF, 200, {}),
    (TBF_OVERLOAD, 300, {}),
    (SEQ, 150, dict(seq_slots=16)),
], ids=["indep", "tbf", "tbf-fallback", "seq-holdback"])
def test_depth2_delivery_order_matches_depth1(props, n, kwargs):
    """The in-flight ring must not reorder, drop, or re-shape anything:
    byte-identical per-wire delivery sequences at depth 1 vs 2."""
    got1, p1 = _run_plane(1, props, n, **kwargs)
    got2, p2 = _run_plane(2, props, n, **kwargs)
    assert p1.shaped == p2.shaped
    assert p1.dropped == p2.dropped
    for w1, w2 in zip(got1, got2):
        assert w1 == w2  # byte-identical, in order
    # the workload actually delivered something (guards a vacuous pass)
    assert sum(len(w) for w in got1) > 0


@pytest.mark.parametrize("props,n,kwargs", [
    (INDEP, 200, {}),
    (TBF_OVERLOAD, 300, {}),
    (SEQ, 150, dict(seq_slots=16)),
], ids=["indep", "tbf-fallback", "seq-holdback"])
def test_depth2_matches_depth1_with_telemetry_on(props, n, kwargs):
    """The link telemetry plane adds NO per-tick host sync and changes
    NOTHING the plane computes: with the window ring + flight recorder
    enabled, depth 1 and depth 2 still deliver byte-identical per-wire
    sequences (incl. the TBF fallback re-shape, whose telemetry goes
    through the host-side window patch)."""
    got1, p1 = _run_plane(1, props, n, telemetry=True, **kwargs)
    got2, p2 = _run_plane(2, props, n, telemetry=True, **kwargs)
    assert p1.shaped == p2.shaped
    assert p1.dropped == p2.dropped
    for w1, w2 in zip(got1, got2):
        assert w1 == w2  # byte-identical, in order
    assert sum(len(w) for w in got1) > 0
    # telemetry ON vs OFF delivers the same bytes too (has_tel is a
    # separate jit variant; the shaping math is shared)
    got_off, p_off = _run_plane(2, props, n, **kwargs)
    assert p_off.shaped == p2.shaped
    for w1, w2 in zip(got_off, got2):
        assert w1 == w2
    # both recorders saw the deterministic sampling schedule
    assert p1.recorder.sampled == p2.recorder.sampled > 0


def test_depth2_sustained_tbf_overload_matches_depth1():
    """Overload bursts arriving EVERY tick keep a fallback-tripping
    batch and a fresh dispatch in flight together — the tick after a
    fallback must not shape from the stale (pre-correction) token
    chain. 120 64B frames ≈ 120ms of service at 512Kbit against the
    50ms queue cap: every burst trips the exact-scan fallback."""
    got1, p1 = _run_plane(1, TBF_OVERLOAD, 120, ticks=20, feed_every=1)
    got2, p2 = _run_plane(2, TBF_OVERLOAD, 120, ticks=20, feed_every=1)
    assert p1.shaped == p2.shaped
    assert p1.dropped == p2.dropped
    for w1, w2 in zip(got1, got2):
        assert w1 == w2
    assert sum(len(w) for w in got1) > 0
    assert p1.dropped > 0  # the fallback path actually engaged


def test_depth2_with_continuous_feed_matches_depth1():
    """Steady multi-tick ingress keeps the ring FULL (the overlap case the
    soak exercises): order parity must hold there too, not just for a
    one-shot burst."""
    got1, p1 = _run_plane(1, INDEP, 50, ticks=60, feed_every=5)
    got2, p2 = _run_plane(2, INDEP, 50, ticks=60, feed_every=5)
    assert p1.shaped == p2.shaped
    for w1, w2 in zip(got1, got2):
        assert w1 == w2


def test_export_pending_flushes_inflight_dispatch():
    """A depth-2 plane with a dispatch still in flight must not export a
    half-empty delay line: export_pending crosses the flush barrier."""
    daemon, _e, win, wout = _daemon_with_pairs(1, LinkProperties(
        latency="50ms"))
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=2)
    plane.pipeline_explicit_clock = True
    win[0].ingress.extend(_tagged_frames(0, 40))
    plane.tick(now_s=5.0)
    # the dispatch is (or was) in flight; nothing released yet at 50ms
    assert len(wout[0].egress) == 0
    exported = plane.export_pending()
    assert len(exported) == 40
    assert not plane._inflight  # barrier drained the ring
    # remaining delay is the full 50ms (quantized to this tick's clock)
    assert all(0.0 < rem <= 50_000.0 for _pk, _uid, _f, rem in exported)
    # restore into a FRESH plane and verify the frames complete their
    # remaining delay (the checkpoint round-trip the barrier protects)
    daemon2, _e2, _win2, wout2 = _daemon_with_pairs(1, LinkProperties(
        latency="50ms"))
    plane2 = WireDataPlane(daemon2, dt_us=2_000.0, pipeline_depth=2)
    plane2.pipeline_explicit_clock = True
    assert plane2.restore_pending(exported, now_s=1.0) == 40
    plane2.tick(now_s=1.049)
    assert len(wout2[0].egress) == 0   # not due yet
    plane2.tick(now_s=1.051)
    assert len(wout2[0].egress) == 40  # due after the remaining delay


def test_fast_forward_flushes_pipelined_ticks():
    """fast_forward's epilogue must land the last in-flight dispatch:
    shaped/delivered totals match the synchronous plane exactly."""
    results = []
    for depth in (1, 2):
        daemon, _e, win, wout = _daemon_with_pairs(1, INDEP)
        plane = WireDataPlane(daemon, dt_us=2_000.0,
                              pipeline_depth=depth)
        plane.pipeline_explicit_clock = True
        win[0].ingress.extend(_tagged_frames(0, 120))
        r = plane.fast_forward(1.0)
        assert not plane._inflight
        results.append((r["shaped"], list(wout[0].egress)))
    (s1, d1), (s2, d2) = results
    assert s1 == s2
    assert d1 == d2
    assert len(d1) > 0


def test_stop_flushes_inflight_dispatch():
    """stop() after the runner exits mid-pipeline must not strand
    shaped frames in the ring (they belong in the delay line, and their
    counters must accumulate)."""
    daemon, _e, win, _wout = _daemon_with_pairs(1, LinkProperties(
        latency="100ms"))
    plane = WireDataPlane(daemon, dt_us=2_000.0, pipeline_depth=2)
    plane.pipeline_explicit_clock = True
    win[0].ingress.extend(_tagged_frames(0, 30))
    plane.tick(now_s=3.0)
    plane.stop()  # runner never started — stop() must still flush
    assert not plane._inflight
    assert plane.shaped == 30
    assert len(plane.export_pending()) == 30


def test_drain_backlog_excludes_undrainable_queues():
    """last_drain_backlog is the runner's shed-the-sleep and grow-the-
    batch signal: it must count only residue another tick COULD drain.
    A wire whose link is not realized retries via re-mark but must not
    make the runner busy-spin a core until the control plane catches
    up."""
    from kubedtn_tpu.wire import proto as pb

    daemon, _e, win, _wout = _daemon_with_pairs(1, INDEP)
    orphan = daemon._add_wire(pb.WireDef(
        local_pod_name="a0", kube_ns="default", link_uid=99,
        intf_name_in_pod="eth9"))
    orphan.ingress.extend(_tagged_frames(0, 10))
    drained = daemon.drain_ingress(max_per_wire=4096)
    assert all(w.wire_id != orphan.wire_id for w, *_ in drained)
    assert daemon.last_drain_backlog == 0   # undrainable: no signal
    assert len(orphan.ingress) == 10        # still queued for later
    # budget residue on a realized wire IS the signal
    win[0].ingress.extend(_tagged_frames(0, 30))
    daemon.drain_ingress(max_per_wire=10)
    assert daemon.last_drain_backlog == 20


def test_adaptive_budget_tracks_backlog():
    """Backpressure doubles the drain budget toward max_slots while the
    ingress backlog grows, and empty backlog halves it back toward
    adapt_min_slots — never leaving [adapt_min_slots, max_slots]."""
    daemon, _e, _win, _wout = _daemon_with_pairs(1, INDEP)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    assert plane._drain_budget == plane.max_slots
    # empty backlog long enough → shrink to the floor
    daemon.last_drain_backlog = 0
    for _ in range(40):
        plane._adapt_budget()
    assert plane._drain_budget == plane.adapt_min_slots
    # growing backlog → grow back to the ceiling
    for bl in range(1, 41):
        daemon.last_drain_backlog = bl * 100
        plane._adapt_budget()
    assert plane._drain_budget == plane.max_slots
    assert plane.last_backlog == 4000


def _run_plane_with_ladder(props, n_per_wire, transitions,
                           pairs: int = 2, ticks: int = 40,
                           dt: float = 0.002, feed_every: int = 5):
    """Like _run_plane at depth 2, but forcing degradation-ladder
    transitions at scheduled tick indices (transitions: {tick: level})."""
    daemon, _engine, win, wout = _daemon_with_pairs(pairs, props)
    plane = WireDataPlane(daemon, dt_us=dt * 1e6, pipeline_depth=2)
    plane.pipeline_explicit_clock = True
    t = 100.0
    for k, wa in enumerate(win):
        wa.ingress.extend(_tagged_frames(k, n_per_wire))
    for j in range(ticks):
        if j in transitions:
            plane.force_degrade(transitions[j])
        if feed_every and j and j % feed_every == 0:
            for k, wa in enumerate(win):
                wa.ingress.extend(_tagged_frames(k, n_per_wire))
        t += dt
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert plane.tick_errors == 0
    assert not plane._inflight
    return [list(w.egress) for w in wout], plane


@pytest.mark.parametrize("props,n", [
    (INDEP, 50),
    (TBF, 50),
    (TBF_OVERLOAD, 60),
    (SEQ, 40),
], ids=["indep", "tbf", "tbf-fallback", "seq"])
def test_degradation_ladder_matches_depth1(props, n):
    """The graceful-degradation ladder active MID-STREAM — depth 2 → 1 →
    synchronous un-fused → back up — must deliver byte-identical
    per-wire order to a depth-1 run: every transition crosses the
    flush() barrier and the un-fused per-class dispatches reuse the
    fused program's key split and fold_in constants."""
    got1, p1 = _run_plane(1, props, n, ticks=40, feed_every=5)
    got2, p2 = _run_plane_with_ladder(
        props, n, transitions={8: 1, 16: 2, 24: 1, 30: 0})
    assert p1.shaped == p2.shaped
    assert p1.dropped == p2.dropped
    for w1, w2 in zip(got1, got2):
        assert w1 == w2  # byte-identical, in order
    assert sum(len(w) for w in got1) > 0
    # the ladder actually moved (guards a vacuous pass)
    assert p2.degradations == 2 and p2.promotions == 2
    assert p2.degrade_level == 0


def test_gc_tuner_refcounts_and_restores():
    """_GCTuner freezes/relaxes once for N overlapping planes and
    restores the interpreter defaults when the last one releases."""
    before = gc.get_threshold()
    _GCTuner.acquire()
    _GCTuner.acquire()
    relaxed = gc.get_threshold()
    assert relaxed[2] >= max(before[2] * 10, 100)
    _GCTuner.release()
    assert gc.get_threshold() == relaxed  # still one holder
    _GCTuner.release()
    assert gc.get_threshold() == before
    gc.unfreeze()  # leave no frozen objects behind for other tests


def test_stage_breakdown_reports_pipeline_gauges():
    """The observability contract: stage seconds + share via
    tracing.stage_shares, plus the pipeline depth/backlog gauges the
    metrics exporter scrapes."""
    daemon, _e, win, _wout = _daemon_with_pairs(1, INDEP)
    plane = WireDataPlane(daemon, dt_us=1_000.0, pipeline_depth=2)
    win[0].ingress.extend(_tagged_frames(0, 10))
    plane.tick(now_s=1.0)
    bd = plane.stage_breakdown()
    assert set(bd["seconds"]) == {"drain", "decide", "kernel", "sync",
                                  "schedule", "release"}
    assert bd["seconds"]["kernel"] > 0.0
    assert abs(sum(bd["share"].values()) - 1.0) < 0.01
    pipe = bd["pipeline"]
    assert pipe["depth"] == 2 and pipe["inflight"] == 0
    assert pipe["drain_budget"] == plane.max_slots
    assert pipe["holdback_wires"] == 0
