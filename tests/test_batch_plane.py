"""Tests for the batched live data plane (round 4).

Covers the pieces that replaced the round-3 per-frame tick:
- netem.shape_slots_nodonate (gathered scan) and
  shape_slots_indep_nodonate (elementwise fast path) — row routing,
  state scoping, padding inertness;
- native FlowTable.decide_batch — bypass-semantics parity with the
  per-frame _try_bypass path;
- native TimingWheel.schedule_batch — parity with per-frame schedule;
- the coalesced PacketBatch transport (InjectBulk/SendToBulk) end to
  end through a real gRPC daemon and the shaping pipeline;
- the live_plane scenario smoke (tiny sizes).
"""

import dataclasses
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu import native
from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import netem
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore


def _state(E=64, seed=0, n_seq=8):
    rng = np.random.default_rng(seed)
    state = es.init_state(E)
    props = np.zeros((E, es.NPROP), np.float32)
    props[:, es.P_LATENCY_US] = rng.uniform(0, 50_000, E)
    props[:, es.P_LOSS] = rng.uniform(1, 10, E)
    props[:, es.P_JITTER_US] = rng.uniform(0, 5_000, E)
    props[:n_seq, es.P_RATE_BPS] = 1e8       # TBF → sequential
    props[:n_seq, es.P_LOSS_CORR] = 25.0     # AR(1) → sequential
    return dataclasses.replace(
        state, props=jnp.asarray(props),
        active=jnp.asarray(np.ones(E, bool))), props


def test_slot_independent_rows_classification():
    _, props = _state()
    ind = np.asarray(netem.slot_independent_rows(props))
    assert not ind[:8].any()      # rate/corr rows are sequential
    assert ind[8:].all()          # latency/jitter/loss rows are free
    # each disqualifier alone flips the row
    for col in (es.P_RATE_BPS, es.P_LATENCY_CORR, es.P_LOSS_CORR,
                es.P_DUPLICATE_CORR, es.P_CORRUPT_CORR,
                es.P_REORDER_CORR, es.P_REORDER_PROB):
        p = np.zeros((1, es.NPROP), np.float32)
        assert bool(netem.slot_independent_rows(p)[0])
        p[0, col] = 1.0
        assert not bool(netem.slot_independent_rows(p)[0])


def test_shape_slots_updates_only_gathered_rows():
    state, _ = _state()
    key = jax.random.key(42)
    rng = np.random.default_rng(1)
    R, K = 8, 16
    row_idx = np.arange(8, dtype=np.int32)
    sizes = rng.uniform(64, 1500, (R, K)).astype(np.float32)
    valid = rng.random((R, K)) < 0.8
    st1, res = netem.shape_slots_nodonate(
        state, jnp.asarray(row_idx), jnp.asarray(sizes),
        jnp.asarray(valid), key)
    for fld in ("tokens", "t_last", "backlog_until", "corr", "pkt_count"):
        a0 = np.asarray(getattr(state, fld))
        a1 = np.asarray(getattr(st1, fld))
        assert np.array_equal(a0[8:], a1[8:]), f"{fld}: untouched rows"
        assert not np.array_equal(a0[:8], a1[:8]), f"{fld}: should change"
    # per-slot results only on valid slots
    assert not np.asarray(res.delivered)[~valid].any()


def test_shape_slots_padding_rows_are_inert():
    """Padding convention: row_idx >= capacity + valid=False never
    perturbs real rows — even when the LAST real row is busy (the
    scatter-drop guard)."""
    state, _ = _state()
    E = state.capacity
    key = jax.random.key(7)
    rng = np.random.default_rng(2)
    R, K = 2, 8
    row_idx = np.array([E - 1, 5], np.int32)
    sizes = rng.uniform(64, 1500, (R, K)).astype(np.float32)
    valid = np.ones((R, K), bool)
    row_pad = np.concatenate([row_idx, np.full(6, E, np.int32)])
    sz_pad = np.concatenate([sizes, np.zeros((6, K), np.float32)])
    va_pad = np.concatenate([valid, np.zeros((6, K), bool)])
    st, _res = netem.shape_slots_nodonate(
        state, jnp.asarray(row_pad), jnp.asarray(sz_pad),
        jnp.asarray(va_pad), key)
    assert int(np.asarray(st.pkt_count)[E - 1]) > 0  # real row advanced
    res2, new_cnt = netem.shape_slots_indep_nodonate(
        state, jnp.asarray(row_pad), jnp.asarray(sz_pad),
        jnp.asarray(va_pad), key)
    assert int(np.asarray(new_cnt)[E - 1]) > 0
    # rows not mentioned stay untouched in both kernels
    untouched = [r for r in range(E) if r not in (E - 1, 5)]
    assert np.array_equal(np.asarray(st.tokens)[untouched],
                          np.asarray(state.tokens)[untouched])
    assert np.array_equal(np.asarray(new_cnt)[untouched],
                          np.asarray(state.pkt_count)[untouched])


def test_shape_slots_indep_changes_only_pkt_count():
    """A slot-independent row's only cross-packet state is pkt_count; the
    fast path returns it and by construction cannot move tokens/corr."""
    state, props = _state()
    key = jax.random.key(3)
    R, K = 4, 32
    row_idx = np.arange(8, 8 + R, dtype=np.int32)  # independent rows
    sizes = np.full((R, K), 500.0, np.float32)
    valid = np.ones((R, K), bool)
    res, new_cnt = netem.shape_slots_indep_nodonate(
        state, jnp.asarray(row_idx), jnp.asarray(sizes),
        jnp.asarray(valid), key)
    deliv = np.asarray(res.delivered)
    loss = np.asarray(res.dropped_loss)
    # survivors = everything netem loss didn't eat; counts match exactly
    expect = (np.ones((R, K), bool) & ~loss).sum(axis=1)
    got = np.asarray(new_cnt)[row_idx] - np.asarray(state.pkt_count)[row_idx]
    assert np.array_equal(expect, got)
    assert deliv.sum() + loss.sum() == R * K  # no TBF: nothing queued


def _mk_tcp(sip, sport, dip, dport, vlan=False, frag=0, proto=6,
            payload=20):
    eth = b"\x02" * 6 + b"\x04" * 6
    eth += (b"\x81\x00\x00\x2a" + b"\x08\x00") if vlan else b"\x08\x00"
    ip = struct.pack(">BBHHHBBH", 0x45, 0, 20 + 8 + payload, 1, frag, 64,
                     proto, 0)
    ip += struct.pack(">II", sip, dip)
    tcp = struct.pack(">HH", sport, dport) + b"\x00" * 4
    return eth + ip + tcp + b"p" * payload


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
def test_decide_batch_matches_per_frame_bypass_semantics():
    """One decide_batch call must reproduce, frame for frame, what the
    per-frame sockops/redir sequence (flag → establish → shaped_egress →
    msg_redirect) produces on a second flow table."""
    import random

    from kubedtn_tpu.runtime import parse_tcp_flow

    ft_ref, ft_bat = native.FlowTable(), native.FlowTable()
    random.seed(3)
    frames, elig, shaped = [], [], []
    for _ in range(400):
        kind = random.random()
        if kind < 0.1:
            frames.append(b"\x00" * random.randint(0, 30))
        elif kind < 0.2:
            frames.append(_mk_tcp(1, 2, 3, 4, proto=17))        # UDP
        elif kind < 0.3:
            frames.append(_mk_tcp(1, 2, 3, 4, frag=0x2000))     # fragment
        elif kind < 0.35:
            s = random.randint(1, 3)                  # self-connection
            frames.append(_mk_tcp(s, 1000 + s, s, 1000 + s))
        else:
            s, d = random.randint(1, 3), random.randint(4, 6)
            frames.append(_mk_tcp(s, 1000 + s, d, 2000 + d,
                                  vlan=random.random() < 0.3))
        elig.append(random.random() < 0.9)
        shaped.append(random.random() < 0.3)

    ref = []
    for f, e, sh in zip(frames, elig, shaped):
        if not e:
            ref.append(0)
            continue
        tup = parse_tcp_flow(f)
        if tup is None:
            ref.append(0)
            continue
        sip, sport, dip, dport = tup
        if ft_ref.flag(sip, sport, dip, dport) is None:
            ft_ref.active_established(sip, sport, dip, dport)
            ft_ref.passive_established(dip, dport, sip, sport)
        if sh:
            ft_ref.shaped_egress(sip, sport, dip, dport)
            ref.append(0)
            continue
        ref.append(1 if ft_ref.msg_redirect(sip, sport, dip, dport) else 0)

    got = ft_bat.decide_batch(frames, elig, shaped)
    assert list(got) == ref
    assert ft_bat.bypassed == ft_ref.bypassed
    assert ft_bat.passed == ft_ref.passed


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
def test_decide_batch_self_connection_stale_entry_parity():
    """Pathological case the random mix can't hit: a SELF-connection
    frame (sip==dip, sport==dport) whose 2-tuple has a STALE active-estab
    entry (left by an embedder's direct active_established call, or by a
    passive that failed at the capacity bound). The per-frame path calls
    passive_established unconditionally — only the active emplace is
    self-guarded — so the stale entry pairs and the flow can reach
    ENABLED; the batched path must diverge in neither verdicts nor
    counters."""
    from kubedtn_tpu.runtime import parse_tcp_flow

    ft_ref, ft_bat = native.FlowTable(), native.FlowTable()
    for ft in (ft_ref, ft_bat):
        ft.active_established(9, 1111, 10, 2222)  # stale: no passive ever

    self_conn = _mk_tcp(9, 1111, 9, 1111)
    frames = [self_conn, self_conn, self_conn]
    elig, shaped = [True] * 3, [False] * 3

    ref = []
    for f in frames:
        sip, sport, dip, dport = parse_tcp_flow(f)
        if ft_ref.flag(sip, sport, dip, dport) is None:
            ft_ref.active_established(sip, sport, dip, dport)
            ft_ref.passive_established(dip, dport, sip, sport)
        ref.append(1 if ft_ref.msg_redirect(sip, sport, dip, dport) else 0)

    got = ft_bat.decide_batch(frames, elig, shaped)
    assert list(got) == ref
    # the stale entry pairs on first sight: INIT passes, then bypasses
    assert ref == [0, 1, 1]
    assert ft_bat.bypassed == ft_ref.bypassed
    assert ft_bat.passed == ft_ref.passed


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
def test_wheel_schedule_batch_matches_per_entry():
    import random

    random.seed(5)
    tw1 = native.TimingWheel(tick_us=1000)
    tw2 = native.TimingWheel(tick_us=1000)
    when = [random.randint(0, 500_000) for _ in range(1000)]
    for i, w in enumerate(when):
        tw1.schedule(w, i)
    tw2.schedule_batch(np.asarray(when, np.float64),
                       np.arange(1000, dtype=np.uint64))
    assert len(tw1) == len(tw2) == 1000
    a1, a2 = tw1.advance(600_000), tw2.advance(600_000)
    assert a1 == a2 and len(a1) == 1000
    # negative deadlines clamp to already-due, like schedule()
    tw2.schedule_batch(np.asarray([-5.0], np.float64),
                       np.asarray([77], np.uint64))
    assert tw2.advance(600_001) == [77]


def _daemon_with_pairs(pairs=2, latency="5ms"):
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * pairs + 8)
    props = LinkProperties(latency=latency)
    for i in range(pairs):
        a, b = f"a{i}", f"b{i}"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=props)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=props)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    win, wout = [], []
    for i in range(pairs):
        win.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"a{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1")))
        wout.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"b{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1")))
    return daemon, engine, win, wout


def test_inject_bulk_through_full_pipeline_over_grpc():
    """PacketBatch ingestion → drain → batched shaping → wheel delay →
    egress, over a REAL gRPC server, deterministic synthetic clock."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient
    from kubedtn_tpu.wire.server import make_server

    daemon, engine, win, wout = _daemon_with_pairs(pairs=2)
    server, port = make_server(daemon, port=0, host="127.0.0.1",
                               log_rpcs=False)
    server.start()
    client = DaemonClient(f"127.0.0.1:{port}")
    plane = WireDataPlane(daemon, dt_us=2_000.0)

    frame = b"\xab" * 120
    n_per = 300  # not a multiple of the chunk on purpose
    batches = []
    for w in win:
        pkts = [pb.Packet(remot_intf_id=w.wire_id, frame=frame)] * 100
        batches.extend(pb.PacketBatch(packets=pkts) for _ in range(3))
    assert client.InjectBulk(iter(batches)).response
    assert sum(len(w.ingress) for w in win) == 2 * n_per

    t = 50.0
    shaped = plane.tick(now_s=t)
    # 5ms latency ⇒ nothing released before the deadline
    assert sum(len(w.egress) for w in wout) == 0
    total_shaped = shaped
    for _ in range(6):
        t += 0.002
        total_shaped += plane.tick(now_s=t)
    assert total_shaped == 2 * n_per
    delivered = sum(len(w.egress) for w in wout)
    assert delivered == 2 * n_per
    client.close()
    server.stop(0)


def test_mixed_seq_and_indep_rows_in_one_tick():
    """A tick whose drain spans a TBF row and a latency-only row routes
    each through the right kernel and delivers both."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    spec = {
        "s": LinkProperties(rate="1Gbit"),      # sequential (TBF)
        "i": LinkProperties(latency="1ms"),     # independent
    }
    for j, (tag, props) in enumerate(spec.items(), start=1):
        a, b = f"{tag}a", f"{tag}b"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=j, properties=props)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=j, properties=props)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    ws = daemon._add_wire(pb.WireDef(local_pod_name="sa",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    daemon._add_wire(pb.WireDef(local_pod_name="sb", kube_ns="default",
                                link_uid=1, intf_name_in_pod="eth1"))
    wi = daemon._add_wire(pb.WireDef(local_pod_name="ia",
                                     kube_ns="default", link_uid=2,
                                     intf_name_in_pod="eth1"))
    daemon._add_wire(pb.WireDef(local_pod_name="ib", kube_ns="default",
                                link_uid=2, intf_name_in_pod="eth1"))
    n = 40
    ws.ingress.extend([b"\x01" * 200] * n)
    wi.ingress.extend([b"\x02" * 200] * n)
    shaped = plane.tick(now_s=9.0)
    assert shaped == 2 * n
    for k in range(1, 6):
        plane.tick(now_s=9.0 + 0.002 * k)
    outs = {w.pod_key: len(w.egress)
            for w in daemon.wires._by_id.values() if w.egress}
    assert outs.get("default/sb") == n   # token bucket: burst covers 40
    assert outs.get("default/ib") == n
    assert plane.dropped == 0


def test_seq_slots_cap_holds_residue_in_order():
    """Sequential rows cap the scan length at plane.seq_slots; the
    residue waits in the plane's holdback buffer in FIFO order (NOT back
    on wire.ingress — a re-queued frame would be re-classified into
    frame_stats and re-run the bypass decision) and shapes on the
    following ticks."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    seq_props = LinkProperties(rate="10Gbit", duplicate="0",
                               duplicate_corr="10")  # corr -> scan class
    store.create(Topology(name="a", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="b", uid=1,
             properties=seq_props)])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=seq_props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    plane.seq_slots = 16
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="b",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    frames = [bytes([i]) * 60 for i in range(50)]
    wa.ingress.extend(frames)
    shaped = plane.tick(now_s=4.0)
    assert shaped == 16                      # capped at seq_slots
    assert len(wa.ingress) == 0              # drain took everything
    hb = plane._holdback[wa.wire_id]
    assert len(hb[2]) == 34                  # residue held back
    assert bytes(hb[2][0]) == frames[16]     # order preserved
    # frame_stats counted each frame exactly ONCE despite the cap
    if daemon.frame_stats:
        assert sum(daemon.frame_stats.values()) == 50
    # subsequent ticks shape the holdback first, then nothing remains
    total = shaped
    for k in range(1, 8):
        total += plane.tick(now_s=4.0 + 0.001 * k)
    assert total == 50
    assert not plane._holdback
    if daemon.frame_stats:
        assert sum(daemon.frame_stats.values()) == 50  # still once each
    plane.tick(now_s=4.2)
    assert len(wb.egress) == 50


def test_live_plane_scenario_smoke():
    """The bench's live_plane scenario end to end at tiny scale: real
    gRPC server, real-time runner, out-of-process injector."""
    from kubedtn_tpu.scenarios import live_plane

    r = live_plane(pairs=2, frames_per_wire=1_000, rounds=1,
                   timeout_s=120.0)
    assert r["tick_errors"] == 0
    assert r["dropped"] == 0
    assert r["frames_per_s"] > 0
    # injector rounds up to whole 256-frame chunks
    assert r["frames_delivered"] == 2 * 1024


def test_holdback_requeue_on_vanished_row_preserves_invariant():
    """Holdback residue whose ROW vanished between ticks (link deleted
    mid-wait) must go back into the holdback buffer, not wire.ingress —
    re-queueing onto ingress would re-classify the frames into
    frame_stats and re-run the bypass verdict (each frame counts and
    decides exactly once). Once the link is re-added, the frames shape
    and deliver normally."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    seq_props = LinkProperties(rate="10Gbit", duplicate="0",
                               duplicate_corr="10")  # corr -> scan class
    link_ab = Link(local_intf="eth1", peer_intf="eth1", peer_pod="b",
                   uid=1, properties=seq_props)
    store.create(Topology(name="a", spec=TopologySpec(links=[link_ab])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=seq_props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    plane.seq_slots = 16
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="b",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    frames = [bytes([i]) * 60 for i in range(40)]
    wa.ingress.extend(frames)
    shaped = plane.tick(now_s=4.0)
    assert shaped == 16 and len(plane._holdback[wa.wire_id][2]) == 24
    stats_after_drain = sum(daemon.frame_stats.values()) \
        if daemon.frame_stats else None

    # the link vanishes while the residue waits
    topo_a = store.get("default", "a")
    assert engine.del_links(topo_a, [link_ab])
    assert engine.row_of("default/a", 1) is None
    shaped = plane.tick(now_s=4.001)
    assert shaped == 0
    # residue back in HOLDBACK (not ingress), predecided state intact
    assert len(wa.ingress) == 0
    assert len(plane._holdback[wa.wire_id][2]) == 24
    if stats_after_drain is not None:
        assert sum(daemon.frame_stats.values()) == stats_after_drain

    # link re-realizes: holdback shapes first, everything delivers
    assert engine.add_links(topo_a, [link_ab])
    total = 0
    for k in range(2, 10):
        total += plane.tick(now_s=4.0 + 0.001 * k)
    assert total == 24
    plane.tick(now_s=4.3)
    assert len(wb.egress) == 40
    if stats_after_drain is not None:
        assert sum(daemon.frame_stats.values()) == stats_after_drain
    assert plane.undeliverable == 0


def test_holdback_requeue_on_deregistered_wire_is_counted():
    """If the WIRE itself was deregistered while residue waited, its
    frames can never be drained again — they must be counted in
    plane.undeliverable, not leaked silently."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    seq_props = LinkProperties(rate="10Gbit", duplicate="0",
                               duplicate_corr="10")  # corr -> scan class
    link_ab = Link(local_intf="eth1", peer_intf="eth1", peer_pod="b",
                   uid=1, properties=seq_props)
    store.create(Topology(name="a", spec=TopologySpec(links=[link_ab])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=seq_props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    plane.seq_slots = 16
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    daemon._add_wire(pb.WireDef(local_pod_name="b", kube_ns="default",
                                link_uid=1, intf_name_in_pod="eth1"))
    wa.ingress.extend(bytes([i]) * 60 for i in range(40))
    assert plane.tick(now_s=4.0) == 16

    # pod torn down: row gone AND wire deregistered
    topo_a = store.get("default", "a")
    assert engine.del_links(topo_a, [link_ab])
    daemon.wires.delete_by_pod("default/a")
    plane.tick(now_s=4.001)
    assert plane.undeliverable == 24
    assert wa.wire_id not in plane._holdback


def test_bulk_unresolved_frames_are_counted():
    """SendToBulk/InjectBulk frames whose remot_intf_id resolves to no
    wire are dropped by design (a stream can't abort per-message) — but
    they must be COUNTED so a mis-plumbed peer is diagnosable."""
    import grpc

    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.client import DaemonClient
    from kubedtn_tpu.wire.server import Daemon, make_server

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0, host="127.0.0.1",
                               log_rpcs=False)
    server.start()
    try:
        client = DaemonClient(f"127.0.0.1:{port}")
        wire = daemon._add_wire(pb.WireDef(
            local_pod_name="w", kube_ns="default", link_uid=1,
            intf_name_in_pod="eth0"))
        good = pb.Packet(remot_intf_id=wire.wire_id, frame=b"g" * 64)
        bad = pb.Packet(remot_intf_id=9999, frame=b"b" * 64)
        client.SendToBulk(iter([pb.PacketBatch(packets=[good, bad, bad])]))
        client.InjectBulk(iter([pb.PacketBatch(packets=[bad, good])]))
        assert daemon.bulk_unresolved == 3
        assert len(wire.ingress) == 2  # the good frames still landed
        # per-frame SendToOnce keeps its NOT_FOUND abort semantics
        with pytest.raises(grpc.RpcError) as ei:
            client.SendToOnce(bad)
        assert ei.value.code() == grpc.StatusCode.NOT_FOUND
        client.close()
    finally:
        server.stop(0)


def test_live_plane_soak_smoke():
    """The sustained-rate soak at tiny scale: continuous injector,
    windowed delivery counting, no drops, every window alive."""
    from kubedtn_tpu.scenarios import live_plane_soak

    r = live_plane_soak(pairs=2, seconds=3.0, window_s=1.0)
    assert r["dropped"] == 0 and r["tick_errors"] == 0
    assert len(r["windows_frames_per_s"]) >= 2
    assert r["sustained_frames_per_s"] > 0
    assert all(w > 0 for w in r["windows_frames_per_s"])


# -- zero-copy segment ingress (round 5) --------------------------------
#
# Bulk-transport frames stay FrameSeg windows over the raw PacketBatch
# blob from gRPC ingress through the native decide call; bytes objects
# appear only at delivery. These tests pin the invariants the
# representation must preserve: frame-exact len() semantics, FIFO across
# mixed entries, seq-cap splitting by window index, exactly-once
# classification, and checkpoint export of still-lazy in-flight batches.


def _seg_for(wire_id: int, frames: list[bytes]):
    """Serialize frames into a PacketBatch blob and ingest it through
    the daemon's raw-bytes bulk path, as the gRPC server does."""
    from kubedtn_tpu.wire import proto as pb

    return pb.PacketBatch(packets=[
        pb.Packet(remot_intf_id=wire_id, frame=f) for f in frames
    ]).SerializeToString()


def test_segment_ingest_len_and_fifo_with_mixed_entries():
    """len(wire.ingress) counts FRAMES whatever the representation, and
    a drain interleaving direct bytes appends with segment entries
    preserves arrival order end to end."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire.server import FrameSeg

    daemon, engine, win, wout = _daemon_with_pairs(pairs=1)
    plane = WireDataPlane(daemon, dt_us=2_000.0)
    wa, wb = win[0], wout[0]

    first = [bytes([i]) * 60 for i in range(5)]
    mid = [bytes([0x10 + i]) * 60 for i in range(7)]
    last = [bytes([0x20 + i]) * 60 for i in range(3)]
    for f in first:
        wa.ingress.append(f)
    for _wid, group in daemon._bulk_groups(_seg_for(wa.wire_id, mid),
                                           want_segs=True):
        assert type(group) is FrameSeg and len(group) == 7
        wa.ingress.append(group)
    for f in last:
        wa.ingress.append(f)
    assert len(wa.ingress) == 15  # frames, not entries
    assert wa.ingress.entries() == 9

    t = 10.0
    plane.tick(now_s=t)
    for _ in range(5):
        t += 0.002
        plane.tick(now_s=t)
    assert len(wa.ingress) == 0
    got = list(wb.egress)
    assert got == first + mid + last  # FIFO across representations


def test_segment_seq_cap_splits_window_exactly_once():
    """A segment bigger than seq_slots on a TBF row splits by window
    index: the head shapes this tick, the residue holds back (never
    re-queued to ingress), every frame classifies exactly once, and all
    frames deliver in order."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon, FrameSeg

    store = TopologyStore()
    engine = SimEngine(store, capacity=8)
    seq_props = LinkProperties(rate="1Gbit", duplicate="0",
                               duplicate_corr="10")  # corr -> scan class
    store.create(Topology(name="a", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="b", uid=1,
             properties=seq_props)])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=seq_props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    plane.seq_slots = 16
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="b",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    frames = [bytes([i]) * 60 for i in range(50)]
    for _wid, group in daemon._bulk_groups(_seg_for(wa.wire_id, frames),
                                           want_segs=True):
        wa.ingress.append(group)
    assert wa.ingress.entries() == 1 and len(wa.ingress) == 50

    shaped = plane.tick(now_s=4.0)
    assert shaped == 16                      # capped at seq_slots
    assert len(wa.ingress) == 0              # drain took the whole seg
    hb = plane._holdback[wa.wire_id]
    assert len(hb[1]) == 34                  # residue lens
    assert type(hb[2][0]) is FrameSeg        # residue stays zero-copy
    assert bytes(hb[2][0].materialize()[0]) == frames[16]
    if daemon.frame_stats:
        assert sum(daemon.frame_stats.values()) == 50  # exactly once
    total = shaped
    for k in range(1, 8):
        total += plane.tick(now_s=4.0 + 0.001 * k)
    assert total == 50
    assert not plane._holdback
    # 1Gbit on 60B frames: everything delivers, in order
    for _ in range(30):
        plane.tick(now_s=4.1)
    assert list(wb.egress) == frames


def test_segment_pending_exports_in_flight_frames():
    """export_pending sees frames still held lazily in their transport
    blob: the checkpoint path materializes them without disturbing the
    release accounting."""
    from kubedtn_tpu.runtime import WireDataPlane, _LazyFrames

    daemon, engine, win, wout = _daemon_with_pairs(pairs=1,
                                                   latency="50ms")
    plane = WireDataPlane(daemon, dt_us=2_000.0)
    wa, wb = win[0], wout[0]
    frames = [bytes([i]) * 80 for i in range(20)]
    for _wid, group in daemon._bulk_groups(_seg_for(wa.wire_id, frames),
                                           want_segs=True):
        wa.ingress.append(group)
    plane.tick(now_s=7.0)
    assert any(type(e[2]) is _LazyFrames
               for e in plane._pending.values())
    pend = plane.export_pending()
    assert sorted(f for _pk, _uid, f, _rem in pend) == sorted(frames)
    assert all(rem > 0 for *_x, rem in pend)  # still in flight
    # export materialized in place; release still delivers exactly once
    t = 7.0
    while len(wb.egress) < 20 and t < 8.0:
        t += 0.002
        plane.tick(now_s=t)
    assert list(wb.egress) == frames


# -- exact max-plus TBF batch kernel (round 5) --------------------------
#
# Rate-limited rows without other cross-slot state shape their WHOLE
# drained batch in one associative-scan dispatch
# (netem.shape_slots_tbf_nodonate) — the token bucket is max-plus
# linear in (depart, V = depart - tokens/rate) coordinates. These tests
# pin exact parity with the sequential scan, the overload fallback (the
# affine form cannot skip a dropped packet's token charge), and the
# end-to-end effect: TBF wires escape the seq_slots per-tick ceiling.


def _tbf_state(E=16, seed=7):
    rng = np.random.default_rng(seed)
    props = np.zeros((E, es.NPROP), np.float32)
    props[:, es.P_RATE_BPS] = rng.choice([2e7, 1e8, 1e9], E)
    props[:, es.P_LATENCY_US] = rng.integers(0, 20_000, E)
    props[:, es.P_JITTER_US] = rng.choice([0, 1000, 3000], E)
    props[:, es.P_LOSS] = rng.choice([0, 0, 5, 20], E)
    props[:, es.P_DUPLICATE] = rng.choice([0, 0, 10], E)
    props[:, es.P_CORRUPT_PROB] = rng.choice([0, 5], E)
    state = es.init_state(E)
    return dataclasses.replace(
        state, active=jnp.ones(E, bool), props=jnp.asarray(props),
        tokens=jnp.asarray(rng.uniform(0, 5e4, E).astype(np.float32)),
        t_last=jnp.asarray(rng.uniform(-1e4, 0, E).astype(np.float32)),
        backlog_until=jnp.asarray(
            rng.uniform(0, 1e4, E).astype(np.float32)),
        pkt_count=jnp.asarray(rng.integers(0, 5, E), jnp.int32),
        corr=jnp.asarray(rng.random((E, es.NCORR)).astype(np.float32)),
    ), props


def test_tbf_batch_rows_classification():
    _, props = _tbf_state()
    assert bool(np.asarray(netem.tbf_batch_rows(props)).all())
    # disjoint from slot-independent (rate > 0 there means NOT indep)
    assert not np.asarray(netem.slot_independent_rows(props)).any()
    # any correlation or reorder drops a row out of the class
    for col in (es.P_LATENCY_CORR, es.P_LOSS_CORR, es.P_DUPLICATE_CORR,
                es.P_CORRUPT_CORR, es.P_REORDER_CORR, es.P_REORDER_PROB):
        p = props.copy()
        p[0, col] = 10.0
        assert not bool(np.asarray(netem.tbf_batch_rows(p))[0])
    p = props.copy()
    p[0, es.P_RATE_BPS] = 0.0
    assert not bool(np.asarray(netem.tbf_batch_rows(p))[0])


@pytest.mark.parametrize("seed,K", [(7, 64), (11, 128), (13, 37)])
def test_tbf_maxplus_matches_sequential_scan(seed, K):
    """No-drop rows: the max-plus kernel and the lax.scan produce the
    SAME flags (exact) and departs/state (f32-close) from the same PRNG
    stream."""
    state, _props = _tbf_state(seed=seed)
    rng = np.random.default_rng(seed + 1)
    R = 8
    row_idx = jnp.asarray(rng.choice(16, R, replace=False), jnp.int32)
    sizes = jnp.asarray(rng.uniform(60, 1500, (R, K)), jnp.float32)
    valid = jnp.asarray(rng.random((R, K)) < 0.95)
    key = jax.random.PRNGKey(seed)
    res_t, tok, dep, delta, hacc, fb = netem.shape_slots_tbf_nodonate(
        state, row_idx, sizes, valid, key)
    st2, res_s = netem.shape_slots_nodonate(state, row_idx, sizes,
                                            valid, key)
    ok = ~np.asarray(fb)
    assert ok.any()  # provisioned rows exist at these rates/sizes
    for f in dataclasses.fields(netem.ShapeResult):
        a = np.asarray(getattr(res_t, f.name))[ok]
        b = np.asarray(getattr(res_s, f.name))[ok]
        if a.dtype == bool:
            np.testing.assert_array_equal(a, b, err_msg=f.name)
        else:
            m = np.isfinite(b)
            assert (np.isfinite(a) == m).all(), f.name
            np.testing.assert_allclose(a[m], b[m], rtol=1e-4, atol=0.5,
                                       err_msg=f.name)
    ri = np.asarray(row_idx)[ok]
    np.testing.assert_allclose(np.asarray(tok)[ok],
                               np.asarray(st2.tokens)[ri],
                               rtol=1e-4, atol=1.0)
    np.testing.assert_allclose(np.asarray(dep)[ok],
                               np.asarray(st2.t_last)[ri],
                               rtol=1e-4, atol=0.5)
    np.testing.assert_allclose(np.asarray(dep)[ok],
                               np.asarray(st2.backlog_until)[ri],
                               rtol=1e-4, atol=0.5)
    want = np.asarray(state.pkt_count)[ri] + np.asarray(delta)[ok]
    np.testing.assert_array_equal(want, np.asarray(st2.pkt_count)[ri])


def test_tbf_maxplus_flags_overloaded_rows_for_fallback():
    """Any 50ms-queue drop in the batch marks the row fallback; the
    sequential scan confirms those rows really drop."""
    E = 4
    props = np.zeros((E, es.NPROP), np.float32)
    props[:, es.P_RATE_BPS] = [1e6, 1e6, 1e9, 1e9]
    state = es.init_state(E)
    state = dataclasses.replace(state, active=jnp.ones(E, bool),
                                props=jnp.asarray(props))
    row_idx = jnp.arange(4, dtype=jnp.int32)
    sizes = jnp.full((4, 64), 1500.0, jnp.float32)
    valid = jnp.ones((4, 64), bool)
    key = jax.random.PRNGKey(0)
    *_x, fb = netem.shape_slots_tbf_nodonate(state, row_idx, sizes,
                                             valid, key)
    _st, res_s = netem.shape_slots_nodonate(state, row_idx, sizes,
                                            valid, key)
    scan_drops = np.asarray(res_s.dropped_queue).any(axis=1)
    np.testing.assert_array_equal(np.asarray(fb), scan_drops)
    assert np.asarray(fb)[:2].all() and not np.asarray(fb)[2:].any()


def test_tbf_wire_shapes_whole_batch_in_one_tick():
    """End to end: a rate-limited wire (no correlations) shapes frames
    far beyond seq_slots in ONE tick — the ceiling the round-4 verdict
    documented for ALL shaped wires now applies only to
    correlated/reordering rows — and delivery order and TBF spacing
    hold."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=8)
    props = LinkProperties(rate="1Gbit")
    store.create(Topology(name="a", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="b", uid=1,
             properties=props)])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    plane.seq_slots = 16
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="b",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    frames = [bytes([i % 251]) * 1000 for i in range(200)]
    wa.ingress.extend(frames)
    shaped = plane.tick(now_s=5.0)
    assert shaped == 200           # whole batch, one tick, NO seq cap
    assert not plane._holdback
    # 1Gbit on 1000B frames: 8µs spacing after the burst; everything
    # delivers within a couple of ms of virtual time, in order
    t = 5.0
    for k in range(1, 6):
        t += 0.002
        plane.tick(now_s=t)
    assert list(wb.egress) == frames
    assert plane.dropped == 0


def test_tbf_wire_overload_falls_back_to_exact_scan():
    """An overloaded TBF wire (queue drops) breaks the max-plus
    kernel's linearity; _complete re-shapes the affected rows' WHOLE
    batches with the exact sequential scan (pipelined-engine contract,
    ARCHITECTURE.md "Pipelined data plane") — every frame is decided in
    its own tick with no holdback residue, drops are counted, and the
    frames that DO deliver arrive in order."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=8)
    props = LinkProperties(rate="1Mbit")   # 12ms per 1500B frame
    store.create(Topology(name="a", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="b", uid=1,
             properties=props)])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    plane.seq_slots = 16
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="b",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    frames = [bytes([i % 251]) * 1500 for i in range(50)]
    wa.ingress.extend(frames)
    shaped = plane.tick(now_s=3.0)
    # fallback engaged: the exact scan decided ALL 50 frames this tick
    # (shaped counts DELIVERED frames — the 50ms queue limit drops the
    # rest), so nothing waits in holdback for later ticks
    assert 0 < shaped < 20
    assert not plane._holdback
    t = 3.0
    for k in range(60):
        t += 0.001
        plane.tick(now_s=t)
    # 50ms TBF queue limit at 12ms/frame: ~4-6 accepted, rest dropped
    delivered = [bytes(f) for f in wb.egress]
    assert 0 < len(delivered) < 20
    assert plane.dropped == 50 - len(delivered)
    assert delivered == frames[:len(delivered)]


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
def test_bulk_groups_multi_wire_segments_partition_exactly():
    """A bulk message interleaving several wires yields one FrameSeg per
    wire (stable argsort grouping over the shared offset/len arrays);
    the segments partition the batch exactly, preserve per-wire arrival
    order, and materialize to the original frames."""
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon, FrameSeg

    store = TopologyStore()
    engine = SimEngine(store, capacity=8)
    daemon = Daemon(engine)
    rng = np.random.default_rng(5)
    wids = [101, 202, 303]
    pkts = []
    per_wire: dict[int, list[bytes]] = {w: [] for w in wids}
    for i in range(60):
        w = int(rng.choice(wids))
        f = bytes([i]) * int(rng.integers(40, 200))
        pkts.append(pb.Packet(remot_intf_id=w, frame=f))
        per_wire[w].append(f)
    blob = pb.PacketBatch(packets=pkts).SerializeToString()
    groups = list(daemon._bulk_groups(blob, want_segs=True))
    assert sorted(w for w, _g in groups) == sorted(
        w for w in wids if per_wire[w])
    total = 0
    for wid, seg in groups:
        assert type(seg) is FrameSeg
        assert seg.materialize() == per_wire[wid]  # order preserved
        total += len(seg)
    assert total == 60
    # pointer arrays line up with the materialized bytes
    for wid, seg in groups:
        ptrs = seg.ptrs()
        lens = seg.win_lens()
        base = seg.base_addr()
        for j, f in enumerate(seg.materialize()):
            off = int(ptrs[j]) - base
            assert blob[off:off + int(lens[j])] == f


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_segment_path_fuzz_against_frame_oracle(seed):
    """Randomized bulk traffic through the segment pipeline vs a
    frame-level oracle: arbitrary frame sizes (including empty),
    arbitrary per-message wire interleavings, random drain budgets that
    split segments at odd boundaries — every frame must deliver exactly
    once, in per-wire FIFO order, with frame_stats counting each
    exactly once."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb

    rng = np.random.default_rng(seed)
    pairs = 3
    daemon, engine, win, wout = _daemon_with_pairs(pairs=pairs,
                                                   latency="1ms")
    plane = WireDataPlane(daemon, dt_us=1_000.0)

    expected: dict[int, list[bytes]] = {i: [] for i in range(pairs)}
    total = 0
    # several bulk messages, each interleaving wires with odd sizes —
    # incl. EMPTY frames (len 0 is a legal protobuf bytes field)
    for _m in range(6):
        pkts = []
        for _f in range(int(rng.integers(1, 120))):
            i = int(rng.integers(0, pairs))
            size = int(rng.choice([0, 1, 7, 60, 300, 1499]))
            f = bytes(rng.integers(0, 256, size, dtype=np.uint8))
            pkts.append(pb.Packet(remot_intf_id=win[i].wire_id, frame=f))
            expected[i].append(f)
            total += 1
        blob = pb.PacketBatch(packets=pkts).SerializeToString()
        for wid, group in daemon._bulk_groups(blob, want_segs=True):
            w = daemon.wires.get_by_id(wid)
            w.ingress.append(group)
    assert sum(len(w.ingress) for w in win) == total

    # random per-tick drain budgets force segment splits mid-window
    t = 30.0
    for k in range(60):
        plane.max_slots = int(rng.choice([1, 3, 17, 64, 1024]))
        t += 0.001
        plane.tick(now_s=t)
    plane.max_slots = 4096  # flush unconditionally, whatever the RNG left
    for _ in range(10):
        t += 0.002
        plane.tick(now_s=t)
    got = {i: list(wout[i].egress) for i in range(pairs)}
    for i in range(pairs):
        assert got[i] == expected[i], f"wire {i}: order or loss"
    assert plane.dropped == 0 and plane.tick_errors == 0
    if daemon.frame_stats:
        assert sum(daemon.frame_stats.values()) == total


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
def test_bulk_groups_malformed_blob_falls_back_to_protobuf():
    """Garbage that the native walker rejects goes to the protobuf
    runtime (the arbiter); true garbage raises, a valid-but-odd message
    still parses. want_segs must not change that contract."""
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=4)
    daemon = Daemon(engine)
    # truncated message: valid tag, length running past the end
    bad = b"\x0a\xff\xff\xff\x7f\x01\x02"
    with pytest.raises(Exception):
        list(daemon._bulk_groups(bad, want_segs=True))
    # an EMPTY PacketBatch is valid and yields nothing
    empty = pb.PacketBatch().SerializeToString()
    assert list(daemon._bulk_groups(empty, want_segs=True)) == []


def test_three_kernel_classes_interleave_under_live_load():
    """One plane, three wire classes — latency-only (elementwise
    kernel), plain rate limit (max-plus TBF kernel), rate+correlation
    (seq scan, seq_slots-capped) — all carrying traffic in the SAME
    ticks: every class delivers completely and in order, the seq class
    alone trips the holdback machinery, and counters account for every
    frame exactly once."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    spec = {
        "lat": LinkProperties(latency="2ms"),
        "tbf": LinkProperties(rate="1Gbit"),
        "seq": LinkProperties(rate="1Gbit", duplicate="0",
                              duplicate_corr="10"),
    }
    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    for j, (tag, props) in enumerate(spec.items(), start=1):
        a, b = f"{tag}a", f"{tag}b"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=j, properties=props)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=j, properties=props)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    plane.seq_slots = 16
    wires = {}
    for j, tag in enumerate(spec, start=1):
        wires[tag] = (
            daemon._add_wire(pb.WireDef(local_pod_name=f"{tag}a",
                                        kube_ns="default", link_uid=j,
                                        intf_name_in_pod="eth1")),
            daemon._add_wire(pb.WireDef(local_pod_name=f"{tag}b",
                                        kube_ns="default", link_uid=j,
                                        intf_name_in_pod="eth1")))
    N = 120
    frames = {tag: [bytes([j]) + bytes([i % 251]) * 199
                    for i in range(N)]
              for j, tag in enumerate(spec, start=1)}
    # bulk-ingest all three classes as segments in the same window
    for tag in spec:
        blob = _seg_for(wires[tag][0].wire_id, frames[tag])
        for wid, group in daemon._bulk_groups(blob, want_segs=True):
            daemon.wires.get_by_id(wid).ingress.append(group)

    t = 8.0
    shaped_first = plane.tick(now_s=t)
    # the seq wire is capped at 16 this tick; lat+tbf deliver all N
    # each and nothing drops at these rates — the count is exact
    assert shaped_first == 2 * N + plane.seq_slots
    assert wires["seq"][0].wire_id in plane._holdback
    assert wires["lat"][0].wire_id not in plane._holdback
    assert wires["tbf"][0].wire_id not in plane._holdback
    for k in range(40):
        t += 0.001
        plane.tick(now_s=t)
    for tag in spec:
        got = list(wires[tag][1].egress)
        assert got == frames[tag], f"{tag}: loss or reorder"
    assert plane.dropped == 0 and plane.tick_errors == 0
    assert not plane._holdback
    if daemon.frame_stats:
        assert sum(daemon.frame_stats.values()) == 3 * N


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
def test_segment_requeue_on_vanished_row_before_decide():
    """A SEGMENT drained in the same tick its row vanished (compact or
    delete between drain and the locked re-resolve) re-queues onto
    wire.ingress as entries — frames not yet counted or decided, so the
    exactly-once invariant allows the re-drain — and delivers fully
    once the link re-realizes, in order, counted once."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon, FrameSeg

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    link_ab = Link(local_intf="eth1", peer_intf="eth1", peer_pod="b",
                   uid=1, properties=LinkProperties(latency="1ms"))
    store.create(Topology(name="a", spec=TopologySpec(links=[link_ab])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=LinkProperties(latency="1ms"))])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1_000.0)
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="b",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    frames = [bytes([i]) * 80 for i in range(30)]
    for _wid, group in daemon._bulk_groups(_seg_for(wa.wire_id, frames),
                                           want_segs=True):
        wa.ingress.append(group)

    # delete the link AFTER the drain hands the segment to the tick but
    # BEFORE the locked row re-resolution (the compact()-race window)
    topo_a = store.get("default", "a")
    orig_drain = daemon.drain_ingress

    def hooked(*a, **k):
        out = orig_drain(*a, **k)
        if out:
            assert engine.del_links(topo_a, [link_ab])
        return out

    daemon.drain_ingress = hooked
    assert plane.tick(now_s=6.0) == 0
    daemon.drain_ingress = orig_drain
    # segment re-queued intact: frames stay 30, entries stay segments
    assert len(wa.ingress) == 30
    assert any(type(e) is FrameSeg for e in list(wa.ingress))
    if daemon.frame_stats:
        assert sum(daemon.frame_stats.values()) == 0  # not counted yet

    assert engine.add_links(topo_a, [link_ab])
    t = 6.0
    total = 0
    for k in range(1, 8):
        t += 0.001
        total += plane.tick(now_s=t)
    assert total == 30
    assert list(wb.egress) == frames
    if daemon.frame_stats:
        assert sum(daemon.frame_stats.values()) == 30  # exactly once


@pytest.mark.skipif(not native.have_native(), reason="no native lib")
def test_kdt_ext_materialize_matches_python_fallback():
    """The CPython slice_frames fast path and the pure-Python fallback
    produce identical frames for arbitrary windows, and the extension
    bounds-checks rather than reading outside the blob."""
    import kubedtn_tpu.wire.server as srv

    rng = np.random.default_rng(3)
    frames = [bytes(rng.integers(0, 256, int(rng.integers(0, 300)),
                                 dtype=np.uint8)) for _ in range(64)]
    from kubedtn_tpu.wire import proto as pb

    blob = pb.PacketBatch(packets=[
        pb.Packet(remot_intf_id=1, frame=f) for f in frames
    ]).SerializeToString()
    store = TopologyStore()
    daemon = srv.Daemon(SimEngine(store, capacity=4))
    (wid, seg), = daemon._bulk_groups(blob, want_segs=True)
    ext = srv._kdt_ext()
    if ext is None:
        pytest.skip("kdt_ext did not build (no Python headers) — "
                    "equivalence would compare the fallback to itself")
    for lo, hi in ((0, 64), (5, 40), (63, 64), (10, 10)):
        win = srv.FrameSeg(seg.blob, seg.offs, seg.lens, lo, hi)
        via_path = win.materialize()
        # force the fallback on an identical window
        saved, srv._KDT_EXT, srv._KDT_EXT_TRIED = srv._KDT_EXT, None, True
        try:
            via_python = win.materialize()
        finally:
            srv._KDT_EXT = saved
        assert via_path == via_python == frames[lo:hi]
    bad_offs = np.asarray([len(blob) + 5], np.uint64)
    with pytest.raises(ValueError):
        ext.slice_frames(blob, bad_offs,
                         np.asarray([10], np.uint64), 0, 1)
    with pytest.raises(ValueError):
        ext.slice_frames(blob, seg.offs, seg.lens, 0,
                         len(seg.offs) + 3)
