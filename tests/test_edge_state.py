"""Tests for EdgeState and batched link ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.ops import edge_state as es


def make_props_batch(prop_list):
    return jnp.stack([es.props_row(p.to_numeric()) for p in prop_list])


def test_init_state():
    s = es.init_state(64)
    assert s.capacity == 64
    assert int(s.num_active) == 0
    assert np.all(np.asarray(s.uid) == -1)


def test_apply_and_delete_links():
    s = es.init_state(16)
    props = make_props_batch([
        LinkProperties(latency="10ms", rate="100Mbit"),
        LinkProperties(loss="25"),
    ])
    rows = jnp.array([0, 1], dtype=jnp.int32)
    s = es.apply_links(
        s, rows,
        uids=jnp.array([1, 2], dtype=jnp.int32),
        src=jnp.array([0, 0], dtype=jnp.int32),
        dst=jnp.array([1, 2], dtype=jnp.int32),
        props=props,
        valid=jnp.array([True, True]),
    )
    assert int(s.num_active) == 2
    assert int(s.uid[0]) == 1 and int(s.uid[1]) == 2
    assert float(s.props[0, es.P_LATENCY_US]) == 10_000
    assert float(s.props[0, es.P_RATE_BPS]) == 100e6
    # bucket starts full: burst = max(rate/250, 5000) = 400_000
    assert float(s.tokens[0]) == pytest.approx(400_000)
    assert float(s.tokens[1]) == pytest.approx(5000)  # rate 0 -> floor

    s = es.delete_links(s, jnp.array([0], dtype=jnp.int32),
                        jnp.array([True]))
    assert int(s.num_active) == 1
    assert int(s.uid[0]) == -1
    assert float(s.props[0, es.P_LATENCY_US]) == 0


def test_padding_lanes_dropped():
    s = es.init_state(8)
    props = make_props_batch([LinkProperties(), LinkProperties(latency="1ms")])
    s = es.apply_links(
        s,
        rows=jnp.array([3, 0], dtype=jnp.int32),
        uids=jnp.array([7, 99], dtype=jnp.int32),
        src=jnp.zeros(2, jnp.int32),
        dst=jnp.zeros(2, jnp.int32),
        props=props,
        valid=jnp.array([True, False]),  # second lane is padding
    )
    assert int(s.num_active) == 1
    assert int(s.uid[3]) == 7
    assert int(s.uid[0]) == -1  # padding lane did not write


def test_update_links_resets_shaping_state():
    s = es.init_state(8)
    props = make_props_batch([LinkProperties(latency="10ms", rate="1Gbit")])
    rows = jnp.array([2], dtype=jnp.int32)
    ok = jnp.array([True])
    s = es.apply_links(s, rows, jnp.array([5], jnp.int32),
                       jnp.zeros(1, jnp.int32), jnp.ones(1, jnp.int32),
                       props, ok)
    # dirty the shaping state
    s = s.__class__(**{**{f: getattr(s, f) for f in (
        "uid", "src", "dst", "active", "props", "t_last", "backlog_until")},
        "tokens": s.tokens.at[2].set(1.0),
        "corr": s.corr.at[2].set(0.5),
        "pkt_count": s.pkt_count.at[2].set(42)})

    new_props = make_props_batch([LinkProperties(latency="50ms", rate="20Mbit")])
    s = es.update_links(s, rows, new_props, ok)
    assert float(s.props[2, es.P_LATENCY_US]) == 50_000
    assert float(s.tokens[2]) == pytest.approx(80_000)  # 20e6/250
    assert float(s.corr[2, 0]) == 0.0
    assert int(s.pkt_count[2]) == 0
    assert int(s.uid[2]) == 5  # identity untouched


def test_grow_state_preserves_rows():
    s = es.init_state(4)
    props = make_props_batch([LinkProperties(latency="10ms")])
    s = es.apply_links(s, jnp.array([1], jnp.int32), jnp.array([9], jnp.int32),
                       jnp.zeros(1, jnp.int32), jnp.ones(1, jnp.int32),
                       props, jnp.array([True]))
    g = es.grow_state(s, 16)
    assert g.capacity == 16
    assert int(g.uid[1]) == 9
    assert float(g.props[1, es.P_LATENCY_US]) == 10_000
    assert int(g.num_active) == 1


def test_no_recompile_on_same_shapes():
    s = es.init_state(32)
    props = make_props_batch([LinkProperties(latency="5ms")] * 4)
    rows = jnp.arange(4, dtype=jnp.int32)
    ok = jnp.ones(4, dtype=bool)
    uids = jnp.arange(4, dtype=jnp.int32)
    zeros = jnp.zeros(4, jnp.int32)
    with jax.log_compiles(False):
        s = es.apply_links(s, rows, uids, zeros, zeros, props, ok)
        n0 = es.apply_links._cache_size()
        s = es.apply_links(s, rows + 4, uids + 4, zeros, zeros, props, ok)
        assert es.apply_links._cache_size() == n0


def test_update_links_empty_batch_noop():
    import jax.numpy as jnp

    st = es.init_state(8)
    out = es.update_links(st, jnp.zeros((0,), jnp.int32),
                          jnp.zeros((0, es.NPROP), jnp.float32),
                          jnp.zeros((0,), bool))
    assert out.capacity == 8


class TestContiguousUpdate:
    """update_links(contiguous=True) — the dynamic-slice streaming path —
    must be bit-identical to the general formulation."""

    def _mk(self, E=64, B=16, seed=0):
        import numpy as np

        rng = np.random.default_rng(seed)
        state = es.init_state(E)
        # make every row active with random props/dynamics so resets and
        # preserved lanes are both observable
        rows0 = jnp.arange(E, dtype=jnp.int32)
        state = es.apply_links(
            state, rows0, rows0, rows0, rows0,
            jnp.asarray(rng.random((E, es.NPROP), np.float32)),
            jnp.ones((E,), bool))
        props = jnp.asarray(rng.random((B, es.NPROP), np.float32) * 1e6)
        return state, props

    def _clone(self, st):
        return jax.tree.map(lambda x: x.copy(), st)

    def assert_equal(self, a, b):
        import numpy as np

        for name in ("props", "tokens", "corr", "pkt_count",
                     "backlog_until", "uid", "active"):
            av, bv = np.asarray(getattr(a, name)), np.asarray(
                getattr(b, name))
            assert np.array_equal(av, bv), name

    def test_matches_general_path_full_valid(self):
        state, props = self._mk()
        rows = jnp.arange(8, 24, dtype=jnp.int32)
        valid = jnp.ones((16,), bool)
        ref = es.update_links(self._clone(state), rows, props, valid)
        got = es.update_links(self._clone(state), rows, props, valid,
                              True)
        self.assert_equal(ref, got)

    def test_matches_general_path_with_padding(self):
        import numpy as np

        state, props = self._mk(B=16)
        # 11 real lanes + 5 padding lanes (valid False, garbage rows)
        rows = np.arange(40, 56, dtype=np.int32)
        rows[11:] = 0  # pad garbage
        valid = np.zeros((16,), bool)
        valid[:11] = True
        ref = es.update_links(self._clone(state), jnp.asarray(rows),
                              props, jnp.asarray(valid))
        got = es.update_links(self._clone(state), jnp.asarray(rows),
                              props, jnp.asarray(valid), True)
        self.assert_equal(ref, got)

    def test_window_detection(self):
        import numpy as np

        cw = es.contiguous_window
        r = np.arange(8, 24, dtype=np.int32)
        v = np.ones((16,), bool)
        assert cw(r, v, 64)
        assert not cw(r, v, 20)            # window out of bounds
        r2 = r.copy(); r2[5] = 99
        assert not cw(r2, v, 64)           # hole
        v2 = v.copy(); v2[5] = False       # hole only in a padding lane
        assert cw(r2, v2, 64)
        assert not cw(r, np.zeros((16,), bool), 64)  # first lane invalid
        assert not cw(np.array([], np.int32), np.array([], bool), 64)

    def test_engine_flush_uses_contiguous_when_possible(self, monkeypatch):
        from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                           TopologySpec)
        from kubedtn_tpu.topology import SimEngine, TopologyStore
        from kubedtn_tpu.topology import engine as engine_mod

        # record the static `contiguous` arg actually handed to the kernel
        # — the end state alone can't distinguish the two paths
        seen: list[bool] = []
        real = engine_mod._update_links_nd

        def spy(state, rows, props, valid, contiguous=False):
            seen.append(contiguous)
            return real(state, rows, props, valid, contiguous)

        monkeypatch.setattr(engine_mod, "_update_links_nd", spy)

        store = TopologyStore()
        engine = SimEngine(store, capacity=64)
        links = [Link(local_intf=f"e{u}", peer_intf=f"p{u}",
                      peer_pod=f"physical/10.0.0.{u % 250}", uid=u,
                      properties=LinkProperties(latency="1ms"))
                 for u in range(1, 17)]
        t = Topology(name="c", spec=TopologySpec(links=links))
        store.create(t)
        engine.setup_pod("c")
        engine.flush()
        from dataclasses import replace as _rp
        new = [_rp(l, properties=LinkProperties(
            latency="7ms")) for l in links]
        engine.update_links(t, new)
        engine.flush()
        assert seen == [True], f"contiguous path not taken: {seen}"
        for u in range(1, 17):
            assert engine.link_row("default/c", u)["latency_us"] == 7000.0
