"""Fleet supervisor — plane health, automated evacuation, rolling
upgrades (ISSUE 14).

The headline pins:

- ACCEPTANCE: kill -9 of a loaded plane under multi-tenant traffic,
  mid-migration → the supervisor detects death over real gRPC health
  probes, evacuates with NO operator action, the restored rows are
  byte-identical to the last crash-consistent capture, and the
  failover accounting is EXACT (fed == delivered_src + delivered_dst
  + reported_lost, mismatch gauge 0) — `scenarios.plane_failover`.
- `kdt fleet upgrade` across two real gRPC daemons with live runners:
  cordon → drain via live migration → restart on the same port →
  health-verify → refill, ZERO frame loss —
  `scenarios.fleet_rolling_upgrade`.
- The suspicion state machine's hysteresis: suspect needs consecutive
  failures, dead needs more consecutive HARD failures, a degraded
  (answering) plane can never be declared dead, recovery needs
  consecutive clean probes, dead is final until `mark_restarted`.
- The placement ledger's crash discipline (journal `.prev`
  resolution) and the scoring policy's determinism/no-oscillation.
- `save_live` (the autosave): barrier-consistent capture of a RUNNING
  plane byte-identical to a stopped save; queued ingress + wires +
  counters now ride the checkpoint.
- Orphaned migration journals auto-resume on supervisor attach;
  rolled-back records stay refused.
- Local.Health / FleetStatus RPCs and the grpc.health.v1 handler
  reporting NOT_SERVING from real plane state.
"""

import tempfile
import threading
import time

import numpy as np
import pytest

from kubedtn_tpu import checkpoint
from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.chaos import ChaosError, ChaosInjector
from kubedtn_tpu.federation import (FederationController,
                                    MigrationStats, PlaneHandle)
from kubedtn_tpu.federation import journal as fjournal
from kubedtn_tpu.federation.placement import (PlacementLedger,
                                              choose_plane,
                                              plane_score,
                                              rebalance_plan)
from kubedtn_tpu.federation.supervisor import (DEAD, HEALTHY, SUSPECT,
                                               FleetStats,
                                               FleetSupervisor)
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.tenancy import TenantRegistry
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.server import Daemon

pytestmark = pytest.mark.fleet

PAIRS = 1
DT = 0.002


def _build_plane(tenants, addr, seed=0):
    """One in-process plane hosting `tenants` (ns → base uid)."""
    store = TopologyStore()
    engine = SimEngine(store, capacity=64, node_ip=addr)
    registry = TenantRegistry(engine)
    props = LinkProperties(latency="2ms")
    for ns, base in tenants.items():
        registry.create(ns)
        for i in range(PAIRS):
            uid = base + i + 1
            a, b = f"{ns}-a{i}", f"{ns}-b{i}"
            for name, peer in ((a, b), (b, a)):
                store.create(Topology(name=name, namespace=ns,
                                      spec=TopologySpec(links=[
                    Link(local_intf="eth1", peer_intf="eth1",
                         peer_pod=peer, uid=uid, properties=props)])))
                engine.setup_pod(name, ns)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=2_000.0, seed=seed)
    plane.pipeline_explicit_clock = True
    plane.attach_tenancy(registry)
    for ns, base in tenants.items():
        for i in range(PAIRS):
            uid = base + i + 1
            for side in ("a", "b"):
                daemon._add_wire(pb.WireDef(
                    local_pod_name=f"{ns}-{side}{i}", kube_ns=ns,
                    link_uid=uid, intf_name_in_pod="eth1"))
    return daemon, plane, registry, store, engine


def _two_plane_fleet(tmp, chaos=None, ck_a=None, **sup_kw):
    d_a, p_a, r_a, s_a, e_a = _build_plane({"t1": 0}, "10.0.0.1")
    d_b, p_b, r_b, s_b, e_b = _build_plane({"bg": PAIRS}, "10.0.0.2")
    stats = MigrationStats()
    fed = FederationController(f"{tmp}/journal", stats=stats,
                               chaos=chaos)
    fed.register(PlaneHandle("A", d_a, p_a, r_a, checkpoint_dir=ck_a))
    fed.register(PlaneHandle("B", d_b, p_b, r_b))
    sup = FleetSupervisor(fed, f"{tmp}/ledger", chaos=chaos,
                          **sup_kw).attach()
    return {"A": (d_a, p_a, r_a, s_a, e_a),
            "B": (d_b, p_b, r_b, s_b, e_b),
            "fed": fed, "sup": sup, "stats": stats}


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# -- suspicion state machine -------------------------------------------

def test_suspicion_hysteresis_hard_failures():
    tmp = tempfile.mkdtemp()
    chaos = ChaosInjector()
    f = _two_plane_fleet(tmp, chaos=chaos, suspect_after=2,
                         dead_after=4, healthy_after=2)
    sup = f["sup"]

    def state(name):
        return sup.status()["planes"][0 if name == "A" else 1]["state"]

    # one failure: still healthy (hysteresis)
    chaos.fail_probes("A", 1)
    sup.sweep()
    assert state("A") == HEALTHY
    # recovery resets the count: two MORE failures needed for suspect
    sup.sweep()
    chaos.fail_probes("A", 2)
    sup.sweep()
    sup.sweep()
    assert state("A") == SUSPECT
    # one clean probe does NOT clear suspicion...
    sup.sweep()
    assert state("A") == SUSPECT
    # ...the second consecutive one does
    sup.sweep()
    assert state("A") == HEALTHY
    # dead needs dead_after CONSECUTIVE hard failures
    chaos.fail_probes("A", 4)
    transitions = {}
    for _ in range(4):
        transitions.update(sup.sweep())
    assert state("A") == DEAD
    assert transitions["A"] == DEAD
    # dead is final: clean probes do not resurrect
    sup.sweep()
    assert state("A") == DEAD
    # ...until an explicit re-admission
    sup.mark_restarted("A")
    sup.sweep()
    assert state("A") == HEALTHY
    assert f["sup"].stats.snapshot()["transitions"][SUSPECT] >= 1


def test_degraded_plane_suspect_never_dead():
    """A plane that ANSWERS its probe but reports serving=False (bottom
    ladder rung) turns suspect — and can never be declared dead: a
    responding plane still owns its state."""
    tmp = tempfile.mkdtemp()
    f = _two_plane_fleet(tmp, suspect_after=2, dead_after=3,
                         healthy_after=2)
    sup = f["sup"]
    _d_a, p_a, *_rest = f["A"]
    p_a.force_degrade(2)
    for _ in range(10):
        sup.sweep()
    st = {p["name"]: p["state"] for p in sup.status()["planes"]}
    assert st["A"] == SUSPECT
    # recovery: promote back, clean probes clear suspicion
    p_a.force_degrade(0)
    sup.sweep()
    sup.sweep()
    st = {p["name"]: p["state"] for p in sup.status()["planes"]}
    assert st["A"] == HEALTHY


# -- placement ---------------------------------------------------------

def test_ledger_journal_crash_discipline(tmp_path):
    root = str(tmp_path / "ledger")
    led = PlacementLedger(root)
    led.assign("t1", "A", qos="gold")
    led.assign("t2", "B", qos="bronze")
    led.cordon("B")
    # crash between save_record's two renames: current generation torn,
    # `.prev` holds the previous complete one
    import os
    import shutil

    cur = fjournal.record_dir(root, "placement")
    shutil.copytree(cur, cur + ".prev")
    with open(os.path.join(cur, "manifest.json"), "w") as fh:
        fh.write('{"torn')
    led2 = PlacementLedger(root)
    assert led2.placements() == {"t1": "A", "t2": "B"}
    assert led2.cordoned() == {"B"}
    assert led2.qos_of("t2") == "bronze"
    # both generations gone: starts empty, loudly (logged), not fatal
    shutil.rmtree(cur + ".prev")
    led3 = PlacementLedger(root)
    assert led3.placements() == {}


def test_placement_policy_deterministic_and_stable():
    healths = {
        "A": {"capacity": 128, "headroom_rows": 120, "serving": True,
              "degrade_level": 0, "backlog": 0},
        "B": {"capacity": 128, "headroom_rows": 16, "serving": True,
              "degrade_level": 0, "backlog": 0},
        "C": {"capacity": 128, "headroom_rows": 120, "serving": True,
              "degrade_level": 1, "backlog": 0},
    }
    qos = {"t1": "gold", "t2": "bronze", "t3": "gold"}.get
    # headroom dominates; the degraded twin of A loses; ties break by
    # name (deterministic)
    assert choose_plane(healths, {}, qos) == "A"
    assert plane_score(healths["A"], 0.0) > plane_score(healths["C"],
                                                        0.0)
    # a full plane rebalances onto the empty one...
    placed = {"B": ["t1", "t2", "t3"], "A": [], "C": []}
    moves = rebalance_plan(healths, placed, qos)
    assert moves, "overloaded plane should shed tenants"
    assert all(dst == "A" or dst == "C" for _t, _s, dst in moves)
    # ...and the plan is stable: applying it then re-planning with the
    # SAME healths moves nothing back (no oscillation)
    placed2 = {p: list(ts) for p, ts in placed.items()}
    for t, s, d in moves:
        placed2[s].remove(t)
        placed2.setdefault(d, []).append(t)
    assert rebalance_plan(healths, placed2, qos) == []
    # cordoned planes are never targets
    moves3 = rebalance_plan(healths, placed, qos, exclude={"A", "C"})
    assert moves3 == []


# -- autosave (save_live) ----------------------------------------------

def _feed_and_tick(daemon, plane, ns, base, ticks, fpt=3, k0=0):
    k = k0
    for _ in range(ticks):
        k += 1
        for i in range(PAIRS):
            w = daemon.wires.get_by_key(f"{ns}/{ns}-a{i}", base + i + 1)
            for _ in range(fpt):
                w.ingress.append(b"x" * 64)
        plane.tick(now_s=100.0 + k * DT)
    return k


def test_save_live_matches_stopped_save(tmp_path):
    d, p, _r, s, e = _build_plane({"t1": 0}, "10.0.0.1")
    k = _feed_and_tick(d, p, "t1", 0, 10)
    for _ in range(10):
        k += 1
        p.tick(now_s=100.0 + k * DT)
    p.flush()
    ck_live = str(tmp_path / "live")
    ck_stop = str(tmp_path / "stop")
    # live save: barrier-consistent capture while the plane COULD tick
    checkpoint.save_live(ck_live, s, e, p)
    # stopped save of the same state
    checkpoint.save(ck_stop, s, e, dataplane=p)
    za = np.load(str(tmp_path / "live" / "edge_state.npz"))
    zb = np.load(str(tmp_path / "stop" / "edge_state.npz"))
    for name in za.files:
        assert np.array_equal(za[name], zb[name]), name
    _s2, e2 = checkpoint.load(ck_live)
    assert e2._rows == e._rows
    # the plane section + counters + wires sections landed
    assert checkpoint.plane_meta(ck_live)["has_counters"]
    cnt = checkpoint.load_plane_counters(ck_live)
    assert float(cnt["rx_packets"].sum()) > 0
    d2 = Daemon(e2)
    assert checkpoint.load_wires(ck_live, d2) == 2 * PAIRS


def test_save_refuses_running_plane_points_at_save_live():
    d, p, _r, s, e = _build_plane({"t1": 0}, "10.0.0.1")
    p._thread = threading.Thread(target=lambda: time.sleep(0.2))
    p._thread.start()
    try:
        with pytest.raises(RuntimeError, match="save_live"):
            checkpoint.save("/tmp/nope", s, e, dataplane=p)
    finally:
        p._thread.join()
        p._thread = None


def test_autosaver_loop(tmp_path):
    d, p, _r, s, e = _build_plane({"t1": 0}, "10.0.0.1")
    _feed_and_tick(d, p, "t1", 0, 5)
    auto = checkpoint.Autosaver(str(tmp_path / "ck"), s, e, p,
                                interval_s=0.05)
    auto.start()
    time.sleep(0.3)
    auto.stop()
    assert auto.saves >= 2
    assert auto.errors == 0
    _s2, e2 = checkpoint.load(str(tmp_path / "ck"))
    assert e2._rows == e._rows


def test_ingress_checkpoint_roundtrip(tmp_path):
    """Frames accepted but not yet drained survive a restart: the
    checkpoint carries wire-ingress queues, and consume removes both
    frame files so a crash can't re-deliver them."""
    import os

    d, p, _r, s, e = _build_plane({"t1": 0}, "10.0.0.1")
    k = _feed_and_tick(d, p, "t1", 0, 5)
    w = d.wires.get_by_key("t1/t1-a0", 1)
    for j in range(7):
        w.ingress.append(bytes([j]) * 64)
    ck = str(tmp_path / "ck")
    checkpoint.save(ck, s, e, dataplane=p)
    entries = checkpoint.read_ingress_entries(ck)
    assert len(entries) == 7
    # restore into a fresh daemon: wires first, then their queues
    _s2, e2 = checkpoint.load(ck)
    d2 = Daemon(e2)
    checkpoint.load_wires(ck, d2)
    assert checkpoint.load_ingress(ck, d2) == 7
    w2 = d2.wires.get_by_key("t1/t1-a0", 1)
    assert list(w2.ingress) == [bytes([j]) * 64 for j in range(7)]
    checkpoint.consume_pending(ck)
    assert not os.path.exists(os.path.join(ck, "wire_ingress.npz"))
    assert checkpoint.read_ingress_entries(ck) == []


# -- evacuation + failover accounting (ACCEPTANCE) ---------------------

def test_kill9_evacuation_acceptance():
    """THE acceptance pin: SIGKILL a loaded plane under multi-tenant
    traffic mid-migration → tenants re-placed on survivors with NO
    operator action, restored state byte-identical to the capture,
    total accounting exact (fed == delivered_src + delivered_dst +
    reported_lost, mismatch gauge 0). The chaos scenario IS the drive;
    its verdict is the contract."""
    from kubedtn_tpu.scenarios import plane_failover

    r = plane_failover(pairs=2, warm_ticks=20)
    assert r["restored_rows_byte_identical"]
    assert r["evacuation"]["survivor"] == "B"
    assert r["evacuation"]["source"] == "journal-fork"
    acct = r["accounting"]
    assert acct["mismatch"] == 0.0
    assert acct["reported_lost"] == r["gap_frames"] > 0
    assert r["fed"] == (acct["delivered_src"] + acct["delivered_dst"]
                        + acct["reported_lost"])
    assert r["delivered"] == acct["delivered_src"] \
        + acct["delivered_dst"]
    assert r["accounting_mismatch_gauge"] == 0.0
    assert r["in_guardrails"], r


def test_evacuation_restores_pending_and_ingress(tmp_path):
    """A checkpoint taken with frames IN FLIGHT (delay line) and
    QUEUED (ingress) hands both to the survivor: in-flight frames
    complete their remaining delay there, queued frames drain on its
    first tick — nothing silently vanishes with the dead plane."""
    tmp = str(tmp_path)
    ck_a = f"{tmp}/ckA"
    chaos = ChaosInjector()
    f = _two_plane_fleet(tmp, chaos=chaos, ck_a=ck_a,
                         suspect_after=1, dead_after=2)
    d_a, p_a, _r_a, s_a, e_a = f["A"]
    d_b, p_b, r_b, _s_b, _e_b = f["B"]
    sup = f["sup"]
    # warm B's clock so restored deadlines land on its timeline
    k = _feed_and_tick(d_b, p_b, "bg", PAIRS, 3, fpt=1)
    k = _feed_and_tick(d_a, p_a, "t1", 0, 3, fpt=2, k0=k)
    # one tick's frames are now IN the delay line (2ms latency at 2ms
    # ticks: not yet due); more frames sit QUEUED
    w = d_a.wires.get_by_key("t1/t1-a0", 1)
    in_flight = len(p_a.export_pending())
    for _ in range(4):
        w.ingress.append(b"Q" * 64)
    checkpoint.save_live(ck_a, s_a, e_a, p_a)
    chaos.kill_plane(f["fed"].handle("A"))
    for _ in range(4):
        sup.sweep()
    ev = sup.evacuations()[-1]["tenants"]["t1"]
    assert ev["survivor"] == "B"
    assert ev["pending_restored"] == in_flight > 0
    assert ev["ingress_restored"] == 4
    # the survivor delivers them: queued frames drain + in-flight
    # frames complete their REMAINING delay on B's clock
    got = 0
    for _ in range(30):
        k += 1
        p_b.tick(now_s=100.0 + k * DT)
    p_b.flush()
    k += 5000
    p_b.tick(now_s=100.0 + k * DT)
    for i in range(PAIRS):
        wb = d_b.wires.get_by_key(f"t1/t1-b{i}", i + 1)
        wa = d_b.wires.get_by_key(f"t1/t1-a{i}", i + 1)
        for wx in (wb, wa):
            if wx is not None:
                got += len(wx.egress)
    assert got == in_flight + 4
    assert r_b.rows_of("t1").size == 2 * PAIRS


def test_evacuation_retries_until_a_survivor_is_healthy(tmp_path):
    """A plane dying while the only survivor is itself SUSPECT must
    not strand its tenants: the failed evacuation is retried on later
    sweeps and lands once the survivor recovers — and the retry never
    re-restores tenants that already made it across."""
    tmp = str(tmp_path)
    ck_a = f"{tmp}/ckA"
    chaos = ChaosInjector()
    f = _two_plane_fleet(tmp, chaos=chaos, ck_a=ck_a,
                         suspect_after=1, dead_after=2,
                         healthy_after=1)
    d_a, p_a, _r_a, s_a, e_a = f["A"]
    _d_b, _p_b, r_b, *_rest = f["B"]
    sup = f["sup"]
    _feed_and_tick(d_a, p_a, "t1", 0, 3)
    checkpoint.save_live(ck_a, s_a, e_a, p_a)
    # B turns suspect, THEN A dies: no healthy survivor at death time
    chaos.fail_probes("B", 1)
    chaos.kill_plane(f["fed"].handle("A"))
    for _ in range(3):
        sup.sweep()
    first = next(r for r in sup.evacuations() if r["plane"] == "A")
    assert first["tenants"]["t1"].get("survivor") is None
    # B recovers; the sweep loop retries A's evacuation by itself
    for _ in range(4):
        sup.sweep()
    assert sup.ledger.get("t1") == "B"
    assert r_b.rows_of("t1").size == 2 * PAIRS
    done = [r for r in sup.evacuations() if r["plane"] == "A"
            and r["tenants"].get("t1", {}).get("survivor") == "B"]
    assert done, "retry should have landed the tenant on B"
    # latched complete: further sweeps do not re-evacuate
    n = len(sup.evacuations())
    sup.sweep()
    assert len(sup.evacuations()) == n


def test_evacuation_without_checkpoint_reports_loss(tmp_path):
    """No checkpoint dir configured → the tenant cannot be restored;
    the evacuation record says so LOUDLY instead of pretending."""
    tmp = str(tmp_path)
    chaos = ChaosInjector()
    f = _two_plane_fleet(tmp, chaos=chaos, suspect_after=1,
                         dead_after=2)
    sup = f["sup"]
    chaos.kill_plane(f["fed"].handle("A"))
    for _ in range(3):
        sup.sweep()
    ev = sup.evacuations()[-1]["tenants"]["t1"]
    assert ev["survivor"] is None
    assert "no durable state" in ev["error"] \
        or "no checkpoint" in ev["error"]


def test_post_cutover_dst_death_rolls_forward(tmp_path):
    """The other half of the crash contract: a migration that COMMITTED
    cutover and then lost its dst plane rolls FORWARD — the cut-over
    slice evacuates from the journal fork onto a survivor (here: back
    onto the alive src plane, the only one left), the src-side RELEASE
    is finished, and the record closes as done."""
    tmp = str(tmp_path)
    chaos = ChaosInjector()
    f = _two_plane_fleet(tmp, chaos=chaos, suspect_after=1,
                         dead_after=2)
    fed, sup = f["fed"], f["sup"]
    d_a, p_a, r_a, *_rest = f["A"]
    d_b, p_b, *_rest_b = f["B"]

    def settle():
        p_a.tick(now_s=200.0)
        p_b.tick(now_s=200.0)

    # crash at RECONCILE: cutover committed, release not yet run
    chaos.fail_migration_step("reconcile")
    with pytest.raises(ChaosError):
        fed.migrate("t1", "A", "B", settle=settle)
    mid = fed.status(tenant="t1")[-1]["migration_id"]
    assert "cutover" in fjournal.load_record_meta(
        f"{tmp}/journal", mid)["steps_done"]
    chaos.kill_plane(fed.handle("B"))
    for _ in range(3):
        sup.sweep()
    meta = fjournal.load_record_meta(f"{tmp}/journal", mid)
    assert meta["state"] == "done"
    assert meta["failover"] == "B"
    assert "release" in meta["steps_done"]  # src slice freed
    ev = sup.evacuations()[-1]["tenants"]["t1"]
    assert ev["survivor"] == "A"
    assert ev["source"] == "journal-fork"
    # the tenant serves again on A: rows re-adopted, ledger agrees
    assert r_a.rows_of("t1").size == 2 * PAIRS
    assert sup.ledger.get("t1") == "A"


# -- orphaned migration journals ---------------------------------------

def test_orphan_resume_on_attach(tmp_path):
    tmp = str(tmp_path)
    chaos = ChaosInjector()
    f = _two_plane_fleet(tmp, chaos=chaos)
    fed = f["fed"]
    d_a, p_a, *_rest = f["A"]

    def settle():
        p_a.tick(now_s=200.0)
        f["B"][1].tick(now_s=200.0)

    chaos.fail_migration_step("restore")
    with pytest.raises(ChaosError):
        fed.migrate("t1", "A", "B", settle=settle)
    mid = fed.status(tenant="t1")[-1]["migration_id"]
    assert fjournal.load_record_meta(f"{tmp}/journal",
                                     mid)["state"] == "running"
    # a FRESH supervisor over the same journal auto-resumes it
    sup2 = FleetSupervisor(fed, f"{tmp}/ledger2")
    fed.coordinator(mid).settle = settle
    sup2.attach()  # attach() resumes the orphan itself
    assert fjournal.load_record_meta(f"{tmp}/journal",
                                     mid)["state"] == "done"
    assert sup2.stats.snapshot()["orphans_resumed"] >= 1
    # the completed move landed in the ledger via the placement hook
    assert sup2.ledger.get("t1") == "B"


def test_orphan_resume_refuses_rolled_back(tmp_path):
    tmp = str(tmp_path)
    chaos = ChaosInjector()
    f = _two_plane_fleet(tmp, chaos=chaos)
    fed = f["fed"]
    chaos.fail_migration_step("fork")
    with pytest.raises(ChaosError):
        fed.migrate("t1", "A", "B")
    mid = fed.status(tenant="t1")[-1]["migration_id"]
    fed.coordinator(mid).rollback()
    sup2 = FleetSupervisor(fed, f"{tmp}/ledger2").attach()
    assert sup2.stats.snapshot()["orphans_resumed"] == 0
    assert fjournal.load_record_meta(
        f"{tmp}/journal", mid)["state"] == "rolled_back"


# -- health surfaces ---------------------------------------------------

def test_health_rpc_reflects_ladder_and_tenants():
    d, p, r, _s, _e = _build_plane({"t1": 0}, "10.0.0.1")
    resp = d.Health(pb.HealthRequest(), None)
    assert resp.ok and resp.serving and not resp.running
    assert resp.tenants == 1
    assert resp.capacity > 0
    assert resp.headroom_rows == resp.capacity - resp.active_rows
    p.force_degrade(2)
    resp = d.Health(pb.HealthRequest(), None)
    assert resp.ok and not resp.serving
    assert resp.degrade_level == 2
    p.force_degrade(0)
    assert d.Health(pb.HealthRequest(), None).serving


def test_health_rpc_by_plane_name(tmp_path):
    f = _two_plane_fleet(str(tmp_path))
    d_a = f["A"][0]
    resp = d_a.Health(pb.HealthRequest(plane="B"), None)
    assert resp.ok and resp.node == "10.0.0.2"
    resp = d_a.Health(pb.HealthRequest(plane="nope"), None)
    assert not resp.ok


def test_grpc_health_v1_not_serving_when_degraded():
    """The generic grpc.health.v1 probe agrees with Local.Health:
    NOT_SERVING while the ladder sits at its bottom rung."""
    import grpc

    from kubedtn_tpu.wire.server import make_server

    d, p, _r, _s, _e = _build_plane({"t1": 0}, "10.0.0.1")
    server, port = make_server(d, port=0, host="127.0.0.1",
                               log_rpcs=False)
    server.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        check = channel.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=lambda m: m,
            response_deserializer=lambda b: b)
        assert check(b"") == b"\x08\x01"          # SERVING
        p.force_degrade(2)
        assert check(b"") == b"\x08\x02"          # NOT_SERVING
        p.force_degrade(0)
        assert check(b"") == b"\x08\x01"
        channel.close()
    finally:
        server.stop(0)


def test_fleet_status_rpc_and_metrics(tmp_path):
    from kubedtn_tpu.metrics.metrics import FleetStatsCollector

    f = _two_plane_fleet(str(tmp_path))
    sup, d_a = f["sup"], f["A"][0]
    sup.sweep()
    resp = d_a.FleetStatus(pb.FleetStatusRequest(), None)
    assert resp.ok
    assert sorted(p.name for p in resp.planes) == ["A", "B"]
    assert all(p.state == HEALTHY for p in resp.planes)
    assert {e.tenant: e.plane for e in resp.placements} == {
        "t1": "A", "bg": "B"}
    a = next(p for p in resp.planes if p.name == "A")
    assert a.health.ok and a.health.tenants == 1
    fams = {m.name for m in FleetStatsCollector(sup).collect()}
    for want in ("kubedtn_fleet_probes", "kubedtn_fleet_sweeps",
                 "kubedtn_fleet_planes", "kubedtn_fleet_evacuations",
                 "kubedtn_fleet_reported_lost",
                 "kubedtn_fleet_transitions",
                 "kubedtn_fleet_placements"):
        assert want in fams, want
    # a daemon without a supervisor answers ok=False, not an exception
    d_solo = _build_plane({"x": 0}, "10.0.0.9")[0]
    assert not d_solo.FleetStatus(pb.FleetStatusRequest(), None).ok
    assert not d_solo.FleetUpgrade(pb.FleetUpgradeRequest(), None).ok


def test_fleet_stats_snapshot_shape():
    s = FleetStats()
    s.add(probes=3, sweeps=1)
    s.add_transition(SUSPECT)
    s.set_reported_lost(7.0)
    snap = s.snapshot()
    assert snap["probes"] == 3
    assert snap["transitions"] == {SUSPECT: 1}
    assert snap["reported_lost"] == 7.0


# -- rolling upgrade (zero loss, tier-1 smoke) -------------------------

@pytest.mark.chaos
def test_rolling_upgrade_smoke():
    """<30s tier-1 smoke of the full `kdt fleet upgrade` choreography
    across two REAL gRPC daemons with live runners: both planes
    drained / restarted on their original port / health-verified /
    refilled, zero frame loss for every accepted frame, mismatch
    gauge 0."""
    from kubedtn_tpu.scenarios import fleet_rolling_upgrade

    r = fleet_rolling_upgrade(steady_s=0.4,
                              offered_frames_per_s=1_000)
    assert r["frames_lost"] == 0, r
    assert r["migrations"] == 4
    assert all(rep["restarted"] and rep["healthy"]
               and not rep["error"] for rep in r["reports"]), r
    assert r["accounting_mismatch_gauge"] == 0.0
    assert r["in_guardrails"], r


def test_rolling_upgrade_refuses_without_restarter(tmp_path):
    f = _two_plane_fleet(str(tmp_path))
    out = f["sup"].rolling_upgrade(planes=["A"])
    assert out["reports"][0]["error"].startswith("plane A has no")
    assert out["migrations"] == 0
    # nothing was cordoned or drained
    assert f["sup"].ledger.cordoned() == set()
    assert f["sup"].ledger.get("t1") == "A"
