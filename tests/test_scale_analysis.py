"""dtnscale: per-rule fixture self-tests, waiver/stale semantics, the
budget-file gate, the empirical probe smoke, and the clean-tree
tier-1 gate (writes ANALYSIS.json with the schema-v3 `scale`
section).

Each scost rule kind gets at least one triggering and one clean
fixture under tests/fixtures/dtnscale/ — parsed, never imported —
including the seeded O(capacity) loop injected into a tick-path
helper (tickwalk_bad)."""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

from kubedtn_tpu.analysis import (
    CallGraph,
    Project,
    default_root,
    run_suite,
    write_json,
)
from kubedtn_tpu.analysis.core import apply_waivers
from kubedtn_tpu.analysis.scale.bounds import run_scale_pass
from kubedtn_tpu.analysis.scale.entrypoints import (
    CLASS_CAPACITY,
    CLASS_ROWS,
    SCALE_ENTRIES,
)

FIXTURES = Path(__file__).parent / "fixtures" / "dtnscale"
REPO = default_root()


def run_fixture(name: str, qual: str, budget: str):
    project = Project(FIXTURES, packages=(name,))
    graph = CallGraph(project)
    entries = {"fx": {"budget": budget, "roots": ((name, qual),)}}
    findings, report = run_scale_pass(project, graph, entries=entries)
    return apply_waivers(project, findings), report


# ---- per-rule fixtures ------------------------------------------------

def test_seeded_tick_capacity_walk_killed():
    """The seeded O(capacity) loop in a tick-path helper fires under
    the tick budget."""
    f, rep = run_fixture("tickwalk_bad.py", "dispatch_inner",
                         CLASS_ROWS)
    assert len(f) == 1, [x.format() for x in f]
    assert "O(capacity)" in f[0].message
    assert "range(capacity)" in f[0].message
    assert rep["fx"]["inferred"] == CLASS_CAPACITY


def test_batch_scoped_tick_helper_silent():
    f, rep = run_fixture("tickwalk_clean.py", "dispatch_inner",
                         CLASS_ROWS)
    assert f == [], [x.format() for x in f]
    assert rep["fx"]["inferred"] == CLASS_ROWS


def test_range_materialize_killed_even_at_capacity_budget():
    f, _ = run_fixture("rangemat_bad.py", "compact", CLASS_CAPACITY)
    assert len(f) == 1
    assert "materializes an O(capacity) Python collection" \
        in f[0].message


def test_columnar_rebuild_silent():
    f, _ = run_fixture("rangemat_clean.py", "compact", CLASS_CAPACITY)
    assert f == [], [x.format() for x in f]


def test_freelist_scan_killed():
    f, _ = run_fixture("scan_bad.py", "reclaim", CLASS_CAPACITY)
    msgs = "\n".join(x.message for x in f)
    assert "<x> in _free" in msgs          # membership scan
    assert "_free.remove(...)" in msgs     # per-element remove
    assert len(f) == 2


def test_vectorized_reclaim_silent():
    f, _ = run_fixture("scan_clean.py", "reclaim", CLASS_CAPACITY)
    assert f == [], [x.format() for x in f]


def test_tenant_walk_killed_under_rows_budget():
    f, _ = run_fixture("tenantwalk_bad.py", "ensure_capacity",
                       CLASS_ROWS)
    assert len(f) == 1
    assert "O(tenants)" in f[0].message


def test_counter_read_silent():
    f, _ = run_fixture("tenantwalk_clean.py", "ensure_capacity",
                       CLASS_ROWS)
    assert f == [], [x.format() for x in f]


def test_nested_capacity_walk_killed_even_at_capacity_budget():
    f, _ = run_fixture("nested_bad.py", "rollback", CLASS_CAPACITY)
    assert len(f) == 1
    assert "superlinear" in f[0].message


def test_single_pass_reclaim_silent():
    f, _ = run_fixture("nested_clean.py", "rollback", CLASS_CAPACITY)
    assert f == [], [x.format() for x in f]


# ---- waiver + stale-waiver semantics ---------------------------------

def test_scost_waiver_marks_but_does_not_hide():
    f, _ = run_fixture("waivered.py", "rebuild_masks", CLASS_ROWS)
    assert len(f) == 1
    assert f[0].waived
    assert "slow path" in f[0].waiver_reason


def test_scost_waiver_not_stale_when_scale_off(tmp_path):
    """Without the scale layer, scost staleness is unjudgeable — the
    waiver must be left alone (same rule as --rules subset runs)."""
    p = tmp_path / "m.py"
    p.write_text('"""f."""\n'
                 "X = 1  # dtnlint: scost-ok(designated slow path)\n")
    _p, f = run_suite(root=tmp_path, packages=("m.py",))
    assert [x for x in f if x.rule == "waiver"] == [], \
        [x.format() for x in f]


def test_scost_waiver_stale_when_scale_on(tmp_path):
    p = tmp_path / "m.py"
    p.write_text('"""f."""\n'
                 "X = 1  # dtnlint: scost-ok(designated slow path)\n")
    _p, f = run_suite(root=tmp_path, packages=("m.py",), scale={})
    stale = [x for x in f if x.rule == "waiver"]
    assert len(stale) == 1
    assert "scost-ok" in stale[0].message


# ---- SCALE_BUDGET.json gate ------------------------------------------

def test_missing_budget_file_is_a_finding(tmp_path):
    from kubedtn_tpu.analysis.scale import budget

    findings = []
    status = budget.check_budget(tmp_path, findings)
    assert status["present"] is False
    assert len(findings) == 1
    assert "SCALE_BUDGET.json missing" in findings[0].message


def test_unbudgeted_entry_is_a_finding(tmp_path):
    from kubedtn_tpu.analysis.scale import budget

    doc = budget.write_budget(tmp_path, None)
    assert set(doc["entries"]) == set(SCALE_ENTRIES)
    # drop one entry: the gate names it
    doc["entries"].pop("compact")
    (tmp_path / budget.BUDGET_FILE).write_text(json.dumps(doc))
    findings = []
    budget.check_budget(tmp_path, findings)
    assert any("`compact` has no budget record" in f.message
               for f in findings)


def test_update_budgets_keeps_hand_edited_classes(tmp_path):
    from kubedtn_tpu.analysis.scale import budget

    doc = budget.write_budget(tmp_path, None)
    doc["entries"]["tick"] = "O(1)"  # a deliberate tightening
    (tmp_path / budget.BUDGET_FILE).write_text(json.dumps(doc))
    new = budget.write_budget(tmp_path, {"compact": 1.7})
    assert new["entries"]["tick"] == "O(1)"            # kept
    assert new["probe"]["max_slope"]["compact"] >= 1.7  # measured+margin


# ---- the empirical half ----------------------------------------------

def test_fit_slope_separates_flat_linear_quadratic():
    from kubedtn_tpu.analysis.scale.probe import fit_slope

    sizes = [1_000, 10_000, 100_000]
    assert abs(fit_slope(sizes, [0.01, 0.01, 0.01])) < 0.05
    assert 0.9 < fit_slope(sizes, [1e-3, 1e-2, 1e-1]) < 1.1
    assert 1.9 < fit_slope(sizes, [1e-4, 1e-2, 1.0]) < 2.1


def test_probe_slope_gate_fires_on_superlinear(tmp_path, monkeypatch):
    """A superlinear measured slope past the ceiling is a scost
    finding (the probe-drift gate), without paying a real probe."""
    from kubedtn_tpu.analysis.scale import budget, runner

    budget.write_budget(tmp_path, None)
    fake = {"sizes": [1000, 10000],
            "phases": {"compact": {"seconds": [0.01, 1.0],
                                   "slope": 2.0},
                       "alloc_churn": {"seconds": [0.01, 0.01],
                                       "slope": 0.0}}}
    monkeypatch.setattr("kubedtn_tpu.analysis.scale.probe.run_probe",
                        lambda sizes: dict(fake))
    findings, probe = runner.run_scale(tmp_path, sizes=[1000, 10000])
    assert len(findings) == 1
    assert "`compact`" in findings[0].message
    assert "superlinear" in findings[0].message


def test_probe_smoke_small_sizes():
    """The real probe at tiny sizes: every phase reports and the
    capacity-independent phases stay in the timer-noise regime
    (absolute bound — slope judgments at these sizes are noise; the
    10k/100k/1M slopes are bench.py's host_scale phase)."""
    from kubedtn_tpu.analysis.scale.probe import run_probe

    r = run_probe([256, 1024])
    assert set(r["phases"]) == {"alloc_churn", "drain_policy",
                                "stage_barrier", "compact",
                                "checkpoint_save"}
    for name in ("alloc_churn", "drain_policy", "stage_barrier"):
        # far under the 5ms judging floor even on a loaded host
        assert max(r["phases"][name]["seconds"]) < 0.05, (name, r)


# ---- the tier-1 gate: the tree itself is clean ------------------------

def test_tree_scale_clean_and_artifact_written():
    """Zero active scost findings on kubedtn_tpu/ with every
    configured entry root resolved, and the scale section lands in
    ANALYSIS.json (schema v3)."""
    scale_out: dict = {}
    _project, findings = run_suite(root=REPO, scale=scale_out)
    scost = [f for f in findings if f.rule == "scost"]
    active = [f for f in scost if not f.waived]
    assert active == [], "\n" + "\n".join(f.format() for f in active)
    # every configured root must resolve — a renamed entry function
    # silently shrinking a closure is exactly the drift this catches
    for name, rep in scale_out["entries"].items():
        assert rep["roots_resolved"] == rep["roots_configured"], \
            (name, rep)
    assert scale_out["budget"]["present"] is True
    assert scale_out["budget"]["missing_entries"] == []
    out = REPO / "ANALYSIS.json"
    ast_findings = [f for f in findings if f.rule != "scost"]
    scale_section = {
        "rules": ["scost"],
        "entries": scale_out["entries"],
        "budget": scale_out["budget"],
        "findings": [f.to_json() for f in scost],
        "summary": {"total": len(scost),
                    "unwaivered": len(active)},
    }
    write_json(out, ast_findings, REPO, scale=scale_section)
    doc = json.loads(out.read_text())
    assert doc["schema_version"] == 3
    assert doc["scale"]["summary"]["unwaivered"] == 0


def test_write_json_preserves_scale_section(tmp_path):
    out = tmp_path / "a.json"
    write_json(out, [], REPO, scale={"findings": [], "marker": 7})
    write_json(out, [], REPO)  # a scale-less writer
    doc = json.loads(out.read_text())
    assert doc["scale"]["marker"] == 7


def test_diff_keys_scale_layer(tmp_path):
    from kubedtn_tpu.analysis.diff import diff_docs

    old = {"schema_version": 2, "findings": []}
    new = {"schema_version": 3, "findings": [],
           "scale": {"findings": [
               {"rule": "scost", "path": "a.py", "line": 3,
                "message": "m", "waived": False}]}}
    d = diff_docs(old, new)
    assert len(d["new"]) == 1 and d["new"][0]["rule"] == "scost"


def test_cli_scale_exit_codes(tmp_path):
    """--scale on the real tree exits 0 with the scale section in the
    artifact; a root with no package and no budget file exits 1."""
    out = tmp_path / "a.json"
    r = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "-q", "--scale",
         "--probe-sizes", "128,256",
         "--root", str(REPO), "--json", str(out)],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["schema_version"] == 3
    assert doc["scale"]["summary"]["unwaivered"] == 0
    assert "probe" in doc["scale"]
    # a bare root: no SCALE_BUDGET.json → active scost finding → 1
    r2 = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "-q", "--scale",
         "--probe-sizes", "128,256", "--root", str(tmp_path)],
        capture_output=True, text=True, timeout=600)
    assert r2.returncode == 1, r2.stdout + r2.stderr
