"""Row compaction: defragmentation after churn (SURVEY §7 hard part (a)).

Heavy delete/add churn scatters rows across capacity (the allocator
recycles LIFO); compact() repacks the active set to [0, n) with one
device gather, the host registries follow, and the data plane's
cumulative counters move with their rows.
"""

import numpy as np

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, TopologySpec
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore


def _cluster(n_pods=8, uids_per=3):
    store = TopologyStore()
    engine = SimEngine(store, capacity=128)
    props = LinkProperties(latency="2ms")
    names = [f"c{i}" for i in range(n_pods)]
    specs = {p: [] for p in names}
    uid = 0
    for i, a in enumerate(names):
        b = names[(i + 1) % n_pods]
        for _ in range(uids_per):
            uid += 1
            specs[a].append(Link(local_intf=f"e{uid}a", peer_intf=f"e{uid}b",
                                 peer_pod=b, uid=uid, properties=props))
            specs[b].append(Link(local_intf=f"e{uid}b", peer_intf=f"e{uid}a",
                                 peer_pod=a, uid=uid, properties=props))
    for p in names:
        store.create(Topology(name=p, spec=TopologySpec(links=specs[p])))
    for p in names:
        engine.setup_pod(p)
    Reconciler(store, engine).drain()
    return store, engine, names


def _fragment(engine, names):
    """Destroy/re-setup alternating pods twice: each pod's rows end up
    scattered (the global set may stay dense — what churn breaks is the
    PER-TOPOLOGY consecutiveness the contiguous fast path needs)."""
    for _ in range(2):
        for p in names[::2]:
            engine.destroy_pod(p)
        for p in names[::2]:
            engine.setup_pod(p)


def _pod_rows(engine, pod_key):
    return np.sort(np.array([r for (k, _), r in engine._rows.items()
                             if k == pod_key]))


def _is_consecutive(rows):
    return len(rows) > 0 and (np.diff(rows) == 1).all()


def test_compact_preserves_links_and_properties():
    store, engine, names = _cluster()
    _fragment(engine, names)
    before = {k: engine.link_row(*k) for k in engine._rows}
    n = engine.num_active
    scattered = [p for p in names
                 if not _is_consecutive(_pod_rows(engine, f"default/{p}"))]
    assert scattered, "fragmentation premise failed"

    info = engine.compact()
    assert info["active"] == n and info["moved"] > 0
    # dense layout
    assert sorted(engine._rows.values()) == list(range(n))
    assert engine._row_owner == {r: k for k, r in engine._rows.items()}
    # device agreement: same active count, same per-link properties
    assert int(np.asarray(engine.state.active).sum()) == n
    for key, old in before.items():
        new = engine.link_row(*key)
        assert new["uid"] == old["uid"]
        assert new["latency_us"] == old["latency_us"]
    # shaped-row mirror follows the renumbering (all links are shaped)
    assert engine._shaped_rows == set(range(n))
    # the engine keeps working: ping across a compacted link
    p = engine.ping(names[0], names[1], uid=1)
    assert p["reachable"] and p["rtt_us"] == 4000.0


def test_compact_restores_contiguous_update_eligibility():
    store, engine, names = _cluster()
    _fragment(engine, names)
    # a whole-topology update batch (one pod's rows) is the unit that
    # must be consecutive for the streaming path
    frag_pod = next(p for p in names
                    if not _is_consecutive(_pod_rows(engine,
                                                     f"default/{p}")))
    rows = _pod_rows(engine, f"default/{frag_pod}")
    pad = np.zeros(16, np.int64)
    pad[:len(rows)] = rows
    valid = np.arange(16) < len(rows)
    assert not es.contiguous_window(pad, valid, engine.state.capacity)
    engine.compact()
    # compact orders rows by (pod_key, uid): every pod's block is
    # consecutive again
    for p in names:
        assert _is_consecutive(_pod_rows(engine, f"default/{p}")), p
    rows2 = _pod_rows(engine, f"default/{frag_pod}")
    pad2 = np.zeros(16, np.int64)
    pad2[:len(rows2)] = rows2
    assert es.contiguous_window(pad2, valid, engine.state.capacity)


def test_compact_moves_dataplane_counters():
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store, engine, names = _cluster()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1000.0)
    wa = daemon._add_wire(pb.WireDef(local_pod_name=names[0],
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    daemon._add_wire(pb.WireDef(local_pod_name=names[1], kube_ns="default",
                                link_uid=1, intf_name_in_pod="eth1"))
    daemon._frame_in(wa, b"z" * 90)
    t = 0.0
    for _ in range(10):
        plane.tick(now_s=t)
        t += 0.001
    old_row = engine.row_of(f"default/{names[0]}", 1)
    assert float(np.asarray(plane.counters.tx_packets)[old_row]) == 1.0

    _fragment(engine, names[2:])  # scatter other pods, keep names[0]
    engine.compact()
    new_row = engine.row_of(f"default/{names[0]}", 1)
    tx = np.asarray(plane.counters.tx_packets)
    assert float(tx[new_row]) == 1.0
    assert float(tx.sum()) == 1.0  # nothing duplicated or stranded


def test_compact_between_drain_and_snapshot_keeps_frames_on_their_link():
    """Regression for the drain/compact race: rows are re-resolved under
    the engine lock, so a compact() landing between the ingress drain and
    the snapshot must NOT shape a batch with another link's qdiscs or
    deliver it to the wrong pod."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=128)
    # fast link f0<->f1 (2ms) among slow 500ms links
    names = [f"f{i}" for i in range(6)]
    specs = {p: [] for p in names}
    uid = 0
    for i, a in enumerate(names):
        b = names[(i + 1) % len(names)]
        uid += 1
        props = LinkProperties(latency="2ms" if uid == 1 else "500ms")
        specs[a].append(Link(local_intf=f"e{uid}a", peer_intf=f"e{uid}b",
                             peer_pod=b, uid=uid, properties=props))
        specs[b].append(Link(local_intf=f"e{uid}b", peer_intf=f"e{uid}a",
                             peer_pod=a, uid=uid, properties=props))
    for p in names:
        store.create(Topology(name=p, spec=TopologySpec(links=specs[p])))
    for p in names:
        engine.setup_pod(p)
    Reconciler(store, engine).drain()
    # fragment so compact() actually renumbers
    for p in names[::2]:
        engine.destroy_pod(p)
    for p in names[::2]:
        engine.setup_pod(p)

    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1000.0)
    wa = daemon._add_wire(pb.WireDef(local_pod_name="f0",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="f1",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))

    # interpose: compact() fires right after the tick's ingress drain,
    # exactly inside the race window
    orig = daemon.drain_ingress
    fired = {"n": 0}

    def hooked(**kw):
        out = orig(**kw)
        if out and not fired["n"]:
            fired["n"] = 1
            engine.compact()
        return out

    daemon.drain_ingress = hooked

    frame = b"\xfa" * 80
    daemon._frame_in(wa, frame)
    t = 0.0
    for _ in range(10):   # 10ms of ticks: far less than the 500ms links
        plane.tick(now_s=t)
        t += 0.001
    assert fired["n"] == 1, "race window never exercised"
    # delivered to f1 (the 2ms link's peer), on 2ms timing — a stale-row
    # shaping would have applied a 500ms delay or misdelivered
    assert list(wb.egress) == [frame]
    for w in daemon.wires._by_id.values():
        if w not in (wa, wb):
            assert not w.egress, "frame misdelivered after compact"
