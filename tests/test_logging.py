"""Structured logging: KV formatter, gRPC request interceptor, and the
engine/reconciler per-action fields — the logrus/zap parity subsystem
(reference daemon/kubedtn/kubedtn.go:175-189 request/response
interceptors, common/context.go:11-29 field loggers, main.go:61-78 zap)."""

import io
import logging

import grpc
import pytest

from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                   TopologySpec)
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.utils.logging import (KVFormatter, fields, get_logger,
                                       setup)
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.client import DaemonClient
from kubedtn_tpu.wire.server import Daemon, make_server


def test_fields_rendering():
    assert fields(a=1, b="x") == "a=1 b=x"
    assert fields(msg="two words") == 'msg="two words"'
    assert fields(q='say "hi"') == 'q="say \\"hi\\""'
    assert fields(empty="") == 'empty=""'
    assert fields(eq="a=b") == 'eq="a=b"'


def test_formatter_logrus_shape():
    logger = logging.getLogger("kubedtn.test.fmt")
    logger.setLevel(logging.DEBUG)
    buf = io.StringIO()
    h = logging.StreamHandler(buf)
    h.setFormatter(KVFormatter())
    logger.addHandler(h)
    try:
        logger.info("hello %s", fields(pod="default/r1"))
        line = buf.getvalue().strip()
        assert line.startswith("time=")
        assert " level=info " in line
        assert 'msg="hello pod=default/r1"' in line
        assert line.endswith("logger=kubedtn.test.fmt")
    finally:
        logger.removeHandler(h)


def test_setup_idempotent_and_level():
    root = setup(level="warning", stream=io.StringIO())
    assert root.level == logging.WARNING
    n = len(root.handlers)
    setup(level="info", stream=io.StringIO())
    assert len(logging.getLogger("kubedtn").handlers) == n  # replaced


@pytest.fixture
def capture():
    """Capture kubedtn.* records at DEBUG without global side effects."""
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(record)

    root = logging.getLogger("kubedtn")
    old_level = root.level
    sink = Sink(level=logging.DEBUG)
    root.addHandler(sink)
    root.setLevel(logging.DEBUG)
    yield records
    root.removeHandler(sink)
    root.setLevel(old_level)


def test_grpc_interceptor_logs_ok_and_error(capture):
    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    store.create(Topology(name="r1", spec=TopologySpec(links=[])))
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0, host="127.0.0.1")
    server.start()
    client = DaemonClient(f"127.0.0.1:{port}")

    client.Get(pb.PodQuery(name="r1"))
    with pytest.raises(grpc.RpcError):
        client.Get(pb.PodQuery(name="ghost"))   # NOT_FOUND abort

    msgs = [(r.levelname, r.getMessage()) for r in capture
            if r.name == "kubedtn.grpc"]
    ok = [m for lvl, m in msgs
          if lvl == "INFO" and "Local/Get" in m and "code=OK" in m]
    failed = [m for lvl, m in msgs
              if lvl == "WARNING" and "Local/Get" in m]
    assert ok, msgs
    assert failed, msgs
    assert "ms=" in ok[0]
    client.close()
    server.stop(0)


def test_engine_and_reconciler_action_fields(capture):
    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    t = Topology(name="p", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth0",
             peer_pod="physical/10.0.0.9", uid=1,
             properties=LinkProperties(latency="1ms"))]))
    t.status.links = []
    store.create(t)
    rec = Reconciler(store, engine)
    rec.drain()

    eng = [r.getMessage() for r in capture if r.name == "kubedtn.engine"]
    ctl = [r.getMessage() for r in capture
           if r.name == "kubedtn.reconciler"]
    assert any("action=add" in m and "pod=default/p" in m for m in eng), eng
    assert any("action=changed" in m and "topology=default/p" in m
               for m in ctl), ctl


def test_reconcile_failure_logged_warning(capture):
    class Failing(SimEngine):
        def add_links(self, topo, links):
            return False if links else True

    store = TopologyStore()
    engine = Failing(store, capacity=16)
    t = Topology(name="p", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth0",
             peer_pod="physical/10.0.0.9", uid=1)]))
    t.status.links = []
    store.create(t)
    Reconciler(store, engine).reconcile("default", "p")
    warnings = [r.getMessage() for r in capture
                if r.name == "kubedtn.reconciler"
                and r.levelname == "WARNING"]
    assert any("requeue=True" in m for m in warnings), warnings
    # the partial-apply warning names the failed link set (ISSUE 8)
    assert any("failed_links" in m and "add" in m for m in warnings), \
        warnings


def test_wire_data_rpcs_log_at_debug_not_info(capture):
    """Per-frame RPCs must not emit info-level lines (kpps rates would
    throttle forwarding); control-plane RPCs stay at info."""
    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    daemon = Daemon(engine)
    server, port = make_server(daemon, port=0, host="127.0.0.1")
    server.start()
    client = DaemonClient(f"127.0.0.1:{port}")
    wire = daemon._add_wire(pb.WireDef(
        local_pod_name="w", kube_ns="default", link_uid=1,
        intf_name_in_pod="eth0", peer_ip="10.0.0.2"))
    client.SendToOnce(pb.Packet(remot_intf_id=wire.wire_id, frame=b"x" * 60))
    client.GenerateNodeInterfaceName(pb.GenerateNodeInterfaceNameRequest(
        pod_name="p", pod_intf_name="eth0"))
    grpc_logs = [(r.levelname, r.getMessage()) for r in capture
                 if r.name == "kubedtn.grpc"]
    send = [lvl for lvl, m in grpc_logs if "SendToOnce" in m]
    ctrl = [lvl for lvl, m in grpc_logs if "GenerateNodeInterfaceName" in m]
    assert send == ["DEBUG"], grpc_logs
    assert ctrl == ["INFO"], grpc_logs
    client.close()
    server.stop(0)


def test_fields_escapes_newlines():
    """A value with newlines must stay ONE log line (no record forgery)."""
    out = fields(error="bad\ntime=x level=info msg=forged")
    assert "\n" not in out
    assert out == 'error="bad\\ntime=x level=info msg=forged"'
