"""Metrics parity tests: series names, buckets, labels, HTTP exposition."""

import pytest

import urllib.request

from kubedtn_tpu.api.types import LinkProperties, load_yaml
from kubedtn_tpu.metrics.metrics import (
    BUCKETS,
    MetricsServer,
    make_registry,
)
from kubedtn_tpu.models.traffic import cbr_everywhere
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu import sim as S
from prometheus_client import generate_latest


REFERENCE_3NODE = "/root/reference/config/samples/3node.yml"


def build_cluster_with_traffic():
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    for t in load_yaml(REFERENCE_3NODE):
        store.create(t)
    for n in ("r1", "r2", "r3"):
        engine.setup_pod(n)
    Reconciler(store, engine).drain()
    sim = S.init_sim(engine.state)
    spec = cbr_everywhere(64, 6, rate_bps=12_000_000)
    sim = S.run(sim, spec, steps=50, dt_us=1000.0)
    return engine, sim


def test_histogram_name_and_buckets():
    registry, hist = make_registry()
    hist.observe("add", 3.0)
    hist.observe("update", 123.0)
    text = generate_latest(registry).decode()
    assert "kubedtnd_request_duration_milliseconds_bucket" in text
    for b in BUCKETS:
        assert f'le="{float(b)}"' in text
    assert 'method="add"' in text and 'method="update"' in text


@pytest.mark.requires_reference_yaml
def test_interface_series():
    engine, sim = build_cluster_with_traffic()
    registry, _ = make_registry(engine, lambda: sim.counters)
    text = generate_latest(registry).decode()
    for series in ("interface_rx_packets", "interface_tx_packets",
                   "interface_rx_bytes", "interface_tx_bytes",
                   "interface_rx_errors", "interface_tx_errors",
                   "interface_rx_dropped", "interface_tx_dropped"):
        assert series in text, series
    assert 'pod="r1"' in text and 'namespace="default"' in text
    # traffic flowed: some tx_packets gauge is positive
    lines = [l for l in text.splitlines()
             if l.startswith("interface_tx_packets{")]
    assert any(float(l.rsplit(" ", 1)[1]) > 0 for l in lines)


@pytest.mark.requires_reference_yaml
def test_http_exposition():
    engine, sim = build_cluster_with_traffic()
    registry, hist = make_registry(engine, lambda: sim.counters)
    hist.observe("setup", 1.5)
    srv = MetricsServer(registry, port=0)  # ephemeral port
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics") as resp:
            body = resp.read().decode()
        assert "kubedtnd_request_duration_milliseconds" in body
        assert "interface_tx_packets" in body
        # 404 on other paths
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


@pytest.mark.requires_reference_yaml
def test_node_aggregates_and_series_cap():
    """Node totals are always exported; per-interface series truncate at
    max_interfaces with the truncation count reported (the 100k-interface
    scale guard — a full exposition would be tens of MB)."""
    engine, sim = build_cluster_with_traffic()
    # capped at 2 of the 6 realized ends
    registry, _ = make_registry(engine, lambda: sim.counters,
                                max_interfaces=2)
    text = generate_latest(registry).decode()
    assert "kubedtn_node_tx_packets_total" in text
    assert "kubedtn_node_rx_bytes_total" in text
    tx_total = [l for l in text.splitlines()
                if l.startswith("kubedtn_node_tx_packets_total")][0]
    assert float(tx_total.rsplit(" ", 1)[1]) > 0
    lines = [l for l in text.splitlines()
             if l.startswith("interface_tx_packets{")]
    assert len(lines) == 2  # capped
    trunc = [l for l in text.splitlines()
             if l.startswith("kubedtn_interface_series_truncated")][0]
    assert float(trunc.rsplit(" ", 1)[1]) == 4.0
    # uncapped: all ends present, truncation gauge zero
    registry2, _ = make_registry(engine, lambda: sim.counters)
    text2 = generate_latest(registry2).decode()
    lines2 = [l for l in text2.splitlines()
              if l.startswith("interface_tx_packets{")]
    assert len(lines2) == 6
    trunc2 = [l for l in text2.splitlines()
              if l.startswith("kubedtn_interface_series_truncated")][0]
    assert float(trunc2.rsplit(" ", 1)[1]) == 0.0


@pytest.mark.requires_reference_yaml
def test_node_totals_exclude_deleted_links():
    """Freed rows keep their cumulative counters until reuse; node totals
    must sum ACTIVE rows only, so deleting a pod's links removes its
    traffic from the node aggregate."""
    engine, sim = build_cluster_with_traffic()
    registry, _ = make_registry(engine, lambda: sim.counters)

    def node_tx(text):
        line = [l for l in text.splitlines()
                if l.startswith("kubedtn_node_tx_packets_total")][0]
        return float(line.rsplit(" ", 1)[1])

    before = node_tx(generate_latest(registry).decode())
    assert before > 0
    engine.destroy_pod("r1")  # removes r1's link ends (rows keep counters)
    after = node_tx(generate_latest(registry).decode())
    assert after < before


@pytest.mark.requires_reference_yaml
def test_dataplane_stats_series():
    """kubedtn_dataplane_* counters track the wire plane's runtime
    health (no reference analogue — its data plane is kernel state)."""
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    engine, sim = build_cluster_with_traffic()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1000.0)
    w1 = daemon._add_wire(pb.WireDef(local_pod_name="r1",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    daemon._add_wire(pb.WireDef(local_pod_name="r2", kube_ns="default",
                                link_uid=1, intf_name_in_pod="eth1"))
    daemon._frame_in(w1, b"\x01" * 60)
    t = 0.0
    for _ in range(20):
        plane.tick(now_s=t)
        t += 0.001
    registry, _ = make_registry(engine, lambda: sim.counters,
                                dataplane=plane)
    text = generate_latest(registry).decode()

    def val(name):
        line = [l for l in text.splitlines()
                if l.startswith(f"kubedtn_dataplane_{name}_total ")][0]
        return float(line.rsplit(" ", 1)[1])

    assert val("ticks") == 20.0
    assert val("shaped") == 1.0
    assert val("undeliverable") == 0.0
    assert val("tick_errors") == 0.0


# -- MetricsServer robustness (round 8) --------------------------------

def test_server_unknown_path_404_plain_registry():
    """404 on unknown paths needs no engine or reference YAML."""
    registry, _ = make_registry()
    srv = MetricsServer(registry, port=0)
    srv.start()
    try:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/definitely-not-metrics")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_server_concurrent_scrapes():
    """Many simultaneous scrapes (ThreadingHTTPServer) all succeed and
    all see the same complete exposition."""
    import threading

    registry, hist = make_registry()
    hist.observe("add", 2.0)
    srv = MetricsServer(registry, port=0)
    srv.start()
    results: list = []

    def scrape():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics") as resp:
            results.append(resp.read().decode())

    try:
        threads = [threading.Thread(target=scrape) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(results) == 8
        assert all("kubedtnd_request_duration_milliseconds" in r
                   for r in results)
    finally:
        srv.stop()


class _FlakyCollector:
    """Raises on the first N collects, then behaves."""

    def __init__(self, failures: int) -> None:
        self.failures = failures
        self.calls = 0

    def collect(self):
        self.calls += 1
        if self.calls <= self.failures:
            raise RuntimeError("collector exploded mid-scrape")
        from prometheus_client.core import GaugeMetricFamily

        g = GaugeMetricFamily("flaky_ok", "recovered")
        g.add_metric([], 1.0)
        yield g


def test_collector_raising_mid_scrape_does_not_kill_server():
    """A collector raising mid-scrape costs THAT scrape a 500 — the
    handler thread survives and subsequent scrapes succeed (including
    the same collector recovering)."""
    registry, _ = make_registry()
    flaky = _FlakyCollector(failures=2)
    registry.register(flaky)
    srv = MetricsServer(registry, port=0)
    srv.start()
    url = f"http://127.0.0.1:{srv.port}/metrics"
    try:
        for _ in range(2):
            try:
                urllib.request.urlopen(url)
                assert False, "expected 500"
            except urllib.error.HTTPError as e:
                assert e.code == 500
                assert "scrape failed" in e.read().decode()
        # server not wedged: the recovered collector now scrapes clean
        with urllib.request.urlopen(url) as resp:
            body = resp.read().decode()
        assert "flaky_ok" in body
        assert flaky.calls == 3
    finally:
        srv.stop()


def test_link_telemetry_collector_series():
    """kubedtn_link_* per-edge series appear once the plane's telemetry
    is on, with the coverage gauges and the truncation guard."""
    from kubedtn_tpu.api.types import Link, Topology, TopologySpec
    from kubedtn_tpu.runtime import WireDataPlane
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    props = LinkProperties(latency="2ms")
    store.create(Topology(name="ma", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="mb",
             uid=1, properties=props)])))
    store.create(Topology(name="mb", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="ma",
             uid=1, properties=props)])))
    engine.setup_pod("ma")
    engine.setup_pod("mb")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon, dt_us=1000.0)
    registry, _ = make_registry(engine, plane.counters_fn,
                                dataplane=plane)
    # telemetry off: no kubedtn_link_ series at all
    assert "kubedtn_link_" not in generate_latest(registry).decode()
    plane.enable_telemetry(window_s=0.05, sample_period=4)
    w1 = daemon._add_wire(pb.WireDef(local_pod_name="ma",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    daemon._add_wire(pb.WireDef(local_pod_name="mb", kube_ns="default",
                                link_uid=1, intf_name_in_pod="eth1"))
    w1.ingress.extend([b"\x01" * 60] * 50)
    t = 0.0
    for _ in range(30):
        plane.tick(now_s=t)
        t += 0.01
    text = generate_latest(registry).decode()
    assert "kubedtn_link_delivered" in text
    assert "kubedtn_link_dropped_loss" in text
    assert "kubedtn_link_dropped_queue" in text
    assert "kubedtn_link_p99_us" in text
    assert "kubedtn_link_window_seconds" in text
    assert "kubedtn_link_series_truncated 0.0" in text
    assert 'pod="ma"' in text
    line = [l for l in text.splitlines()
            if l.startswith("kubedtn_link_delivered{")][0]
    assert float(line.rsplit(" ", 1)[1]) == 50.0
