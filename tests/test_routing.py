"""Routing kernel + multi-hop forwarding tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models import topologies as T
from kubedtn_tpu.models.traffic import cbr_everywhere
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import routing as R
from kubedtn_tpu import router as RT


def build(el):
    state, rows = T.load_edge_list_into_state(el)
    return state


class TestReachability:
    def test_line_reachable(self):
        s = build(T.line(4))
        r = np.asarray(R.reachability(s, 4))
        assert r.all()  # bidirectional line: all pairs reach

    def test_partition(self):
        el = T.line(5)
        s = build(el)
        # cut the middle link (uid 2 connects nodes 1-2): delete both rows
        rows = jnp.array([1, 1 + el.n_links], jnp.int32)
        s = es.delete_links(s, rows, jnp.ones(2, bool))
        r = np.asarray(R.reachability(s, 5))
        assert r[0, 1] and not r[0, 2] and not r[1, 3]
        assert r[2, 3] and r[3, 4]

    def test_directedness(self):
        # only one direction active: u->v reachable, v->u not
        s = es.init_state(8)
        props = jnp.stack([es.props_row(LinkProperties().to_numeric())])
        s = es.apply_links(s, jnp.array([0], jnp.int32),
                           jnp.array([1], jnp.int32),
                           jnp.array([0], jnp.int32),
                           jnp.array([1], jnp.int32), props,
                           jnp.array([True]))
        r = np.asarray(R.reachability(s, 2))
        assert r[0, 1] and not r[1, 0]


class TestShortestPath:
    def test_line_distances(self):
        el = T.line(4, LinkProperties(latency="10ms"))
        s = build(el)
        dist, nh = R.recompute_routes(s, 4, max_hops=8)
        d = np.asarray(dist)
        # metric = latency_us + 1 per hop
        assert d[0, 1] == pytest.approx(10_001)
        assert d[0, 3] == pytest.approx(3 * 10_001)
        assert d[2, 0] == pytest.approx(2 * 10_001)
        n = np.asarray(nh)
        # node 0's next hop toward 3 is its only edge (row 0: 0->1)
        assert n[0, 3] == 0
        assert n[0, 0] == -1  # self

    def test_latency_weighted_path_choice(self):
        # triangle: 0-1 fast+fast vs 0-2 direct slow
        el = T.ring(3)
        s = build(el)
        rows = jnp.arange(3, dtype=jnp.int32)  # a-side rows: 0-1, 1-2, 2-0
        props = jnp.stack([
            es.props_row(LinkProperties(latency="1ms").to_numeric()),
            es.props_row(LinkProperties(latency="1ms").to_numeric()),
            es.props_row(LinkProperties(latency="100ms").to_numeric()),
        ])
        s = es.update_links(s, rows, props, jnp.ones(3, bool))
        # update b-side rows with same props
        s = es.update_links(s, rows + 3, props, jnp.ones(3, bool))
        dist, nh = R.recompute_routes(s, 3, max_hops=8)
        d = np.asarray(dist)
        # 0->2: via 1 costs 2ms+2 < direct 100ms+1
        assert d[0, 2] == pytest.approx(2002)
        n = np.asarray(nh)
        assert n[0, 2] == 0  # row 0 is edge 0->1

    def test_unreachable_inf(self):
        el = T.line(3)
        s = build(el)
        rows = jnp.array([1, 1 + el.n_links], jnp.int32)  # cut 1-2
        s = es.delete_links(s, rows, jnp.ones(2, bool))
        dist, nh = R.recompute_routes(s, 3, max_hops=8)
        assert np.isinf(np.asarray(dist)[0, 2])
        assert np.asarray(nh)[0, 2] == -1

    def test_chunked_matches_unchunked(self):
        el = T.fat_tree(4, LinkProperties(latency="1ms"))
        s = build(el)
        d1, n1 = R.recompute_routes(s, el.n_nodes, max_hops=8)
        d2, n2 = R.recompute_routes(s, el.n_nodes, max_hops=8, dst_chunk=5)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))
        np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))

    def test_link_event_recompute(self):
        # the BGP-like scenario: link down changes routes
        el = T.ring(4, LinkProperties(latency="1ms"))
        s = build(el)
        dist0, _ = R.recompute_routes(s, 4, max_hops=8)
        assert np.asarray(dist0)[0, 2] == pytest.approx(2 * 1001)
        # take down edge 1-2 (uid 2 => rows 1 and 1+4)
        s = es.delete_links(s, jnp.array([1, 5], jnp.int32),
                            jnp.ones(2, bool))
        dist1, _ = R.recompute_routes(s, 4, max_hops=8)
        # 0->2 now must go the long way: 0-3-2
        assert np.asarray(dist1)[0, 2] == pytest.approx(2 * 1001)
        # 1->2 goes 1-0-3-2
        assert np.asarray(dist1)[1, 2] == pytest.approx(3 * 1001)


class TestMultiHopForwarding:
    def test_line_end_to_end(self):
        # 3-node line, 10ms per hop; flow from node0's edge to node 2
        el = T.line(3, LinkProperties(latency="10ms"))
        s = build(el)
        n = el.n_nodes
        dist, nh = R.recompute_routes(s, n, max_hops=8)
        rs = RT.init_router(s, nh, n)
        cap = s.capacity
        spec = cbr_everywhere(cap, 0, 0.0)
        # put CBR on edge row 0 (0->1) with final destination node 2
        import dataclasses as dc
        from kubedtn_tpu.models.traffic import MODE_CBR
        spec = dc.replace(
            spec,
            mode=spec.mode.at[0].set(MODE_CBR),
            rate_bps=spec.rate_bps.at[0].set(12_000_000.0),
        )
        flow_dst = jnp.full((cap,), -1, jnp.int32).at[0].set(2)
        rs = RT.run_routed(rs, spec, flow_dst, steps=100, dt_us=1000.0)
        node_rx = np.asarray(rs.node_rx_packets)
        assert node_rx[2] > 0          # packets crossed two hops
        assert node_rx[1] == 0         # transit node keeps nothing
        assert float(rs.no_route_dropped) == 0
        # ~100ms sim, 2x10ms path, 1 pkt/ms -> ≈80 delivered at node 2
        assert node_rx[2] == pytest.approx(80, abs=5)

    def test_no_route_counted(self):
        el = T.line(3, LinkProperties())
        s = build(el)
        n = el.n_nodes
        _, nh = R.recompute_routes(s, n, max_hops=8)
        rs = RT.init_router(s, nh, n)
        cap = s.capacity
        import dataclasses as dc
        from kubedtn_tpu.models.traffic import MODE_CBR
        spec = cbr_everywhere(cap, 0, 0.0)
        spec = dc.replace(
            spec,
            mode=spec.mode.at[0].set(MODE_CBR),
            rate_bps=spec.rate_bps.at[0].set(12_000_000.0),
        )
        # destination node 7 does not exist in the table (n=3): route to a
        # disconnected id -> packets dropped as no-route after hop 1
        flow_dst = jnp.full((cap,), -1, jnp.int32).at[0].set(1)
        # make node 1 NOT the final dst: send to node 0 via edge 0->1
        flow_dst = flow_dst.at[0].set(0)
        rs = RT.run_routed(rs, spec, flow_dst, steps=20, dt_us=1000.0)
        # 0->1 edge delivers at node 1; next hop back to 0 exists, so no
        # drops; eventually node 0 receives
        assert float(rs.no_route_dropped) == 0
        assert np.asarray(rs.node_rx_packets)[0] > 0

    def test_clos_host_to_host(self):
        # 2 spines, 4 leaves; flow from leaf0's uplink to leaf3
        el = T.clos(2, 4, 0, props=LinkProperties(latency="1ms"))
        s = build(el)
        n = el.n_nodes  # 6: spine0,1, leaf0..3
        dist, nh = R.recompute_routes(s, n, max_hops=8)
        rs = RT.init_router(s, nh, n)
        cap = s.capacity
        import dataclasses as dc
        from kubedtn_tpu.models.traffic import MODE_CBR
        spec = cbr_everywhere(cap, 0, 0.0)
        # edge 0 is spine0<->leaf0 a-side (spine0->leaf0); use the b-side
        # row (leaf0->spine0) = row el.n_links + 0
        src_row = el.n_links + 0
        spec = dc.replace(
            spec,
            mode=spec.mode.at[src_row].set(MODE_CBR),
            rate_bps=spec.rate_bps.at[src_row].set(12_000_000.0),
        )
        leaf3 = 2 + 3  # spines first
        flow_dst = jnp.full((cap,), -1, jnp.int32).at[src_row].set(leaf3)
        rs = RT.run_routed(rs, spec, flow_dst, steps=60, dt_us=1000.0)
        assert np.asarray(rs.node_rx_packets)[leaf3] > 0
        assert float(rs.no_route_dropped) == 0
        assert float(rs.fwd_dropped) == 0


class TestECMP:
    def _diamond(self):
        """a(0) -> b(1)/c(2) -> d(3), equal 1ms cost both ways."""
        el = T._mk(["a", "b", "c", "d"],
                   [(0, 1), (0, 2), (1, 3), (2, 3)],
                   LinkProperties(latency="1ms"))
        return el, build(el)

    def test_group_has_both_paths(self):
        el, s = self._diamond()
        dist, nh = R.recompute_routes_ecmp(s, 4, k_paths=4, max_hops=8)
        g = np.asarray(nh)[0, 3]  # a's group toward d
        valid = g[g >= 0]
        assert len(valid) == 2
        # the two tied egresses are a->b (row 0) and a->c (row 1)
        assert set(valid.tolist()) == {0, 1}
        # unreachable/self entries are fully -1
        assert (np.asarray(nh)[0, 0] == -1).all()

    def test_k1_matches_single_path(self):
        el, s = self._diamond()
        dist, nh1 = R.recompute_routes(s, 4, max_hops=8)
        _, nhk = R.recompute_routes_ecmp(s, 4, k_paths=1, max_hops=8)
        np.testing.assert_array_equal(np.asarray(nh1),
                                      np.asarray(nhk)[:, :, 0])

    def test_flows_split_across_paths(self):
        """Two ingress feeders into the diamond: ECMP hashing on
        (ingress edge, dst) spreads them over both equal-cost paths."""
        el = T._mk(
            ["s1", "s2", "a", "b", "c", "d"],
            # feeders s1->a, s2->a, then the diamond a->b/c->d
            [(0, 2), (1, 2), (2, 3), (2, 4), (3, 5), (4, 5)],
            LinkProperties(latency="1ms"))
        s = build(el)
        n = el.n_nodes
        dist, nh = R.recompute_routes_ecmp(s, n, k_paths=4, max_hops=8)
        rs = RT.init_router(s, nh, n)
        cap = s.capacity
        import dataclasses as dc
        from kubedtn_tpu.models.traffic import MODE_CBR
        spec = cbr_everywhere(cap, 0, 0.0)
        # CBR on both feeder edges (s1->a row 0, s2->a row 1), dst d(5)
        spec = dc.replace(
            spec,
            mode=spec.mode.at[jnp.array([0, 1])].set(MODE_CBR),
            rate_bps=spec.rate_bps.at[jnp.array([0, 1])].set(12_000_000.0),
        )
        flow_dst = jnp.full((cap,), -1, jnp.int32)
        flow_dst = flow_dst.at[jnp.array([0, 1])].set(5)
        rs = RT.run_routed(rs, spec, flow_dst, steps=60, dt_us=1000.0)
        c = rs.sim.counters
        tx = np.asarray(c.tx_packets)
        # both diamond arms carried traffic (rows 2: a->b, 3: a->c)
        assert np.asarray(rs.node_rx_packets)[5] > 0
        assert float(rs.no_route_dropped) == 0
        arm_ab, arm_ac = tx[2], tx[3]
        assert arm_ab > 0 and arm_ac > 0, (arm_ab, arm_ac)

    def test_sharded_router_rejects_ecmp(self):
        el, s = self._diamond()
        _, nh = R.recompute_routes_ecmp(s, 4, k_paths=2, max_hops=8)
        rs = RT.init_router(s, nh, 4)
        from kubedtn_tpu.parallel.mesh import make_mesh
        from kubedtn_tpu.parallel.router import shard_router_state
        with pytest.raises(AssertionError, match="single-path"):
            shard_router_state(rs, make_mesh(8))
