"""Routing kernel + multi-hop forwarding tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models import topologies as T
from kubedtn_tpu.models.traffic import cbr_everywhere
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import routing as R
from kubedtn_tpu import router as RT


def build(el):
    state, rows = T.load_edge_list_into_state(el)
    return state


class TestReachability:
    def test_line_reachable(self):
        s = build(T.line(4))
        r = np.asarray(R.reachability(s, 4))
        assert r.all()  # bidirectional line: all pairs reach

    def test_partition(self):
        el = T.line(5)
        s = build(el)
        # cut the middle link (uid 2 connects nodes 1-2): delete both rows
        rows = jnp.array([1, 1 + el.n_links], jnp.int32)
        s = es.delete_links(s, rows, jnp.ones(2, bool))
        r = np.asarray(R.reachability(s, 5))
        assert r[0, 1] and not r[0, 2] and not r[1, 3]
        assert r[2, 3] and r[3, 4]

    def test_directedness(self):
        # only one direction active: u->v reachable, v->u not
        s = es.init_state(8)
        props = jnp.stack([es.props_row(LinkProperties().to_numeric())])
        s = es.apply_links(s, jnp.array([0], jnp.int32),
                           jnp.array([1], jnp.int32),
                           jnp.array([0], jnp.int32),
                           jnp.array([1], jnp.int32), props,
                           jnp.array([True]))
        r = np.asarray(R.reachability(s, 2))
        assert r[0, 1] and not r[1, 0]


class TestShortestPath:
    def test_line_distances(self):
        el = T.line(4, LinkProperties(latency="10ms"))
        s = build(el)
        dist, nh = R.recompute_routes(s, 4, max_hops=8)
        d = np.asarray(dist)
        # metric = latency_us + 1 per hop
        assert d[0, 1] == pytest.approx(10_001)
        assert d[0, 3] == pytest.approx(3 * 10_001)
        assert d[2, 0] == pytest.approx(2 * 10_001)
        n = np.asarray(nh)
        # node 0's next hop toward 3 is its only edge (row 0: 0->1)
        assert n[0, 3] == 0
        assert n[0, 0] == -1  # self

    def test_latency_weighted_path_choice(self):
        # triangle: 0-1 fast+fast vs 0-2 direct slow
        el = T.ring(3)
        s = build(el)
        rows = jnp.arange(3, dtype=jnp.int32)  # a-side rows: 0-1, 1-2, 2-0
        props = jnp.stack([
            es.props_row(LinkProperties(latency="1ms").to_numeric()),
            es.props_row(LinkProperties(latency="1ms").to_numeric()),
            es.props_row(LinkProperties(latency="100ms").to_numeric()),
        ])
        s = es.update_links(s, rows, props, jnp.ones(3, bool))
        # update b-side rows with same props
        s = es.update_links(s, rows + 3, props, jnp.ones(3, bool))
        dist, nh = R.recompute_routes(s, 3, max_hops=8)
        d = np.asarray(dist)
        # 0->2: via 1 costs 2ms+2 < direct 100ms+1
        assert d[0, 2] == pytest.approx(2002)
        n = np.asarray(nh)
        assert n[0, 2] == 0  # row 0 is edge 0->1

    def test_unreachable_inf(self):
        el = T.line(3)
        s = build(el)
        rows = jnp.array([1, 1 + el.n_links], jnp.int32)  # cut 1-2
        s = es.delete_links(s, rows, jnp.ones(2, bool))
        dist, nh = R.recompute_routes(s, 3, max_hops=8)
        assert np.isinf(np.asarray(dist)[0, 2])
        assert np.asarray(nh)[0, 2] == -1

    def test_chunked_matches_unchunked(self):
        el = T.fat_tree(4, LinkProperties(latency="1ms"))
        s = build(el)
        d1, n1 = R.recompute_routes(s, el.n_nodes, max_hops=8)
        d2, n2 = R.recompute_routes(s, el.n_nodes, max_hops=8, dst_chunk=5)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))
        np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))

    def test_link_event_recompute(self):
        # the BGP-like scenario: link down changes routes
        el = T.ring(4, LinkProperties(latency="1ms"))
        s = build(el)
        dist0, _ = R.recompute_routes(s, 4, max_hops=8)
        assert np.asarray(dist0)[0, 2] == pytest.approx(2 * 1001)
        # take down edge 1-2 (uid 2 => rows 1 and 1+4)
        s = es.delete_links(s, jnp.array([1, 5], jnp.int32),
                            jnp.ones(2, bool))
        dist1, _ = R.recompute_routes(s, 4, max_hops=8)
        # 0->2 now must go the long way: 0-3-2
        assert np.asarray(dist1)[0, 2] == pytest.approx(2 * 1001)
        # 1->2 goes 1-0-3-2
        assert np.asarray(dist1)[1, 2] == pytest.approx(3 * 1001)


class TestMultiHopForwarding:
    def test_line_end_to_end(self):
        # 3-node line, 10ms per hop; flow from node0's edge to node 2
        el = T.line(3, LinkProperties(latency="10ms"))
        s = build(el)
        n = el.n_nodes
        dist, nh = R.recompute_routes(s, n, max_hops=8)
        rs = RT.init_router(s, nh, n)
        cap = s.capacity
        spec = cbr_everywhere(cap, 0, 0.0)
        # put CBR on edge row 0 (0->1) with final destination node 2
        import dataclasses as dc
        from kubedtn_tpu.models.traffic import MODE_CBR
        spec = dc.replace(
            spec,
            mode=spec.mode.at[0].set(MODE_CBR),
            rate_bps=spec.rate_bps.at[0].set(12_000_000.0),
        )
        flow_dst = jnp.full((cap,), -1, jnp.int32).at[0].set(2)
        rs = RT.run_routed(rs, spec, flow_dst, steps=100, dt_us=1000.0)
        node_rx = np.asarray(rs.node_rx_packets)
        assert node_rx[2] > 0          # packets crossed two hops
        assert node_rx[1] == 0         # transit node keeps nothing
        assert float(rs.no_route_dropped) == 0
        # ~100ms sim, 2x10ms path, 1 pkt/ms -> ≈80 delivered at node 2
        assert node_rx[2] == pytest.approx(80, abs=5)

    def test_no_route_counted(self):
        el = T.line(3, LinkProperties())
        s = build(el)
        n = el.n_nodes
        _, nh = R.recompute_routes(s, n, max_hops=8)
        rs = RT.init_router(s, nh, n)
        cap = s.capacity
        import dataclasses as dc
        from kubedtn_tpu.models.traffic import MODE_CBR
        spec = cbr_everywhere(cap, 0, 0.0)
        spec = dc.replace(
            spec,
            mode=spec.mode.at[0].set(MODE_CBR),
            rate_bps=spec.rate_bps.at[0].set(12_000_000.0),
        )
        # destination node 7 does not exist in the table (n=3): route to a
        # disconnected id -> packets dropped as no-route after hop 1
        flow_dst = jnp.full((cap,), -1, jnp.int32).at[0].set(1)
        # make node 1 NOT the final dst: send to node 0 via edge 0->1
        flow_dst = flow_dst.at[0].set(0)
        rs = RT.run_routed(rs, spec, flow_dst, steps=20, dt_us=1000.0)
        # 0->1 edge delivers at node 1; next hop back to 0 exists, so no
        # drops; eventually node 0 receives
        assert float(rs.no_route_dropped) == 0
        assert np.asarray(rs.node_rx_packets)[0] > 0

    def test_clos_host_to_host(self):
        # 2 spines, 4 leaves; flow from leaf0's uplink to leaf3
        el = T.clos(2, 4, 0, props=LinkProperties(latency="1ms"))
        s = build(el)
        n = el.n_nodes  # 6: spine0,1, leaf0..3
        dist, nh = R.recompute_routes(s, n, max_hops=8)
        rs = RT.init_router(s, nh, n)
        cap = s.capacity
        import dataclasses as dc
        from kubedtn_tpu.models.traffic import MODE_CBR
        spec = cbr_everywhere(cap, 0, 0.0)
        # edge 0 is spine0<->leaf0 a-side (spine0->leaf0); use the b-side
        # row (leaf0->spine0) = row el.n_links + 0
        src_row = el.n_links + 0
        spec = dc.replace(
            spec,
            mode=spec.mode.at[src_row].set(MODE_CBR),
            rate_bps=spec.rate_bps.at[src_row].set(12_000_000.0),
        )
        leaf3 = 2 + 3  # spines first
        flow_dst = jnp.full((cap,), -1, jnp.int32).at[src_row].set(leaf3)
        rs = RT.run_routed(rs, spec, flow_dst, steps=60, dt_us=1000.0)
        assert np.asarray(rs.node_rx_packets)[leaf3] > 0
        assert float(rs.no_route_dropped) == 0
        assert float(rs.fwd_dropped) == 0


class TestECMP:
    def _diamond(self):
        """a(0) -> b(1)/c(2) -> d(3), equal 1ms cost both ways."""
        el = T._mk(["a", "b", "c", "d"],
                   [(0, 1), (0, 2), (1, 3), (2, 3)],
                   LinkProperties(latency="1ms"))
        return el, build(el)

    def test_group_has_both_paths(self):
        el, s = self._diamond()
        dist, nh = R.recompute_routes_ecmp(s, 4, k_paths=4, max_hops=8)
        g = np.asarray(nh)[0, 3]  # a's group toward d
        valid = g[g >= 0]
        assert len(valid) == 2
        # the two tied egresses are a->b (row 0) and a->c (row 1)
        assert set(valid.tolist()) == {0, 1}
        # unreachable/self entries are fully -1
        assert (np.asarray(nh)[0, 0] == -1).all()

    def test_k1_matches_single_path(self):
        el, s = self._diamond()
        dist, nh1 = R.recompute_routes(s, 4, max_hops=8)
        _, nhk = R.recompute_routes_ecmp(s, 4, k_paths=1, max_hops=8)
        np.testing.assert_array_equal(np.asarray(nh1),
                                      np.asarray(nhk)[:, :, 0])

    def test_flows_split_across_paths(self):
        """Two ingress feeders into the diamond: ECMP hashing on
        (ingress edge, dst) spreads them over both equal-cost paths."""
        el = T._mk(
            ["s1", "s2", "a", "b", "c", "d"],
            # feeders s1->a, s2->a, then the diamond a->b/c->d
            [(0, 2), (1, 2), (2, 3), (2, 4), (3, 5), (4, 5)],
            LinkProperties(latency="1ms"))
        s = build(el)
        n = el.n_nodes
        dist, nh = R.recompute_routes_ecmp(s, n, k_paths=4, max_hops=8)
        rs = RT.init_router(s, nh, n)
        cap = s.capacity
        import dataclasses as dc
        from kubedtn_tpu.models.traffic import MODE_CBR
        spec = cbr_everywhere(cap, 0, 0.0)
        # CBR on both feeder edges (s1->a row 0, s2->a row 1), dst d(5)
        spec = dc.replace(
            spec,
            mode=spec.mode.at[jnp.array([0, 1])].set(MODE_CBR),
            rate_bps=spec.rate_bps.at[jnp.array([0, 1])].set(12_000_000.0),
        )
        flow_dst = jnp.full((cap,), -1, jnp.int32)
        flow_dst = flow_dst.at[jnp.array([0, 1])].set(5)
        rs = RT.run_routed(rs, spec, flow_dst, steps=60, dt_us=1000.0)
        c = rs.sim.counters
        tx = np.asarray(c.tx_packets)
        # both diamond arms carried traffic (rows 2: a->b, 3: a->c)
        assert np.asarray(rs.node_rx_packets)[5] > 0
        assert float(rs.no_route_dropped) == 0
        arm_ab, arm_ac = tx[2], tx[3]
        assert arm_ab > 0 and arm_ac > 0, (arm_ab, arm_ac)

    def test_sharded_router_rejects_ecmp(self):
        el, s = self._diamond()
        _, nh = R.recompute_routes_ecmp(s, 4, k_paths=2, max_hops=8)
        rs = RT.init_router(s, nh, 4)
        from kubedtn_tpu.parallel.mesh import make_mesh
        from kubedtn_tpu.parallel.router import shard_router_state
        with pytest.raises(AssertionError, match="single-path"):
            shard_router_state(rs, make_mesh(8))


class TestIncrementalReconvergence:
    """ops.routing.update_routes_incremental — the delta path for link
    events: per-edge affected-set projection, row/column/full fixpoint
    chooser, exactness against a CONVERGED full recompute."""

    @staticmethod
    def _full_exact(state, n_nodes, dst_chunk=None):
        seed = jnp.full((n_nodes, n_nodes), jnp.inf, jnp.float32)
        d = R.refine_dist(state, n_nodes, seed, 64, dst_chunk)
        return d, R.next_hop_edges(state, d, n_nodes, dst_chunk)

    @staticmethod
    def _hetero(state, seed):
        import dataclasses

        rng = np.random.default_rng(seed)
        props = np.asarray(state.props).copy()
        lat = rng.uniform(1000, 20000, state.capacity).astype(np.float32)
        props[:, es.P_LATENCY_US] = lat
        return dataclasses.replace(state, props=jnp.asarray(props)), lat

    def _mesh(self, n_nodes=200, n_links=600, seed=7):
        from kubedtn_tpu.models import topologies as T

        el = T.random_mesh(n_nodes, n_links, seed=seed,
                           props=LinkProperties(latency="1ms"))
        state, rows = T.load_edge_list_into_state(el)
        state, lat = self._hetero(state, seed + 1)
        return el, state, lat

    def test_down_and_up_events_match_full_recompute(self):
        import dataclasses

        el, state, lat = self._mesh()
        n = el.n_nodes
        src0, dst0, uid0, props0 = el.directed()
        dist, nh = self._full_exact(state, n)
        rng = np.random.default_rng(0)
        for ev in range(3):
            flap = rng.choice(el.n_links, 2, replace=False)
            both = np.concatenate([flap, flap + el.n_links]) \
                .astype(np.int32)
            w_old = np.asarray(R.edge_weights_latency(state))[both]
            s_k = np.asarray(state.src)[both]
            d_k = np.asarray(state.dst)[both]
            state = es.delete_links(state, jnp.asarray(both),
                                    jnp.ones(len(both), bool))
            dist, nh, cells = R.update_routes_incremental(
                state, n, dist, nh, s_k, d_k, w_old,
                np.full(len(both), np.inf, np.float32))
            dist_f, _ = self._full_exact(state, n)
            assert np.allclose(np.asarray(dist), np.asarray(dist_f),
                               rtol=1e-5, atol=1e-1, equal_nan=True)
            assert cells > 0
            # up: restore the same links (same latencies)
            state = es.apply_links(
                state, jnp.asarray(both), jnp.asarray(uid0[both]),
                jnp.asarray(src0[both]), jnp.asarray(dst0[both]),
                jnp.asarray(props0[both]), jnp.ones(len(both), bool))
            props2 = np.asarray(state.props).copy()
            props2[:, es.P_LATENCY_US] = lat
            state = dataclasses.replace(state, props=jnp.asarray(props2))
            w_new = np.asarray(R.edge_weights_latency(state))[both]
            dist, nh, _ = R.update_routes_incremental(
                state, n, dist, nh, s_k, d_k,
                np.full(len(both), np.inf, np.float32), w_new)
            dist_f, _ = self._full_exact(state, n)
            assert np.allclose(np.asarray(dist), np.asarray(dist_f),
                               rtol=1e-5, atol=1e-1, equal_nan=True)

    def test_next_hops_always_realize_shortest_distance(self):
        el, state, _ = self._mesh(seed=9)
        n = el.n_nodes
        dist, nh = self._full_exact(state, n)
        rng = np.random.default_rng(2)
        for _ in range(3):
            flap = rng.choice(el.n_links, 2, replace=False)
            both = np.concatenate([flap, flap + el.n_links]) \
                .astype(np.int32)
            w_old = np.asarray(R.edge_weights_latency(state))[both]
            s_k = np.asarray(state.src)[both]
            d_k = np.asarray(state.dst)[both]
            state = es.delete_links(state, jnp.asarray(both),
                                    jnp.ones(len(both), bool))
            dist, nh, _ = R.update_routes_incremental(
                state, n, dist, nh, s_k, d_k, w_old,
                np.full(len(both), np.inf, np.float32))
        dn, nhn = np.asarray(dist), np.asarray(nh)
        w = np.asarray(R.edge_weights_latency(state))
        dstv = np.asarray(state.dst)
        ii, jj = np.nonzero(nhn >= 0)
        e = nhn[ii, jj]
        np.testing.assert_allclose(w[e] + dn[dstv[e], jj], dn[ii, jj],
                                   rtol=1e-5, atol=1e-1)
        # unreachable pairs have no next hop
        assert (nhn[~np.isfinite(dn)] == -1).all()

    def test_stub_uplink_takes_the_row_projection(self):
        """A leaf's only-uplink failure touches one source row across
        every destination: the chooser must take the row path (bounded
        cells), not a full-width recompute."""
        from kubedtn_tpu.models import topologies as T

        el = T.three_tier(pods=4, leaves_per_pod=12, aggs_per_pod=2,
                          cores=4, uplinks_per_leaf=2, cores_per_agg=2,
                          seed=1)
        state, rows = T.load_edge_list_into_state(el)
        n = el.n_nodes
        dist, nh = self._full_exact(state, n)
        # leaf uplink = a link whose src is a leaf (beyond cores+aggs)
        leaf0 = 4 + 4 * 2
        src_np = np.asarray(state.src)
        leaf_rows = np.nonzero(src_np >= leaf0)[0]
        row = int(leaf_rows[0])
        link = row % el.n_links
        both = np.array([link, link + el.n_links], np.int32)
        w_old = np.asarray(R.edge_weights_latency(state))[both]
        s_k = src_np[both]
        d_k = np.asarray(state.dst)[both]
        state = es.delete_links(state, jnp.asarray(both),
                                jnp.ones(2, bool))
        dist, nh, cells = R.update_routes_incremental(
            state, n, dist, nh, s_k, d_k, w_old,
            np.full(2, np.inf, np.float32))
        dist_f, _ = self._full_exact(state, n)
        assert np.allclose(np.asarray(dist), np.asarray(dist_f),
                           rtol=1e-5, atol=1e-1, equal_nan=True)
        # bounded work: far less than the n*n a full recompute touches
        assert cells < n * n // 4, (cells, n * n)

    def test_ten_link_flap_batch_matches_full_recompute(self):
        """Round-5: a chaos-style 10-link flap (20 directed edges) is
        ONE event — one batched detection, one (or two grouped)
        restricted fixpoints — and must agree exactly with a converged
        full recompute, down and up, including the link-up direction
        where improvements can compose across several restored links."""
        import dataclasses

        el, state, lat = self._mesh(n_nodes=300, n_links=900, seed=21)
        n = el.n_nodes
        src0, dst0, uid0, props0 = el.directed()
        dist, nh = self._full_exact(state, n)
        rng = np.random.default_rng(5)
        flap = rng.choice(el.n_links, 10, replace=False)
        both = np.concatenate([flap, flap + el.n_links]).astype(np.int32)
        w_old = np.asarray(R.edge_weights_latency(state))[both]
        s_k = np.asarray(state.src)[both]
        d_k = np.asarray(state.dst)[both]

        state = es.delete_links(state, jnp.asarray(both),
                                jnp.ones(len(both), bool))
        dist, nh, cells = R.update_routes_incremental(
            state, n, dist, nh, s_k, d_k, w_old,
            np.full(len(both), np.inf, np.float32))
        dist_f, _ = self._full_exact(state, n)
        assert np.allclose(np.asarray(dist), np.asarray(dist_f),
                           rtol=1e-5, atol=1e-1, equal_nan=True)
        assert cells > 0
        # next hops still realize the shortest distances
        dn_, nhn = np.asarray(dist), np.asarray(nh)
        w = np.asarray(R.edge_weights_latency(state))
        dstv = np.asarray(state.dst)
        ii, jj = np.nonzero(nhn >= 0)
        e = nhn[ii, jj]
        np.testing.assert_allclose(w[e] + dn_[dstv[e], jj], dn_[ii, jj],
                                   rtol=1e-5, atol=1e-1)

        # all 10 links back up in ONE event: composed improvements
        # (pairs whose new path crosses SEVERAL restored links) must
        # come out exact via the endpoint-block decomposition
        state = es.apply_links(
            state, jnp.asarray(both), jnp.asarray(uid0[both]),
            jnp.asarray(src0[both]), jnp.asarray(dst0[both]),
            jnp.asarray(props0[both]), jnp.ones(len(both), bool))
        props2 = np.asarray(state.props).copy()
        props2[:, es.P_LATENCY_US] = lat
        state = dataclasses.replace(state, props=jnp.asarray(props2))
        w_new = np.asarray(R.edge_weights_latency(state))[both]
        dist, nh, _ = R.update_routes_incremental(
            state, n, dist, nh, s_k, d_k,
            np.full(len(both), np.inf, np.float32), w_new)
        dist_f, _ = self._full_exact(state, n)
        assert np.allclose(np.asarray(dist), np.asarray(dist_f),
                           rtol=1e-5, atol=1e-1, equal_nan=True)

    @pytest.mark.parametrize("mesh_seed,ev_seed", [(31, 9), (44, 17),
                                                    (58, 23)])
    def test_mixed_up_down_batch_matches_full_recompute(self, mesh_seed,
                                                        ev_seed):
        """One event containing BOTH increases and decreases (some links
        slow down while others come up) exercises the interaction: the
        decrease endpoint blocks must be seeded with increase
        invalidation, every INF'd pair must reach a rebuild block (the
        pair-level inval eps is wider than the witness eps — a stranded
        +inf here is the round-5 review's finding 1), and the final
        fixpoint must rebuild invalidated pairs the products didn't.
        Multiple seeds because the failure mode is a float-tolerance
        corner."""
        import dataclasses

        el, state, lat = self._mesh(n_nodes=250, n_links=750,
                                    seed=mesh_seed)
        n = el.n_nodes
        dist, nh = self._full_exact(state, n)
        rng = np.random.default_rng(ev_seed)
        pick = rng.choice(el.n_links, 6, replace=False)
        slow = np.concatenate([pick[:3], pick[:3] + el.n_links])
        fast = np.concatenate([pick[3:], pick[3:] + el.n_links])
        both = np.concatenate([slow, fast]).astype(np.int32)
        w_old = np.asarray(R.edge_weights_latency(state))[both]
        props = np.asarray(state.props).copy()
        props[slow, es.P_LATENCY_US] *= 50.0    # increases
        props[fast, es.P_LATENCY_US] *= 0.02    # decreases
        state = dataclasses.replace(state, props=jnp.asarray(props))
        w_new = np.asarray(R.edge_weights_latency(state))[both]
        s_k = np.asarray(state.src)[both]
        d_k = np.asarray(state.dst)[both]
        dist, nh, cells = R.update_routes_incremental(
            state, n, dist, nh, s_k, d_k, w_old, w_new)
        dist_f, _ = self._full_exact(state, n)
        assert np.allclose(np.asarray(dist), np.asarray(dist_f),
                           rtol=1e-5, atol=1e-1, equal_nan=True)
        # next hops realize the distances after a mixed event too
        dn_, nhn = np.asarray(dist), np.asarray(nh)
        w = np.asarray(R.edge_weights_latency(state))
        dstv = np.asarray(state.dst)
        ii, jj = np.nonzero(nhn >= 0)
        e = nhn[ii, jj]
        np.testing.assert_allclose(w[e] + dn_[dstv[e], jj], dn_[ii, jj],
                                   rtol=1e-5, atol=1e-1)

    def test_no_change_event_is_free(self):
        """Deleting an edge that no shortest path uses re-derives
        nothing."""
        el, state, _ = self._mesh(seed=12)
        n = el.n_nodes
        dist, nh = self._full_exact(state, n)
        # craft: raise one link's latency sky-high first so nothing
        # routes through it, then delete it
        import dataclasses

        props = np.asarray(state.props).copy()
        both = np.array([0, el.n_links], np.int32)
        props[both, es.P_LATENCY_US] = 1e9
        state = dataclasses.replace(state, props=jnp.asarray(props))
        dist, nh = self._full_exact(state, n)
        w_old = np.asarray(R.edge_weights_latency(state))[both]
        s_k = np.asarray(state.src)[both]
        d_k = np.asarray(state.dst)[both]
        state = es.delete_links(state, jnp.asarray(both),
                                jnp.ones(2, bool))
        dist2, nh2, cells = R.update_routes_incremental(
            state, n, dist, nh, s_k, d_k, w_old,
            np.full(2, np.inf, np.float32))
        assert cells == 0
        assert dist2 is not None

    def test_reconverge_scenario_smoke(self):
        """The bench rung end to end at toy scale (three_tier scaled
        down via monkeypatched builder params would change the rung;
        instead run the real function with fewer events — still 10k
        nodes, so keep it single-event and coarse)."""
        from kubedtn_tpu.models import topologies as T

        el = T.three_tier(pods=4, leaves_per_pod=12, aggs_per_pod=2,
                          cores=4, cores_per_agg=2, seed=0)
        assert el.n_nodes == 4 * 14 + 4
        assert el.n_links == 4 * 12 * 2 + 4 * 2 * 2
        # per-link latency spread breaks ties deterministically
        lat = el.props[:, es.P_LATENCY_US]
        assert len(np.unique(lat)) > el.n_links // 2


def test_link_up_reconnects_partition_incrementally():
    """Regression (r4 review): a link-up that reconnects previously
    UNREACHABLE pairs must flag them — inf - eps is NaN and a naive
    `via < old - eps` never fires, silently leaving the partition
    routed as permanently unreachable."""
    import dataclasses

    from kubedtn_tpu.models import topologies as T

    # path 0-1-2-3 plus node 4 reachable only via 3-4
    el = T.random_mesh(5, 5, seed=1, props=LinkProperties(latency="1ms"))
    names = ["n0", "n1", "n2", "n3", "n4"]
    pairs = [(0, 1), (1, 2), (2, 3), (3, 4)]
    el = T._mk(names, pairs, LinkProperties(latency="1ms"))
    state, rows = T.load_edge_list_into_state(el)
    n = 5
    seed = jnp.full((n, n), jnp.inf, jnp.float32)
    dist = R.refine_dist(state, n, seed, 16)
    nh = R.next_hop_edges(state, dist, n)
    # take 3-4 down (both directions), reconverge incrementally
    both = np.array([3, 3 + el.n_links], np.int32)
    w_old = np.asarray(R.edge_weights_latency(state))[both]
    s_k = np.asarray(state.src)[both]
    d_k = np.asarray(state.dst)[both]
    src0, dst0, uid0, props0 = el.directed()
    state = es.delete_links(state, jnp.asarray(both), jnp.ones(2, bool))
    dist, nh, _ = R.update_routes_incremental(
        state, n, dist, nh, s_k, d_k, w_old,
        np.full(2, np.inf, np.float32))
    assert not np.isfinite(np.asarray(dist)[0, 4])
    # bring it back: node 4 must become reachable again
    state = es.apply_links(state, jnp.asarray(both),
                           jnp.asarray(uid0[both]),
                           jnp.asarray(src0[both]),
                           jnp.asarray(dst0[both]),
                           jnp.asarray(props0[both]), jnp.ones(2, bool))
    w_new = np.asarray(R.edge_weights_latency(state))[both]
    dist, nh, cells = R.update_routes_incremental(
        state, n, dist, nh, s_k, d_k,
        np.full(2, np.inf, np.float32), w_new)
    assert cells > 0, "reconnection event was silently skipped"
    dn = np.asarray(dist)
    assert np.isfinite(dn[0, 4]) and np.isfinite(dn[4, 0])
    dist_f = R.refine_dist(state, n,
                           jnp.full((n, n), jnp.inf, jnp.float32), 16)
    assert np.allclose(dn, np.asarray(dist_f), rtol=1e-5, atol=1e-1,
                       equal_nan=True)
    assert int(np.asarray(nh)[0, 4]) >= 0


def test_prng_bits_to_uniform_handles_sign_bit():
    """Regression (r4 review): pltpu.prng_random_bits yields SIGNED
    int32; an arithmetic shift would map half of all draws to negative
    'uniforms' (≈ certain loss hits on TPU). The conversion must
    bitcast to uint32 first."""
    from kubedtn_tpu.ops.pallas import shaping

    bits = jnp.asarray(
        np.array([-1, -16777216, 0, 1 << 30, -(1 << 30)], np.int32))
    u = np.asarray(shaping._bits_to_uniform(bits))
    assert (u >= 0.0).all() and (u < 1.0).all(), u
    # top bit set -> upper half of [0,1)
    assert u[0] > 0.99
    assert abs(u[3] - 0.25) < 1e-6
