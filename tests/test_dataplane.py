"""Data-plane tests: traffic generation, delay lines, end-to-end scenarios.

The iperf/bandwidth and latency scenarios mirror the reference's e2e test
matrix (reference config/samples/tc/bandwidth.yaml, tc/latency.yaml) in
virtual time.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.models import topologies as T
from kubedtn_tpu.models.traffic import (
    MODE_CBR,
    MODE_OFF,
    MODE_ONOFF,
    MODE_POISSON,
    TrafficSpec,
    cbr_everywhere,
    generate,
    init_traffic_state,
)
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops.queues import init_inflight, insert_inflight, pop_due
from kubedtn_tpu import sim as S


def mk_sim(props: LinkProperties, n_pairs=4, q=32):
    el = T.line(n_pairs + 1, props)
    state, rows = T.load_edge_list_into_state(el)
    return S.init_sim(state, q=q), el, state.capacity


class TestTraffic:
    def test_cbr_rate(self):
        cap = 8
        spec = cbr_everywhere(cap, 4, rate_bps=12_000_000, pkt_bytes=1500)
        ts = init_traffic_state(cap)
        total = np.zeros(cap)
        key = jax.random.key(0)
        for i in range(100):
            key, k = jax.random.split(key)
            ts, sizes, valid, t_arr = generate(spec, ts, jnp.float32(1000.0),
                                               8, k)
            total += np.asarray(sizes.sum(axis=1))
        # 12 Mbit/s for 0.1s = 150_000 bytes on edges 0..3, none elsewhere
        np.testing.assert_allclose(total[:4], 150_000, rtol=0.02)
        assert np.all(total[4:] == 0)

    def test_poisson_mean(self):
        cap = 4
        spec = TrafficSpec(
            mode=jnp.full((cap,), MODE_POISSON, jnp.int32),
            rate_bps=jnp.full((cap,), 12_000_000.0),
            pkt_bytes=jnp.full((cap,), 1500.0),
            on_us=jnp.zeros((cap,)), off_us=jnp.zeros((cap,)))
        ts = init_traffic_state(cap)
        counts = []
        key = jax.random.key(1)
        for i in range(300):
            key, k = jax.random.split(key)
            ts, sizes, valid, _ = generate(spec, ts, jnp.float32(1000.0), 8, k)
            counts.append(np.asarray(valid.sum(axis=1)))
        mean = np.mean(counts)  # lambda = 1.5e6/8e6*1000/1500 = 1 pkt/step
        assert mean == pytest.approx(1.0, abs=0.1)

    def test_onoff_duty_cycle(self):
        cap = 64
        spec = TrafficSpec(
            mode=jnp.full((cap,), MODE_ONOFF, jnp.int32),
            rate_bps=jnp.full((cap,), 12_000_000.0),
            pkt_bytes=jnp.full((cap,), 1500.0),
            on_us=jnp.full((cap,), 10_000.0),
            off_us=jnp.full((cap,), 30_000.0))
        ts = init_traffic_state(cap)
        key = jax.random.key(2)
        on_frac = []
        for i in range(400):
            key, k = jax.random.split(key)
            ts, *_ = generate(spec, ts, jnp.float32(1000.0), 8, k)
            on_frac.append(np.asarray(ts.on).mean())
        # stationary P(on) = off->on rate share = 10/(10+30) = 0.25
        assert np.mean(on_frac[100:]) == pytest.approx(0.25, abs=0.07)


class TestInflight:
    def test_insert_and_pop(self):
        fl = init_inflight(2, q=4)
        dep = jnp.array([[100.0, 900.0], [jnp.inf, jnp.inf]])
        sz = jnp.array([[10.0, 20.0], [0.0, 0.0]])
        fd = jnp.zeros((2, 2), jnp.int32)
        co = jnp.zeros((2, 2), dtype=bool)
        ok = jnp.array([[True, True], [False, False]])
        fl, dropped = insert_inflight(fl, dep, sz, fd, co, ok)
        assert float(dropped.sum()) == 0
        fl2, due = pop_due(fl, jnp.float32(500.0))
        assert int(due[0].sum()) == 1  # only the 100µs packet is due
        assert float(jnp.where(due, fl.size, 0).sum()) == 10.0
        # remaining packet's clock rolled: 900 - 500 = 400
        assert float(fl2.t[0].min()) == pytest.approx(400.0)

    def test_ring_overflow_drops(self):
        fl = init_inflight(1, q=2)
        dep = jnp.full((1, 4), 1e6, jnp.float32)  # none due soon
        sz = jnp.ones((1, 4))
        ok = jnp.ones((1, 4), dtype=bool)
        fl, dropped = insert_inflight(fl, dep, sz,
                                      jnp.zeros((1, 4), jnp.int32),
                                      jnp.zeros((1, 4), dtype=bool), ok)
        assert float(dropped[0]) == 2.0  # q=2 holds 2, drops 2

    def test_time_ordered_delivery_overtake(self):
        # a later-inserted packet with smaller t delivers first
        fl = init_inflight(1, q=4)
        dep = jnp.array([[5000.0, 100.0]])
        sz = jnp.array([[111.0, 222.0]])
        ok = jnp.ones((1, 2), dtype=bool)
        fl, _ = insert_inflight(fl, dep, sz, jnp.zeros((1, 2), jnp.int32),
                                jnp.zeros((1, 2), dtype=bool), ok)
        fl2, due = pop_due(fl, jnp.float32(1000.0))
        delivered_bytes = float(jnp.where(due, fl.size, 0).sum())
        assert delivered_bytes == 222.0  # the overtaker only


class TestEndToEnd:
    def test_latency_pipe(self):
        # 10ms link: CBR traffic goes in, arrives exactly one latency later.
        sim, el, cap = mk_sim(LinkProperties(latency="10ms"), n_pairs=1)
        spec = cbr_everywhere(cap, 2, rate_bps=12_000_000, pkt_bytes=1500)
        sim1 = S.run(sim, spec, steps=9, dt_us=1000.0, k_slots=4)
        # after 9ms: packets in flight, none delivered
        assert float(sim1.counters.tx_packets.sum()) > 0
        assert float(sim1.counters.rx_packets.sum()) == 0
        sim2 = S.run(sim1, spec, steps=30, dt_us=1000.0, k_slots=4, seed=1)
        c = sim2.counters
        assert float(c.rx_packets.sum()) > 0
        # conservation: tx = rx + in-flight (no drops configured)
        infl = float((sim2.inflight.t[:, :] != jnp.inf).sum())
        assert float(c.tx_packets.sum()) == float(c.rx_packets.sum()) + infl

    def test_iperf_rate_capped(self):
        # offer 100 Mbit through a 20 Mbit TBF: goodput ≈ 20 Mbit after the
        # initial burst drains (the bandwidth.yaml scenario, virtualized).
        # ring must cover the TBF's 50ms backlog: 20Mbit*50ms/1500B ≈ 84
        # queued packets, so q=32 (the default) would overflow — size it
        # like the kernel's qdisc limit.
        sim, el, cap = mk_sim(LinkProperties(rate="20Mbit"), n_pairs=1,
                              q=128)
        spec = cbr_everywhere(cap, 1, rate_bps=100_000_000, pkt_bytes=1500)
        # warm 300ms to burn the initial 80KB burst, then measure 1s
        sim = S.run(sim, spec, steps=300, dt_us=1000.0, k_slots=16)
        before = sim.counters
        sim = S.run(sim, spec, steps=1000, dt_us=1000.0, k_slots=16, seed=9)
        bps = float(S.throughput_bps(before, sim.counters, 1_000_000.0)[0])
        assert bps == pytest.approx(20e6, rel=0.05)
        assert float(sim.counters.dropped_queue.sum()) > 0  # overload drops

    def test_loss_reduces_goodput(self):
        sim, el, cap = mk_sim(LinkProperties(loss="25"), n_pairs=1)
        spec = cbr_everywhere(cap, 1, rate_bps=12_000_000, pkt_bytes=1500)
        sim = S.run(sim, spec, steps=500, dt_us=1000.0, k_slots=8)
        c = sim.counters
        lost = float(c.dropped_loss[0])
        tx = float(c.tx_packets[0])
        assert lost / tx == pytest.approx(0.25, abs=0.04)

    def test_duplicate_inflates_rx(self):
        sim, el, cap = mk_sim(LinkProperties(duplicate="50"), n_pairs=1)
        spec = cbr_everywhere(cap, 1, rate_bps=12_000_000, pkt_bytes=1500)
        sim = S.run(sim, spec, steps=400, dt_us=1000.0, k_slots=8)
        c = sim.counters
        # rx ≈ 1.5x tx (half the packets delivered twice), minus in-flight
        ratio = float(c.rx_packets[0]) / float(c.tx_packets[0])
        assert ratio == pytest.approx(1.5, abs=0.06)

    def test_jitter_spreads_delivery(self):
        sim, el, cap = mk_sim(
            LinkProperties(latency="5ms", jitter="2ms"), n_pairs=1)
        spec = cbr_everywhere(cap, 1, rate_bps=12_000_000, pkt_bytes=1500)
        sim = S.run(sim, spec, steps=200, dt_us=1000.0, k_slots=8)
        assert float(sim.counters.rx_packets[0]) > 0

    def test_clock_advances(self):
        sim, el, cap = mk_sim(LinkProperties(), n_pairs=1)
        spec = cbr_everywhere(cap, 0, 0.0)
        sim = S.run(sim, spec, steps=10, dt_us=500.0)
        assert float(sim.clock_us) == 5000.0
