"""K8s bridge tests: cluster↔store sync against an in-memory fake cluster
(the same duck-typed transport surface the real `kubernetes`-backed adapter
implements), mirroring how the reference scaffolds controller tests against
envtest (reference controllers/suite_test.go:44-80) — but with behavior
actually exercised."""

import pytest

from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                   TopologySpec)
from kubedtn_tpu.topology.k8s import K8sBridge, K8sUnavailable, make_kube_api
from kubedtn_tpu.topology.store import NotFoundError, TopologyStore


class FakeClusterApi:
    """Minimal apiserver double for the bridge transport surface. Every
    stored/queued manifest is deep-copied — a real apiserver serializes,
    so objects never share structure with watch events."""

    def __init__(self):
        self.objects: dict[str, dict] = {}
        self.rv = 0
        self.events: list[tuple[str, dict]] = []
        self.status_patches: list[tuple[str, str, dict]] = []

    # -- test helpers --------------------------------------------------
    def put(self, manifest, event="ADDED"):
        import copy

        manifest = copy.deepcopy(manifest)
        self.rv += 1
        manifest.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        key = (manifest["metadata"].get("namespace", "default") + "/"
               + manifest["metadata"]["name"])
        self.objects[key] = manifest
        self.events.append((event, copy.deepcopy(manifest)))

    def remove(self, ns, name):
        key = f"{ns}/{name}"
        manifest = dict(self.objects.pop(key))
        self.rv += 1
        manifest.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        self.events.append(("DELETED", manifest))

    # -- transport surface ---------------------------------------------
    def list_topologies(self):
        return list(self.objects.values()), str(self.rv)

    def watch_topologies(self, resource_version):
        # like the real apiserver: only events newer than the given rv
        since = int(resource_version)
        pending = [e for e in self.events
                   if int(e[1]["metadata"]["resourceVersion"]) > since]
        self.events = []
        yield from pending

    def patch_status(self, ns, name, status):
        import copy

        key = f"{ns}/{name}"
        if key not in self.objects:
            raise NotFoundError(key)
        self.status_patches.append((ns, name, copy.deepcopy(status)))
        self.rv += 1
        obj = copy.deepcopy(self.objects[key])
        obj["status"] = copy.deepcopy(status)
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        self.objects[key] = obj
        self.events.append(("MODIFIED", copy.deepcopy(obj)))


def manifest(name, uid=1, peer="r2", latency="10ms"):
    return {
        "apiVersion": "y-young.github.io/v1",
        "kind": "Topology",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"links": [{
            "uid": uid, "local_intf": "eth1", "peer_intf": "eth1",
            "peer_pod": peer, "properties": {"latency": latency},
        }]},
    }


def test_sync_once_seeds_store_and_prunes_stale():
    api = FakeClusterApi()
    api.put(manifest("r1"))
    api.put(manifest("r2", peer="r1"))
    store = TopologyStore()
    store.create(Topology(name="ghost", spec=TopologySpec(links=[])))
    bridge = K8sBridge(store, api)
    assert bridge.sync_once() == 2
    assert {t.name for t in store.list()} == {"r1", "r2"}
    with pytest.raises(NotFoundError):
        store.get("default", "ghost")


def test_watch_pump_applies_spec_changes_and_deletes():
    api = FakeClusterApi()
    api.put(manifest("r1"))
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    bridge.sync_once()

    # spec edit upstream
    m = manifest("r1", latency="50ms")
    api.put(m, event="MODIFIED")
    # a new pod + a deletion
    api.put(manifest("r3", peer="r1"))
    api.remove("default", "r1")
    n = bridge.pump(api.watch_topologies(bridge.cluster_rv))
    assert n == 3
    assert {t.name for t in store.list()} == {"r3"}
    assert bridge.stats["deleted"] == 1


def test_spec_update_preserves_local_status():
    """Cluster owns spec; locally-written status (placement) survives the
    fold-in — the CNI-vs-controller split-writer discipline."""
    api = FakeClusterApi()
    api.put(manifest("r1"))
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    bridge.sync_once()

    t = store.get("default", "r1")
    t.status.src_ip, t.status.net_ns = "10.0.0.5", "/proc/ns/1"
    store.update_status(t)

    api.put(manifest("r1", latency="99ms"), event="MODIFIED")
    bridge.pump(api.watch_topologies(bridge.cluster_rv))
    t2 = store.get("default", "r1")
    assert t2.spec.links[0].properties.latency == "99ms"
    assert t2.status.src_ip == "10.0.0.5"


def test_push_status_and_echo_suppression():
    api = FakeClusterApi()
    api.put(manifest("r1"))
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    bridge.sync_once()

    t = store.get("default", "r1")
    t.status.src_ip, t.status.net_ns = "10.0.0.7", "/proc/ns/2"
    store.update_status(t)
    assert bridge.push_status(store.get("default", "r1"))
    assert api.status_patches and api.status_patches[-1][2]["src_ip"] == \
        "10.0.0.7"
    # identical second push is a no-op
    assert bridge.push_status(store.get("default", "r1"))
    assert len(api.status_patches) == 1
    # the MODIFIED echo from our own patch does not churn the store
    rv_before = store.get("default", "r1").resource_version
    bridge.pump(api.watch_topologies(bridge.cluster_rv))
    assert bridge.stats["echoes_skipped"] == 1
    assert store.get("default", "r1").resource_version == rv_before


def test_bridge_drives_engine_end_to_end():
    """Cluster events -> store -> reconciler -> device arrays, with the
    status pushed back: the reference's controller+informer loop shape."""
    from kubedtn_tpu.topology import Reconciler, SimEngine

    api = FakeClusterApi()
    api.put(manifest("r1", peer="r2"))
    api.put(manifest("r2", peer="r1"))
    store = TopologyStore()
    engine = SimEngine(store)
    rec = Reconciler(store, engine)
    bridge = K8sBridge(store, api)
    bridge.sync_once()
    for name in ("r1", "r2"):
        engine.setup_pod(name)
    rec.drain()
    assert engine.num_active == 2
    for t in store.list():
        assert bridge.push_status(t)
    assert len(api.status_patches) == 2

    # upstream latency change flows through to the device row
    api.put(manifest("r1", peer="r2", latency="77ms"), event="MODIFIED")
    bridge.pump(api.watch_topologies(bridge.cluster_rv))
    rec.drain()
    row = engine.link_row("default/r1", 1)
    assert row["latency_us"] == 77_000


def test_real_client_gated():
    with pytest.raises(K8sUnavailable):
        make_kube_api()


def test_foreign_status_write_does_not_churn_store():
    """A status-only MODIFIED from ANOTHER writer (not in our pushed
    cache) must not bump the store rv / re-trigger reconciliation."""
    api = FakeClusterApi()
    api.put(manifest("r1"))
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    bridge.sync_once()
    rv_before = store.get("default", "r1").resource_version

    peer_view = dict(api.objects["default/r1"])
    peer_view["status"] = {"src_ip": "10.9.9.9", "net_ns": "/proc/ns/77"}
    api.put(peer_view, event="MODIFIED")
    bridge.pump(api.watch_topologies(bridge.cluster_rv))
    assert store.get("default", "r1").resource_version == rv_before


def test_push_status_transient_error_propagates_not_false():
    """A network blip must not read as 'object deleted' (False)."""
    api = FakeClusterApi()
    api.put(manifest("r1"))
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    bridge.sync_once()
    t = store.get("default", "r1")
    t.status.src_ip, t.status.net_ns = "1.2.3.4", "/ns"
    store.update_status(t)

    boom = RuntimeError("apiserver 500")
    api.patch_status_orig = api.patch_status
    api.patch_status = lambda *a: (_ for _ in ()).throw(boom)
    with pytest.raises(RuntimeError):
        bridge.push_status(store.get("default", "r1"))
    api.patch_status = api.patch_status_orig
    assert bridge.push_status(store.get("default", "r1"))
    # vanished upstream: False, not an exception
    api.remove("default", "r1")
    t.status.src_ip = "5.6.7.8"
    assert bridge.push_status(t) is False


def test_finalizer_patch_failure_keeps_echo_suppression():
    api = FakeClusterApi()
    api.put(manifest("r1"))
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    bridge.sync_once()
    t = store.get("default", "r1")
    t.status.src_ip, t.status.net_ns = "1.1.1.1", "/ns"
    t.finalizers = ["kubedtn"]
    store.update_status(t)

    api.patch_finalizers = lambda *a: (_ for _ in ()).throw(
        RuntimeError("transient"))
    with pytest.raises(RuntimeError):
        bridge.push_status(store.get("default", "r1"))
    # the status DID land; its echo must still be suppressed
    rv_before = store.get("default", "r1").resource_version
    bridge.pump(api.watch_topologies(bridge.cluster_rv))
    assert bridge.stats["echoes_skipped"] == 1
    assert store.get("default", "r1").resource_version == rv_before


def test_restarted_informer_gets_fresh_stop_event():
    """A predecessor thread wedged in a watch must stay stopped: each
    start() binds a new stop event, never un-stopping the old thread."""
    import threading

    api = FakeClusterApi()
    store = TopologyStore()
    bridge = K8sBridge(store, api)
    release = threading.Event()

    def blocking_watch(rv):
        release.wait(10)
        return iter(())

    api.watch_topologies = blocking_watch
    bridge.start()
    ev1 = bridge._stop
    bridge.stop()            # join times out? no — watch returns on release
    assert ev1.is_set()
    bridge.start()
    assert bridge._stop is not ev1 and not bridge._stop.is_set()
    release.set()
    bridge.stop()


def test_sync_once_gc_scoped_to_transport_namespace():
    """Regression: a namespace-scoped LIST says nothing about other
    namespaces — resync GC must not delete store objects outside the
    transport's scope."""
    api = FakeClusterApi()
    api.namespace = "scoped"          # transport advertises its scope
    m = manifest("r1")
    m["metadata"]["namespace"] = "scoped"
    api.put(m)
    store = TopologyStore()
    store.create(Topology(name="other", namespace="default",
                          spec=TopologySpec(links=[])))
    # stale object INSIDE the scope: still GCed
    store.create(Topology(name="gone", namespace="scoped",
                          spec=TopologySpec(links=[])))
    bridge = K8sBridge(store, api)
    bridge.sync_once()
    assert store.get("scoped", "r1") is not None
    assert store.get("default", "other") is not None   # survived the resync
    with pytest.raises(NotFoundError):
        store.get("scoped", "gone")


def test_sync_once_gc_cluster_scoped_unchanged():
    """Without a namespace attribute the transport is cluster-scoped and
    GC covers everything, as before."""
    api = FakeClusterApi()
    api.put(manifest("r1"))
    store = TopologyStore()
    store.create(Topology(name="stale", namespace="elsewhere",
                          spec=TopologySpec(links=[])))
    bridge = K8sBridge(store, api)
    bridge.sync_once()
    with pytest.raises(NotFoundError):
        store.get("elsewhere", "stale")
