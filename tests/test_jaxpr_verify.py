"""dtnverify: mutation-fixture kills per pass family, the real-tree
tier-1 gate (zero unwaivered jaxpr findings, ANALYSIS.json schema v2),
and the COST_BUDGET.json dispatch pin.

Mutation methodology: tests/fixtures/dtnverify/mutants.py re-introduces
each historical bug shape (raw key() into a sampler, f32 clock-anchor
cast, arithmetic on mailbox foreign bits, an un-fused two-dispatch
tick); every pass must KILL its mutant while the corresponding clean
control — and the real tree — stay silent. A pass that reports nothing
on its mutant has rotted, whatever it says about the tree.
"""

from __future__ import annotations

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from kubedtn_tpu.analysis import default_root
from kubedtn_tpu.analysis.verify.dtype_flow import check_dtype_flow
from kubedtn_tpu.analysis.verify.entrypoints import EntryPoint
from kubedtn_tpu.analysis.verify.ops_allowlist import check_keys, check_ops
from kubedtn_tpu.analysis.verify.sharding_audit import check_sharding

REPO = default_root()
_SPEC = importlib.util.spec_from_file_location(
    "dtnverify_mutants",
    Path(__file__).parent / "fixtures" / "dtnverify" / "mutants.py")
mutants = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(mutants)
# registered so the dispatch counter can wrap its jitted callables by
# module name, exactly as it wraps the runtime modules
sys.modules["dtnverify_mutants"] = mutants


def _entry(name, fn, *args, **kw) -> EntryPoint:
    ep = EntryPoint(name, "tests/fixtures/dtnverify/mutants.py", 1, **kw)
    ep.jaxpr = jax.make_jaxpr(fn)(*args)
    return ep


# ---- jkey / jops: key provenance --------------------------------------

def test_raw_key_mutant_killed():
    ep = _entry("mutant_raw_key", mutants.mutant_raw_key,
                jnp.zeros((4,)))
    found: list = []
    check_keys(ep, found)
    assert any("random_seed" in f.message for f in found), found
    ops: list = []
    check_ops(ep, ops)
    assert any("denied primitive `random_seed`" in f.message
               for f in ops), ops


def test_unsplit_key_mutant_killed():
    ep = _entry("mutant_unsplit_key", mutants.mutant_unsplit_key,
                jax.random.key(0), jnp.zeros((4,)))
    found: list = []
    check_keys(ep, found)
    assert any("consumed RAW" in f.message for f in found), found


def test_clean_key_control_silent():
    ep = _entry("clean_key_use", mutants.clean_key_use,
                jax.random.key(0), jnp.zeros((4,)))
    found: list = []
    check_keys(ep, found)
    check_ops(ep, found)
    assert found == []


# ---- jdtype: f64 anchor taint -----------------------------------------

def test_f32_anchor_mutant_killed():
    from jax.experimental import enable_x64

    with enable_x64():
        ep = _entry("mutant_f32_anchor", mutants.mutant_f32_anchor,
                    jnp.arange(3, dtype=jnp.float64),
                    jnp.zeros((4,), jnp.float32))
        found: list = []
        check_dtype_flow(ep, found)
    msgs = [f.message for f in found]
    assert any("truncating cast" in m for m in msgs), msgs
    assert any("scattered into" in m or "written into" in m
               for m in msgs), msgs


def test_clean_anchor_control():
    """The relative-time idiom still narrows f64→f32 — but only AFTER
    the anchor subtraction; the taint pass reports the cast (the value
    descends from the anchor) yet the scatter carries a small delta.
    The tree-level contract is stronger: NO f64 inside traced code at
    all (x64 off), which `test_real_tree_clean` pins; this control
    documents what the taint sees on an x64 trace."""
    from jax.experimental import enable_x64

    with enable_x64():
        ep = _entry("clean_anchor_use", mutants.clean_anchor_use,
                    jnp.arange(3, dtype=jnp.float64),
                    jnp.zeros((4,), jnp.float32))
        found: list = []
        check_dtype_flow(ep, found)
    # no f64 value lands in the f32 SoA without the narrowing being
    # visible: the cast IS reported (descends from the anchor)...
    assert any("truncating cast" in f.message for f in found)


def test_f32_only_program_silent():
    ep = _entry("f32_prog", lambda x: x * 2.0, jnp.zeros((4,)))
    found: list = []
    check_dtype_flow(ep, found)
    assert found == []


# ---- jshard: mailbox select-combine -----------------------------------

@pytest.fixture
def mesh2():
    from kubedtn_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices for a shard_map mailbox ring")
    return make_mesh(2)


def test_mailbox_arith_mutant_killed(mesh2):
    from kubedtn_tpu.parallel.mesh import EDGE_AXIS

    fn = mutants.make_mutant_mailbox_arith(mesh2, EDGE_AXIS)
    ep = _entry("mutant_mailbox_arith", fn,
                jnp.zeros((4, 3), jnp.float32),
                jnp.zeros((4, 2), jnp.int32),
                expect_shard_map=True,
                allowed_collectives=("ppermute", "axis_index"))
    found: list = []
    check_sharding(ep, found)
    assert any("BEFORE the ownership select" in f.message
               for f in found), found


def test_mailbox_cast_laundered_arith_killed(mesh2):
    """A dtype cast must not launder foreign-bit taint: the arithmetic
    combine hidden behind `astype` is still caught."""
    from kubedtn_tpu.parallel.mesh import EDGE_AXIS

    fn = mutants.make_mutant_mailbox_cast_arith(mesh2, EDGE_AXIS)
    ep = _entry("mutant_mailbox_cast_arith", fn,
                jnp.zeros((4, 3), jnp.float32),
                jnp.zeros((4, 2), jnp.int32),
                expect_shard_map=True,
                allowed_collectives=("ppermute", "axis_index"))
    found: list = []
    check_sharding(ep, found)
    assert any("BEFORE the ownership select" in f.message
               for f in found), found


def test_clean_mailbox_control_silent(mesh2):
    from kubedtn_tpu.parallel.mesh import EDGE_AXIS

    fn = mutants.make_clean_mailbox(mesh2, EDGE_AXIS)
    ep = _entry("clean_mailbox", fn,
                jnp.zeros((4, 3), jnp.float32),
                jnp.zeros((4, 2), jnp.int32),
                expect_shard_map=True,
                allowed_collectives=("ppermute", "axis_index"))
    found: list = []
    check_sharding(ep, found)
    assert found == []


# ---- jcost: dispatch counting + budget gate ---------------------------

def test_two_dispatch_mutant_counted():
    """The dispatch counter sees BOTH jitted calls of the un-fused
    mutant tick — a fused program would count one."""
    from kubedtn_tpu.analysis.verify.dispatch import count_dispatches

    x = jnp.zeros((8,))
    mutants.mutant_two_dispatch_tick(x)  # warm the compiles
    n = count_dispatches(lambda: mutants.mutant_two_dispatch_tick(x),
                         ["dtnverify_mutants"])
    assert n == 2


def test_budget_flags_dispatch_regression(tmp_path):
    """A dispatch count above the pinned budget is a jcost finding —
    the fusion-regression gate."""
    from kubedtn_tpu.analysis.verify import budget as bm

    (tmp_path / "COST_BUDGET.json").write_text(json.dumps({
        "schema_version": 1, "backend": jax.default_backend(),
        "jax": jax.__version__, "tolerance": 1.5,
        "entries": {}, "dispatch": {"fused_tick_d1": 1}}))
    found: list = []
    bm.check_budget(tmp_path, [], {"fused_tick_d1": 2.0}, found)
    assert any("dispatches per tick" in f.message for f in found)
    found2: list = []
    bm.check_budget(tmp_path, [], {"fused_tick_d1": 1.0}, found2)
    assert found2 == []


def test_budget_flags_cost_regression(tmp_path):
    from kubedtn_tpu.analysis.verify import budget as bm

    (tmp_path / "COST_BUDGET.json").write_text(json.dumps({
        "schema_version": 1, "backend": jax.default_backend(),
        "jax": jax.__version__, "tolerance": 1.5,
        "entries": {"e": {"flops": 100.0, "bytes": 100.0, "eqns": 1}},
        "dispatch": {}}))
    ep = EntryPoint("e", "kubedtn_tpu/runtime.py", 1)
    ep.jaxpr = jax.make_jaxpr(lambda x: x)(jnp.zeros(()))
    ep.cost = {"flops": 200.0, "bytes": 90.0}
    found: list = []
    bm.check_budget(tmp_path, [ep], {}, found)
    assert any("flops regression" in f.message for f in found), found
    assert not any("bytes regression" in f.message for f in found)


def test_budget_missing_entry_is_finding(tmp_path):
    from kubedtn_tpu.analysis.verify import budget as bm

    (tmp_path / "COST_BUDGET.json").write_text(json.dumps({
        "schema_version": 1, "backend": jax.default_backend(),
        "jax": jax.__version__, "tolerance": 1.5,
        "entries": {}, "dispatch": {}}))
    ep = EntryPoint("brand_new", "kubedtn_tpu/runtime.py", 1)
    ep.jaxpr = jax.make_jaxpr(lambda x: x)(jnp.zeros(()))
    ep.cost = {"flops": 1.0, "bytes": 1.0}
    found: list = []
    bm.check_budget(tmp_path, [ep], {}, found)
    assert any("no budget pinned" in f.message for f in found)


# ---- the real tree: tier-1 gate ---------------------------------------

@pytest.fixture(scope="module")
def real_verify():
    """ONE full dtnverify run shared by the gate assertions below
    (tracing + compiling every entry point costs tens of seconds)."""
    from kubedtn_tpu.analysis.verify import run_verify

    return run_verify(root=REPO)


def test_real_tree_clean_and_artifact_written(real_verify):
    """Every entry point traces, all four pass families run, zero
    unwaivered jaxpr findings — and the combined schema-v3 artifact
    lands in ANALYSIS.json alongside the AST layer."""
    findings, report = real_verify
    active = [f for f in findings if not f.waived]
    assert active == [], "\n" + "\n".join(f.format() for f in active)
    eps = report["entry_points"]
    assert set(eps) == {
        "fused_tick_d1", "fused_tick_d2", "class_tick_tbf",
        "class_tick_seq", "class_tick_ind", "sharded_fused",
        "twin_sweep", "update_gate_sweep"}
    # on the tier-1 8-device CPU mesh nothing may skip
    skipped = {k: v for k, v in eps.items() if "skipped" in v}
    assert not skipped, skipped

    from kubedtn_tpu.analysis import run_suite, write_json

    _project, ast_findings = run_suite(root=REPO)
    section = dict(report)
    section["findings"] = [f.to_json() for f in findings]
    section["summary"] = {**report["summary"],
                          "total": len(findings),
                          "unwaivered": len(active)}
    out = REPO / "ANALYSIS.json"
    write_json(out, ast_findings, REPO, jaxpr=section)
    doc = json.loads(out.read_text())
    assert doc["schema_version"] == 3
    assert doc["jaxpr"]["summary"]["unwaivered"] == 0
    assert set(doc["jaxpr"]["entry_points"]) == set(eps)


def test_fused_tick_dispatch_pinned(real_verify):
    """COST_BUDGET.json pins the fused tick at ONE dispatch per steady
    tick, both pipeline depths — the measured probe must agree, so a
    fusion regression fails here before any bench run."""
    _findings, report = real_verify
    assert report["dispatch"]["fused_tick_d1"] == 1.0
    assert report["dispatch"]["fused_tick_d2"] == 1.0
    doc = json.loads((REPO / "COST_BUDGET.json").read_text())
    assert doc["dispatch"]["fused_tick_d1"] == 1.0
    assert doc["dispatch"]["fused_tick_d2"] == 1.0
    assert set(doc["entries"]) == set(report["entry_points"])


def test_sharded_entry_audited(real_verify):
    """The sharded program actually contains the shard_map + ring the
    audit reasons about (a trivially-empty audit would pass
    vacuously)."""
    from kubedtn_tpu.analysis.verify.entrypoints import trace_entry_points
    from kubedtn_tpu.analysis.verify.jaxpr_tools import primitive_set

    eps = trace_entry_points(entries=("sharded_fused",),
                             compile_costs=False)
    assert eps[0].jaxpr is not None, eps[0].skip_reason
    prims = primitive_set(eps[0].jaxpr.jaxpr)
    assert "shard_map" in prims and "ppermute" in prims


def test_cli_verify_subset(tmp_path):
    """`--verify --entries ...` runs the jaxpr layer end-to-end in a
    fresh process and writes the schema-v3 artifact."""
    out = tmp_path / "a.json"
    r = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.analysis", "-q",
         "--root", str(REPO), "--verify",
         "--entries", "twin_sweep", "--json", str(out)],
        capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["schema_version"] == 3
    assert "twin_sweep" in doc["jaxpr"]["entry_points"]


def test_subset_run_merges_into_full_artifact(tmp_path):
    """`--verify --entries X --json PATH` must not clobber a full
    artifact's jaxpr section: the re-traced entry merges over the old
    section, dispatch pins and other entries' findings survive."""
    from kubedtn_tpu.analysis.__main__ import _merge_subset_section

    full = {"schema_version": 2, "findings": [], "summary": {},
            "jaxpr": {
                "entry_points": {"fused_tick_d1": {"eqns": 10},
                                 "twin_sweep": {"eqns": 20}},
                "dispatch": {"fused_tick_d1": 1.0},
                "budget": {"checked": True},
                "findings": [
                    {"rule": "jops", "path": "a.py", "line": 1,
                     "message": "[fused_tick_d1] old finding",
                     "waived": False},
                    {"rule": "jcost", "path": "a.py", "line": 1,
                     "message": "[twin_sweep] dispatches per tick = "
                                "2.0 (budget 1.0)", "waived": False},
                    {"rule": "jops", "path": "a.py", "line": 1,
                     "message": "[twin_sweep] stale for this entry",
                     "waived": False}],
                "summary": {"total": 3}}}
    p = tmp_path / "A.json"
    p.write_text(json.dumps(full))
    subset = {"entry_points": {"twin_sweep": {"eqns": 21}},
              "dispatch": {}, "budget": {},
              "findings": [], "summary": {"total": 0}}
    merged = _merge_subset_section(p, subset, ("twin_sweep",))
    assert merged["dispatch"] == {"fused_tick_d1": 1.0}
    assert merged["entry_points"]["twin_sweep"]["eqns"] == 21
    assert merged["entry_points"]["fused_tick_d1"]["eqns"] == 10
    msgs = [f["message"] for f in merged["findings"]]
    assert "[fused_tick_d1] old finding" in msgs      # kept
    assert "[twin_sweep] stale for this entry" not in msgs  # re-traced
    # jcost findings survive even for the re-traced entry: a subset run
    # never re-measures dispatches/budgets, so dropping one would flip
    # the artifact to clean with the regression still live
    assert any("dispatches per tick" in m for m in msgs)
    assert merged["summary"]["total"] == 2


def test_verify_cache_roundtrip(tmp_path, real_verify):
    """The result cache replays a stored run while the tree hash
    matches and misses after any package-source edit."""
    from kubedtn_tpu.analysis.verify import runner

    findings, report = real_verify
    (tmp_path / "kubedtn_tpu").mkdir()
    (tmp_path / "kubedtn_tpu" / "m.py").write_text("x = 1\n")
    key = runner._tree_hash(tmp_path)
    runner._save_cache(tmp_path, key, findings, report)
    hit = runner._load_cache(tmp_path, key)
    assert hit is not None
    cached_findings, cached_report = hit
    assert [f.to_json() for f in cached_findings] == \
        [f.to_json() for f in findings]
    assert cached_report["entry_points"] == dict(report)["entry_points"]
    # a source edit moves the hash; the old key no longer hits
    (tmp_path / "kubedtn_tpu" / "m.py").write_text("x = 2\n")
    new_key = runner._tree_hash(tmp_path)
    assert new_key != key
    assert runner._load_cache(tmp_path, new_key) is None
