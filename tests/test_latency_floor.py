"""Live-plane delivery-latency floor: measured, pinned, documented.

The reference's netem delays are enforced by the kernel's qdisc watchdog
(hrtimer, ~µs accuracy). This plane binds virtual time to the wall clock
in the runner thread: ingress wakes a tick immediately and the runner
sleeps until the timing wheel's next deadline, so the expected error is

- delays >= ~1 tick period: sub-millisecond (the wheel wakes the runner
  just-in-time; measured ~0.2ms median on an idle CPU host);
- sub-tick delays (e.g. 1ms): one or two device-dispatch times (the
  shaping call itself takes ~1-3ms on the CPU backend), bounded by one
  tick period.

These tests pin those bounds with CI headroom. docs/OPERATIONS.md
carries the numbers and the kernel comparison. One-time jit compiles of
new batch-size buckets (seconds each) are excluded by warming the
kernels first — a fresh daemon pays them during its first seconds of
traffic unless the persistent compilation cache is primed.
"""

import os
import time
from collections import deque

import numpy as np

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore
from kubedtn_tpu.wire import proto as pb
from kubedtn_tpu.wire.server import Daemon

TICK_S = 0.010  # the plane's default period (dt_us=10_000)


class _TimedDeque(deque):
    def __init__(self):
        super().__init__()
        self.times = []

    def append(self, x):
        super().append(x)
        self.times.append(time.monotonic())

    def extend(self, xs):
        xs = list(xs)
        super().extend(xs)
        now = time.monotonic()
        self.times.extend([now] * len(xs))


def _build(latency: str):
    store = TopologyStore()
    engine = SimEngine(store, capacity=16)
    props = LinkProperties(latency=latency)
    store.create(Topology(name="a", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="b", uid=1,
             properties=props)])))
    store.create(Topology(name="b", spec=TopologySpec(links=[
        Link(local_intf="eth1", peer_intf="eth1", peer_pod="a", uid=1,
             properties=props)])))
    engine.setup_pod("a")
    engine.setup_pod("b")
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    plane = WireDataPlane(daemon)
    wa = daemon._add_wire(pb.WireDef(local_pod_name="a",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    wb = daemon._add_wire(pb.WireDef(local_pod_name="b",
                                     kube_ns="default", link_uid=1,
                                     intf_name_in_pod="eth1"))
    return plane, wa, wb


def _warm_buckets():
    """Compile the (R, K) batch-kernel buckets a single-wire measurement
    touches, on a throwaway plane with deterministic ticks — the
    one-time compile cost must not masquerade as delivery latency."""
    plane, wa, _wb = _build("1ms")
    t = 50.0
    for burst in (1, 3, 10, 1):
        wa.ingress.extend([b"w" * 100] * burst)
        t += 0.02
        plane.tick(now_s=t)
        t += 0.02
        plane.tick(now_s=t)


def _measure(latency_s: float, latency: str, n: int = 25):
    plane, wa, wb = _build(latency)
    wb.egress = _TimedDeque()
    plane.start()
    try:
        wa.ingress.append(b"w" * 100)  # runner warm (clock, hot set)
        time.sleep(0.3 + latency_s)
        wb.egress.times.clear()
        wb.egress.clear()
        sends = []
        for i in range(n):
            sends.append(time.monotonic())
            wa.ingress.append(bytes([i % 256]) * 120)
            time.sleep(0.02)
        deadline = time.monotonic() + 5 + latency_s
        while len(wb.egress.times) < n and time.monotonic() < deadline:
            time.sleep(0.005)
    finally:
        plane.stop()
    assert len(wb.egress.times) == n, (
        f"only {len(wb.egress.times)}/{n} frames delivered")
    return np.array([(d - s - latency_s) * 1000
                     for s, d in zip(sends, wb.egress.times)])


# Tight (floor-level) bounds hold on an idle host but a heavily
# oversubscribed CI machine can exceed them on scheduler jitter alone;
# they run only under KUBEDTN_STRICT_TIMING=1 (the perf-gate used when
# the latency floor itself is the thing under test). The regression-scale
# bounds — catching tick-bound (>= period) or runaway (seconds) behavior,
# i.e. a broken wake-early path or a compile in the hot loop — always run.
STRICT = os.environ.get("KUBEDTN_STRICT_TIMING", "") == "1"


def test_live_delivery_error_bounds():
    """One warmed process, three delay scales. Early delivery (a frame
    released BEFORE its netem delay elapsed) is a correctness bug no
    scheduler jitter can cause, so that bound is unconditional too."""
    _warm_buckets()
    # >= 1 tick period: the wheel wake makes delivery sub-millisecond
    for lat_s, lat in ((0.010, "10ms"), (0.100, "100ms")):
        errs = _measure(lat_s, lat)
        med = float(np.median(errs))
        p90 = float(np.percentile(errs, 90))
        assert med <= 10 * TICK_S * 1e3, f"{lat}: median error {med:.2f}ms"
        assert p90 <= 1000.0, f"{lat}: p90 {p90:.2f}ms (runaway)"
        assert errs.min() >= -1.0, f"{lat}: early delivery {errs.min()}ms"
        if STRICT:
            assert med <= 5.0, f"{lat}: median error {med:.2f}ms"
            assert p90 <= TICK_S * 1e3 + 10.0, f"{lat}: p90 {p90:.2f}ms"
    # sub-tick delay: error = a couple of device dispatches, bounded by
    # ~one tick period (kernel netem would be ~µs here — documented gap)
    errs = _measure(0.001, "1ms")
    med = float(np.median(errs))
    p90 = float(np.percentile(errs, 90))
    assert med <= 10 * TICK_S * 1e3, f"1ms: median error {med:.2f}ms"
    assert p90 <= 1000.0, f"1ms: p90 {p90:.2f}ms (runaway)"
    assert errs.min() >= -1.0, f"1ms: early delivery {errs.min()}ms"
    if STRICT:
        assert med <= TICK_S * 1e3, f"1ms: median error {med:.2f}ms"
        assert p90 <= TICK_S * 1e3 + 15.0, f"1ms: p90 {p90:.2f}ms"
