"""Shared-memory ingest plane (kubedtn_tpu/shm + native section 5).

Pins the contracts ARCHITECTURE.md's "Shared-memory ingest plane"
section states:

- ring protocol: FIFO roundtrip, exact pending/committed accounting,
  ring-full returns (never drops), oversized frames rejected;
- crash safety: an uncommitted reservation (the frozen image of a
  producer killed between reserve and publish) is NEVER surfaced as a
  frame, and is only crossed after the producer pid provably died —
  committed frames beyond the tear still deliver;
- transport equivalence: the same frame sequence fed via the shm ring
  vs the gRPC stream RPC yields byte-identical delivered payload
  streams AND identical link-telemetry ring totals, at pipeline
  depths 1 and 2;
- admission at the ring head: an over-budget tenant's frames stay
  parked IN its ring (typed verdicts still metered), and ring residue
  folds into the adaptive-budget backlog signal;
- producer-side `ShmSender` backpressure: ring-full queues in the
  outage buffer with exact accounting — every frame is pushed exactly
  once, in order, or still counted in buffered().

Everything here needs the native library; the module auto-skips with
an honest reason when the host has neither a C toolchain nor the
prebuilt .so (tests/conftest.py, `requires_native_shm`).
"""

import os
import random
import struct
import subprocess
import sys

import numpy as np
import pytest

from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore

pytestmark = [pytest.mark.shm, pytest.mark.requires_native_shm]


# -- harness ------------------------------------------------------------

def _daemon_with_pairs(pairs, props, namespaces=None):
    """test_pipeline_determinism's pair builder, with optional per-pair
    namespaces (tenancy tests)."""
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * pairs + 8)
    nss = namespaces or ["default"] * pairs
    for i in range(pairs):
        ns = nss[i]
        a, b = f"a{i}", f"b{i}"
        store.create(Topology(name=a, namespace=ns,
                              spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=props)])))
        store.create(Topology(name=b, namespace=ns,
                              spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=props)])))
        engine.setup_pod(a, ns)
        engine.setup_pod(b, ns)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    win, wout = [], []
    for i in range(pairs):
        win.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"a{i}", kube_ns=nss[i], link_uid=i + 1,
            intf_name_in_pod="eth1")))
        wout.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"b{i}", kube_ns=nss[i], link_uid=i + 1,
            intf_name_in_pod="eth1")))
    return daemon, engine, win, wout


def _tagged_frames(wire_i: int, n: int, size: int = 64):
    return [bytes([wire_i]) + i.to_bytes(4, "big")
            + b"\x00" * (size - 5) for i in range(n)]


def _make_ring(tmp_path, name="p1.ring", slots=8192, slot_size=2048,
               namespace=""):
    from kubedtn_tpu.shm import ShmRing

    return ShmRing.create(str(tmp_path / name), slots=slots,
                          slot_size=slot_size, namespace=namespace)


# -- ring protocol ------------------------------------------------------

def test_ring_roundtrip_columns():
    """Push (single + batch) then batch-dequeue: FIFO bytes, correct
    wire/len/trace columns, exact pending accounting."""
    from kubedtn_tpu.shm import ShmRing
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        ring = ShmRing.create(os.path.join(d, "r.ring"), slots=64,
                              slot_size=256, namespace="teamx")
        assert ring.namespace == "teamx"
        assert ring.producer_pid() == os.getpid()
        assert ring.push(b"hello", 7, trace_id=0xABC) == 1
        frames = [bytes([i]) * (i + 1) for i in range(10)]
        assert ring.push_batch(frames, 9) == 10
        assert ring.pending() == 11 == len(ring)
        assert ring.committed() == 11

        blob, wires, offs, lens, traces, skipped = ring.dequeue(100)
        assert skipped == 0
        assert len(wires) == 11 and ring.pending() == 0
        got = [blob[int(o):int(o + ln)] for o, ln in zip(offs, lens)]
        assert got == [b"hello"] + frames
        assert wires.tolist() == [7] + [9] * 10
        assert traces.tolist() == [0xABC] + [0] * 10
        # empty dequeue: the no-frames shape
        blob, wires, *_rest, skipped = ring.dequeue(10)
        assert blob == b"" and wires is None and skipped == 0
        ring.close()


def test_ring_full_never_drops_and_oversize_rejected(tmp_path):
    from kubedtn_tpu.shm import ShmRingError

    ring = _make_ring(tmp_path, slots=8, slot_size=128)
    for i in range(8):
        assert ring.push(bytes([i]) * 16, 1) == 1
    assert ring.push(b"x", 1) == 0          # full: refused, not dropped
    assert ring.push_batch([b"a", b"b"], 1) == 0
    assert ring.full_failures() >= 2
    assert ring.pending() == 8              # nothing torn or lost
    assert ring.push(b"z" * 1000, 1) == -1  # > payload cap
    with pytest.raises(ShmRingError):
        ring.push_batch([b"z" * 1000], 1)
    # drain one slot -> exactly one more push fits
    _, wires, *_ = ring.dequeue(1)
    assert len(wires) == 1
    assert ring.push(b"y", 1) == 1
    assert ring.push(b"y", 1) == 0
    ring.close()


def test_ring_wraparound_property():
    """Seeded random push/push_batch/dequeue sequence against a python
    FIFO model: byte-exact order, column-exact metadata, pending
    accounting — across many wrap generations of a small ring."""
    import tempfile

    rng = random.Random(0x5157)
    with tempfile.TemporaryDirectory() as d:
        from kubedtn_tpu.shm import ShmRing

        ring = ShmRing.create(os.path.join(d, "r.ring"), slots=32,
                              slot_size=96)
        model = []  # (frame, wire, trace)
        seq = 0
        delivered = 0
        for _step in range(1500):
            op = rng.random()
            if op < 0.45 and len(model) < 32:
                k = rng.randint(1, 6)
                wid = rng.randint(1, 3)
                batch = []
                for _ in range(k):
                    f = struct.pack("<I", seq) + bytes(
                        [seq & 0xFF] * rng.randint(0, 60))
                    batch.append(f)
                    seq += 1
                pushed = ring.push_batch(
                    batch, wid, [s & 0xFFFF for s in range(seq - k, seq)])
                for j in range(pushed):
                    model.append((batch[j], wid,
                                  (seq - k + j) & 0xFFFF))
            elif op < 0.6 and len(model) < 32:
                f = struct.pack("<I", seq)
                if ring.push(f, 5, trace_id=seq) == 1:
                    model.append((f, 5, seq))
                    seq += 1
            else:
                want = rng.randint(1, 10)
                blob, wires, offs, lens, traces, skipped = \
                    ring.dequeue(want)
                assert skipped == 0
                n = 0 if wires is None else len(wires)
                assert n <= want and n <= len(model)
                for j in range(n):
                    ef, ew, et = model.pop(0)
                    o, ln = int(offs[j]), int(lens[j])
                    assert blob[o:o + ln] == ef
                    assert int(wires[j]) == ew
                    assert int(traces[j]) == et
                delivered += n
            assert ring.pending() == len(model)
        assert delivered > 300  # the schedule actually exercised wraps
        ring.close()


# -- crash safety: torn frames ------------------------------------------

def test_torn_reservation_blocks_while_producer_lives(tmp_path):
    """A reserve-without-commit gap (crash image) stalls the consumer
    at the gap — frames behind it deliver, frames beyond it wait, and
    the torn slot is NEVER surfaced."""
    ring = _make_ring(tmp_path, slots=64, slot_size=128)
    ring.push_batch([b"a", b"b"], 1)
    assert ring.push_torn(1)
    ring.push_batch([b"c", b"d"], 1)
    assert ring.pending() == 5
    assert ring.committed() == 4

    blob, wires, offs, lens, traces, skipped = ring.dequeue(100)
    assert skipped == 0
    assert [blob[int(o):int(o + ln)] for o, ln in zip(offs, lens)] \
        == [b"a", b"b"]
    # stalled at the gap: nothing more without skip_uncommitted
    blob, wires, *_rest, skipped = ring.dequeue(100)
    assert wires is None and skipped == 0
    assert ring.pending() == 3

    # the producer (us) is alive; only after a PROVEN death may the
    # consumer cross — simulate by passing skip explicitly (the driver
    # only does so after producer_dead())
    blob, wires, offs, lens, traces, skipped = ring.dequeue(
        100, skip_uncommitted=True)
    assert skipped == 1  # the torn slot: counted, never surfaced
    assert [blob[int(o):int(o + ln)] for o, ln in zip(offs, lens)] \
        == [b"c", b"d"]
    assert ring.pending() == 0
    ring.close()


def test_producer_death_detection(tmp_path):
    """producer_dead() needs a PROOF: a reaped child pid is dead, our
    own pid is not."""
    ring = _make_ring(tmp_path)
    assert not ring.producer_dead()  # it's us
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    from kubedtn_tpu import native

    native._load().kdt_shm_set_pid(ring._buf, proc.pid)
    assert ring.producer_pid() == proc.pid
    assert ring.producer_dead()
    ring.close()


# -- transport equivalence: shm vs gRPC stream --------------------------

DET_PROPS = [
    LinkProperties(latency="3ms"),
    LinkProperties(rate="2Gbit"),
]


def _run_plane_transport(depth, props, n_per_wire, transport,
                         tmp_path, pairs=2, ticks=30, dt=0.002,
                         feed_every=5):
    """Identical deterministic schedule over either transport. Frames
    feed in per-tick bursts below the explicit-clock drain budget
    (max_slots=4096), so arrival ticks — hence shaping and telemetry —
    are transport-independent, not just delivery order."""
    from kubedtn_tpu.shm import ShmIngest, ShmRing, ShmSender
    from kubedtn_tpu.wire import proto as pb

    daemon, _engine, win, wout = _daemon_with_pairs(pairs, props)
    plane = WireDataPlane(daemon, dt_us=dt * 1e6, pipeline_depth=depth)
    plane.pipeline_explicit_clock = True
    plane.enable_telemetry(window_s=0.01, sample_period=4)

    sender = ingest = None
    if transport == "shm":
        shm_dir = tmp_path / f"shm-d{depth}-{id(props) & 0xFFFF}"
        shm_dir.mkdir()
        sender = ShmSender(str(shm_dir / "prod.ring"),
                           namespace="default")
        ingest = ShmIngest(str(shm_dir))
        ingest.attach_ring(ShmRing.attach(sender.ring.path))
        plane.attach_shm(ingest, watcher=False)

    def feed(burst):
        for k, wa in enumerate(win):
            frames = _tagged_frames(k, burst)
            if transport == "shm":
                sender.send(wa.wire_id, frames)
            else:
                daemon.SendToStream(
                    iter([pb.Packet(remot_intf_id=wa.wire_id, frame=f)
                          for f in frames]), None)

    t = 100.0
    feeds = 0
    per_feed = -(-n_per_wire // (1 + (ticks - 1) // feed_every))
    fed = 0
    for j in range(ticks):
        if j % feed_every == 0 and fed < n_per_wire:
            burst = min(per_feed, n_per_wire - fed)
            feed(burst)
            fed += burst
            feeds += 1
        t += dt
        plane.tick(now_s=t)
    assert fed == n_per_wire
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert plane.tick_errors == 0
    if transport == "shm":
        assert sender.buffered() == 0
        assert ingest.pending_total() == 0
        st = ingest.stats()
        assert st["frames_in"] == pairs * n_per_wire
        sender.close()
        ingest.close()
    totals, _secs = plane.telemetry.window_sum()
    return [list(w.egress) for w in wout], totals, plane


@pytest.mark.parametrize("depth", [1, 2])
@pytest.mark.parametrize("props", DET_PROPS, ids=["latency", "tbf"])
def test_shm_matches_grpc_stream_byte_identical(depth, props, tmp_path):
    """The satellite contract: same frames via ring vs stream RPC →
    byte-identical per-wire delivered sequences AND identical
    link-telemetry ring totals, at depths 1 and 2."""
    got_g, tot_g, pg = _run_plane_transport(
        depth, props, 120, "grpc", tmp_path)
    got_s, tot_s, ps = _run_plane_transport(
        depth, props, 120, "shm", tmp_path)
    assert pg.shaped == ps.shaped
    assert pg.dropped == ps.dropped == 0
    for wg, ws in zip(got_g, got_s):
        assert wg == ws  # byte-identical, in order
    assert sum(len(w) for w in got_g) == 2 * 120
    assert np.array_equal(tot_g, tot_s)  # telemetry ring totals


def test_shm_depth2_matches_depth1(tmp_path):
    """Pipeline overlap must not reorder ring traffic either."""
    got1, tot1, _p1 = _run_plane_transport(
        1, DET_PROPS[0], 120, "shm", tmp_path)
    got2, tot2, _p2 = _run_plane_transport(
        2, DET_PROPS[0], 120, "shm", tmp_path)
    for w1, w2 in zip(got1, got2):
        assert w1 == w2
    assert np.array_equal(tot1, tot2)


def test_trace_id_survives_ring_to_delivery(tmp_path):
    """A producer-minted sampled trace id rides the slot layout and
    comes out the far side: received -> ingress -> ... -> delivered,
    all under the SAME id (`kdt trace` spans shm ingest like gRPC)."""
    from kubedtn_tpu import telemetry as tele
    from kubedtn_tpu.shm import ShmIngest, ShmRing, ShmSender

    daemon, _engine, win, wout = _daemon_with_pairs(1, DET_PROPS[0])
    plane = WireDataPlane(daemon, dt_us=2000.0, pipeline_depth=1)
    plane.pipeline_explicit_clock = True
    plane.enable_telemetry(window_s=0.01, sample_period=4)
    shm_dir = tmp_path / "rings"
    shm_dir.mkdir()
    sender = ShmSender(str(shm_dir / "p.ring"), namespace="default",
                       sample_period=4)
    ingest = ShmIngest(str(shm_dir))
    ingest.attach_ring(ShmRing.attach(sender.ring.path))
    plane.attach_shm(ingest, watcher=False)

    sender.send(win[0].wire_id, _tagged_frames(0, 20))
    assert len(sender.minted) == 5  # every 4th frame stamped
    t = 100.0
    for _ in range(10):
        t += 0.002
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert len(wout[0].egress) == 20

    rec = plane.recorder
    spanning = 0
    for tid in sender.minted:
        stages = [e[3] for e in rec.events_for(tid)]
        if stages:
            assert tele.ST_RECEIVED in stages
            assert tele.ST_INGRESS in stages
            assert tele.ST_DELIVERED in stages
            spanning += 1
    assert spanning == 5, "all minted ids must span ingest->delivery"
    sender.close()
    ingest.close()
    plane.stop()


# -- admission at the ring head -----------------------------------------

def test_admission_parks_frames_in_ring(tmp_path):
    """An over-budget tenant's frames NEVER leave its ring: typed
    verdicts are recorded and metered, nothing is dropped, and once
    the budget refills everything delivers."""
    from kubedtn_tpu.shm import ShmIngest, ShmRing, ShmSender
    from kubedtn_tpu.tenancy import TenantRegistry

    daemon, engine, win, wout = _daemon_with_pairs(
        1, DET_PROPS[0], namespaces=["busy"])
    reg = TenantRegistry(engine)
    reg.create("busy", frame_budget_per_s=50.0)  # burst = 50 frames
    plane = WireDataPlane(daemon, dt_us=2000.0, pipeline_depth=1)
    plane.pipeline_explicit_clock = True
    plane.attach_tenancy(reg)
    shm_dir = tmp_path / "rings"
    shm_dir.mkdir()
    sender = ShmSender(str(shm_dir / "busy.ring"), namespace="busy")
    ingest = ShmIngest(str(shm_dir))
    ingest.attach_ring(ShmRing.attach(sender.ring.path))
    plane.attach_shm(ingest, watcher=False)

    fed = 200
    t = 50.0
    throttled_seen = 0
    pushed = 0
    for j in range(30):
        if j < 10:  # 20 frames/tick overruns the 50-frame burst fast
            sender.send(win[0].wire_id,
                        _tagged_frames(0, fed)[pushed:pushed + 20])
            pushed += 20
        t += 0.002
        plane.tick(now_s=t)
        st = ingest.stats()
        throttled_seen = max(throttled_seen,
                             st["throttled_frames_last"])
        # parked frames stay IN the ring: accounting closes every tick
        assert st["frames_in"] + st["pending"] == pushed
    assert pushed == fed
    assert throttled_seen > 0, "budget never throttled the ring"
    st = ingest.stats()
    assert st["throttled_events"] > 0
    assert st["pending"] > 0  # still parked at this point

    verds = [v for v in reg.admission.recent() if v.tenant == "busy"]
    assert verds and verds[-1].reason == "frame-budget"
    assert verds[-1].queued_frames > 0  # ring depth rode the verdict
    assert reg.admission.stats_for("busy")["throttle_events"] \
        == len(verds)

    # budget refills with sim time: everything parked must deliver
    for _ in range(80):
        t += 0.05
        plane.tick(now_s=t)
        if ingest.pending_total() == 0:
            break
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert ingest.pending_total() == 0
    assert len(wout[0].egress) == fed  # throttled, never dropped
    assert list(wout[0].egress) == _tagged_frames(0, fed)
    sender.close()
    ingest.close()
    plane.stop()


def test_ring_residue_folds_into_backlog_signal(tmp_path):
    """Budget residue left in the ring surfaces in
    daemon.last_drain_backlog (entry-denominated) — throttled rings are
    excluded (ticking harder cannot drain them)."""
    from kubedtn_tpu.shm import ShmIngest, ShmRing, ShmSender

    daemon, _engine, win, _wout = _daemon_with_pairs(1, DET_PROPS[0])
    shm_dir = tmp_path / "rings"
    shm_dir.mkdir()
    sender = ShmSender(str(shm_dir / "p.ring"), namespace="default")
    ingest = ShmIngest(str(shm_dir))
    ingest.attach_ring(ShmRing.attach(sender.ring.path))
    daemon.shm = ingest

    sender.send(win[0].wire_id, _tagged_frames(0, 600, size=32))
    out = daemon.drain_ingress(max_per_wire=8)
    assert sum(len(p) for _w, _r, _l, parts in out for p in parts) == 8
    # 592 frames left / 256 per entry -> 2 entries of backlog
    assert daemon.last_drain_backlog == 2

    # a throttled ring contributes NOTHING to the signal
    out = daemon.drain_ingress(max_per_wire=8, admit=lambda w: 0)
    assert out == []
    assert daemon.last_drain_backlog == 0
    assert ingest.stats()["throttled_events"] == 1
    sender.close()
    ingest.close()


def test_unknown_wire_and_unrealized_row(tmp_path):
    """Ring frames for a wire id the daemon never added count as bulk
    unresolved (dropped with accounting, like the gRPC bulk path)."""
    from kubedtn_tpu.shm import ShmIngest, ShmRing, ShmSender

    daemon, _engine, win, _wout = _daemon_with_pairs(1, DET_PROPS[0])
    shm_dir = tmp_path / "rings"
    shm_dir.mkdir()
    sender = ShmSender(str(shm_dir / "p.ring"))
    ingest = ShmIngest(str(shm_dir))
    ingest.attach_ring(ShmRing.attach(sender.ring.path))
    daemon.shm = ingest

    sender.send(0x5FFFFF, [b"lost"] * 3)        # no such wire
    sender.send(win[0].wire_id, [b"kept"] * 2)  # real wire
    out = daemon.drain_ingress(max_per_wire=64)
    st = ingest.stats()
    assert st["unresolved_frames"] == 3
    assert daemon.bulk_unresolved == 3
    assert sum(len(p) for _w, _r, _l, parts in out for p in parts) == 2
    sender.close()
    ingest.close()


# -- sender backpressure ------------------------------------------------

def test_sender_outage_buffer_exact_accounting(tmp_path):
    """Ring-full parks frames in the outage buffer (never drops);
    accepted == pushed + buffered at every step; final delivery is
    every frame exactly once, in order."""
    from kubedtn_tpu.shm import ShmRing, ShmSender

    sender = ShmSender(str(tmp_path / "p.ring"), slots=16,
                       slot_size=96, max_buffered=1 << 16)
    consumer = ShmRing.attach(sender.ring.path)
    frames = [struct.pack("<I", i) for i in range(400)]
    got = []
    for i in range(0, 400, 40):
        sender.send(3, frames[i:i + 40])
        st = sender.stats()
        assert st["accepted"] == st["pushed"] + st["buffered"]
        # consumer drains a little, slower than the producer feeds
        blob, wires, offs, lens, *_ = consumer.dequeue(16)
        if wires is not None:
            got.extend(blob[int(o):int(o + ln)]
                       for o, ln in zip(offs, lens))
    assert sender.stats()["ring_full_failures"] > 0
    assert sender.buffered_peak > 0
    # drain the rest end to end
    while True:
        ok = sender.flush(timeout_s=0.0)
        blob, wires, offs, lens, *_ = consumer.dequeue(64)
        if wires is not None:
            got.extend(blob[int(o):int(o + ln)]
                       for o, ln in zip(offs, lens))
        elif ok:
            break
    assert got == frames  # exactly once, in order, zero drops
    st = sender.stats()
    assert st["accepted"] == st["pushed"] == 400
    assert st["buffered"] == 0
    consumer.close()
    sender.close()


def test_sender_block_timeout_keeps_accounting(tmp_path):
    """A full buffer with a dead consumer blocks then raises — with
    every frame still accounted for (pushed or buffered)."""
    from kubedtn_tpu.shm import ShmSender

    sender = ShmSender(str(tmp_path / "p.ring"), slots=8, slot_size=96,
                       max_buffered=8)
    with pytest.raises(TimeoutError):
        sender.send(1, [b"f"] * 64, block_timeout_s=0.05)
    st = sender.stats()
    assert st["pushed"] == 8          # the ring took its 8 slots
    assert st["buffered"] == 8        # the buffer its 8
    assert st["blocked_s"] > 0.0
    sender.close()


# -- dead-producer drain via a real subprocess --------------------------

def test_dead_producer_ring_drains_and_retires(tmp_path):
    """A real producer subprocess pushes frames + torn reservations and
    exits. The driver delivers every committed frame, skips the torn
    tail only after the pid provably died, then retires the ring."""
    from kubedtn_tpu.shm import ShmIngest

    ring_path = str(tmp_path / "dead.ring")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.shm.producer", ring_path,
         "77", "50", "--frame-size", "64", "--torn", "3"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "done pushed=50" in proc.stdout

    daemon, _engine, win, wout = _daemon_with_pairs(1, DET_PROPS[0])
    ingest = ShmIngest(str(tmp_path), scan_interval_s=0.0)
    daemon.shm = ingest
    # remap the producer's wire id onto our real wire
    ingest.scan(force=True)
    [st] = list(ingest._rings.values())
    assert st.ring.producer_dead()
    assert st.ring.pending() == 53  # 50 committed + 3 torn

    out = daemon.drain_ingress(max_per_wire=4096)
    stats = ingest.stats()
    assert stats["skipped_uncommitted"] == 3
    assert stats["unresolved_frames"] == 50  # wire 77 does not exist
    assert stats["pending"] == 0

    # empty + dead -> linger one (zero-length) interval, then retire
    daemon.drain_ingress(max_per_wire=64)
    daemon.drain_ingress(max_per_wire=64)
    stats = ingest.stats()
    assert stats["rings_retired"] == 1 and stats["rings"] == 0
    assert out == []  # nothing resolvable was emitted
    ingest.close()


def test_producer_frames_deliver_end_to_end(tmp_path):
    """The subprocess producer's deterministic frames (index in the
    first 8 bytes) arrive complete and in order on a real wire."""
    from kubedtn_tpu.shm import ShmIngest

    daemon, _engine, win, wout = _daemon_with_pairs(1, DET_PROPS[0])
    plane = WireDataPlane(daemon, dt_us=2000.0, pipeline_depth=1)
    plane.pipeline_explicit_clock = True
    ring_path = str(tmp_path / "live.ring")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, "-m", "kubedtn_tpu.shm.producer", ring_path,
         str(win[0].wire_id), "80", "--frame-size", "64"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr

    ingest = ShmIngest(str(tmp_path), scan_interval_s=0.0)
    plane.attach_shm(ingest, watcher=False)
    t = 10.0
    for _ in range(10):
        t += 0.002
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert len(wout[0].egress) == 80
    idx = [struct.unpack("<Q", f[:8])[0] for f in wout[0].egress]
    assert idx == list(range(80))  # complete, in order
    ingest.close()
    plane.stop()
