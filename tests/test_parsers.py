"""Golden tests for property-string parsers.

Expected values derived from the reference parser semantics
(reference common/qdisc.go:128-199): Go time.ParseDuration truncated to µs,
strconv.ParseFloat for percentages, integer + prefix/suffix rate grammar.
"""

import pytest

from kubedtn_tpu.api.parsers import (
    parse_duration_us,
    parse_percentage,
    parse_rate_bps,
    tbf_burst_bytes,
)


class TestParsePercentage:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("", 0.0),
            (None, 0.0),
            ("0", 0.0),
            ("100", 100.0),
            ("25.5", 25.5),
            ("0.001", 0.001),
            ("1e1", 10.0),  # strconv.ParseFloat accepts scientific notation
        ],
    )
    def test_valid(self, s, expected):
        assert parse_percentage(s) == pytest.approx(expected)

    @pytest.mark.parametrize("s", ["-1", "100.1", "abc", "NaN", "nan"])
    def test_invalid(self, s):
        with pytest.raises(ValueError):
            parse_percentage(s)


class TestParseDuration:
    @pytest.mark.parametrize(
        "s,expected_us",
        [
            ("", 0),
            (None, 0),
            ("0", 0),
            ("300ms", 300_000),
            ("10ms", 10_000),
            ("50ms", 50_000),
            ("1.5s", 1_500_000),
            ("1s", 1_000_000),
            ("100us", 100),
            ("100µs", 100),
            ("100μs", 100),
            ("1500ns", 1),     # 1500ns = 1.5µs, Microseconds() truncates
            ("999ns", 0),
            ("1h", 3_600_000_000),
            ("1m", 60_000_000),
            ("1h2m3s", 3_723_000_000),
            ("1.5ms", 1_500),
            (".5s", 500_000),  # Go allows leading-dot decimals
            ("2m30s", 150_000_000),
        ],
    )
    def test_valid(self, s, expected_us):
        assert parse_duration_us(s) == expected_us

    @pytest.mark.parametrize("s", ["10", "ms", "10x", "-10ms", "10 ms", "1.5"])
    def test_invalid(self, s):
        with pytest.raises(ValueError):
            parse_duration_us(s)


class TestParseRate:
    @pytest.mark.parametrize(
        "s,expected_bps",
        [
            ("", 0),
            (None, 0),
            ("1000", 1000),
            ("100kbit", 100_000),
            ("100Mbit", 100_000_000),
            ("1Gbit", 1_000_000_000),
            ("100Mbps", 800_000_000),
            ("1Gibps", 8 * 1024**3),
            ("1Kibit", 1024),
            ("20Mbit", 20_000_000),
            ("50Mbit", 50_000_000),
            ("1Tbit", 10**12),
            ("5", 5),
            ("8bps", 64),
            ("10bit", 10),
            (" 100kbit ", 100_000),  # reference trims whitespace
        ],
    )
    def test_valid(self, s, expected_bps):
        assert parse_rate_bps(s) == expected_bps

    @pytest.mark.parametrize("s", ["1.5Mbit", "abc", "k", "-5", "1.5"])
    def test_invalid(self, s):
        # Go strconv.ParseUint rejects decimals and signs.
        with pytest.raises(ValueError):
            parse_rate_bps(s)


class TestTbfBurst:
    def test_floor(self):
        # below 1.25 Mbit/s the 5000-byte floor wins (qdisc.go:364-367)
        assert tbf_burst_bytes(1_000_000) == 5000

    def test_rate_over_hz(self):
        assert tbf_burst_bytes(1_000_000_000) == 4_000_000
        assert tbf_burst_bytes(20_000_000) == 80_000
