"""SLO autopilot (ISSUE 19) — kubedtn_tpu.autopilot.

Pins:

- **Grid determinism**: the same seed and the same paging verdict
  produce the identical candidate grid (names, edits, order) — the
  exploration block is seeded, the fixed rungs are literal.
- **One-sweep search**: the whole grid scores as ONE batched twin
  sweep on the tenant's snapshot fork; the same seed ranks the same
  order and picks the same winner, twice.
- **Closed loop**: burn page → search → gate-approved staged delta →
  burn clears, with ZERO post-cutover frame loss (`burn_recovery`
  chaos scenario, <30s smoke).
- **Same seed ⇒ same winning delta**: two independent planes with
  the identical topology, fault, and seed stage the identical
  candidate — the determinism contract the controller advertises.
- **Gate-REJECTED leaves the plane byte-identical**: SoA columns and
  engine registries compare equal before/after a rejected actuation.
- **Dry-run stages nothing**: gate verdicts are recorded, the plane
  does not move.
- Satellites: Local.AutopilotCtl / AutopilotStatus wire surface,
  kubedtn_autopilot_* metrics (cardinality cap + truncation guard),
  fleet escalation with cooldown and dry-run.
"""

import dataclasses
from types import SimpleNamespace

import numpy as np
import pytest

from kubedtn_tpu.autopilot import Autopilot, AutopilotConfig
from kubedtn_tpu.autopilot.actuator import actuate
from kubedtn_tpu.autopilot.candidates import Candidate, candidate_grid
from kubedtn_tpu.autopilot.search import score_candidates
from kubedtn_tpu.api.types import LinkProperties
from kubedtn_tpu.scenarios import _tenant_plane_setup, burn_recovery
from kubedtn_tpu.slo import SloEvaluator
from kubedtn_tpu.topology import Reconciler
from kubedtn_tpu.updates.gate import Guardrails
from kubedtn_tpu.wire import proto as pb

pytestmark = pytest.mark.autopilot

FRAME = b"\xab" * 200


# -- harness ------------------------------------------------------------


def _harness(prefix, pairs=1, dt_us=1000.0, qos="gold"):
    """One live tenant plane on the explicit tick clock, with a
    `ticks(n, feed)` driver and canonical-path loss injection — the
    same shape the `burn_recovery` scenario runs."""
    cfg = {"t0": {"pairs": pairs, "qos": qos}}
    daemon, _srv, _port, plane, registry, wires = _tenant_plane_setup(
        cfg, "2ms", dt_us, prefix)
    engine = plane.engine
    store = engine.store
    rec = Reconciler(store, engine)
    win, wout = wires["t0"]
    clock = [100.0]

    def ticks(n, feed=0):
        for _ in range(n):
            if feed:
                for w in win:
                    w.ingress.extend([FRAME] * feed)
            clock[0] += 0.05
            plane.tick(now_s=clock[0])
            for w in wout:
                while True:
                    try:
                        w.egress.popleft()
                    except IndexError:
                        break

    def inject_loss(loss="25"):
        for topo in store.list("t0"):
            if "-a" not in topo.name:
                continue
            fresh = store.get(topo.namespace, topo.name)
            fresh.spec.links = [
                l.with_properties(
                    dataclasses.replace(l.properties, loss=loss))
                for l in fresh.spec.links]
            store.update(fresh)
        rec.drain()

    return SimpleNamespace(daemon=daemon, plane=plane,
                           registry=registry, engine=engine,
                           store=store, rec=rec, ticks=ticks,
                           inject_loss=inject_loss)


def _page(h, ev, feed=40, max_iters=40):
    """Warm a healthy baseline, inject loss, tick until the fast burn
    pages; returns the paging verdict."""
    h.ticks(10, feed=feed)
    ev.maybe_evaluate()
    h.inject_loss()
    for _ in range(max_iters):
        h.ticks(5, feed=feed)
        ev.maybe_evaluate()
        v = ev.verdicts().get("t0")
        if v is not None and v.severity == "page":
            return v
    raise AssertionError("tenant never paged")


def _engine_snapshot(engine):
    """Every observable data-plane bit: SoA columns + the engine's
    row/peer/owner/shaped registries (test_updates' byte-identity
    idiom)."""
    cols = {n: np.asarray(getattr(engine.state, n)).copy()
            for n in ("uid", "src", "dst", "active", "props")}
    regs = (dict(engine._rows), dict(engine._peer),
            dict(engine._row_owner), set(engine._shaped_rows))
    return cols, regs


def _assert_snapshot_equal(a, b):
    cols_a, regs_a = a
    cols_b, regs_b = b
    for n in cols_a:
        np.testing.assert_array_equal(cols_a[n], cols_b[n],
                                      err_msg=f"column {n} moved")
    assert regs_a == regs_b


# -- candidate grid -----------------------------------------------------


def _fake_verdict(backlog=0.0):
    return SimpleNamespace(throttle_backlog=backlog)


def test_candidate_grid_same_seed_identical():
    props = {1: LinkProperties(latency="2ms", loss="25"),
             2: LinkProperties(latency="4ms", loss="10")}
    g1 = candidate_grid(_fake_verdict(), props, seed=3, width=4)
    g2 = candidate_grid(_fake_verdict(), props, seed=3, width=4)
    assert g1 == g2                      # frozen dataclasses: deep eq
    names = [c.name for c in g1]
    assert len(names) == len(set(names))
    assert all(c.kind in ("shape", "reroute", "quota", "drain")
               for c in g1)
    # fixed rungs present regardless of the exploration block
    assert any(c.name == "shape:loss0" for c in g1)
    assert any(c.name.startswith("reroute:fail-") for c in g1)
    assert any(c.name == "quota:trim50" for c in g1)


def test_candidate_grid_width_and_drain_gating():
    props = {1: LinkProperties(latency="2ms", loss="25")}
    narrow = candidate_grid(_fake_verdict(), props, seed=0, width=0)
    wide = candidate_grid(_fake_verdict(), props, seed=0, width=4)
    assert len(wide) >= len(narrow)
    # drain:boost only when admission pressure exists
    assert not any(c.kind == "drain" for c in narrow)
    backed = candidate_grid(_fake_verdict(backlog=7.0), props,
                            seed=0, width=0)
    assert any(c.name == "drain:boost" for c in backed)


# -- one-sweep search ---------------------------------------------------


def test_search_one_sweep_deterministic_ranking():
    h = _harness("apsearch")
    ev = SloEvaluator(h.registry, h.plane)
    try:
        v = _page(h, ev)
        ap = Autopilot(h.registry, h.plane, ev)
        snap = h.registry.tenant_snapshot(h.plane, "t0")
        edge_props = ap._edge_props(snap, "t0")
        assert edge_props, "no live tenant edges in the fork"
        grid = candidate_grid(v, edge_props, seed=0, width=2)

        def run():
            return score_candidates(
                snap, "t0", v.qos, v.spec, grid, verdict=v,
                steps=80, dt_us=1000.0, seed=0)

        sr1, sr2 = run(), run()
        # the whole grid was ONE sweep: baseline + one replica each
        assert sr1.candidates == len(grid)
        assert sr1.replicas == len(grid) + 1
        assert sr1.run_s > 0.0
        # deterministic: identical ranking and identical winner
        order1 = [s.candidate.name for s in sr1.ranked]
        order2 = [s.candidate.name for s in sr2.ranked]
        assert order1 == order2
        assert (sr1.winner.name if sr1.winner else None) == \
               (sr2.winner.name if sr2.winner else None)
        burns1 = [s.projected_burn for s in sr1.ranked]
        burns2 = [s.projected_burn for s in sr2.ranked]
        assert burns1 == burns2
        # a 25% loss page has a strictly-improving repair in the grid
        assert sr1.winner is not None
        assert sr1.ranked[0].projected_burn < sr1.baseline_burn
    finally:
        ev.stop()
        h.plane.stop()


# -- the closed loop ----------------------------------------------------


def _staged_record(seed, prefix):
    """Page a fresh plane, run the controller until it stages, return
    (record, status) — the same-seed determinism probe."""
    h = _harness(prefix)
    ev = SloEvaluator(h.registry, h.plane)
    ap = Autopilot(h.registry, h.plane, ev,
                   config=AutopilotConfig(seed=seed, width=2,
                                          steps=120, page_polls=1,
                                          cooldown_s=5.0,
                                          verify_polls=20),
                   tick_driver=lambda n: h.ticks(n))
    ap.enable()
    try:
        h.ticks(10, feed=40)
        ev.maybe_evaluate()
        h.inject_loss()
        staged = None
        for _ in range(50):
            h.ticks(5, feed=40)
            ev.maybe_evaluate()
            for a in ap.poll():
                if a.get("verdict") == "staged":
                    staged = a
            if staged:
                break
        assert staged is not None, ap.history()
        return staged, ap.status()
    finally:
        ap.stop()
        ev.stop()
        h.plane.stop()


def test_same_seed_stages_identical_winning_delta():
    rec1, st1 = _staged_record(7, "apdet1")
    rec2, _ = _staged_record(7, "apdet2")
    # the pinned contract: same seed + same burn ⇒ same winning delta
    assert rec1["candidate"] == rec2["candidate"]
    assert rec1["kind"] == rec2["kind"]
    assert rec1["candidates"] == rec2["candidates"]
    # the search was ONE batched sweep with the split recorded
    assert st1["stats"]["searches_run"] == 1
    assert rec1["run_s"] > 0.0
    assert rec1["plans"] > 0 and rec1["staged"]
    assert rec1["projected_burn"] < rec1["baseline_burn"]
    # the tenant sits in verify after a stage
    assert st1["tenants"]["t0"]["state"] in ("verify", "hold")


def test_burn_recovery_smoke():
    """The whole loop end-to-end (<30s): page → one sweep → staged
    delta → green, zero post-cutover frame loss."""
    r = burn_recovery(pairs=1, feed_per_tick=30, width=2, steps=120,
                      max_polls=50)
    assert r["in_guardrails"], r
    assert r["paged"] and r["staged"]
    assert r["searches_run"] == 1
    assert r["post_frames_fed"] > 0
    assert r["post_frames_lost"] == 0
    assert r["post_frames_delivered"] == r["post_frames_fed"]
    assert r["tick_errors"] == 0
    assert r["time_to_green_s"] > 0.0
    assert r["wall_s"] < 30.0


# -- gate rejection and dry-run -----------------------------------------


def _shape_candidate(h, ev):
    v = _page(h, ev)
    ap = Autopilot(h.registry, h.plane, ev)
    snap = h.registry.tenant_snapshot(h.plane, "t0")
    grid = candidate_grid(v, ap._edge_props(snap, "t0"),
                          seed=0, width=0)
    return v, next(c for c in grid if c.kind == "shape")


def test_gate_rejected_leaves_plane_byte_identical():
    h = _harness("apreject")
    ev = SloEvaluator(h.registry, h.plane)
    try:
        v, cand = _shape_candidate(h, ev)
        before = _engine_snapshot(h.engine)
        # max_delivery_drop=-1.0 makes every gate verdict a rejection
        out = actuate(h.plane, h.registry, "t0", cand, v,
                      guardrails=Guardrails(max_delivery_drop=-1.0,
                                            ticks=40, dt_us=1000.0),
                      tick_driver=lambda n: h.ticks(n))
        assert out.rejected and not out.staged and not out.ok
        assert "delivery" in out.reason
        _assert_snapshot_equal(before, _engine_snapshot(h.engine))
        # the paged loss is still on the wire, untouched
        snap2 = h.registry.tenant_snapshot(h.plane, "t0")
        ap = Autopilot(h.registry, h.plane, ev)
        assert any("25" in (p.loss or "")
                   for p in ap._edge_props(snap2, "t0").values())
    finally:
        ev.stop()
        h.plane.stop()


def test_dry_run_stages_nothing():
    h = _harness("apdry")
    ev = SloEvaluator(h.registry, h.plane)
    try:
        v, cand = _shape_candidate(h, ev)
        before = _engine_snapshot(h.engine)
        out = actuate(h.plane, h.registry, "t0", cand, v,
                      dry_run=True, tick_driver=lambda n: h.ticks(n))
        assert out.dry_run and not out.staged
        # the gate DID run and its verdicts are in the outcome
        assert out.plans and out.gate_s >= 0.0
        assert all(p.gate_ok for p in out.plans)
        _assert_snapshot_equal(before, _engine_snapshot(h.engine))
    finally:
        ev.stop()
        h.plane.stop()


# -- escalation ---------------------------------------------------------


class _FakeFleet:
    def __init__(self):
        self.calls = 0

    def rebalance(self):
        self.calls += 1
        return ["move-a", "move-b"]


class _FakeEvaluator:
    def __init__(self, names):
        self.names = names

    def verdicts(self):
        return {n: SimpleNamespace(severity="page", qos="gold",
                                   spec=None, throttle_backlog=0.0)
                for n in self.names}


def test_fleet_wide_burn_escalates_with_cooldown():
    now = [100.0]
    fleet = _FakeFleet()
    ap = Autopilot(None, None, _FakeEvaluator(["a", "b", "c"]),
                   fleet=fleet,
                   config=AutopilotConfig(page_polls=99,
                                          cooldown_s=30.0,
                                          fleet_page_tenants=3),
                   clock=lambda: now[0])
    ap.enable()
    acts = ap.poll()
    assert [a["verdict"] for a in acts] == ["escalated"]
    assert acts[0]["kind"] == "escalate"
    assert acts[0]["candidate"] == "fleet:rebalance"
    assert acts[0]["moves"] == 2 and fleet.calls == 1
    # rate-limited by the cooldown...
    now[0] = 110.0
    assert ap.poll() == [] and fleet.calls == 1
    # ...and fires again once it elapses
    now[0] = 140.0
    assert [a["verdict"] for a in ap.poll()] == ["escalated"]
    assert fleet.calls == 2
    assert ap.status()["stats"]["escalations"] == 2


def test_escalation_dry_run_does_not_rebalance():
    now = [100.0]
    fleet = _FakeFleet()
    ap = Autopilot(None, None, _FakeEvaluator(["a", "b", "c"]),
                   fleet=fleet,
                   config=AutopilotConfig(page_polls=99,
                                          fleet_page_tenants=3),
                   clock=lambda: now[0])
    ap.enable()
    ap.set_dry_run(True)
    acts = ap.poll()
    assert [a["verdict"] for a in acts] == ["dry-run"]
    assert fleet.calls == 0


def test_disabled_autopilot_observes_but_never_acts():
    now = [100.0]
    fleet = _FakeFleet()
    ap = Autopilot(None, None, _FakeEvaluator(["a", "b", "c"]),
                   fleet=fleet,
                   config=AutopilotConfig(page_polls=1,
                                          fleet_page_tenants=3),
                   clock=lambda: now[0])
    assert ap.poll() == []               # no remediation, no escalate
    assert fleet.calls == 0
    st = ap.status()
    assert st["enabled"] is False
    assert st["stats"]["pages_seen"] == 3   # observing is free


# -- wire surface -------------------------------------------------------


def test_autopilot_wire_ctl_and_status():
    import grpc  # noqa: F401

    from kubedtn_tpu.wire.client import DaemonClient
    from kubedtn_tpu.wire.server import make_server

    h = _harness("apwire")
    srv, port = make_server(h.daemon, port=0, host="127.0.0.1",
                            log_rpcs=False)
    srv.start()
    client = DaemonClient(f"127.0.0.1:{port}")
    try:
        # no controller attached: a clean refusal, not a crash
        resp = client.AutopilotCtl(
            pb.AutopilotCtlRequest(action="enable"), timeout=10.0)
        assert not resp.ok and "not attached" in resp.error

        ap = Autopilot(h.registry, h.plane, None).attach(h.daemon)
        resp = client.AutopilotCtl(
            pb.AutopilotCtlRequest(action="enable"), timeout=10.0)
        assert resp.ok and resp.enabled and not resp.dry_run
        assert ap.enabled
        resp = client.AutopilotCtl(
            pb.AutopilotCtlRequest(action="dry-run-on"), timeout=10.0)
        assert resp.ok and resp.dry_run and ap.dry_run
        resp = client.AutopilotCtl(
            pb.AutopilotCtlRequest(action="sideways"), timeout=10.0)
        assert not resp.ok and "unknown action" in resp.error

        # seed one action record and read it back over the wire
        ap._state_of("t0")
        rec = ap._new_record("t0", None, 1.0)
        rec.update(kind="shape", candidate="shape:loss0",
                   verdict="staged", staged=True, plans=2,
                   projected_burn=0.25)
        ap._record("t0", rec, 1.0, hold=False)
        resp = client.AutopilotStatus(
            pb.AutopilotStatusRequest(history=10), timeout=10.0)
        assert resp.ok and resp.enabled and resp.dry_run
        assert len(resp.actions) == 1
        act = resp.actions[0]
        assert act.tenant == "t0" and act.candidate == "shape:loss0"
        assert act.verdict == "staged" and act.staged
        assert act.plans == 2
        assert act.projected_burn == pytest.approx(0.25)
        assert len(resp.states) == 1
        st = resp.states[0]
        assert st.tenant == "t0"
        assert st.last_action.candidate == "shape:loss0"
        # tenant filter
        resp = client.AutopilotStatus(
            pb.AutopilotStatusRequest(tenant="nope", history=10),
            timeout=10.0)
        assert resp.ok and len(resp.states) == 0

        resp = client.AutopilotCtl(
            pb.AutopilotCtlRequest(action="disable"), timeout=10.0)
        assert resp.ok and not resp.enabled
    finally:
        client.close()
        srv.stop(0)
        h.plane.stop()


# -- metrics ------------------------------------------------------------


def test_autopilot_metrics_series_and_truncation_guard():
    from prometheus_client import generate_latest

    from kubedtn_tpu.metrics.metrics import make_registry

    ap = Autopilot(None, None, _FakeEvaluator(["a", "b"]),
                   config=AutopilotConfig(page_polls=99),
                   clock=lambda: 100.0)
    ap.enable()
    ap.poll()                            # both tenants observed
    registry, _hist = make_registry(autopilot=ap)
    text = generate_latest(registry).decode()
    assert "kubedtn_autopilot_enabled 1.0" in text
    assert "kubedtn_autopilot_dry_run 0.0" in text
    assert 'kubedtn_autopilot_state{tenant="a"}' in text
    assert 'kubedtn_autopilot_pages{tenant="b"}' in text
    assert "kubedtn_autopilot_pages_seen_total 2.0" in text
    assert "kubedtn_autopilot_searches_run_total 0.0" in text
    assert "kubedtn_autopilot_series_truncated 0.0" in text

    # the cardinality cap: one tenant survives, the guard flags one
    capped, _ = make_registry(autopilot=ap, max_tenants=1)
    text = generate_latest(capped).decode()
    assert 'kubedtn_autopilot_state{tenant="a"}' in text
    assert 'tenant="b"' not in text
    assert "kubedtn_autopilot_series_truncated 1.0" in text
