"""Link telemetry plane: window-ring accounting, sampling determinism,
flight-recorder lifecycle, and cross-node trace correlation.

The contracts pinned here (ARCHITECTURE.md "Observability"):

- the per-edge window ring's counts are EXACT: tx == frames offered,
  delivered == plane.shaped, and the per-cause drop columns sum to
  plane.dropped — including through the TBF 50ms-queue fallback
  re-shape, whose stats arrive via the host-side window patch;
- sampling is deterministic counter arithmetic: the i-th frame ever
  drained onto row r is sampled iff (i + phase(r)) % period == 0, so
  two recorders replay identically;
- a sampled frame's lifecycle is complete: ingress → shaped →
  delivered | dropped(cause) locally, plus staged-peer → peer-sent and
  the remote daemon's received event over a real gRPC hop
  (Packet.trace_id), reconstructable via merge_trace / Local.ObserveTrace;
- the query surfaces (link_rows, Local.ObserveLinks) rank by rate and
  serve bucket-ladder percentiles — the same percentile code the
  what-if plane uses.
"""

import time

import numpy as np
import pytest

from kubedtn_tpu import telemetry as tele
from kubedtn_tpu.api.types import Link, LinkProperties, Topology, \
    TopologySpec
from kubedtn_tpu.runtime import WireDataPlane
from kubedtn_tpu.topology import Reconciler, SimEngine, TopologyStore


def _daemon_with_pairs(pairs, props, prefix="t"):
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon

    store = TopologyStore()
    engine = SimEngine(store, capacity=4 * pairs + 8)
    for i in range(pairs):
        a, b = f"{prefix}a{i}", f"{prefix}b{i}"
        store.create(Topology(name=a, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=b,
                 uid=i + 1, properties=props)])))
        store.create(Topology(name=b, spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth1", peer_pod=a,
                 uid=i + 1, properties=props)])))
        engine.setup_pod(a)
        engine.setup_pod(b)
    Reconciler(store, engine).drain()
    daemon = Daemon(engine)
    win, wout = [], []
    for i in range(pairs):
        win.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"{prefix}a{i}", kube_ns="default",
            link_uid=i + 1, intf_name_in_pod="eth1")))
        wout.append(daemon._add_wire(pb.WireDef(
            local_pod_name=f"{prefix}b{i}", kube_ns="default",
            link_uid=i + 1, intf_name_in_pod="eth1")))
    return daemon, engine, win, wout


def _run(plane, win, frames_per_wire, ticks=40, dt=0.002, start=100.0):
    for k, w in enumerate(win):
        w.ingress.extend(
            [bytes([k]) + i.to_bytes(4, "big") + b"\x00" * 59
             for i in range(frames_per_wire)])
    t = start
    for _ in range(ticks):
        t += dt
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 10.0)
    assert plane.tick_errors == 0
    return t + 10.0


# -- window ring accounting --------------------------------------------

def test_window_ring_exact_accounting_lossy():
    daemon, engine, win, wout = _daemon_with_pairs(
        2, LinkProperties(latency="3ms", jitter="1ms", loss="10"))
    plane = WireDataPlane(daemon, dt_us=2000.0)
    tel, rec = plane.enable_telemetry(window_s=0.01, sample_period=8)
    _run(plane, win, 300)
    total, secs = tel.window_sum()
    assert secs > 0
    assert tel.windows_closed > 0
    assert total[:, tele.T_TX].sum() == 600
    assert total[:, tele.T_DELIVERED].sum() == plane.shaped
    assert (total[:, tele.T_DROP_LOSS].sum()
            + total[:, tele.T_DROP_QUEUE].sum()) == plane.dropped
    assert total[:, tele.T_DROP_QUEUE].sum() == 0  # no TBF here
    # bucket counts partition the delivered population exactly
    assert total[:, tele.T_HIST0:].sum() == plane.shaped
    # delivered frames reached the far wires
    assert sum(len(w.egress) for w in wout) == plane.shaped


def test_window_ring_tbf_fallback_patch_exact():
    """TBF overload trips the max-plus kernel's exact-scan fallback;
    the fallback rows' telemetry arrives via the host-side window
    patch and the per-cause totals must STILL sum exactly."""
    daemon, engine, win, wout = _daemon_with_pairs(
        1, LinkProperties(rate="512Kbit"))
    plane = WireDataPlane(daemon, dt_us=2000.0, pipeline_depth=2)
    plane.pipeline_explicit_clock = True
    tel, rec = plane.enable_telemetry(window_s=0.01, sample_period=4)
    # 300 64-byte frames ≈ 300ms of service at 512Kbit vs the 50ms
    # queue cap: most of the batch must drop dropped_queue
    _run(plane, win, 300, ticks=30)
    total, _secs = tel.window_sum()
    assert total[:, tele.T_TX].sum() == 300
    assert plane.dropped > 0
    assert total[:, tele.T_DROP_QUEUE].sum() > 0
    assert total[:, tele.T_DELIVERED].sum() == plane.shaped
    assert (total[:, tele.T_DROP_LOSS].sum()
            + total[:, tele.T_DROP_QUEUE].sum()) == plane.dropped
    assert total[:, tele.T_HIST0:].sum() == plane.shaped
    # the recorder attributed sampled drops to the queue cause
    causes = [e[4].get("cause") for e in list(rec.events)
              if e[3] == tele.ST_DROPPED]
    assert causes and all(c == "dropped_queue" for c in causes)


def test_window_ring_bounded_and_idle_rollover():
    daemon, engine, win, wout = _daemon_with_pairs(
        1, LinkProperties(latency="1ms"))
    plane = WireDataPlane(daemon, dt_us=2000.0)
    tel, _rec = plane.enable_telemetry(window_s=0.004, windows=3)
    t = _run(plane, win, 50, ticks=10)
    # idle ticks keep closing windows (touch())
    for _ in range(40):
        t += 0.002
        plane.tick(now_s=t)
    assert tel.windows_closed > 3
    assert len(tel._ring) == 3  # bounded ring
    # restricting the query window restricts coverage
    _tot_all, secs_all = tel.window_sum()
    _tot_1, secs_1 = tel.window_sum(last=1, include_open=False)
    assert 0 < secs_1 < secs_all


# -- sampling determinism ----------------------------------------------

def test_sampling_contract_deterministic_and_periodic():
    a = tele.FlightRecorder(node="n1", sample_period=16)
    b = tele.FlightRecorder(node="n1", sample_period=16)
    seq = [(3, 10), (3, 25), (7, 40), (3, 7), (7, 1)]
    got_a = [a.sample_batch(r, m) for r, m in seq]
    got_b = [b.sample_batch(r, m) for r, m in seq]
    assert got_a == got_b  # replays exactly
    # exactly every 16th frame of row 3 is sampled, at the row's phase
    offs = []
    base = 0
    for (r, m), sm in zip(seq, got_a):
        if r == 3:
            offs.extend(base + o for o, _t in sm)
            base += m
    phase = (3 * 2654435761) % 16
    expect = [i for i in range(base) if (i + phase) % 16 == 0]
    assert offs == expect
    # trace ids are stable, nonzero, and distinct per (row, seq)
    tids = [t for sm in got_a for _o, t in sm]
    assert len(set(tids)) == len(tids)
    assert all(t for t in tids)
    # a different node samples the SAME offsets but mints DIFFERENT ids
    # (cross-node uniqueness of the correlation key)
    c = tele.FlightRecorder(node="n2", sample_period=16)
    c.sample_batch(3, 10)
    got_c = c.sample_batch(3, 25)
    assert [o for o, _t in got_c] == [o for o, _t in got_a[1]]
    assert [t for _o, t in got_c] != [t for _o, t in got_a[1]]


def test_recorder_lifecycle_local_delivery():
    daemon, engine, win, wout = _daemon_with_pairs(
        1, LinkProperties(latency="2ms"))
    plane = WireDataPlane(daemon, dt_us=2000.0)
    tel, rec = plane.enable_telemetry(window_s=1.0, sample_period=4)
    _run(plane, win, 64, ticks=20)
    assert rec.sampled == 16
    by_tid = {}
    for tid, _t, _n, stage, _d in list(rec.events):
        by_tid.setdefault(tid, []).append(stage)
    assert len(by_tid) == 16
    for stages in by_tid.values():
        assert stages[0] == tele.ST_INGRESS
        assert tele.ST_SHAPED in stages
        assert stages[-1] == tele.ST_DELIVERED
    # merge_trace renders a coherent single-node path
    tid = next(iter(by_tid))
    path = tele.merge_trace(tid, rec)
    assert [e["stage"] for e in path][0] == tele.ST_INGRESS
    assert tele.render_trace(path).startswith("trace ")


# -- cross-node correlation over real gRPC -----------------------------

def _two_daemons(props, pairs=1):
    from kubedtn_tpu.wire import proto as pb
    from kubedtn_tpu.wire.server import Daemon, make_server

    nodes = []
    for _ in range(2):
        store = TopologyStore()
        engine = SimEngine(store, capacity=4 * pairs + 8)
        daemon = Daemon(engine)
        server, port = make_server(daemon, port=0, host="127.0.0.1",
                                   log_rpcs=False)
        server.start()
        addr = f"127.0.0.1:{port}"
        engine.node_ip = addr
        nodes.append((store, engine, daemon, server, addr))
    (store_a, engine_a, daemon_a, server_a, addr_a) = nodes[0]
    (store_b, engine_b, daemon_b, server_b, addr_b) = nodes[1]
    for store in (store_a, store_b):
        for i in range(pairs):
            ta = Topology(name=f"xa{i}", spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1",
                     peer_pod=f"xb{i}", uid=i + 1, properties=props)]))
            tb = Topology(name=f"xb{i}", spec=TopologySpec(links=[
                Link(local_intf="eth1", peer_intf="eth1",
                     peer_pod=f"xa{i}", uid=i + 1, properties=props)]))
            ta.status.src_ip, ta.status.net_ns = addr_a, "/ns/a"
            tb.status.src_ip, tb.status.net_ns = addr_b, "/ns/b"
            store.create(ta)
            store.create(tb)
    for i in range(pairs):
        t = store_a.get("default", f"xa{i}")
        assert engine_a.add_links(t, t.spec.links)
    wires_in, wires_out = [], []
    for i in range(pairs):
        wb = daemon_b._add_wire(pb.WireDef(
            local_pod_name=f"xb{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1", peer_ip=addr_a))
        wa = daemon_a._add_wire(pb.WireDef(
            local_pod_name=f"xa{i}", kube_ns="default", link_uid=i + 1,
            intf_name_in_pod="eth1", peer_ip=addr_b,
            peer_intf_id=wb.wire_id))
        wires_in.append(wa)
        wires_out.append(wb)
    return nodes, wires_in, wires_out


def test_cross_node_trace_and_observe_rpcs():
    nodes, wires_in, wires_out = _two_daemons(
        LinkProperties(latency="1ms"))
    (_sa, _ea, daemon_a, server_a, addr_a) = nodes[0]
    (_sb, _eb, daemon_b, server_b, addr_b) = nodes[1]
    plane = WireDataPlane(daemon_a, dt_us=2000.0)
    _tel, rec_a = plane.enable_telemetry(window_s=0.5, sample_period=4,
                                         node=addr_a)
    rec_b = tele.FlightRecorder(node=addr_b)
    daemon_b.recorder = rec_b
    plane.start()
    try:
        frame = b"\x02" * 12 + b"\x07\x77" + b"\x00" * 50
        for w in wires_in:
            w.ingress.extend([frame] * 64)
        deadline = time.monotonic() + 60.0
        while (sum(len(w.egress) for w in wires_out) < 64
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert sum(len(w.egress) for w in wires_out) == 64
        # the sampled frames crossed with their trace ids: B recorded
        # `received` for ids A staged/sent
        deadline = time.monotonic() + 10.0
        while not rec_b.events and time.monotonic() < deadline:
            time.sleep(0.02)
        a_sent = {e[0] for e in list(rec_a.events)
                  if e[3] == tele.ST_SENT}
        b_recv = {e[0] for e in list(rec_b.events)
                  if e[3] == tele.ST_RECEIVED}
        assert a_sent and b_recv
        assert a_sent & b_recv
        tid = next(iter(a_sent & b_recv))
        path = tele.merge_trace(tid, rec_a, rec_b)
        stages = [e["stage"] for e in path]
        assert tele.ST_INGRESS in stages
        assert tele.ST_STAGED in stages
        assert {e["node"] for e in path} == {addr_a, addr_b}

        # -- the wire query surface over real gRPC ---------------------
        from kubedtn_tpu.wire import proto as pb
        from kubedtn_tpu.wire.client import DaemonClient

        client_a = DaemonClient(addr_a)
        client_b = DaemonClient(addr_b)
        try:
            links = client_a.ObserveLinks(
                pb.ObserveLinksRequest(top_n=10), timeout=10.0)
            assert links.ok, links.error
            assert len(links.links) >= 1
            assert links.links[0].delivered > 0
            tr_a = client_a.ObserveTrace(
                pb.ObserveTraceRequest(trace_id=tid), timeout=10.0)
            tr_b = client_b.ObserveTrace(
                pb.ObserveTraceRequest(trace_id=tid), timeout=10.0)
            assert tr_a.ok and tr_b.ok
            merged = sorted(
                [{"trace_id": int(e.trace_id), "t": e.t,
                  "node": e.node, "stage": e.stage,
                  "detail": e.detail}
                 for e in list(tr_a.events) + list(tr_b.events)],
                key=lambda e: e["t"])
            assert [e["stage"] for e in merged][0] == tele.ST_INGRESS
            assert {e["node"] for e in merged} == {addr_a, addr_b}
            # a daemon WITHOUT telemetry answers ok=False, not an error
            resp = client_b.ObserveLinks(pb.ObserveLinksRequest(),
                                         timeout=10.0)
            assert not resp.ok and "not enabled" in resp.error
        finally:
            client_a.close()
            client_b.close()

        # -- the CLI verbs, end to end ---------------------------------
        from kubedtn_tpu import cli

        assert cli.main(["top", "--daemon", addr_a, "--json"]) == 0
        assert cli.main(["top", "--daemon", addr_a, "-n", "5"]) == 0
        assert cli.main(["trace", "latest", "--daemon", addr_a,
                         "--daemon", addr_b]) == 0
        assert cli.main(["trace", f"{tid:#x}", "--daemon", addr_a,
                         "--daemon", addr_b, "--json"]) == 0
        # a bogus id is a clean one-line error, not a traceback
        assert cli.main(["trace", "not-a-tid",
                         "--daemon", addr_a]) == 1
    finally:
        plane.stop()
        server_a.stop(0)
        server_b.stop(0)


# -- query surface details ---------------------------------------------

def test_link_rows_ranked_and_percentiles():
    daemon, engine, win, wout = _daemon_with_pairs(
        2, LinkProperties(latency="3ms"))
    plane = WireDataPlane(daemon, dt_us=2000.0)
    tel, _rec = plane.enable_telemetry(window_s=10.0)
    # wire 0 carries 3x the traffic of wire 1
    win[0].ingress.extend([b"\x00" * 60] * 150)
    win[1].ingress.extend([b"\x00" * 60] * 50)
    t = 100.0
    for _ in range(30):
        t += 0.002
        plane.tick(now_s=t)
    plane.flush()
    plane.tick(now_s=t + 10.0)
    rows, secs, trunc = tel.link_rows(engine)
    assert trunc == 0
    assert len(rows) == 2
    assert rows[0]["delivered"] == 150  # busiest first
    assert rows[1]["delivered"] == 50
    # 3ms fixed latency → p50 and p99 in the (1ms, 5ms] bucket
    assert 1000.0 < rows[0]["p50_us"] <= 5000.0
    assert 1000.0 < rows[0]["p99_us"] <= 5000.0
    assert rows[0]["mean_lat_us"] == pytest.approx(3000.0, rel=0.1)


def test_percentiles_shared_with_twin():
    """ONE histogram_quantile implementation: the what-if plane's sweep
    percentiles and the link telemetry surface are the same function."""
    from kubedtn_tpu.twin import engine as twin_engine

    assert twin_engine._percentiles is tele.percentiles_from_hist
    assert twin_engine.BUCKET_EDGES_US == tele.BUCKET_EDGES_US
    assert twin_engine.N_BINS == tele.N_BINS
    hist = np.zeros(tele.N_BINS)
    hist[1] = 100.0  # all mass in (1ms, 5ms]
    p = tele.percentiles_from_hist(hist)
    assert 1000.0 < p["p50_us"] <= 5000.0
    assert tele.percentiles_from_hist(np.zeros(tele.N_BINS))["p99_us"] \
        is None


def test_determinism_depth_parity_with_telemetry_ring():
    """The ring's totals are identical at depth 1 vs depth 2 — the
    device reductions ride the chained dispatches without changing
    them (the delivery-order parity lives in
    test_pipeline_determinism; this pins the telemetry outputs).
    The SAME pod names both rounds: a row's random stream is keyed by
    the link's (pod_key, uid) identity (the multi-tenant byte-identity
    mechanism), so two planes agree only when their topologies do."""
    totals = {}
    for depth in (1, 2):
        daemon, engine, win, wout = _daemon_with_pairs(
            2, LinkProperties(latency="2ms", loss="20"),
            prefix="dp")
        plane = WireDataPlane(daemon, dt_us=2000.0,
                              pipeline_depth=depth)
        plane.pipeline_explicit_clock = True
        tel, _rec = plane.enable_telemetry(window_s=10.0,
                                           sample_period=8)
        _run(plane, win, 200)
        total, _secs = tel.window_sum()
        totals[depth] = total
    assert np.array_equal(totals[1], totals[2])


def test_dispatch_fault_rolls_sampling_back():
    """A failed dispatch requeues undecided frames to the ingress
    front; the recorder's per-row counters roll back so the retry
    replays the SAME sampling schedule and trace ids (the determinism
    contract holds across tick faults), and nothing is lost. The fault
    is injected at the DECIDE stage — after sampling, before the
    exactly-once decide verdict — the exact window the rollback
    exists for (a pre-sampling chaos fault never advances counters)."""
    daemon, engine, win, wout = _daemon_with_pairs(
        1, LinkProperties(latency="1ms"), prefix="df")
    plane = WireDataPlane(daemon, dt_us=2000.0)
    tel, rec = plane.enable_telemetry(window_s=10.0, sample_period=4)
    if plane._flowtable is None:
        pytest.skip("native flow table unavailable")
    orig = plane._flowtable.decide_classify_ptrs
    fails = [2]

    def flaky(*a, **kw):
        if fails[0] > 0:
            fails[0] -= 1
            raise RuntimeError("injected decide fault")
        return orig(*a, **kw)

    plane._flowtable.decide_classify_ptrs = flaky
    win[0].ingress.extend([b"\x00" * 60] * 64)
    t = 100.0
    for _ in range(10):
        t += 0.002
        try:
            plane.tick(now_s=t)
        except Exception:
            pass  # the runner would survive; explicit ticks surface it
    plane.flush()
    plane.tick(now_s=t + 10.0)
    # every frame still delivered exactly once after the faults
    assert sum(len(w.egress) for w in wout) == 64
    # sampling replayed, not double-counted: 64 frames / period 4
    assert rec.sampled == 16
    ingress_tids = [e[0] for e in list(rec.events)
                    if e[3] == tele.ST_INGRESS]
    assert len(set(ingress_tids)) == 16  # same ids re-recorded, no new
    delivered_tids = {e[0] for e in list(rec.events)
                      if e[3] == tele.ST_DELIVERED}
    assert delivered_tids == set(ingress_tids)
    # the retry is visible as a requeued marker between the attempts
    requeued = [e for e in list(rec.events)
                if e[3] == tele.ST_REQUEUED
                and e[4].get("reason") == "dispatch-fault-retry"]
    assert requeued
    total, _secs = tel.window_sum()
    assert total[:, tele.T_TX].sum() == 64
    assert total[:, tele.T_DELIVERED].sum() == 64
