"""Sharded multi-hop forwarding tests on the virtual 8-device mesh.

Edges are deliberately placed so consecutive hops live on DIFFERENT shards:
every forwarded packet must ride the all_to_all exchange (the ICI stand-in
for the reference's daemon-to-daemon per-packet RPC). With deterministic
shaping (pure latency, CBR traffic) the sharded run must match the
single-device router exactly."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from kubedtn_tpu import router as RT
from kubedtn_tpu.models import traffic as TR
from kubedtn_tpu.ops import edge_state as es
from kubedtn_tpu.ops import routing as R
from kubedtn_tpu.parallel.mesh import make_mesh
from kubedtn_tpu.parallel.router import (
    make_sharded_router_step,
    shard_router_state,
)

E = 1024          # 8 shards x 128 rows
N_SHARDS = 8
E_LOC = E // N_SHARDS


def chain_state(n_nodes: int, latency_us: float = 1000.0):
    """Directed chain 0→1→…→n-1 with hop i's edge on shard i."""
    n_links = n_nodes - 1
    assert n_links <= N_SHARDS
    rows = np.arange(n_links, dtype=np.int32) * E_LOC  # one per shard
    props = np.zeros((n_links, es.NPROP), np.float32)
    props[:, es.P_LATENCY_US] = latency_us
    state = es.init_state(E)
    state = es.apply_links(
        state, jnp.asarray(rows), jnp.arange(1, n_links + 1, dtype=jnp.int32),
        jnp.arange(n_links, dtype=jnp.int32),
        jnp.arange(1, n_links + 1, dtype=jnp.int32),
        jnp.asarray(props), jnp.ones(n_links, dtype=bool))
    return state, rows


def cbr_on_rows(rows, rate_bps=8e6, pkt=1000.0):
    mode = np.zeros((E,), np.int32)
    rate = np.zeros((E,), np.float32)
    size = np.full((E,), pkt, np.float32)
    for r in rows:
        mode[r] = TR.MODE_CBR
        rate[r] = rate_bps
    z = np.zeros((E,), np.float32)
    return TR.TrafficSpec(mode=jnp.asarray(mode), rate_bps=jnp.asarray(rate),
                          pkt_bytes=jnp.asarray(size), on_us=jnp.asarray(z),
                          off_us=jnp.asarray(z))


def build(n_nodes: int):
    state, rows = chain_state(n_nodes)
    dist, nh = R.recompute_routes(state, n_nodes, max_hops=8)
    rs = RT.init_router(state, nh, n_nodes, q=32, k_fwd=8)
    spec = cbr_on_rows([rows[0]])
    flow_dst = np.full((E,), -1, np.int32)
    flow_dst[rows[0]] = n_nodes - 1   # source flow targets the chain end
    return rs, spec, jnp.asarray(flow_dst)


def run_single(rs, spec, flow_dst, steps, dt_us=2000.0):
    for i in range(steps):
        rs = RT.router_step(rs, spec, flow_dst, jax.random.key(i), 2, 8,
                            jnp.float32(dt_us))
    return rs


def run_sharded(rs, spec, flow_dst, steps, mesh, n_nodes, dt_us=2000.0,
                budget=None):
    step = make_sharded_router_step(mesh, n_nodes, k_slots=2, k_fwd=8,
                                    budget=budget)
    rs = shard_router_state(rs, mesh)
    for i in range(steps):
        rs = step(rs, spec, flow_dst, jax.random.key(i), dt_us)
    return rs


def test_sharded_matches_single_device(devices8):
    """Deterministic chain: sharded == single-device, packets cross shards
    on every hop."""
    n_nodes = 5
    mesh = make_mesh(N_SHARDS)
    steps = 12

    rs_a, spec, flow_dst = build(n_nodes)
    rs_b = jax.tree.map(lambda x: x.copy(), rs_a)

    single = run_single(rs_a, spec, flow_dst, steps)
    sharded = run_sharded(rs_b, spec, flow_dst, steps, mesh, n_nodes)

    np.testing.assert_array_equal(np.asarray(single.node_rx_packets),
                                  np.asarray(sharded.node_rx_packets))
    np.testing.assert_allclose(np.asarray(single.node_rx_bytes),
                               np.asarray(sharded.node_rx_bytes), rtol=1e-6)
    # traffic actually reached the chain end, over 4 cross-shard hops
    assert float(np.asarray(sharded.node_rx_packets)[n_nodes - 1]) > 0
    assert float(sharded.fwd_dropped) == 0
    assert float(sharded.no_route_dropped) == 0


def test_counters_match_single_device(devices8):
    n_nodes = 4
    mesh = make_mesh(N_SHARDS)
    rs_a, spec, flow_dst = build(n_nodes)
    rs_b = jax.tree.map(lambda x: x.copy(), rs_a)

    single = run_single(rs_a, spec, flow_dst, 8)
    sharded = run_sharded(rs_b, spec, flow_dst, 8, mesh, n_nodes)
    np.testing.assert_array_equal(
        np.asarray(single.sim.counters.tx_packets),
        np.asarray(sharded.sim.counters.tx_packets))
    np.testing.assert_array_equal(
        np.asarray(single.sim.counters.rx_packets),
        np.asarray(sharded.sim.counters.rx_packets))


def test_exchange_budget_overflow_is_counted(devices8):
    """A starved exchange budget drops forwarded packets and counts them."""
    n_nodes = 3
    mesh = make_mesh(N_SHARDS)
    rs, spec, flow_dst = build(n_nodes)
    # heavy CBR: many packets per step onto one next-hop edge, budget 1
    spec = cbr_on_rows([0], rate_bps=64e6)
    sharded = run_sharded(rs, spec, flow_dst, 10, mesh, n_nodes, budget=1)
    assert float(sharded.fwd_dropped) > 0


def test_no_route_counted(devices8):
    """Packets whose destination is unreachable count as no_route drops."""
    n_nodes = 4
    mesh = make_mesh(N_SHARDS)
    rs, spec, flow_dst = build(n_nodes)
    # point the source flow at an isolated node id
    fd = np.asarray(flow_dst).copy()
    fd[0] = n_nodes - 1
    state, rows = chain_state(n_nodes)
    # destination beyond the chain: node n_nodes-1 unreachable from node 1
    # if we cut the last link's route by targeting a node with no path
    fd[rows[0]] = n_nodes - 1
    # rebuild routes WITHOUT the last hop edge so dest is unreachable
    state2 = es.delete_links(state, jnp.asarray([rows[-1]]),
                             jnp.asarray([True]))
    _, nh = R.recompute_routes(state2, n_nodes, max_hops=8)
    rs = dataclasses.replace(rs, next_edge=nh,
                             sim=dataclasses.replace(rs.sim, edges=state2))
    sharded = run_sharded(rs, spec, jnp.asarray(fd), 10, mesh, n_nodes)
    assert float(sharded.no_route_dropped) > 0
    assert float(np.asarray(sharded.node_rx_packets)[n_nodes - 1]) == 0
