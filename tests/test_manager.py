"""ControllerManager: leader election, failover, health/ready probes, and
the continuous reconcile loop — the controller-runtime Manager surface of
the reference (reference main.go:80-126)."""

import json
import time
import urllib.request

import pytest

from kubedtn_tpu.api.types import (Link, LinkProperties, Topology,
                                   TopologySpec)
from kubedtn_tpu.topology import SimEngine, TopologyStore
from kubedtn_tpu.topology.manager import (LEADER_ELECTION_ID,
                                          ControllerManager, LeaseStore)


def mk_cluster(n_pods=3):
    store = TopologyStore()
    engine = SimEngine(store, capacity=64)
    for i in range(n_pods):
        t = Topology(name=f"p{i}", spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth0",
                 peer_pod="physical/10.0.0.9", uid=i,
                 properties=LinkProperties(latency="1ms"))]))
        t.status.links = []
        store.create(t)
    return store, engine


def wait_for(cond, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def test_manager_reconciles_continuously():
    store, engine = mk_cluster()
    mgr = ControllerManager(store, engine, workers=4)
    mgr.start()
    try:
        assert wait_for(lambda: engine.num_active == 3)
        assert wait_for(lambda: mgr.status.synced)
        # a NEW topology created while running is picked up (no restart)
        t = Topology(name="late", spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth0",
                 peer_pod="physical/10.0.0.9", uid=99)]))
        t.status.links = []
        store.create(t)
        assert wait_for(lambda: engine.num_active == 4)
    finally:
        mgr.stop()
    assert not mgr.status.alive


def test_leader_election_single_leader_and_failover():
    store, engine = mk_cluster()
    leases = LeaseStore()
    a = ControllerManager(store, engine, identity="a", leader_election=True,
                          lease_store=leases, lease_duration_s=0.5,
                          renew_interval_s=0.05)
    b = ControllerManager(store, engine, identity="b", leader_election=True,
                          lease_store=leases, lease_duration_s=0.5,
                          renew_interval_s=0.05)
    a.start()
    assert wait_for(lambda: a.status.is_leader)
    b.start()
    try:
        time.sleep(0.3)
        # exactly one leader, and it reconciles
        assert a.status.is_leader and not b.status.is_leader
        assert leases.holder(LEADER_ELECTION_ID) == "a"
        assert wait_for(lambda: engine.num_active == 3)

        # leader dies -> standby takes over within the lease duration
        a.stop()
        assert wait_for(lambda: b.status.is_leader, timeout=5)
        assert leases.holder(LEADER_ELECTION_ID) == "b"
        # and the new leader serves fresh work
        t = Topology(name="post-failover", spec=TopologySpec(links=[
            Link(local_intf="eth1", peer_intf="eth0",
                 peer_pod="physical/10.0.0.9", uid=50)]))
        t.status.links = []
        store.create(t)
        assert wait_for(lambda: engine.num_active == 4)
    finally:
        a.stop()
        b.stop()


def test_voluntary_release_speeds_up_takeover():
    """stop() releases the lease (ReleaseOnCancel semantics): the standby
    must NOT have to wait out the full lease duration."""
    store, engine = mk_cluster(0)
    leases = LeaseStore()
    kw = dict(leader_election=True, lease_store=leases,
              lease_duration_s=30.0, renew_interval_s=0.05)
    a = ControllerManager(store, engine, identity="a", **kw)
    b = ControllerManager(store, engine, identity="b", **kw)
    a.start()
    assert wait_for(lambda: a.status.is_leader)
    b.start()
    a.stop()  # releases the 30s lease voluntarily
    try:
        assert wait_for(lambda: b.status.is_leader, timeout=5), \
            "takeover waited on a released lease"
    finally:
        b.stop()


def test_probe_endpoints():
    store, engine = mk_cluster()
    mgr = ControllerManager(store, engine, probe_port=0)

    def get(path):
        url = f"http://127.0.0.1:{mgr.probe_port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                raw = r.read()
                return r.status, json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            raw = e.read()
            return e.code, json.loads(raw) if raw else {}

    # not started: healthz/readyz 503
    code, _ = get("/healthz")
    assert code == 503
    mgr.start()
    try:
        assert wait_for(lambda: mgr.status.synced)
        code, body = get("/healthz")
        assert code == 200 and body["checks"]["ping"]
        code, body = get("/readyz")
        assert code == 200 and body["checks"]["synced"]
        code, _ = get("/nope")
        assert code == 404
        # a stopped manager reports unhealthy (probe still answering here;
        # in deployment the pod's probe failures trigger restart)
        mgr._stop.set()
        mgr._thread.join(timeout=10)
        mgr._thread = None
        code, _ = get("/readyz")
        assert code == 503
        code, _ = get("/healthz")
        assert code == 503
    finally:
        mgr.stop()


def test_standby_is_ready_but_idle():
    """A non-leader standby reports ready (it can take over) but performs
    no reconciles while the leader holds the lease."""
    store, engine = mk_cluster()
    leases = LeaseStore()
    kw = dict(leader_election=True, lease_store=leases,
              lease_duration_s=5.0, renew_interval_s=0.05)
    a = ControllerManager(store, engine, identity="a", **kw)
    a.start()
    assert wait_for(lambda: a.status.synced)
    b = ControllerManager(store, engine, identity="b", probe_port=0, **kw)
    b.start()
    try:
        time.sleep(0.3)
        assert not b.status.is_leader
        assert b.status.reconciles == 0
        # the standby is healthy AND ready: it can take over at any time
        # (controller-runtime readyz does not gate on leadership)
        for path in ("/healthz", "/readyz"):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{b.probe_port}{path}",
                    timeout=5) as r:
                assert r.status == 200, path
    finally:
        a.stop()
        b.stop()


def test_leadership_survives_long_drain():
    """The lease renews from a dedicated thread, so a drain longer than
    the lease duration must NOT lose leadership mid-drain (split-brain)."""
    store, engine = mk_cluster(0)
    leases = LeaseStore()

    class SlowReconciler:
        pass

    kw = dict(leader_election=True, lease_store=leases,
              lease_duration_s=0.4, renew_interval_s=0.05)
    a = ControllerManager(store, engine, identity="a", **kw)
    b = ControllerManager(store, engine, identity="b", **kw)
    a.start()
    assert wait_for(lambda: a.status.is_leader)

    # make a's drains slower than the whole lease duration
    orig_drain = None

    def slow_drain(*args, **kwargs):
        time.sleep(1.0)  # 2.5x the lease duration
        return orig_drain(*args, **kwargs)

    assert wait_for(lambda: a.reconciler is not None)
    orig_drain = a.reconciler.drain
    a.reconciler.drain = slow_drain
    b.start()
    try:
        time.sleep(2.0)  # several slow drains
        assert a.status.is_leader, "leader lost lease during a long drain"
        assert not b.status.is_leader, "split-brain: standby took the lease"
        assert leases.holder(LEADER_ELECTION_ID) == "a"
    finally:
        a.stop()
        b.stop()


def test_leader_election_id_parity():
    assert LEADER_ELECTION_ID == "ac2ba29f.y-young.github.io"


def test_manager_restart_recreates_probes():
    """stop() must release the probe socket and start() must bring the
    probes back on the SAME port — a restarted manager with dead probes
    would be killed by its orchestrator."""
    store, engine = mk_cluster(0)
    mgr = ControllerManager(store, engine, probe_port=0)
    port = mgr.probe_port
    mgr.start()
    assert wait_for(lambda: mgr.status.alive)
    mgr.stop()
    mgr.start()
    try:
        assert mgr.probe_port == port
        assert wait_for(lambda: mgr.status.alive)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
            assert r.status == 200
    finally:
        mgr.stop()


class _LeaseApiErr(Exception):
    def __init__(self, status):
        super().__init__(f"http {status}")
        self.status = status


class FakeLeaseApi:
    """coordination.k8s.io double with resourceVersion CAS — the same
    envtest-style surface the KubeLeaseStore adapter drives in a real
    cluster."""

    def __init__(self):
        import copy as _c
        self._c = _c
        self.obj = None
        self.rv = 0

    def read_namespaced_lease(self, name, ns):
        if self.obj is None:
            raise _LeaseApiErr(404)
        return self._c.deepcopy(self.obj)

    def create_namespaced_lease(self, ns, body):
        if self.obj is not None:
            raise _LeaseApiErr(409)
        self.rv += 1
        body = self._c.deepcopy(body)
        body["metadata"]["resourceVersion"] = str(self.rv)
        self.obj = body

    def replace_namespaced_lease(self, name, ns, body):
        if self.obj is None:
            raise _LeaseApiErr(404)
        if body["metadata"].get("resourceVersion") != \
                self.obj["metadata"]["resourceVersion"]:
            raise _LeaseApiErr(409)
        self.rv += 1
        body = self._c.deepcopy(body)
        body["metadata"]["resourceVersion"] = str(self.rv)
        self.obj = body


class _WallClock:
    """Injectable wall clock: the adapter judges freshness on ITS clock
    (cross-pod), ignoring the caller's process-local monotonic time."""

    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestKubeLeaseStore:
    def mk(self):
        from kubedtn_tpu.topology.manager import KubeLeaseStore

        api = FakeLeaseApi()
        clock = _WallClock()
        return (KubeLeaseStore(namespace="ns", api=api, clock=clock),
                KubeLeaseStore(namespace="ns", api=api, clock=clock),
                api, clock)

    def test_acquire_renew_deny(self):
        a, b, api, clock = self.mk()
        assert a.try_acquire("lock", "a", now=0, lease_duration_s=5.0)
        assert a.holder("lock") == "a"
        clock.t += 1
        assert not b.try_acquire("lock", "b", now=0, lease_duration_s=5.0)
        clock.t += 2
        assert a.try_acquire("lock", "a", now=0, lease_duration_s=5.0)
        clock.t += 6  # stale: last renew 6s ago, duration 5
        assert b.try_acquire("lock", "b", now=0, lease_duration_s=5.0)
        assert b.holder("lock") == "b"
        assert api.obj["spec"]["leaseTransitions"] == 1
        # renewal by the SAME holder preserves the transition count
        clock.t += 1
        assert b.try_acquire("lock", "b", now=0, lease_duration_s=5.0)
        assert api.obj["spec"]["leaseTransitions"] == 1
        # renewTime is a real RFC3339 MicroTime, not a raw float
        assert api.obj["spec"]["renewTime"].endswith("Z")

    def test_cas_race_loses_cleanly(self):
        a, b, api, clock = self.mk()
        assert a.try_acquire("lock", "a", 0, 5.0)
        clock.t += 10  # stale, so b will try to take over
        real_read = api.read_namespaced_lease
        state = {}

        def racing_read(name, ns):
            lease = real_read(name, ns)
            if "raced" not in state:
                state["raced"] = True
                a.try_acquire("lock", "a", 0, 5.0)  # rv bump mid-read
            return lease

        api.read_namespaced_lease = racing_read
        assert not b.try_acquire("lock", "b", 0, 5.0)

    def test_release_allows_immediate_takeover(self):
        a, b, api, clock = self.mk()
        assert a.try_acquire("lock", "a", 0, 30.0)
        a.release("lock", "a")
        # released lease is validation-legal (positive duration) and stale
        assert api.obj["spec"]["leaseDurationSeconds"] >= 1
        clock.t += 0.1
        assert b.try_acquire("lock", "b", 0, 30.0)

    def test_interoperates_with_client_go_written_lease(self):
        """A lease written by client-go arrives with datetime renewTime
        (MicroTime) and snake_case-modeled objects; the adapter must read
        it without blowing up."""
        import datetime as dt

        from kubedtn_tpu.topology.manager import KubeLeaseStore

        api = FakeLeaseApi()
        clock = _WallClock()
        s = KubeLeaseStore(namespace="ns", api=api, clock=clock)
        api.obj = {"metadata": {"name": "lock", "resourceVersion": "5"},
                   "spec": {"holderIdentity": "other",
                            "leaseDurationSeconds": 15,
                            "renewTime": dt.datetime.fromtimestamp(
                                clock.t - 2, dt.timezone.utc),
                            "leaseTransitions": 3}}
        assert s.holder("lock") == "other"
        assert not s.try_acquire("lock", "me", 0, 15.0)  # fresh
        clock.t += 20
        assert s.try_acquire("lock", "me", 0, 15.0)      # expired
        assert api.obj["spec"]["leaseTransitions"] == 4

    def test_managers_failover_over_kube_lease(self):
        """End to end: two managers arbitrate through the Lease CAS."""
        from kubedtn_tpu.topology.manager import KubeLeaseStore

        api = FakeLeaseApi()
        store, engine = mk_cluster()
        kw = dict(leader_election=True, lease_duration_s=0.5,
                  renew_interval_s=0.05)
        a = ControllerManager(store, engine, identity="a",
                              lease_store=KubeLeaseStore("ns", api), **kw)
        b = ControllerManager(store, engine, identity="b",
                              lease_store=KubeLeaseStore("ns", api), **kw)
        a.start()
        assert wait_for(lambda: a.status.is_leader)
        b.start()
        try:
            assert wait_for(lambda: engine.num_active == 3)
            assert not b.status.is_leader
            a.stop()
            assert wait_for(lambda: b.status.is_leader, timeout=5)
        finally:
            a.stop()
            b.stop()


def test_manager_metrics_endpoint():
    """Controller metrics parity (reference MetricsBindAddress :8080):
    reconcile totals, error counter, leadership gauge, sync gauge."""
    store, engine = mk_cluster()
    leases = LeaseStore()
    mgr = ControllerManager(store, engine, identity="m0", metrics_port=0,
                            leader_election=True, lease_store=leases,
                            lease_duration_s=5.0, renew_interval_s=0.05)

    def scrape():
        url = f"http://127.0.0.1:{mgr.metrics_port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.read().decode()

    body = scrape()
    assert 'leader_election_master_status{identity="m0"} 0.0' in body
    assert 'controller_synced{identity="m0"} 0.0' in body
    mgr.start()
    try:
        assert wait_for(lambda: mgr.status.synced)
        body = scrape()
        assert 'leader_election_master_status{identity="m0"} 1.0' in body
        assert 'controller_synced{identity="m0"} 1.0' in body
        assert "controller_runtime_reconcile_total" in body
        assert "controller_runtime_reconcile_errors_total" in body
    finally:
        mgr.stop()


def test_manager_restart_recreates_metrics():
    """Like the probes, the metrics endpoint survives stop()/start() and
    its socket is fully released on stop."""
    store, engine = mk_cluster(0)
    mgr = ControllerManager(store, engine, metrics_port=0)
    port = mgr.metrics_port
    mgr.start()
    mgr.stop()
    mgr.start()
    try:
        assert mgr.metrics_port == port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            assert r.status == 200
    finally:
        mgr.stop()
