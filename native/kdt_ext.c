/* kdt_ext — CPython fast paths the plain-C-ABI library cannot provide:
 * building Python objects in C. The rest of the native tier
 * (kubedtn_native.cc) stays Python-free so it loads via ctypes anywhere;
 * this module is OPTIONAL and every caller keeps a pure-Python fallback
 * (wire/server.py FrameSeg.materialize).
 *
 * slice_frames(blob, offs, lens, lo, hi) -> list[bytes]
 *
 * The segment-delivery hot path: one C loop of
 * PyBytes_FromStringAndSize per frame instead of a Python slice loop —
 * frame materialization is the live plane's dominant release cost once
 * everything upstream is zero-copy (the role the kernel's skb clone
 * plays at delivery in the reference's veth path).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>

static PyObject *
slice_frames(PyObject *self, PyObject *args)
{
    PyObject *blob;
    Py_buffer offs, lens;
    Py_ssize_t lo, hi;

    (void)self;
    if (!PyArg_ParseTuple(args, "Sy*y*nn", &blob, &offs, &lens, &lo, &hi))
        return NULL;

    PyObject *out = NULL;
    const char *base = PyBytes_AS_STRING(blob);
    const uint64_t blen = (uint64_t)PyBytes_GET_SIZE(blob);
    const uint64_t *off_p = (const uint64_t *)offs.buf;
    const uint64_t *len_p = (const uint64_t *)lens.buf;
    const Py_ssize_t n_off = offs.len / (Py_ssize_t)sizeof(uint64_t);
    const Py_ssize_t n_len = lens.len / (Py_ssize_t)sizeof(uint64_t);

    if (lo < 0 || hi < lo || hi > n_off || hi > n_len) {
        PyErr_SetString(PyExc_ValueError,
                        "slice_frames: window outside offset/len arrays");
        goto done;
    }
    out = PyList_New(hi - lo);
    if (out == NULL)
        goto done;
    for (Py_ssize_t i = lo; i < hi; i++) {
        const uint64_t o = off_p[i];
        const uint64_t n = len_p[i];
        if (o > blen || n > blen - o) {
            Py_CLEAR(out);
            PyErr_SetString(PyExc_ValueError,
                            "slice_frames: frame window outside blob");
            goto done;
        }
        PyObject *item =
            PyBytes_FromStringAndSize(base + o, (Py_ssize_t)n);
        if (item == NULL) {
            Py_CLEAR(out);
            goto done;
        }
        PyList_SET_ITEM(out, i - lo, item);
    }
done:
    PyBuffer_Release(&offs);
    PyBuffer_Release(&lens);
    return out;
}

static PyMethodDef kdt_ext_methods[] = {
    {"slice_frames", slice_frames, METH_VARARGS,
     "slice_frames(blob, offs_u64, lens_u64, lo, hi) -> list[bytes]\n"
     "Materialize frames [lo, hi) of a segment from its transport blob "
     "in one C loop."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef kdt_ext_module = {
    PyModuleDef_HEAD_INIT, "kdt_ext",
    "CPython fast paths for the kubedtn_tpu data plane.", -1,
    kdt_ext_methods, NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC
PyInit_kdt_ext(void)
{
    return PyModule_Create(&kdt_ext_module);
}
