// kubedtn_native — the framework's native runtime library.
//
// TPU-native stand-ins for the reference's native/kernel-adjacent tier,
// re-implemented as a portable C++ shared library driven from Python via
// ctypes (no pybind11 in this image):
//
//  1. Frame decoder/classifier — behavior parity with the reference's
//     grpc-wire debug decoders (reference daemon/grpcwire/grpcwire.go:465-613):
//     Ethernet → {IPv4,IPv6}[src,dst] → {ICMP,TCP[:BGP|:port],proto},
//     ARP, 802.1Q VLAN (incl. LLC 0xFE/0xFE/0x03 → ISIS), multi-packet
//     frames. Used on the wire ingress path where the reference calls
//     DecodeFrame per captured pcap packet.
//
//  2. Bypass flow table — the userspace realization of the reference's
//     eBPF TCP/IP-bypass state machine (reference bpf/lib/sockops.c,
//     redir.c, redir_disable.c): active/passive TCP establishment pairs
//     same-node flows into a proxy map with 3-state flags
//     (INIT → ENABLED on first message, DISABLED forever once the flow's
//     packets are seen on a shaped device so emulation is never cheated);
//     ENABLED flows short-circuit the shaping data plane exactly as
//     bpf_msg_redirect_hash short-circuits the kernel stack.
//
//  3. SPSC frame ring — single-producer/single-consumer byte ring for the
//     per-wire frame queues (the reference's per-wire pcap goroutine +
//     640KB buffer, grpcwire.go:398-409), lock-free on the hot path.
//
// Build: `make -C native` → libkubedtn_native.so; loaded by
// kubedtn_tpu/native.py (pure-Python fallback when the toolchain or the
// .so is unavailable).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ===================== 1. frame decoder =====================

enum FrameType : int32_t {
  KDT_FRAME_UNKNOWN = 0,
  KDT_FRAME_IPV4 = 1,
  KDT_FRAME_IPV6 = 2,
  KDT_FRAME_ARP = 3,
  KDT_FRAME_VLAN = 4,
  KDT_FRAME_LLC = 5,
  KDT_FRAME_ISIS = 6,
  KDT_FRAME_ICMP = 7,
  KDT_FRAME_TCP = 8,
  KDT_FRAME_BGP = 9,
  KDT_FRAME_UDP = 10,
  KDT_FRAME_ICMP6 = 11,
};

}  // extern "C"

namespace {

constexpr int kEthHdrLen = 14;

uint16_t rd16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) << 8 | p[1];
}

void ip4_str(const uint8_t* p, char* out) {
  std::snprintf(out, 16, "%u.%u.%u.%u", p[0], p[1], p[2], p[3]);
}

void ip6_str(const uint8_t* p, char* out) {
  // Canonical-enough textual form (full groups, no :: compression) — the
  // decoder string is for logs, not parsing.
  std::snprintf(out, 40, "%x:%x:%x:%x:%x:%x:%x:%x", rd16(p), rd16(p + 2),
                rd16(p + 4), rd16(p + 6), rd16(p + 8), rd16(p + 10),
                rd16(p + 12), rd16(p + 14));
}

struct Decoded {
  int len = 0;           // bytes of payload consumed past the Ethernet header
  std::string text;      // ":IPv4[...]:TCP..." suffix
  int32_t innermost = KDT_FRAME_UNKNOWN;
};

// decodeIPv4Pkt parity (grpcwire.go:557-584).
Decoded decode_ipv4(const uint8_t* p, uint64_t n) {
  Decoded d;
  d.text = ":IPv4";
  d.innermost = KDT_FRAME_IPV4;
  if (n < 20) return d;
  const int ihl = (p[0] & 0x0F) * 4;
  const int total_len = rd16(p + 2);
  d.len = total_len;
  char s[16], t[16];
  ip4_str(p + 12, s);
  ip4_str(p + 16, t);
  d.text += "[s:" + std::string(s) + ", d:" + std::string(t) + "]";
  const uint8_t proto = p[9];
  if (proto == 1) {
    d.text += ":ICMP";
    d.innermost = KDT_FRAME_ICMP;
  } else if (proto == 6) {
    d.text += ":TCP";
    d.innermost = KDT_FRAME_TCP;
    if (n >= static_cast<uint64_t>(ihl) + 4) {
      const uint16_t dport = rd16(p + ihl + 2);
      if (dport == 179) {
        d.text += ":BGP";
        d.innermost = KDT_FRAME_BGP;
      } else {
        d.text += ":[Port:" + std::to_string(dport) + "]";
      }
    }
  } else {
    d.text += ":IPv4 with protocol : " + std::to_string(proto);
    if (proto == 17) d.innermost = KDT_FRAME_UDP;
  }
  return d;
}

// decodeIPv6Pkt parity (grpcwire.go:586-613).
Decoded decode_ipv6(const uint8_t* p, uint64_t n) {
  Decoded d;
  d.text = ":IPv6";
  d.innermost = KDT_FRAME_IPV6;
  if (n < 40) return d;
  d.len = rd16(p + 4);  // payload length (the gopacket Length field)
  char s[40], t[40];
  ip6_str(p + 8, s);
  ip6_str(p + 24, t);
  d.text += "[s:" + std::string(s) + ", d:" + std::string(t) + "]";
  const uint8_t next = p[6];
  if (next == 58) {
    d.text += ":ICMPv6";
    d.innermost = KDT_FRAME_ICMP6;
  } else if (next == 6) {
    d.text += ":TCP";
    d.innermost = KDT_FRAME_TCP;
    const uint16_t dport = rd16(p + 40 + 2);
    if (n >= 44 && dport == 179) {
      d.text += ":BGP";
      d.innermost = KDT_FRAME_BGP;
    } else if (n >= 44) {
      d.text += "[Port:" + std::to_string(dport) + "]";
    }
  } else {
    d.text += ":IPv6 with protocol : " + std::to_string(next);
    if (next == 17) d.innermost = KDT_FRAME_UDP;
  }
  return d;
}

// LLC branch parity (grpcwire.go:510-522): 0xFE/0xFE/0x03 + NLPID 0x83 = ISIS.
Decoded decode_llc(const uint8_t* p, uint64_t n, uint16_t length) {
  Decoded d;
  d.text = ":LLC";
  d.innermost = KDT_FRAME_LLC;
  if (n >= 4 && p[0] == 0xFE && p[1] == 0xFE && p[2] == 0x03 &&
      p[3] == 0x83) {
    d.text += ":ISIS";
    d.innermost = KDT_FRAME_ISIS;
  }
  d.len = length;
  return d;
}

// DecodePkt parity (grpcwire.go:500-553) keyed on EtherType.
Decoded decode_next(uint16_t ether_type, const uint8_t* p, uint64_t n) {
  Decoded d;
  if (ether_type == 0x0800) return decode_ipv4(p, n);
  if (ether_type == 0x86DD) return decode_ipv6(p, n);
  if (ether_type == 0x0806) {
    d.text = ":ARP";
    d.innermost = KDT_FRAME_ARP;
    d.len = 28;
    return d;
  }
  if (ether_type == 0x8100) {  // 802.1Q
    d.text = ":VLAN";
    d.innermost = KDT_FRAME_VLAN;
    if (n < 4) return d;
    const uint16_t inner_type = rd16(p + 2);
    Decoded inner;
    if (inner_type >= 0x0600) {
      inner = decode_next(inner_type, p + 4, n - 4);
    } else if (n >= 7 && p[4] == 0xFE && p[5] == 0xFE && p[6] == 0x03) {
      inner = decode_llc(p + 4, n - 4, inner_type);
    }
    d.text += inner.text;
    if (inner.innermost != KDT_FRAME_UNKNOWN) d.innermost = inner.innermost;
    d.len = inner.len + 4;
    return d;
  }
  if (ether_type < 0x0600) {  // 802.3 length ⇒ LLC
    return decode_llc(p, n, ether_type);
  }
  return d;  // unknown EtherType — empty suffix, len 0 (loop will stop)
}

}  // namespace

extern "C" {

// DecodeFrame parity (grpcwire.go:465-498): classify every packet in the
// frame; multi-packet frames get the "Multi Pkts:" prefix.
int64_t kdt_decode_frame(const uint8_t* frame, uint64_t len, char* out,
                         uint64_t out_cap) {
  std::string text;
  int num = 1;
  uint64_t off = 0;
  const uint64_t total = len;
  while (total - off >= kEthHdrLen) {
    const uint8_t* p = frame + off;
    const uint16_t ether_type = rd16(p + 12);
    text += "Pkt no " + std::to_string(num) + ": Ethernet";
    Decoded d = decode_next(ether_type, p + kEthHdrLen,
                            total - off - kEthHdrLen);
    text += d.text;
    const uint64_t consumed = kEthHdrLen + static_cast<uint64_t>(d.len);
    if (d.len <= 0) break;  // undecodable payload: stop like gopacket would
    off += consumed;
    if (total - off >= kEthHdrLen) {
      ++num;
      text += "\n            ";
    } else {
      break;
    }
  }
  if (num > 1) text = "Multi Pkts: " + text;
  const int64_t n =
      static_cast<int64_t>(std::min<uint64_t>(text.size(), out_cap - 1));
  std::memcpy(out, text.data(), n);
  out[n] = '\0';
  return n;
}

int32_t kdt_classify_frame(const uint8_t* frame, uint64_t len) {
  if (len < kEthHdrLen) return KDT_FRAME_UNKNOWN;
  return decode_next(rd16(frame + 12), frame + kEthHdrLen, len - kEthHdrLen)
      .innermost;
}

// Batched classification for wire ingress: one call per drain, not per frame.
void kdt_classify_batch(const uint8_t* buf, const uint64_t* offsets,
                        const uint64_t* lens, int64_t n, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = kdt_classify_frame(buf + offsets[i], lens[i]);
  }
}

// Pointer-array form: the caller passes each frame's own buffer (ctypes
// c_char_p straight into the Python bytes objects) — no concatenated
// blob copy on the hot path.
void kdt_classify_batch_ptrs(const uint8_t* const* frames,
                             const uint64_t* lens, int64_t n,
                             int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = kdt_classify_frame(frames[i], lens[i]);
  }
}

// ============== 1b. PacketBatch wire-format decoder ==============
//
// The bulk ingestion RPCs (SendToBulk/InjectBulk) receive a serialized
// PacketBatch (repeated Packet packets = 1; Packet: int64 remot_intf_id
// = 1 varint, bytes frame = 2 — field numbers fixed by the reference
// IDL, proto/v1/kube_dtn.proto:128-132). Decoding it through a Python
// protobuf runtime materializes one message object per frame; this
// decoder walks the wire format once and emits flat arrays (id, frame
// offset, frame length) so Python touches only numpy arrays plus one
// bytes-slice per frame. Unknown fields are skipped per the wire
// format; returns the packet count, or -1 on malformed input (caller
// falls back to the protobuf runtime).

namespace {
inline bool kdt_read_varint(const uint8_t* b, uint64_t len, uint64_t* p,
                            uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*p < len && shift < 64) {
    const uint8_t byte = b[*p];
    ++*p;
    v |= static_cast<uint64_t>(byte & 0x7F) << shift;
    if (!(byte & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

inline bool kdt_skip_field(const uint8_t* b, uint64_t len, uint64_t* p,
                           uint32_t wiretype) {
  uint64_t v;
  switch (wiretype) {
    case 0:  // varint
      return kdt_read_varint(b, len, p, &v);
    case 1:  // fixed64
      if (*p + 8 > len) return false;
      *p += 8;
      return true;
    case 2:  // length-delimited
      // cursor-relative check: `*p + v > len` computed in uint64 can
      // WRAP on a crafted ~2^64 length and walk the cursor backward
      // into an infinite loop (remote DoS on raw network bytes)
      if (!kdt_read_varint(b, len, p, &v) || v > len - *p) return false;
      *p += v;
      return true;
    case 5:  // fixed32
      if (*p + 4 > len) return false;
      *p += 4;
      return true;
    default:
      return false;
  }
}
}  // namespace

int64_t kdt_parse_packet_batch_t(const uint8_t* blob, uint64_t len,
                                 int64_t* out_ids, uint64_t* out_off,
                                 uint64_t* out_len, uint64_t* out_trace,
                                 int64_t max) {
  uint64_t p = 0;
  int64_t n = 0;
  while (p < len) {
    uint64_t tag;
    if (!kdt_read_varint(blob, len, &p, &tag)) return -1;
    if (tag >> 3 != 1 || (tag & 7) != 2) {  // not `packets`: skip
      if (!kdt_skip_field(blob, len, &p, tag & 7)) return -1;
      continue;
    }
    uint64_t plen;
    if (!kdt_read_varint(blob, len, &p, &plen) || plen > len - p)
      return -1;
    const uint64_t pend = p + plen;
    if (n >= max) return -1;
    int64_t id = 0;
    uint64_t foff = 0, flen = 0, trace = 0;
    while (p < pend) {
      uint64_t ptag;
      if (!kdt_read_varint(blob, pend, &p, &ptag)) return -1;
      if (ptag == 0x08) {  // remot_intf_id, varint
        uint64_t v;
        if (!kdt_read_varint(blob, pend, &p, &v)) return -1;
        id = static_cast<int64_t>(v);
      } else if (ptag == 0x12) {  // frame, bytes
        uint64_t v;
        if (!kdt_read_varint(blob, pend, &p, &v) || v > pend - p)
          return -1;
        foff = p;
        flen = v;
        p += v;
      } else if (ptag == 0x18) {  // trace_id, varint (flight recorder)
        if (!kdt_read_varint(blob, pend, &p, &trace)) return -1;
      } else if (!kdt_skip_field(blob, pend, &p, ptag & 7)) {
        return -1;
      }
    }
    out_ids[n] = id;
    out_off[n] = foff;
    out_len[n] = flen;
    if (out_trace) out_trace[n] = trace;
    ++n;
  }
  return n;
}

int64_t kdt_parse_packet_batch(const uint8_t* blob, uint64_t len,
                               int64_t* out_ids, uint64_t* out_off,
                               uint64_t* out_len, int64_t max) {
  return kdt_parse_packet_batch_t(blob, len, out_ids, out_off, out_len,
                                  nullptr, max);
}

// ===================== 2. bypass flow table =====================

enum ProxyFlag : int32_t {
  KDT_PROXY_INIT = 0,      // pair created, first message not yet seen
  KDT_PROXY_ENABLED = 1,   // messages short-circuit the data plane
  KDT_PROXY_DISABLED = 2,  // flow crosses a shaped device: never bypass
};

}  // extern "C"

namespace {

struct Tuple4 {
  uint32_t lip, rip;
  uint16_t lport, rport;
  bool operator==(const Tuple4& o) const {
    return lip == o.lip && rip == o.rip && lport == o.lport &&
           rport == o.rport;
  }
};

struct Tuple4Hash {
  size_t operator()(const Tuple4& t) const {
    uint64_t h = (static_cast<uint64_t>(t.lip) << 32) | t.rip;
    h ^= (static_cast<uint64_t>(t.lport) << 16 | t.rport) * 0x9E3779B97F4A7C15ULL;
    h ^= h >> 29;
    h *= 0xBF58476D1CE4E5B9ULL;
    h ^= h >> 32;
    return static_cast<size_t>(h);
  }
};

struct ProxyVal {
  Tuple4 peer;     // the redirect target tuple (socket_4_tuple_extended.tuple)
  int32_t flag;    // ProxyFlag
};

struct Addr2 {
  uint32_t ip;
  uint16_t port;
  bool operator==(const Addr2& o) const {
    return ip == o.ip && port == o.port;
  }
};

struct Addr2Hash {
  size_t operator()(const Addr2& a) const {
    return Tuple4Hash{}(Tuple4{a.ip, 0, a.port, 0});
  }
};

struct FlowTable {
  std::mutex mu;
  uint64_t capacity;  // map_proxy max_entries analogue (maps.h: 65535)
  std::unordered_map<Addr2, Addr2, Addr2Hash> active_estab;  // map_active_estab
  std::unordered_map<Tuple4, ProxyVal, Tuple4Hash> proxy;    // map_proxy
  std::atomic<uint64_t> bypassed{0};  // messages short-circuited
  std::atomic<uint64_t> passed{0};    // messages on the normal path
};

}  // namespace

extern "C" {

void* kdt_ft_new(uint64_t capacity) {
  auto* ft = new FlowTable();
  ft->capacity = capacity ? capacity : 65535;  // reference maps.h:13-73
  return ft;
}

void kdt_ft_free(void* h) { delete static_cast<FlowTable*>(h); }

// sockops ACTIVE_ESTABLISHED (sockops.c bpf_sock_ops_active_establish_cb):
// record local→remote so the passive side can pair the flow.
void kdt_ft_active_established(void* h, uint32_t lip, uint16_t lport,
                               uint32_t rip, uint16_t rport) {
  auto* ft = static_cast<FlowTable*>(h);
  if (lip == rip && lport == rport) return;  // self-connection guard
  std::lock_guard<std::mutex> g(ft->mu);
  if (ft->active_estab.size() >= ft->capacity) return;
  // BPF_NOEXIST: first writer wins
  ft->active_estab.emplace(Addr2{lip, lport}, Addr2{rip, rport});
}

// sockops PASSIVE_ESTABLISHED (bpf_sock_ops_passive_establish_cb): if the
// active side registered on this node, create the proxy pair both ways in
// INIT state. Returns 1 when the pair was created (same-node flow).
int32_t kdt_ft_passive_established(void* h, uint32_t lip, uint16_t lport,
                                   uint32_t rip, uint16_t rport) {
  auto* ft = static_cast<FlowTable*>(h);
  std::lock_guard<std::mutex> g(ft->mu);
  auto it = ft->active_estab.find(Addr2{rip, rport});
  if (it == ft->active_estab.end()) return 0;
  if (ft->proxy.size() + 2 > ft->capacity) return 0;
  const Addr2 orig = it->second;
  const Tuple4 proxy_key{rip, orig.ip, rport, orig.port};
  const Tuple4 proxy_val{lip, rip, lport, rport};
  ft->proxy[proxy_key] = ProxyVal{proxy_val, KDT_PROXY_INIT};
  ft->proxy[proxy_val] = ProxyVal{proxy_key, KDT_PROXY_INIT};
  ft->active_estab.erase(it);
  return 1;
}

// sk_msg (redir.c bpf_redir_proxy): 1 ⇒ message bypasses the data plane
// (bpf_msg_redirect_hash path), 0 ⇒ normal path. The first message of an
// INIT flow passes normally and flips the flow to ENABLED.
int32_t kdt_ft_msg_redirect(void* h, uint32_t lip, uint16_t lport,
                            uint32_t rip, uint16_t rport) {
  auto* ft = static_cast<FlowTable*>(h);
  std::lock_guard<std::mutex> g(ft->mu);
  auto it = ft->proxy.find(Tuple4{lip, rip, lport, rport});
  if (it == ft->proxy.end() || it->second.flag == KDT_PROXY_DISABLED) {
    ft->passed.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  if (it->second.flag == KDT_PROXY_INIT) {
    it->second.flag = KDT_PROXY_ENABLED;
    ft->passed.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  ft->bypassed.fetch_add(1, std::memory_order_relaxed);
  return 1;
}

// tc egress on a shaped device (redir_disable.c bpf_redir_disable): the
// flow's packets actually traverse emulation ⇒ bypass is permanently off.
void kdt_ft_shaped_egress(void* h, uint32_t sip, uint16_t sport,
                          uint32_t dip, uint16_t dport) {
  auto* ft = static_cast<FlowTable*>(h);
  std::lock_guard<std::mutex> g(ft->mu);
  auto it = ft->proxy.find(Tuple4{sip, dip, sport, dport});
  if (it != ft->proxy.end() && it->second.flag != KDT_PROXY_DISABLED) {
    it->second.flag = KDT_PROXY_DISABLED;
  }
}

// Batched bypass decision for a whole ingress drain — the per-frame
// Python path (runtime._try_bypass) collapsed to ONE native call per
// tick. For each frame i: parse the IPv4/TCP 4-tuple (802.1Q-aware,
// fragments excluded — non-first fragments carry payload where the TCP
// header would be); when first seen, register both sockops
// establishment hooks (both endpoints are local wires, so active and
// passive establish fire on this node, as at connection setup in the
// reference, BEFORE any frame crosses a device); a frame on a shaped
// row disables its flow forever (redir_disable.c:44-48); otherwise the
// sk_msg verdict decides. eligible[i]=0 (no local peer wire) and
// non-TCP frames always take the shaping path. out_bypass[i]=1 means
// the frame short-circuits shaping. Returns how many bypassed.
}  // extern "C"

namespace {

// One frame's bypass decision with ft->mu already held: parse the
// IPv4/TCP 4-tuple (802.1Q-aware, fragments excluded), establish on
// first sight, disable forever on a shaped row, else the sk_msg
// verdict. Returns 1 when the frame bypasses shaping.
inline uint8_t decide_one(FlowTable* ft, const uint8_t* f, uint64_t len,
                          uint8_t shaped) {
  // -- parse_tcp_flow parity (runtime.py) --
  if (len < 14) return 0;
  uint64_t off = 14;
  uint16_t ether_type = rd16(f + 12);
  if (ether_type == 0x8100 && len >= 18) {
    ether_type = rd16(f + 16);
    off = 18;
  }
  if (ether_type != 0x0800 || len < off + 20) return 0;
  const int ihl = (f[off] & 0x0F) * 4;
  if ((f[off] >> 4) != 4 || ihl < 20 || len < off + ihl + 4) return 0;
  if (f[off + 9] != 6) return 0;  // TCP only
  if ((rd16(f + off + 6) & 0x3FFF) != 0) return 0;  // any fragment
  const uint32_t sip = static_cast<uint32_t>(f[off + 12]) << 24 |
                       static_cast<uint32_t>(f[off + 13]) << 16 |
                       static_cast<uint32_t>(f[off + 14]) << 8 |
                       f[off + 15];
  const uint32_t dip = static_cast<uint32_t>(f[off + 16]) << 24 |
                       static_cast<uint32_t>(f[off + 17]) << 16 |
                       static_cast<uint32_t>(f[off + 18]) << 8 |
                       f[off + 19];
  const uint16_t sport = rd16(f + off + ihl);
  const uint16_t dport = rd16(f + off + ihl + 2);
  const Tuple4 fwd{sip, dip, sport, dport};
  auto it = ft->proxy.find(fwd);
  if (it == ft->proxy.end()) {
    // first sight: active then passive establish (sockops pair). The
    // self-connection guard covers ONLY the active-establish emplace
    // (kdt_ft_active_established's early return); the passive lookup
    // still runs and may pair against a pre-existing active-estab entry
    // for the same 2-tuple — exact parity with the per-frame path
    // (runtime._try_bypass calls passive_established unconditionally).
    if ((sip != dip || sport != dport) &&
        ft->active_estab.size() < ft->capacity) {
      ft->active_estab.emplace(Addr2{sip, sport}, Addr2{dip, dport});
    }
    auto ae = ft->active_estab.find(Addr2{sip, sport});
    if (ae != ft->active_estab.end() &&
        ft->proxy.size() + 2 <= ft->capacity) {
      const Addr2 orig = ae->second;
      const Tuple4 proxy_key{sip, orig.ip, sport, orig.port};
      const Tuple4 proxy_val{dip, sip, dport, sport};
      ft->proxy[proxy_key] = ProxyVal{proxy_val, KDT_PROXY_INIT};
      ft->proxy[proxy_val] = ProxyVal{proxy_key, KDT_PROXY_INIT};
      ft->active_estab.erase(ae);
    }
    it = ft->proxy.find(fwd);
  }
  if (shaped) {
    // traffic crossing a shaped device disables the flow FOREVER
    if (it != ft->proxy.end() && it->second.flag != KDT_PROXY_DISABLED) {
      it->second.flag = KDT_PROXY_DISABLED;
    }
    return 0;
  }
  // sk_msg verdict (kdt_ft_msg_redirect body, lock already held)
  if (it == ft->proxy.end() || it->second.flag == KDT_PROXY_DISABLED) {
    ft->passed.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  if (it->second.flag == KDT_PROXY_INIT) {
    it->second.flag = KDT_PROXY_ENABLED;
    ft->passed.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  ft->bypassed.fetch_add(1, std::memory_order_relaxed);
  return 1;
}

}  // namespace

extern "C" {

// Pointer-array form: no concatenated blob copy (see
// kdt_classify_batch_ptrs).
// Fused verdicts + per-protocol class counts: the data-plane tick needs
// both for the same drained batch, and the frame-pointer marshalling is
// a third of each call's cost — share it. countable[i]=0 (or passing
// countable/out_class as NULL) skips classification (holdback frames
// were counted on their first pass); out_class[i] is the FrameType or
// -1 when skipped. The plain decide form delegates here so there is
// exactly ONE decide loop to keep in sync with the per-frame path.
int64_t kdt_ft_decide_classify_batch_ptrs(
    void* h, const uint8_t* const* frames, const uint64_t* lens,
    int64_t n, const uint8_t* eligible, const uint8_t* shaped,
    const uint8_t* countable, uint8_t* out_bypass, int32_t* out_class) {
  auto* ft = static_cast<FlowTable*>(h);
  std::lock_guard<std::mutex> g(ft->mu);
  int64_t bypassed = 0;
  for (int64_t i = 0; i < n; ++i) {
    out_bypass[i] = eligible[i]
        ? decide_one(ft, frames[i], lens[i], shaped[i])
        : 0;
    bypassed += out_bypass[i];
    if (out_class != nullptr) {
      out_class[i] = (countable != nullptr && countable[i])
          ? kdt_classify_frame(frames[i], lens[i])
          : -1;
    }
  }
  return bypassed;
}

int64_t kdt_ft_decide_batch_ptrs(void* h, const uint8_t* const* frames,
                                 const uint64_t* lens, int64_t n,
                                 const uint8_t* eligible,
                                 const uint8_t* shaped,
                                 uint8_t* out_bypass) {
  return kdt_ft_decide_classify_batch_ptrs(
      h, frames, lens, n, eligible, shaped, nullptr, out_bypass,
      nullptr);
}

// TCP close (sockops.c bpf_sock_ops_state_cb): drop this direction's proxy
// entry and any stale active-establishment record.
void kdt_ft_close(void* h, uint32_t lip, uint16_t lport, uint32_t rip,
                  uint16_t rport) {
  auto* ft = static_cast<FlowTable*>(h);
  std::lock_guard<std::mutex> g(ft->mu);
  ft->proxy.erase(Tuple4{lip, rip, lport, rport});
  ft->active_estab.erase(Addr2{lip, lport});
}

// -1 = not tracked; else the ProxyFlag.
int32_t kdt_ft_flag(void* h, uint32_t lip, uint16_t lport, uint32_t rip,
                    uint16_t rport) {
  auto* ft = static_cast<FlowTable*>(h);
  std::lock_guard<std::mutex> g(ft->mu);
  auto it = ft->proxy.find(Tuple4{lip, rip, lport, rport});
  return it == ft->proxy.end() ? -1 : it->second.flag;
}

uint64_t kdt_ft_size(void* h) {
  auto* ft = static_cast<FlowTable*>(h);
  std::lock_guard<std::mutex> g(ft->mu);
  return ft->proxy.size();
}

uint64_t kdt_ft_bypassed(void* h) {
  return static_cast<FlowTable*>(h)->bypassed.load(std::memory_order_relaxed);
}

uint64_t kdt_ft_passed(void* h) {
  return static_cast<FlowTable*>(h)->passed.load(std::memory_order_relaxed);
}

// ===================== 3. SPSC frame ring =====================

}  // extern "C"

namespace {

// Lock-free single-producer/single-consumer ring of length-prefixed frames.
struct Ring {
  std::vector<uint8_t> buf;
  uint64_t cap;
  std::atomic<uint64_t> head{0};  // consumer cursor (bytes)
  std::atomic<uint64_t> tail{0};  // producer cursor (bytes)
  std::atomic<uint64_t> count{0};
  std::atomic<uint64_t> dropped{0};

  explicit Ring(uint64_t c) : buf(c), cap(c) {}

  void write_bytes(uint64_t pos, const uint8_t* d, uint64_t n) {
    const uint64_t at = pos % cap;
    const uint64_t first = std::min(n, cap - at);
    std::memcpy(buf.data() + at, d, first);
    if (n > first) std::memcpy(buf.data(), d + first, n - first);
  }

  void read_bytes(uint64_t pos, uint8_t* d, uint64_t n) const {
    const uint64_t at = pos % cap;
    const uint64_t first = std::min(n, cap - at);
    std::memcpy(d, buf.data() + at, first);
    if (n > first) std::memcpy(d + first, buf.data(), n - first);
  }
};

}  // namespace

extern "C" {

void* kdt_rb_new(uint64_t capacity_bytes) {
  // 640KB default mirrors the reference's pcap buffer (grpcwire.go:399).
  return new Ring(capacity_bytes ? capacity_bytes : 640 * 1024);
}

void kdt_rb_free(void* h) { delete static_cast<Ring*>(h); }

// 1 = queued; 0 = dropped (ring full — the reference's pcap loop likewise
// drops when its buffer overruns).
int32_t kdt_rb_push(void* h, const uint8_t* data, uint32_t len) {
  auto* r = static_cast<Ring*>(h);
  const uint64_t need = 4 + static_cast<uint64_t>(len);
  const uint64_t head = r->head.load(std::memory_order_acquire);
  const uint64_t tail = r->tail.load(std::memory_order_relaxed);
  if (r->cap - (tail - head) < need) {
    r->dropped.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }
  uint8_t hdr[4] = {static_cast<uint8_t>(len >> 24),
                    static_cast<uint8_t>(len >> 16),
                    static_cast<uint8_t>(len >> 8),
                    static_cast<uint8_t>(len)};
  r->write_bytes(tail, hdr, 4);
  r->write_bytes(tail + 4, data, len);
  r->tail.store(tail + need, std::memory_order_release);
  r->count.fetch_add(1, std::memory_order_relaxed);
  return 1;
}

// Returns the frame length (>=0) or -1 when empty / -2 when out_cap is too
// small (frame left queued).
int64_t kdt_rb_pop(void* h, uint8_t* out, uint64_t out_cap) {
  auto* r = static_cast<Ring*>(h);
  const uint64_t tail = r->tail.load(std::memory_order_acquire);
  const uint64_t head = r->head.load(std::memory_order_relaxed);
  if (tail == head) return -1;
  uint8_t hdr[4];
  r->read_bytes(head, hdr, 4);
  const uint64_t len = static_cast<uint64_t>(hdr[0]) << 24 |
                       static_cast<uint64_t>(hdr[1]) << 16 |
                       static_cast<uint64_t>(hdr[2]) << 8 | hdr[3];
  if (len > out_cap) return -2;
  r->read_bytes(head + 4, out, len);
  r->head.store(head + 4 + len, std::memory_order_release);
  r->count.fetch_sub(1, std::memory_order_relaxed);
  return static_cast<int64_t>(len);
}

uint64_t kdt_rb_count(void* h) {
  return static_cast<Ring*>(h)->count.load(std::memory_order_relaxed);
}

uint64_t kdt_rb_dropped(void* h) {
  return static_cast<Ring*>(h)->dropped.load(std::memory_order_relaxed);
}

// ===================== 4. hierarchical timing wheel =====================
//
// The data plane's delay-line scheduler: frames held for their computed
// netem/TBF delay (kubedtn_tpu/runtime.py) are released by this wheel
// instead of a Python heap. Same role the kernel's qdisc watchdog timer
// plays for netem's tfifo in the reference's data plane — here it is a
// classic hashed hierarchical wheel (Varghese & Lauck): L levels of 2^bits
// slots, level-0 slot = tick_us, level k slot = tick_us * 2^(bits*k);
// entries cascade down as the cursor crosses level boundaries, so
// schedule and advance are O(1) amortized regardless of delay spread.

}  // extern "C"

namespace {

struct TwEntry {
  uint64_t when_us;
  uint64_t token;
};

struct TimingWheel {
  std::mutex mu;
  uint64_t tick_us;
  uint32_t bits;     // log2(slots per level)
  uint32_t levels;
  uint64_t mask;     // slots - 1
  uint64_t cursor;   // current tick index (last_us / tick_us)
  uint64_t last_us;  // time the wheel has been advanced to
  uint64_t size;     // outstanding entries (wheels + overflow + due)
  std::vector<std::vector<std::vector<TwEntry>>> wheel;  // [level][slot]
  std::vector<TwEntry> overflow;  // beyond the top level's horizon
  std::vector<TwEntry> due;       // popped, not yet handed to the caller

  TimingWheel(uint64_t t, uint32_t b, uint32_t l)
      : tick_us(t ? t : 1000),
        bits(b ? b : 8),
        levels(l ? l : 4),
        cursor(0),
        last_us(0),
        size(0) {
    if (bits > 14) bits = 14;
    if (levels < 1) levels = 1;
    // keep span arithmetic far from uint64 overflow
    while (static_cast<uint64_t>(bits) * levels > 56) --levels;
    mask = (1ULL << bits) - 1;
    wheel.assign(levels, std::vector<std::vector<TwEntry>>(1ULL << bits));
  }

  // ticks covered by one slot of level k
  uint64_t span(uint32_t k) const { return 1ULL << (bits * k); }
  // ticks covered by levels 0..k inclusive
  uint64_t horizon(uint32_t k) const { return 1ULL << (bits * (k + 1)); }

  void place(uint64_t when_us, uint64_t token) {
    if (when_us <= last_us) {
      due.push_back({when_us, token});
      return;
    }
    const uint64_t t = when_us / tick_us;
    const uint64_t delta = t > cursor ? t - cursor : 0;
    if (delta == 0) {
      due.push_back({when_us, token});
      return;
    }
    for (uint32_t k = 0; k < levels; ++k) {
      if (delta < horizon(k)) {
        wheel[k][(t / span(k)) & mask].push_back({when_us, token});
        return;
      }
    }
    overflow.push_back({when_us, token});
  }

  void cascade(uint32_t k) {
    if (k >= levels) {
      // top wrapped: re-place everything beyond the horizon
      std::vector<TwEntry> pend;
      pend.swap(overflow);
      for (const TwEntry& e : pend) place(e.when_us, e.token);
      return;
    }
    const uint64_t idx = (cursor / span(k)) & mask;
    std::vector<TwEntry> pend;
    pend.swap(wheel[k][idx]);
    for (const TwEntry& e : pend) place(e.when_us, e.token);
  }

  void advance_to(uint64_t now_us) {
    const uint64_t target = now_us / tick_us;
    while (cursor < target) {
      if (size == due.size() && overflow.empty()) {
        cursor = target;  // wheels empty: nothing can cascade, fast-forward
        break;
      }
      ++cursor;
      last_us = cursor * tick_us;
      for (uint32_t k = 1; k < levels + 1; ++k) {
        if ((cursor % span(k)) == 0) {
          cascade(k);
        } else {
          break;
        }
      }
      const uint64_t idx = cursor & mask;
      std::vector<TwEntry>& slot = wheel[0][idx];
      if (!slot.empty()) {
        due.insert(due.end(), slot.begin(), slot.end());
        slot.clear();
      }
    }
    last_us = now_us;
  }
};

bool tw_entry_lt(const TwEntry& a, const TwEntry& b) {
  return a.when_us < b.when_us ||
         (a.when_us == b.when_us && a.token < b.token);
}

}  // namespace

extern "C" {

void* kdt_tw_new(uint64_t tick_us, uint32_t bits, uint32_t levels) {
  return new TimingWheel(tick_us, bits, levels);
}

void kdt_tw_free(void* h) { delete static_cast<TimingWheel*>(h); }

void kdt_tw_schedule(void* h, uint64_t when_us, uint64_t token) {
  auto* tw = static_cast<TimingWheel*>(h);
  std::lock_guard<std::mutex> g(tw->mu);
  tw->place(when_us, token);
  ++tw->size;
}

// Batched schedule: the whole tick's delivered frames in one call (one
// lock acquisition, no per-frame ctypes crossing).
void kdt_tw_schedule_batch(void* h, const uint64_t* when_us,
                           const uint64_t* tokens, int64_t n) {
  auto* tw = static_cast<TimingWheel*>(h);
  std::lock_guard<std::mutex> g(tw->mu);
  for (int64_t i = 0; i < n; ++i) {
    tw->place(when_us[i], tokens[i]);
  }
  tw->size += static_cast<uint64_t>(n);
}

// Advance virtual time to now_us; write up to cap tokens whose deadline
// has passed (strictly time-ordered, never early) into tokens_out and
// return how many were written. Remaining releasable entries stay queued
// for the next call.
int64_t kdt_tw_advance(void* h, uint64_t now_us, uint64_t* tokens_out,
                       int64_t cap) {
  auto* tw = static_cast<TimingWheel*>(h);
  std::lock_guard<std::mutex> g(tw->mu);
  if (now_us > tw->last_us) tw->advance_to(now_us);
  // due may hold entries whose deadline falls later inside the current
  // tick (place() puts delta==0 entries here): sort, then emit only the
  // prefix that is actually due at the wheel's time.
  std::sort(tw->due.begin(), tw->due.end(), tw_entry_lt);
  int64_t n = 0;
  while (n < cap && static_cast<uint64_t>(n) < tw->due.size() &&
         tw->due[n].when_us <= tw->last_us) {
    tokens_out[n] = tw->due[n].token;
    ++n;
  }
  tw->due.erase(tw->due.begin(), tw->due.begin() + n);
  tw->size -= static_cast<uint64_t>(n);
  return n;
}

uint64_t kdt_tw_size(void* h) {
  auto* tw = static_cast<TimingWheel*>(h);
  std::lock_guard<std::mutex> g(tw->mu);
  return tw->size;
}

// Lower bound on the next release time: exact when something is already
// due, the earliest level-0 slot when one is populated, else the next
// level-0 horizon boundary (a cascade point). UINT64_MAX when empty.
// The runner may sleep until the returned time without missing an event.
uint64_t kdt_tw_next_due_us(void* h) {
  auto* tw = static_cast<TimingWheel*>(h);
  std::lock_guard<std::mutex> g(tw->mu);
  if (tw->size == 0) return UINT64_MAX;
  if (!tw->due.empty()) {
    uint64_t best = UINT64_MAX;
    for (const TwEntry& e : tw->due) best = std::min(best, e.when_us);
    return best;
  }
  for (uint64_t d = 1; d <= tw->mask + 1; ++d) {
    const uint64_t idx = (tw->cursor + d) & tw->mask;
    if (!tw->wheel[0][idx].empty()) return (tw->cursor + d) * tw->tick_us;
  }
  const uint64_t next_boundary =
      ((tw->cursor / tw->horizon(0)) + 1) * tw->horizon(0);
  return next_boundary * tw->tick_us;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Section 5: shared-memory SPSC ingest ring (kdt_shm_*)
//
// One memory-mapped segment per producer process. Layout (all offsets
// fixed, little-endian, 64-bit):
//
//   0    u64  magic "KDTSHMR1"
//   8    u32  version (1)
//   12   u32  slot_size            (bytes per slot, header included)
//   16   u64  slots
//   24   u64  producer_pid         (liveness probe for gap-skip)
//   32   char namespace[64]        (tenant namespace, NUL padded)
//   128  u64  tail                 (producer reserve cursor; own line)
//   192  u64  head                 (consumer cursor; own line)
//   256  u64  full_failures        (producer-side ring-full count)
//   320  u64  commit[slots]        (seqlock-style commit words)
//   ...  slot data, 64-byte aligned, slots * slot_size bytes
//
// Slot: u32 frame_len | u32 wire_id | u64 trace_id | payload.
//
// Commit protocol: position p maps to slot p % slots with generation
// p / slots + 1. A producer RESERVES by advancing tail (release),
// writes the slot body, then stores commit[slot] = generation
// (release). The consumer only consumes a position once its commit
// word equals the expected generation — a producer that dies between
// reserve and commit leaves a visible-but-uncommitted gap that can
// never be read as a torn frame. The consumer stalls at such a gap
// (the producer may still be mid-write) unless the caller passes
// skip_uncommitted, which the Python driver only does after proving
// the producer pid dead; skipped reservations are counted out-param.
// SPSC: exactly one producer writes tail/slots, exactly one consumer
// writes head. All cross-process handoff is via the three atomics.
// ---------------------------------------------------------------------------

namespace {

constexpr uint64_t SHM_MAGIC = 0x31524D4853544B44ull;  // "KDTSHR1" tag
constexpr uint32_t SHM_VERSION = 1;
constexpr uint64_t SHM_OFF_MAGIC = 0;
constexpr uint64_t SHM_OFF_VERSION = 8;
constexpr uint64_t SHM_OFF_SLOT_SIZE = 12;
constexpr uint64_t SHM_OFF_SLOTS = 16;
constexpr uint64_t SHM_OFF_PID = 24;
constexpr uint64_t SHM_OFF_NS = 32;
constexpr uint64_t SHM_NS_CAP = 64;
constexpr uint64_t SHM_OFF_TAIL = 128;
constexpr uint64_t SHM_OFF_HEAD = 192;
constexpr uint64_t SHM_OFF_FULL = 256;
constexpr uint64_t SHM_OFF_COMMIT = 320;
constexpr uint32_t SHM_SLOT_HDR = 16;  // frame_len + wire_id + trace_id

inline uint64_t shm_align64(uint64_t v) { return (v + 63ull) & ~63ull; }

inline uint64_t* shm_u64(uint8_t* mem, uint64_t off) {
  return reinterpret_cast<uint64_t*>(mem + off);
}
inline const uint64_t* shm_u64c(const uint8_t* mem, uint64_t off) {
  return reinterpret_cast<const uint64_t*>(mem + off);
}
inline uint32_t shm_load_u32(const uint8_t* mem, uint64_t off) {
  uint32_t v;
  std::memcpy(&v, mem + off, sizeof(v));
  return v;
}
inline uint64_t shm_data_off(uint64_t slots) {
  return shm_align64(SHM_OFF_COMMIT + slots * 8ull);
}
inline uint8_t* shm_slot_ptr(uint8_t* mem, uint64_t slots,
                             uint32_t slot_size, uint64_t idx) {
  return mem + shm_data_off(slots) + idx * static_cast<uint64_t>(slot_size);
}

}  // namespace

extern "C" {

// Total segment size for a ring with this geometry (for ftruncate).
int64_t kdt_shm_required(uint64_t slots, uint32_t slot_size) {
  if (slots == 0 || slot_size <= SHM_SLOT_HDR) return -1;
  return static_cast<int64_t>(shm_data_off(slots) +
                              slots * static_cast<uint64_t>(slot_size));
}

// Initialize a fresh segment in place. Returns 1 on success, 0 when
// the mapping is too small or the geometry is invalid.
int32_t kdt_shm_init(uint8_t* mem, uint64_t mem_len, uint64_t slots,
                     uint32_t slot_size, uint64_t pid, const char* ns) {
  const int64_t need = kdt_shm_required(slots, slot_size);
  if (need < 0 || mem_len < static_cast<uint64_t>(need)) return 0;
  std::memset(mem, 0, shm_data_off(slots));
  std::memcpy(mem + SHM_OFF_VERSION, &SHM_VERSION, 4);
  std::memcpy(mem + SHM_OFF_SLOT_SIZE, &slot_size, 4);
  *shm_u64(mem, SHM_OFF_SLOTS) = slots;
  *shm_u64(mem, SHM_OFF_PID) = pid;
  if (ns != nullptr) {
    const size_t n = std::min(std::strlen(ns), size_t(SHM_NS_CAP - 1));
    std::memcpy(mem + SHM_OFF_NS, ns, n);
  }
  // magic last, release: a concurrent attach never sees a half-built
  // header as valid
  __atomic_store_n(shm_u64(mem, SHM_OFF_MAGIC), SHM_MAGIC,
                   __ATOMIC_RELEASE);
  return 1;
}

// Validate an attached segment: magic, version, geometry vs mapping
// length. Returns 1 valid / 0 invalid.
int32_t kdt_shm_check(const uint8_t* mem, uint64_t mem_len) {
  if (mem_len < SHM_OFF_COMMIT) return 0;
  if (__atomic_load_n(shm_u64c(mem, SHM_OFF_MAGIC), __ATOMIC_ACQUIRE) !=
      SHM_MAGIC)
    return 0;
  if (shm_load_u32(mem, SHM_OFF_VERSION) != SHM_VERSION) return 0;
  const uint64_t slots = *shm_u64c(mem, SHM_OFF_SLOTS);
  const uint32_t slot_size = shm_load_u32(mem, SHM_OFF_SLOT_SIZE);
  const int64_t need = kdt_shm_required(slots, slot_size);
  return (need > 0 && mem_len >= static_cast<uint64_t>(need)) ? 1 : 0;
}

uint64_t kdt_shm_slots(const uint8_t* mem) {
  return *shm_u64c(mem, SHM_OFF_SLOTS);
}
uint32_t kdt_shm_slot_size(const uint8_t* mem) {
  return shm_load_u32(mem, SHM_OFF_SLOT_SIZE);
}
uint64_t kdt_shm_pid(const uint8_t* mem) {
  return __atomic_load_n(shm_u64c(mem, SHM_OFF_PID), __ATOMIC_ACQUIRE);
}
void kdt_shm_set_pid(uint8_t* mem, uint64_t pid) {
  __atomic_store_n(shm_u64(mem, SHM_OFF_PID), pid, __ATOMIC_RELEASE);
}
int32_t kdt_shm_ns(const uint8_t* mem, char* out, int32_t cap) {
  if (cap <= 0) return 0;
  int32_t n = 0;
  while (n < cap - 1 && n < int32_t(SHM_NS_CAP) &&
         mem[SHM_OFF_NS + n] != 0) {
    out[n] = static_cast<char>(mem[SHM_OFF_NS + n]);
    ++n;
  }
  out[n] = 0;
  return n;
}

// Entries reserved and not yet consumed (committed or not).
uint64_t kdt_shm_pending(const uint8_t* mem) {
  const uint64_t tail =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_TAIL), __ATOMIC_ACQUIRE);
  const uint64_t head =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_HEAD), __ATOMIC_ACQUIRE);
  return tail - head;
}

uint64_t kdt_shm_full_failures(const uint8_t* mem) {
  return __atomic_load_n(shm_u64c(mem, SHM_OFF_FULL), __ATOMIC_ACQUIRE);
}

// Committed-and-unconsumed count: walks [head, tail) checking commit
// words. O(pending) — accounting/verification surface (the chaos
// scenario's zero-committed-loss audit), not the hot path.
uint64_t kdt_shm_committed(const uint8_t* mem) {
  const uint64_t slots = *shm_u64c(mem, SHM_OFF_SLOTS);
  const uint64_t head =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_HEAD), __ATOMIC_ACQUIRE);
  const uint64_t tail =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_TAIL), __ATOMIC_ACQUIRE);
  const uint64_t* commit = shm_u64c(mem, SHM_OFF_COMMIT);
  uint64_t n = 0;
  for (uint64_t p = head; p < tail; ++p) {
    if (__atomic_load_n(commit + p % slots, __ATOMIC_ACQUIRE) ==
        p / slots + 1)
      ++n;
  }
  return n;
}

// Producer: push one frame. 1 = pushed, 0 = ring full (counted in
// full_failures), -1 = frame larger than a slot payload.
int32_t kdt_shm_push(uint8_t* mem, const uint8_t* frame, uint32_t len,
                     uint32_t wire_id, uint64_t trace_id) {
  const uint64_t slots = *shm_u64c(mem, SHM_OFF_SLOTS);
  const uint32_t slot_size = shm_load_u32(mem, SHM_OFF_SLOT_SIZE);
  if (len > slot_size - SHM_SLOT_HDR) return -1;
  const uint64_t tail =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_TAIL), __ATOMIC_RELAXED);
  const uint64_t head =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_HEAD), __ATOMIC_ACQUIRE);
  if (tail - head >= slots) {
    __atomic_add_fetch(shm_u64(mem, SHM_OFF_FULL), 1, __ATOMIC_RELEASE);
    return 0;
  }
  // reserve, write, commit — same order as the batch path so a crash
  // at any point leaves at worst an uncommitted reservation
  __atomic_store_n(shm_u64(mem, SHM_OFF_TAIL), tail + 1, __ATOMIC_RELEASE);
  const uint64_t idx = tail % slots;
  uint8_t* slot = shm_slot_ptr(mem, slots, slot_size, idx);
  std::memcpy(slot, &len, 4);
  std::memcpy(slot + 4, &wire_id, 4);
  std::memcpy(slot + 8, &trace_id, 8);
  if (len) std::memcpy(slot + SHM_SLOT_HDR, frame, len);
  __atomic_store_n(shm_u64(mem, SHM_OFF_COMMIT) + idx, tail / slots + 1,
                   __ATOMIC_RELEASE);
  return 1;
}

// Producer: push a columnar batch (blob + offs/lens, one slot per
// frame). Reserves the whole publishable span up front, then writes
// and commits slot by slot. Returns frames pushed; stops early at
// ring-full (counted once in full_failures) or at the first frame
// that exceeds the slot payload (caller distinguishes by comparing
// lens[returned] against the payload capacity).
int64_t kdt_shm_push_batch(uint8_t* mem, const uint8_t* blob,
                           const uint64_t* offs, const uint64_t* lens,
                           const uint32_t* wire_ids,
                           const uint64_t* trace_ids, int64_t n) {
  if (n <= 0) return 0;
  const uint64_t slots = *shm_u64c(mem, SHM_OFF_SLOTS);
  const uint32_t slot_size = shm_load_u32(mem, SHM_OFF_SLOT_SIZE);
  const uint64_t payload_cap = slot_size - SHM_SLOT_HDR;
  const uint64_t tail =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_TAIL), __ATOMIC_RELAXED);
  const uint64_t head =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_HEAD), __ATOMIC_ACQUIRE);
  const uint64_t avail = slots - (tail - head);
  int64_t k = std::min<int64_t>(n, static_cast<int64_t>(avail));
  int64_t fit = 0;
  while (fit < k && lens[fit] <= payload_cap) ++fit;
  if (fit < n && fit == k && static_cast<uint64_t>(k) == avail) {
    // stopped because the ring is full, not because a frame was too big
    __atomic_add_fetch(shm_u64(mem, SHM_OFF_FULL), 1, __ATOMIC_RELEASE);
  }
  if (fit == 0) return 0;
  __atomic_store_n(shm_u64(mem, SHM_OFF_TAIL),
                   tail + static_cast<uint64_t>(fit), __ATOMIC_RELEASE);
  uint64_t* commit = shm_u64(mem, SHM_OFF_COMMIT);
  for (int64_t i = 0; i < fit; ++i) {
    const uint64_t pos = tail + static_cast<uint64_t>(i);
    const uint64_t idx = pos % slots;
    const uint32_t len = static_cast<uint32_t>(lens[i]);
    uint8_t* slot = shm_slot_ptr(mem, slots, slot_size, idx);
    std::memcpy(slot, &len, 4);
    std::memcpy(slot + 4, &wire_ids[i], 4);
    const uint64_t tid = trace_ids ? trace_ids[i] : 0;
    std::memcpy(slot + 8, &tid, 8);
    if (len) std::memcpy(slot + SHM_SLOT_HDR, blob + offs[i], len);
    __atomic_store_n(commit + idx, pos / slots + 1, __ATOMIC_RELEASE);
  }
  return fit;
}

// Test hook: reserve n slots and never commit them — the frozen image
// of a producer killed between reserve and publish.
int32_t kdt_shm_push_torn(uint8_t* mem, uint32_t n) {
  const uint64_t slots = *shm_u64c(mem, SHM_OFF_SLOTS);
  const uint64_t tail =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_TAIL), __ATOMIC_RELAXED);
  const uint64_t head =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_HEAD), __ATOMIC_ACQUIRE);
  if (slots - (tail - head) < n) return 0;
  __atomic_store_n(shm_u64(mem, SHM_OFF_TAIL), tail + n, __ATOMIC_RELEASE);
  return 1;
}

// Consumer: batch-dequeue committed frames into a contiguous blob +
// columnar arrays (wire_id, byte offset, byte length, trace_id per
// frame). Stops at max_frames, at blob_cap, or at the first
// uncommitted reservation — unless skip_uncommitted (the caller has
// proven the producer dead), in which case gaps are skipped and
// counted in *out_skipped. Returns frames dequeued.
int64_t kdt_shm_dequeue(uint8_t* mem, uint8_t* out_blob, uint64_t blob_cap,
                        uint32_t* out_wire, uint64_t* out_off,
                        uint64_t* out_len, uint64_t* out_trace,
                        int64_t max_frames, int32_t skip_uncommitted,
                        uint64_t* out_skipped) {
  const uint64_t slots = *shm_u64c(mem, SHM_OFF_SLOTS);
  const uint32_t slot_size = shm_load_u32(mem, SHM_OFF_SLOT_SIZE);
  const uint64_t payload_cap = slot_size - SHM_SLOT_HDR;
  uint64_t head =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_HEAD), __ATOMIC_RELAXED);
  const uint64_t tail =
      __atomic_load_n(shm_u64c(mem, SHM_OFF_TAIL), __ATOMIC_ACQUIRE);
  uint64_t* commit = shm_u64(mem, SHM_OFF_COMMIT);
  int64_t n = 0;
  uint64_t used = 0;
  uint64_t skipped = 0;
  while (head < tail && n < max_frames) {
    const uint64_t idx = head % slots;
    const uint64_t gen = head / slots + 1;
    if (__atomic_load_n(commit + idx, __ATOMIC_ACQUIRE) != gen) {
      if (!skip_uncommitted) break;
      ++head;
      ++skipped;
      continue;
    }
    const uint8_t* slot = shm_slot_ptr(mem, slots, slot_size, idx);
    uint32_t len;
    std::memcpy(&len, slot, 4);
    if (len > payload_cap) {  // corrupt slot: never hand it upstream
      ++head;
      ++skipped;
      continue;
    }
    if (used + len > blob_cap) break;
    std::memcpy(&out_wire[n], slot + 4, 4);
    std::memcpy(&out_trace[n], slot + 8, 8);
    if (len) std::memcpy(out_blob + used, slot + SHM_SLOT_HDR, len);
    out_off[n] = used;
    out_len[n] = len;
    used += len;
    ++n;
    ++head;
  }
  // release: the producer's availability check (acquire load of head)
  // must observe our slot reads as complete before reusing them
  __atomic_store_n(shm_u64(mem, SHM_OFF_HEAD), head, __ATOMIC_RELEASE);
  if (out_skipped) *out_skipped = skipped;
  return n;
}

}  // extern "C"
